# Build the native fastwire extension in place (optional: the transport
# falls back to pure-Python socket IO when the extension is absent).
.PHONY: native test lint sanitize chaos latency scale dma shm serve async churn obs privacy ha wan tenant clean

native:
	python setup.py build_ext --inplace

test:
	./test.sh

# Static checks: license headers, fedlint over the shipped drivers AND
# the framework itself (both must be clean — every self-lint finding is
# fixed or suppressed in place with a justification), and the fedlint
# contract tests (fixture corpus + seq-id validation). Mirrors
# .github/workflows/fedlint.yml.
lint:
	python tools/check_license_headers.py
	python -m rayfed_tpu.lint examples
	python -m rayfed_tpu.lint rayfed_tpu
	JAX_PLATFORMS=cpu python -m pytest tests/test_fedlint.py tests/test_seq_id_validation.py -q

# FedSanitizer lane (docs/sanitizer.md): the probe unit tests (each
# probe forced to trip), the chaos FedAvg spawn test under
# FEDTPU_SANITIZE=1 (zero trips, bitwise-identical results vs the
# unsanitized run), and the overhead gate — sanitized round time must
# stay within FEDTPU_SANITIZE_BUDGET_PCT (default 10%) of baseline.
# Mirrors the `sanitize` job in .github/workflows/tests.yml.
sanitize:
	JAX_PLATFORMS=cpu FEDTPU_SANITIZE=1 python -m pytest \
	  tests/test_sanitizer.py -q
	JAX_PLATFORMS=cpu python tools/sanitize_check.py

# Chaos/failure lane (docs/resilience.md): the seeded fault-schedule
# FedAvg run plus the multi-process failure-path tests. Slow by design
# (real timeouts, spawned parties) — mirrors the `chaos` job in
# .github/workflows/tests.yml.
chaos:
	JAX_PLATFORMS=cpu python -m pytest \
	  tests/test_resilience.py tests/test_failure_paths.py -q

# Latency gate: the many-tiny-tasks micro-bench must stay under
# FEDTPU_TINY_BUDGET_MS per task (default 1.0) or this exits 1 — a change
# that re-adds a thread hop or pickle round to the small-message fast
# path fails loudly here. Mirrors the `latency` job in
# .github/workflows/tests.yml.
latency:
	JAX_PLATFORMS=cpu python tools/latency_check.py

# Scale gate: 8- and 16-party simulated hierarchical rounds (real TCP
# proxies over shared epoll reactors, in-process parties) must keep
# their MEDIAN round under budget, with a hard wall-clock cap on the
# whole check — a serialized reactor loop or re-added per-peer thread
# hop fails loudly here. Mirrors the `scale` job in
# .github/workflows/tests.yml.
scale:
	JAX_PLATFORMS=cpu python tools/scale_check.py

# Data-plane gate: the striped multi-stream lane (num_streams reactor
# lanes carrying stripe frames) must out-run the device-DMA lane's
# CPU-sim throughput (FEDTPU_DMA_RATIO, default 1.0x) — a change that
# serializes the stripe lanes or re-adds full-payload staging fails
# loudly here.
dma:
	JAX_PLATFORMS=cpu python tools/dma_check.py

# Shared-memory lane gate: same-host pushes over the /dev/shm ring must
# beat loopback TCP by FEDTPU_SHM_RATIO (default 4.0x), with an
# absolute FEDTPU_SHM_FLOOR_GBPS anti-gaming floor — a change that
# re-adds a staging copy, breaks ring adoption (silent per-push socket
# fallback), or serializes pushes behind the ring lock fails loudly
# here. Mirrors the `shm` job in .github/workflows/tests.yml.
shm: native
	JAX_PLATFORMS=cpu python tools/shm_check.py

# Serving gate (docs/serving.md): the inference engine under 8
# concurrent clients with hot swaps mid-window must hold its
# serve_tokens_s floor and serve_p99_ms ceiling, and continuous
# batching must stay >= FEDTPU_SERVE_BUDGET_SPEEDUP x the naive
# one-request-at-a-time baseline — a serialized batcher or a request
# stalled across a swap fails loudly here. Mirrors the `serve` job in
# .github/workflows/tests.yml.
serve:
	JAX_PLATFORMS=cpu python tools/serve_check.py

# Async gate (docs/async_rounds.md): 3 spawned parties with carol's
# every send delayed by a seeded fault schedule; buffered-async rounds
# (fed.async_round, K-publish without the straggler) must sustain
# FEDTPU_ASYNC_BUDGET_RATIO x (default 3.0) the lock-step baseline's
# rounds/s AND an absolute FEDTPU_ASYNC_BUDGET_FLOOR — a change that
# re-serializes the fold path or makes publish wait for the straggler
# fails loudly here. Mirrors the `async` job in
# .github/workflows/tests.yml.
async:
	JAX_PLATFORMS=cpu python tools/async_check.py

# Churn gate (docs/membership.md): elastic membership under fire — one
# party crash-killed mid-round and liveness-evicted, a replacement
# joining mid-training via fed.join. churn_rounds_lost must stay 0,
# the replacement must take over, and churn_join_ms must stay under
# budget, plus the spawn-based membership lifecycle tests. Mirrors the
# `churn` job in .github/workflows/tests.yml.
churn:
	JAX_PLATFORMS=cpu python tools/churn_check.py
	JAX_PLATFORMS=cpu python -m pytest tests/test_membership.py -q

# Observability gate (docs/observability.md): a 3-party round with the
# telemetry plane on, paired against telemetry-off windows —
# metrics_overhead_pct must stay under FEDTPU_OBS_BUDGET_PCT (default
# 3%), every core series must appear in the collector's /metrics
# scrape, all parties must report in /fleet, and at least one seq-id
# edge in /trace must stitch spans from two parties. Mirrors the `obs`
# job in .github/workflows/tests.yml.
obs:
	JAX_PLATFORMS=cpu python tools/obs_check.py
	JAX_PLATFORMS=cpu python -m pytest tests/test_telemetry.py -q

# Privacy gate (docs/privacy.md): 3 spawned parties with the privacy
# plane on — paired plaintext/secure FedAvg windows, every secure round
# bitwise-checked against the plaintext fold (mask cancellation is
# EXACT or broken, never "close"), secure_agg_overhead_pct under
# FEDTPU_SECAGG_BUDGET_PCT, the int8 quantized push over its floor,
# plus the privacy unit/chaos tests. Mirrors the `privacy` job in
# .github/workflows/tests.yml.
privacy:
	JAX_PLATFORMS=cpu python tools/privacy_check.py
	JAX_PLATFORMS=cpu python -m pytest tests/test_privacy.py -q

# HA gate (docs/ha.md): control-plane failover under fire — the
# configured coordinator crash-killed mid-sync-broadcast, the
# deterministic successor taking over the sync point under term 1.
# ha_rounds_lost must stay 0, the successor must actually hold the
# role, and coordinator_failover_ms must stay under
# FEDTPU_HA_BUDGET_MS, plus the failover/handoff/checkpoint chaos
# tests. Mirrors the `ha` job in .github/workflows/tests.yml.
ha:
	JAX_PLATFORMS=cpu python tools/ha_check.py
	JAX_PLATFORMS=cpu python -m pytest tests/test_ha.py -q

# WAN gate (docs/resilience.md): 3 spawned parties over an in-proxy
# emulated 50ms/100Mbit link (LinkProfile shaper) with frame crc and
# adaptive deadlines on — wan_round_ms must stay latency-bound under
# FEDTPU_WAN_ROUND_BUDGET_MS (and ABOVE the shaper-is-alive floor),
# link_rtt_ms must show the LinkHealth estimator converging on the
# emulated RTT, plus the WAN unit + chaos tests (link shaping, crc
# NACK/retransmit, lane re-promotion, bounded duplicates). Mirrors the
# `wan` job in .github/workflows/tests.yml.
wan:
	JAX_PLATFORMS=cpu python tools/wan_check.py
	JAX_PLATFORMS=cpu python -m pytest tests/test_wan.py -q

# Tenancy gate (docs/multitenancy.md): the full tenancy unit suite +
# the multitenant_isolation chaos test, then tools/tenant_check.py —
# byte-identical isolation between co-resident jobs (non-negotiable)
# and the weighted-fair QoS keys from bench.py's tenant stage:
# tenant_fairness_ratio >= FEDTPU_TENANT_FAIRNESS (default 0.25 at the
# 1:4 weight split) and multitenant_victim_p99_ms under
# FEDTPU_TENANT_P99_MS. Mirrors the `tenant` job in
# .github/workflows/tests.yml.
tenant:
	JAX_PLATFORMS=cpu python -m pytest tests/test_tenancy.py \
	  tests/test_multitenant_chaos.py -q
	JAX_PLATFORMS=cpu python tools/tenant_check.py

clean:
	rm -rf build rayfed_tpu/_fastwire*.so
