# Build the native fastwire extension in place (optional: the transport
# falls back to pure-Python socket IO when the extension is absent).
.PHONY: native test clean

native:
	python setup.py build_ext --inplace

test:
	./test.sh

clean:
	rm -rf build rayfed_tpu/_fastwire*.so
