# Copyright 2026 The rayfed-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Headline benchmark: cross-party push throughput on 100MB tensors.

Prints ONE JSON line:
    {"metric": ..., "value": <GB/s native>, "unit": "GB/s",
     "vs_baseline": <native GB/s / reference-parity gRPC GB/s>}

The baseline is self-measured (the reference publishes no numbers —
BASELINE.md): the same two-party push workload over this repo's
``transport='grpc'`` lane, which reproduces the reference's wire behavior
(one unary RPC per object, payload cloudpickled inside the request,
ref ``fed/proxy/grpc/grpc_proxy.py:193-220``). The native lane is the
binary TCP protocol with the zero-pickle array fast path.

Workload (BASELINE.json config #2): 2 parties on localhost, alice pushes
N x 100MB float32 gradient tensors to bob via ``@fed.remote`` consumers;
bob measures arrival throughput.
"""

from __future__ import annotations

import contextlib
import json
import multiprocessing
import os
import socket
import sys
import tempfile
import time

# gRPC-core WARNING logs (retry_service_config.cc's maxAttempts clamp
# note among them) come from channels jaxlib and the parties create
# internally. Set at MODULE level so it covers the driver AND every
# spawned child — spawn re-imports this module, and subprocesses
# inherit the driver's env — not just the _party_entry trampoline
# (BENCH_r05's tails still carried the clamp spam from the psum/serve/
# MFU children, which bypass _party_entry).
os.environ.setdefault("GRPC_VERBOSITY", "ERROR")

PAYLOAD_MB = 100
ROUNDS = 5
REPS = 8  # best-of-N inside one job (single-core hosts are noisy)
# The paired-ceiling stage records more pairs: its headline is a MEDIAN
# ratio, and hypervisor steal bursts (one per ~30s observed) each poison
# a pair — 12 pairs keep the median in the steady-state regime.
PAIRED_REPS = 12

_FAST_RETRY = {
    "retry_policy": {
        "max_attempts": 20,
        "initial_backoff_ms": 200,
        "max_backoff_ms": 2000,
        "backoff_multiplier": 1.5,
    }
}

# Set by _run_two_party in the parent; spawned parties overwrite their
# mark file at each phase boundary so a hang is diagnosable (a party
# terminated by the timeout can't report anything itself — BENCH_r05
# recorded exactly such an undiagnosable "bench party hung").
_PROGRESS_DIR_VAR = "FEDTPU_BENCH_PROGRESS_DIR"


def _progress(party: str, phase: str) -> None:
    d = os.environ.get(_PROGRESS_DIR_VAR)
    if not d:
        return
    try:
        with open(os.path.join(d, f"{party}.progress"), "w") as f:
            f.write(phase)
    except OSError:
        pass  # diagnostics must never fail the measurement


def _party_entry(target, party, *rest):
    """Spawn trampoline: arm a SIGUSR1 all-thread stack dump into the
    progress dir before the party body runs, so the parent's watchdog
    can capture WHERE a hung party is stuck — not just the last phase
    mark (BENCH_r05's "bench party hung" had no stack to go on)."""
    # gRPC-core WARNING logs (retry_service_config.cc's maxAttempts clamp
    # note among them) come from channels jaxlib creates internally, not
    # from this repo's pre-clamped config — silence them below ERROR so
    # bench stderr stays parseable (see test_grpc_channel_options).
    os.environ.setdefault("GRPC_VERBOSITY", "ERROR")
    d = os.environ.get(_PROGRESS_DIR_VAR)
    if d:
        try:
            import faulthandler
            import signal

            from rayfed_tpu import tracing

            # Span ring on: the hang artifact below needs per-seq-id
            # send/recv/ack events to reconstruct which edge wedged.
            tracing.enable()

            def _dump_timeline(signum, frame):
                # Python-level chained handler: best-effort (only runs
                # when the main thread re-enters the interpreter loop);
                # the C-level faulthandler stacks below always land.
                try:
                    tracing.export_timeline(
                        os.path.join(d, f"{party}.timeline"), party
                    )
                    # Structured twin: feed to tools/trace_view.py for
                    # a per-seq-id text flamegraph of the wedge.
                    tracing.export_seq_timeline(
                        os.path.join(d, f"{party}.seq.json"), party
                    )
                except OSError:
                    pass

            signal.signal(signal.SIGUSR1, _dump_timeline)
            # Keep the file object referenced: faulthandler holds only
            # the fd, and a collected file object would close it.
            _party_entry._stacks_file = open(
                os.path.join(d, f"{party}.stacks"), "w"
            )
            # chain=True: the C handler dumps all-thread stacks first,
            # then invokes the timeline handler installed above.
            faulthandler.register(
                signal.SIGUSR1, file=_party_entry._stacks_file,
                all_threads=True, chain=True,
            )
        except (OSError, ValueError, AttributeError):
            pass  # diagnostics must never fail the measurement
    target(party, *rest)


def _party_main(party, addresses, transport, result_path, device_dma=False,
                pair_ceiling=False, num_streams=0, sharded=False,
                shm=False):
    import numpy as np

    import rayfed_tpu as fed

    comm = dict(_FAST_RETRY)
    if os.environ.get("FEDTPU_BENCH_WINDOW"):
        comm["send_window"] = int(os.environ["FEDTPU_BENCH_WINDOW"])
    if device_dma:
        comm["device_dma"] = True
    if num_streams:
        comm["num_streams"] = num_streams
    if shm:
        # Same-host zero-copy lane: payload bytes ride a /dev/shm ring,
        # only descriptor frames cross the socket (proxy/lanes.py).
        # Ring = in-flight payload budget: adoption is zero-copy, so all
        # ROUNDS pipelined tensors pin their chunks until the driver's
        # FedObjects die at the end of the rep — and with SPMD skew the
        # receiver still holds rep N's tensors while rep N+1's burst is
        # pushing, so the ring must cover TWO reps or pushes wait out
        # shm_push_timeout_ms and fall back to the socket mid-rep.
        comm["shm_enabled"] = True
        comm["shm_ring_mb"] = 2 * ROUNDS * PAYLOAD_MB + 64
    fed.init(
        addresses=addresses,
        party=party,
        config={"cross_silo_comm": comm, "transport": transport},
        job_name=f"bench-{transport}",
        logging_level="error",
    )

    n_elem = PAYLOAD_MB * 1024 * 1024 // 4

    if device_dma:
        # Device-resident payloads: the DMA lane parks live jax buffers
        # on the transfer server and ships only a descriptor over the
        # socket; the receiver pulls through the transfer engine's bulk
        # transport (ICI/DCN on a pod, its socket transport in CPU sim).
        import jax.numpy as jnp

        @fed.remote
        def produce(i):
            import jax

            return jax.block_until_ready(
                jnp.full((n_elem,), float(i), dtype=jnp.float32)
            )
    elif sharded:
        # Sharded-pipeline lane: a 4-way sharded jax.Array (spawned under
        # a forced multi-device CPU backend). The encode worker overlaps
        # per-shard D2H and the stripe planner splits at shard extents,
        # so the payload rides K lanes as parallel stripe frames.
        import jax
        import jax.numpy as jnp
        from jax.sharding import Mesh, NamedSharding, PartitionSpec

        nshards = min(4, len(jax.devices()))
        sh = NamedSharding(
            Mesh(np.array(jax.devices()[:nshards]), ("data",)),
            PartitionSpec("data"),
        )

        @fed.remote
        def produce(i):
            import jax

            return jax.block_until_ready(
                jax.device_put(
                    jnp.full((n_elem,), float(i), dtype=jnp.float32), sh
                )
            )
    elif num_streams:
        # Multi-leaf pytree: stripes split only at buffer (leaf/shard)
        # boundaries, so one dense tensor cannot engage striping; 16
        # chunks give the planner balanced extents for any lane count.
        chunks = 16
        per = n_elem // chunks

        @fed.remote
        def produce(i):
            return [
                np.full((per,), float(i), dtype=np.float32)
                for _ in range(chunks)
            ]
    else:

        @fed.remote
        def produce(i):
            # Fresh tensor per round (dedup would skip repeat pushes).
            return np.full((n_elem,), float(i), dtype=np.float32)

    @fed.remote
    def consume(x):
        if isinstance(x, (list, tuple)):
            return float(x[0][0]) + float(x[-1][-1])
        shards = list(getattr(x, "addressable_shards", None) or ())
        if len(shards) > 1:
            import jax

            first = jax.device_get(shards[0].data)
            last = jax.device_get(shards[-1].data)
            return float(first[0]) + float(last[-1])
        return float(x[0]) + float(x[-1])

    @fed.remote
    def barrier(*xs):
        return len(xs)

    @fed.remote
    def tell_port(p):
        return p

    # Connection warmup (the measurement loop below carries its own
    # discarded warmup cycles).
    _progress(party, "init done; connection warmup")
    w = consume.party("bob").remote(produce.party("alice").remote(-1.0))
    assert fed.get(w) == -2.0
    _progress(party, "warmup done")

    # Paired-ceiling rig: a dedicated raw socket between the SAME two
    # party processes. Each rep runs a raw sendall/recv_into window
    # immediately before the lane window, so every lane sample gets a
    # ceiling sample measured seconds apart under the same host regime —
    # on this class of shared VM, throughput swings 2-3x on a seconds
    # timescale (hypervisor steal), so a ceiling probed minutes away
    # (round-4 methodology) calibrates a different regime than the stage
    # it normalizes. pct_of_ceiling is the MEDIAN of per-rep ratios.
    # The rig is best-effort: any failure here or in a raw window below
    # degrades to lane-only reps (no ceiling keys) — the diagnostic
    # ceiling must never abort the headline measurement. A failure on
    # one side closes the raw socket, which breaks the peer's blocked
    # window immediately (RST on close-with-unread-data), so both sides
    # fall back in the same rep without desyncing the fed loop.
    raw_sock = None
    raw_nbytes = PAYLOAD_MB * 1024 * 1024
    if pair_ceiling:
        try:
            if party == "bob":
                raw_srv = socket.socket()
                raw_srv.bind(("127.0.0.1", 0))
                raw_srv.listen(1)
                raw_srv.settimeout(60)
                raw_port = raw_srv.getsockname()[1]
            else:
                raw_port = 0
        except OSError:
            raw_port = -1
        # Multi-controller port exchange: the task runs at bob with bob's
        # local value; alice's argument is a placeholder.
        port_obj = tell_port.party("bob").remote(raw_port)
        raw_port = fed.get(port_obj)
        try:
            if raw_port < 0:
                raise OSError("peer has no raw listener")
            if party == "alice":
                raw_sock = socket.create_connection(
                    ("127.0.0.1", raw_port), timeout=60
                )
                raw_sock.settimeout(None)
                _tune(raw_sock)
                raw_buf = bytearray(raw_nbytes)
            else:
                raw_sock, _ = raw_srv.accept()
                raw_srv.close()
                raw_sock.settimeout(None)
                _tune(raw_sock)
                raw_view = memoryview(bytearray(raw_nbytes))
        except OSError as e:
            print(f"paired ceiling rig unavailable: {e!r}", file=sys.stderr)
            raw_sock = None

    # Negative reps are warmup cycles with the IDENTICAL per-rep
    # structure (produce, barrier, raw window, lane window), discarded
    # from the stats. Measured: the lane needs ~3 full cycles before its
    # allocator/scheduler steady state — single-push warmups left the
    # first 2-3 timed reps 2-5x slow in every run on this host class.
    samples = []
    raw_samples = []
    warmup_reps = 3
    n_reps = PAIRED_REPS if pair_ceiling else REPS
    for rep in range(-warmup_reps, n_reps):
        _progress(party, f"rep {rep}/{n_reps}")
        # Materialize all tensors at alice BEFORE the timed window so the
        # measurement is transport throughput, not producer memset speed.
        base = 100.0 * rep
        tensors = [produce.party("alice").remote(base + i) for i in range(ROUNDS)]
        ready = barrier.party("alice").remote(*tensors)
        assert fed.get(ready) == ROUNDS

        if raw_sock is not None:
            # Raw window: same bytes, same window structure, same two
            # processes, right before the lane window it calibrates. Uses
            # the strongest IO primitive available (the C++ fastwire
            # calls — one GIL-released call per payload) so the ceiling
            # is a true best-possible socket loop, not a Python recv_into
            # loop the native lane can beat.
            try:
                if party == "alice":
                    for _ in range(ROUNDS):
                        _raw_send(raw_sock, raw_buf)
                else:
                    t0 = time.perf_counter()
                    for _ in range(ROUNDS):
                        _raw_recv(raw_sock, raw_view)
                    if rep >= 0:
                        raw_samples.append(
                            ROUNDS * PAYLOAD_MB / 1024
                            / (time.perf_counter() - t0)
                        )
            except (OSError, ConnectionError, TimeoutError) as e:
                print(
                    f"paired ceiling dropped mid-run: {e!r}", file=sys.stderr
                )
                try:
                    raw_sock.close()
                except OSError:
                    pass
                raw_sock = None
                raw_samples = []  # partial pairing would skew the ratio
        if pair_ceiling:
            # Barrier before the lane window: alice's _raw_send returns
            # with up to ~2x SO_SNDBUF still unread in kernel buffers;
            # starting the lane push then would overlap bob's raw-timer
            # tail with lane work, deflating the ceiling sample in the
            # lane's favor. A bob-owned no-op resolves only after bob's
            # program has finished its raw window. Runs UNCONDITIONALLY
            # under pair_ceiling (cheap no-op when the rig is down):
            # gating it on the per-process raw_sock would deadlock both
            # parties the moment a rig failure is asymmetric — one side
            # waiting at this barrier for a peer that skipped it.
            fed.get(tell_port.party("bob").remote(rep))

        t0 = time.perf_counter()
        outs = [consume.party("bob").remote(t) for t in tensors]
        checks = fed.get(outs)
        dt = time.perf_counter() - t0
        assert checks == [2.0 * (base + i) for i in range(ROUNDS)], checks
        if rep >= 0:
            samples.append(ROUNDS * PAYLOAD_MB / 1024 / dt)

    if raw_sock is not None:
        try:
            raw_sock.close()
        except OSError:
            pass
    # Peak-of-reps: throughput capability, same rule for both lanes.
    gbps = max(samples)
    if party == "bob":
        with open(result_path, "w") as f:
            json.dump(
                {"gbps": gbps, "samples": samples,
                 "raw_samples": raw_samples},
                f,
            )
    _progress(party, "reps done; shutting down")
    fed.shutdown()


@contextlib.contextmanager
def _cpu_forced():
    """Spawned children come up on the CPU jax backend inside this
    context (two party processes cannot share the driver's single chip,
    and a wedged accelerator tunnel must not hang them — env is
    inherited by spawn, and the axon plugin registers at interpreter
    startup)."""
    scrub = {"PALLAS_AXON_POOL_IPS": None, "JAX_PLATFORMS": "cpu"}
    saved = {k: os.environ.get(k) for k in scrub}
    try:
        for k, v in scrub.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        yield
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def _raw_send(sock, buf) -> None:
    try:
        from rayfed_tpu import _fastwire

        _fastwire.sendv(sock.fileno(), -1, [buf])
    except ImportError:
        sock.sendall(buf)


def _raw_recv(sock, view) -> None:
    try:
        from rayfed_tpu import _fastwire

        _fastwire.recv_exact(sock.fileno(), -1, view)
    except ImportError:
        n = view.nbytes
        got = 0
        while got < n:
            k = sock.recv_into(view[got:], n - got)
            if not k:
                raise ConnectionError("raw ceiling sender died")
            got += k


def _free_ports(n):
    socks = [socket.socket() for _ in range(n)]
    for s in socks:
        s.bind(("127.0.0.1", 0))
    ports = [s.getsockname()[1] for s in socks]
    for s in socks:
        s.close()
    return ports


def run_transport(transport: str, device_dma: bool = False,
                  pair_ceiling: bool = False, num_streams: int = 0,
                  sharded: bool = False, shm: bool = False) -> dict:
    res = _run_two_party(
        _party_main, transport,
        (device_dma, pair_ceiling, num_streams, sharded, shm),
        timeout_s=600,
    )
    import statistics

    # max = capability (continuity with earlier rounds); median is
    # robust to the start-clock skew between the two party processes,
    # which can inflate individual short timed windows.
    out = {
        "max": res["gbps"],
        "median": statistics.median(res["samples"]),
        "samples": res["samples"],
    }
    raw = res.get("raw_samples") or []
    if raw and len(raw) == len(res["samples"]):
        ratios = [s / r for s, r in zip(res["samples"], raw) if r > 0]
        out["raw_median"] = statistics.median(raw)
        out["raw_spread"] = [min(raw), max(raw)]
        out["paired_ratio_median"] = statistics.median(ratios)
    return out


def _tune(sock) -> None:
    """Apply the transport's own socket tuning to the ceiling probe —
    without this the 'ceiling' uses default buffer sizes and the tuned
    native lane can beat it (a >100% pct_of_ceiling is a measurement
    artifact, not physics)."""
    try:
        from rayfed_tpu.proxy.tcp import sockio

        sockio.tune_socket(sock)
    except Exception:  # noqa: BLE001 - probe still works untuned
        pass


def _lane_stats(out: dict, key: str, res: dict) -> None:
    """Record a lane's max (capability, the headline) plus median and
    min/max spread of the same rep samples — one lucky rep on this class
    of shared VM can double "max", and a gating script needs the robust
    statistic next to it."""
    out[key] = round(res["max"], 3)
    out[f"{key}_median"] = round(res["median"], 3)
    out[f"{key}_spread"] = [
        round(min(res["samples"]), 3), round(max(res["samples"]), 3)
    ]


def _try_tpu_lanes() -> dict:
    """The ``transport='tpu'`` lanes, CPU-forced (on this driver there is
    ONE real chip and two party processes cannot share it; a wedged
    accelerator tunnel must not hang the children):

    - ``tpu_lane_gbps``: the full TPU transport — native socket wire +
      device placement on arrival (decode lands arrays via device_put,
      native pooled receive buffers are 64-byte aligned for XLA
      ingestion). On a pod the same lane runs per-host over DCN.
    - ``dma_cpu_gbps``: the device-DMA lane (descriptor over the socket,
      buffers pulled through the jax transfer engine). Its CPU-sim bound
      is the engine itself (~0.6 GB/s bare-engine measurement, STATUS);
      on a pod the engine rides ICI.

    Each key comes with ``_median`` and ``_spread`` companions.
    Best-effort: records nothing when the backend is unavailable."""
    out = {}
    with _cpu_forced():
        try:
            _lane_stats(out, "tpu_lane_gbps", run_transport("tpu"))
        except Exception as e:  # noqa: BLE001
            print(f"tpu-lane bench skipped: {e!r}", file=sys.stderr)
        try:
            _lane_stats(
                out, "dma_cpu_gbps", run_transport("tpu", device_dma=True)
            )
        except Exception as e:  # noqa: BLE001
            print(f"dma bench skipped: {e!r}", file=sys.stderr)
    return out


_MULTISTREAM_LANES = 4


@contextlib.contextmanager
def _cpu_devices(n: int = 8):
    """:func:`_cpu_forced` plus a forced multi-device host platform —
    the sharded-pipeline and psum lanes need >1 device per process."""
    flag = f"--xla_force_host_platform_device_count={n}"
    saved = os.environ.get("XLA_FLAGS")
    os.environ["XLA_FLAGS"] = f"{saved} {flag}" if saved else flag
    try:
        with _cpu_forced():
            yield
    finally:
        if saved is None:
            os.environ.pop("XLA_FLAGS", None)
        else:
            os.environ["XLA_FLAGS"] = saved


def _psum_agg_entry(result_path, n_parties, rounds, payload_elems):
    """Spawned child: flat-plan aggregation lowered to one collective
    across a composed party mesh (ops.aggregate.psum_by_plan), checked
    bitwise against the reduce_by_plan fold it replaces, then timed."""
    import statistics

    import jax
    import numpy as np

    from rayfed_tpu import mesh as mesh_mod
    from rayfed_tpu import topology as topo
    from rayfed_tpu.ops.aggregate import psum_by_plan, reduce_by_plan

    parties = [f"p{i}" for i in range(n_parties)]
    mesh_mod.compose_party_mesh(parties)
    plan = topo.plan(parties, "flat")
    rng = np.random.default_rng(7)
    contributions = {
        p: {"w": rng.standard_normal(payload_elems).astype(np.float32)}
        for p in parties
    }

    def timed(fn):
        dts = []
        out = None
        for _ in range(rounds):
            t0 = time.perf_counter()
            out = fn(plan, contributions)
            jax.block_until_ready(jax.tree_util.tree_leaves(out))
            dts.append((time.perf_counter() - t0) * 1000)
        return out, dts

    ref, _ = timed(reduce_by_plan)  # warmup (compiles both folds)
    got, _ = timed(psum_by_plan)
    leaves = zip(jax.tree_util.tree_leaves(got),
                 jax.tree_util.tree_leaves(ref))
    assert all(
        np.asarray(a).tobytes() == np.asarray(b).tobytes()
        for a, b in leaves
    ), "psum_by_plan diverged from reduce_by_plan bits"
    _, psum_dts = timed(psum_by_plan)
    _, fold_dts = timed(reduce_by_plan)
    with open(result_path, "w") as f:
        json.dump(
            {
                "psum_agg_ms": round(statistics.median(psum_dts), 3),
                "psum_agg_ms_spread": [
                    round(min(psum_dts), 3), round(max(psum_dts), 3)
                ],
                "fold_agg_ms": round(statistics.median(fold_dts), 3),
            },
            f,
        )


def _run_psum_agg() -> dict:
    mp = multiprocessing.get_context("spawn")
    with tempfile.TemporaryDirectory() as tmp:
        result_path = os.path.join(tmp, "psum.json")
        p = mp.Process(
            target=_psum_agg_entry,
            args=(
                result_path,
                int(os.environ.get("FEDTPU_BENCH_PSUM_PARTIES", 4)),
                int(os.environ.get("FEDTPU_BENCH_PSUM_ROUNDS", 20)),
                int(os.environ.get("FEDTPU_BENCH_PSUM_ELEMS", 1 << 20)),
            ),
        )
        p.start()
        p.join(timeout=300)
        if p.is_alive():
            p.kill()
            p.join(timeout=30)
            raise RuntimeError("psum agg child hung")
        if p.exitcode != 0 or not os.path.exists(result_path):
            raise RuntimeError(f"psum agg child failed rc={p.exitcode}")
        with open(result_path) as f:
            return json.load(f)


def _try_data_plane() -> dict:
    """The sharded multi-stream data plane:

    - ``multistream_gbps``: the tpu transport with
      ``num_streams=_MULTISTREAM_LANES`` reactor lanes and a chunked
      payload — stripe frames ride K sockets in parallel and the
      receiver reassembles them (tools/dma_check.py gates this against
      ``dma_cpu_gbps``).
    - ``shard_pipeline_gbps``: same lanes, payload a 4-way sharded
      jax.Array on a forced 8-device CPU backend — the shard-extent
      striping + per-shard async D2H pipeline end to end.
    - ``psum_agg_ms``: flat-plan aggregation as ONE collective across a
      composed 4-party mesh, bitwise-checked against reduce_by_plan
      (+ ``fold_agg_ms``, the host fold it replaces, for the ratio).

    Best-effort, like :func:`_try_tpu_lanes`."""
    out = {}
    with _cpu_forced():
        try:
            _lane_stats(
                out, "multistream_gbps",
                run_transport("tpu", num_streams=_MULTISTREAM_LANES),
            )
        except Exception as e:  # noqa: BLE001
            print(f"multistream bench skipped: {e!r}", file=sys.stderr)
    with _cpu_devices(8):
        try:
            _lane_stats(
                out, "shard_pipeline_gbps",
                run_transport(
                    "tpu", num_streams=_MULTISTREAM_LANES, sharded=True
                ),
            )
        except Exception as e:  # noqa: BLE001
            print(f"shard pipeline bench skipped: {e!r}", file=sys.stderr)
        try:
            out.update(_run_psum_agg())
        except Exception as e:  # noqa: BLE001
            print(f"psum agg bench skipped: {e!r}", file=sys.stderr)
    return out


def _paired_baseline_party(party, addresses, transport, result_path,
                           port_plan, pairs):
    """Paired vs_baseline windows: for each pair k, a native-lane window
    and a reference-parity gRPC window run back-to-back in the SAME two
    party processes (fresh fed job per window on preallocated ports).
    The headline vs_baseline is the median of per-pair ratios, so both
    sides of every ratio share the host regime they were measured in —
    the unpaired ratio compares windows minutes apart, and loopback
    throughput on this VM class swings 2-3x on a seconds timescale.
    The outer ``addresses``/``transport`` of the harness are unused:
    every window inits its own job from ``port_plan``."""
    import numpy as np

    import rayfed_tpu as fed

    n_elem = PAYLOAD_MB * 1024 * 1024 // 4
    gbps = {"tcp": [], "grpc": []}
    for k in range(pairs):
        for lane in ("tcp", "grpc"):
            _progress(party, f"pair {k}/{pairs} lane {lane}")
            fed.init(
                addresses=port_plan[f"{k}-{lane}"],
                party=party,
                config={"cross_silo_comm": dict(_FAST_RETRY),
                        "transport": lane},
                job_name=f"bench-pair-{k}-{lane}",
                logging_level="error",
            )

            @fed.remote
            def produce(i):
                return np.full((n_elem,), float(i), dtype=np.float32)

            @fed.remote
            def consume(x):
                return float(x[0]) + float(x[-1])

            @fed.remote
            def barrier(*xs):
                return len(xs)

            # One discarded cycle (connection + allocator warmup), one
            # timed cycle — identical treatment for both lanes, so the
            # ratio cancels any residual cold-start cost.
            for rep in (-1, 0):
                base = 100.0 * rep + k
                tensors = [
                    produce.party("alice").remote(base + i)
                    for i in range(ROUNDS)
                ]
                assert fed.get(
                    barrier.party("alice").remote(*tensors)
                ) == ROUNDS
                t0 = time.perf_counter()
                outs = [consume.party("bob").remote(t) for t in tensors]
                checks = fed.get(outs)
                dt = time.perf_counter() - t0
                assert checks == [2.0 * (base + i) for i in range(ROUNDS)]
                if rep >= 0:
                    gbps[lane].append(ROUNDS * PAYLOAD_MB / 1024 / dt)
            _progress(party, f"pair {k} lane {lane} done; shutting down")
            fed.shutdown()
    if party == "bob":
        with open(result_path, "w") as f:
            json.dump(gbps, f)


def _run_paired_baseline() -> dict:
    """Run the paired vs_baseline stage (see _paired_baseline_party).
    Raises on failure — the caller treats this stage as best-effort and
    falls back to the unpaired ratio."""
    import statistics

    pairs = int(os.environ.get("FEDTPU_BENCH_PAIRS", 3))
    port_plan = {}
    for k in range(pairs):
        for lane in ("tcp", "grpc"):
            p1, p2 = _free_ports(2)
            port_plan[f"{k}-{lane}"] = {
                "alice": f"127.0.0.1:{p1}",
                "bob": f"127.0.0.1:{p2}",
            }
    res = _run_two_party(
        _paired_baseline_party, "tcp", (port_plan, pairs), timeout_s=600
    )
    ratios = [t / g for t, g in zip(res["tcp"], res["grpc"]) if g > 0]
    if not ratios:
        raise RuntimeError("no paired windows completed")
    return {
        "vs_baseline": round(statistics.median(ratios), 3),
        "vs_baseline_pairs": [round(r, 3) for r in ratios],
    }


def _tiny_party(party, addresses, transport, result_path, rounds):
    import rayfed_tpu as fed

    fed.init(
        addresses=addresses,
        party=party,
        config={"cross_silo_comm": dict(_FAST_RETRY), "transport": transport},
        job_name=f"bench-tiny-{transport}",
        logging_level="error",
    )

    @fed.remote
    def inc(x):
        return x + 1

    @fed.remote
    def aggregate(a, b):
        return a + b

    # Warmup (connection + executor spin-up).
    _progress(party, "init done; warmup")
    fed.get(aggregate.party("alice").remote(
        inc.party("alice").remote(0), inc.party("bob").remote(0)))

    _progress(party, "timed rounds")
    t0 = time.perf_counter()
    acc = 0
    for _ in range(rounds):
        a = inc.party("alice").remote(acc)
        b = inc.party("bob").remote(acc)
        acc = fed.get(aggregate.party("alice").remote(a, b))
    dt = time.perf_counter() - t0
    _progress(party, "rounds done; shutting down")
    # 3 fed tasks + 1 get per round (the reference harness's accounting,
    # ref benchmarks/many_tiny_tasks_benchmark.py:48-59).
    if party == "alice":
        with open(result_path, "w") as f:
            json.dump({"per_task_ms": dt / rounds / 3 * 1000}, f)
    fed.shutdown()


def _fedavg_party(party, addresses, transport, result_path, rounds):
    import numpy as np

    import rayfed_tpu as fed
    from rayfed_tpu.federated import FedAvgTrainer

    fed.init(
        addresses=addresses,
        party=party,
        config={"cross_silo_comm": dict(_FAST_RETRY), "transport": transport},
        job_name=f"bench-fedavg-{transport}",
        logging_level="error",
    )

    dim, classes, batch = 784, 10, 128  # MNIST logreg shapes (BASELINE #3)

    @fed.remote
    class Worker:
        def __init__(self, seed):
            rng = np.random.default_rng(seed)
            self.w = np.zeros((dim, classes), np.float32)
            self.b = np.zeros((classes,), np.float32)
            self.x = rng.normal(size=(batch, dim)).astype(np.float32)
            self.y = np.eye(classes, dtype=np.float32)[
                rng.integers(0, classes, size=(batch,))
            ]

        def train(self, global_params):
            if global_params is not None:
                self.w, self.b = global_params
            for _ in range(3):  # local epochs (plain numpy: the round
                # latency under measurement is orchestration + transport)
                logits = self.x @ self.w + self.b
                logits -= logits.max(axis=1, keepdims=True)
                p = np.exp(logits)
                p /= p.sum(axis=1, keepdims=True)
                g = (p - self.y) / batch
                self.w -= 0.1 * (self.x.T @ g)
                self.b -= 0.1 * g.sum(axis=0)
            return (self.w, self.b)

    trainer = FedAvgTrainer(
        Worker, ["alice", "bob"],
        worker_args={"alice": (1,), "bob": (2,)},
    )
    # Warmup round (actor init, first push).
    _progress(party, "init done; warmup round")
    global_params = fed.get(trainer.run(1))
    _progress(party, "timed rounds")
    t0 = time.perf_counter()
    final = fed.get(trainer.run(rounds, global_params))
    dt = time.perf_counter() - t0
    _progress(party, "rounds done; shutting down")
    assert np.isfinite(np.asarray(final[0]).sum())
    if party == "alice":
        with open(result_path, "w") as f:
            json.dump({"round_ms": dt / rounds * 1000}, f)
    fed.shutdown()


def _run_two_party(target, transport, extra_args, timeout_s=300,
                   parties=("alice", "bob")) -> dict:
    """Generic N-party spawn harness: run ``target(party, addresses,
    transport, result_path, *extra_args)`` once per party; return the
    result dict the writer party left at result_path."""
    ports = _free_ports(len(parties))
    addresses = {
        party: f"127.0.0.1:{port}" for party, port in zip(parties, ports)
    }
    mp = multiprocessing.get_context("spawn")
    with tempfile.TemporaryDirectory() as tmp:
        result_path = os.path.join(tmp, "result.json")
        procs = [
            mp.Process(
                target=_party_entry,
                args=(target, party, addresses, transport, result_path)
                + extra_args,
            )
            for party in parties
        ]
        # Children inherit the env at spawn; each party overwrites
        # {tmp}/{party}.progress at phase boundaries (_progress) so a
        # hang below can say WHICH phase each party last reached.
        os.environ[_PROGRESS_DIR_VAR] = tmp
        try:
            for p in procs:
                p.start()
        finally:
            os.environ.pop(_PROGRESS_DIR_VAR, None)
        for p in procs:
            p.join(timeout=timeout_s)
        hung = [p for p in procs if p.is_alive()]
        if hung:
            # Ask each hung party for an all-thread stack dump BEFORE the
            # kill (a terminated process can't report anything itself);
            # _party_entry armed faulthandler on SIGUSR1 at spawn.
            import signal

            usr1 = getattr(signal, "SIGUSR1", None)
            if usr1 is not None:
                for p in hung:
                    try:
                        os.kill(p.pid, usr1)
                    except OSError:
                        pass
                time.sleep(2.0)  # let faulthandler finish writing
        for p in hung:
            p.terminate()
            p.join(timeout=30)
        if hung:
            marks = {}
            stacks = {}
            for party in parties:
                try:
                    with open(os.path.join(tmp, f"{party}.progress")) as f:
                        marks[party] = f.read().strip() or "no mark"
                except OSError:
                    marks[party] = "no mark"
                try:
                    with open(os.path.join(tmp, f"{party}.stacks")) as f:
                        s = f.read().strip()
                    if s:
                        stacks[party] = s[-4000:]
                except OSError:
                    pass
            detail = "".join(
                f"\n--- {party} stacks at kill ---\n{s}"
                for party, s in stacks.items()
            )
            raise RuntimeError(
                f"bench party hung; terminated (last phase marks: {marks})"
                + detail
            )
        for p in procs:
            if p.exitcode != 0:
                raise RuntimeError(f"bench party failed ({p.exitcode})")
        with open(result_path) as f:
            return json.load(f)


# Stage failure diagnostics, keyed "<party_fn>[<key>]". A hung stage's
# faulthandler stacks and phase marks land HERE and then in the headline
# JSON line's "diagnostics" field — BENCH_r05's "bench party hung;
# terminated" left nothing to root-cause with because the dump only went
# to a stderr stream nobody kept.
_DIAGNOSTICS: dict = {}


def _record_diag(stage: str, err: BaseException) -> None:
    msg = str(err)
    head, sep, stacks = msg.partition("\n--- ")
    entry = {"error": head.strip()[:500]}
    if sep:
        # The all-thread faulthandler dumps _run_two_party appended to
        # the hang error, bounded so the JSON line stays printable.
        entry["stacks_tail"] = ("--- " + stacks)[-4000:]
    _DIAGNOSTICS[stage] = entry


def _bench_stage(party_fn, res_field, env_var, default_rounds, keys, *,
                 cpu_force=False, parties=("alice", "bob"), timeout_s=300,
                 digits=2, extra_fields=None) -> dict:
    """Run one two-to-N-party workload per (transport, result-key) pair.

    ``cpu_force`` wraps the spawned parties in :func:`_cpu_forced` —
    required whenever the workload jits (two processes cannot share the
    driver's single chip; a wedged accelerator tunnel must not hang the
    children). ``extra_fields`` maps additional result fields to output
    keys (recorded when present; single-key stages only — the output key
    does not vary by transport). Best-effort: on failure the keys
    gathered so far are kept and the rest are skipped with a stderr
    note — the headline JSON line always prints."""
    out = {}
    try:
        with _cpu_forced() if cpu_force else contextlib.nullcontext():
            rounds = int(os.environ.get(env_var, default_rounds))
            for transport, key in keys:
                # One retry per phase: the recurring gRPC-lane hang
                # (BENCH_r05 "_fedavg_party bench skipped") is a
                # once-per-run wedge, so a surviving second window keeps
                # the key populated instead of dropping it.
                for attempt in (1, 2):
                    try:
                        res = _run_two_party(
                            party_fn, transport, (rounds,),
                            timeout_s=timeout_s, parties=parties,
                        )
                        break
                    except Exception as e:  # noqa: BLE001 - retried once
                        if "bench party hung" in str(e):
                            # The watchdog already burned timeout_s on
                            # this window; a wedged stage hangs the same
                            # way on retry and burns it AGAIN (BENCH_r05
                            # paid 2x the budget for one dead key).
                            # Capture the stacks and skip with reason.
                            _record_diag(f"{party_fn.__name__}[{key}]", e)
                            raise
                        if attempt == 2:
                            _record_diag(f"{party_fn.__name__}[{key}]", e)
                            raise
                        print(
                            f"{party_fn.__name__} [{key}] window failed "
                            f"({e!r}); retrying the phase once",
                            file=sys.stderr,
                        )
                out[key] = round(res[res_field], digits)
                for rf, out_key in (extra_fields or {}).items():
                    v = res.get(rf)
                    if isinstance(v, list):
                        out[out_key] = [round(x, digits) for x in v]
                    elif isinstance(v, (int, float)):
                        out[out_key] = round(v, digits)
    except Exception as e:  # noqa: BLE001 - bench must still print its line
        print(f"{party_fn.__name__} bench skipped: {e!r}", file=sys.stderr)
    return out


_HIER4 = ("alice", "bob", "carol", "dave")


def _hier4_party(party, addresses, transport, result_path, rounds):
    """4-party hierarchical aggregation tree (BASELINE config #4): each
    party contributes a 4MB gradient tree per round; ``fed_aggregate``
    reduces pairwise (2 rounds of 2-way reduces), so the coordinator's
    fan-in is halved versus an all-to-root star."""
    import numpy as np

    import rayfed_tpu as fed
    from rayfed_tpu.federated import fed_aggregate

    fed.init(
        addresses=addresses,
        party=party,
        config={"cross_silo_comm": dict(_FAST_RETRY), "transport": transport},
        job_name=f"bench-hier4-{transport}",
        logging_level="error",
    )
    n_elem = 1 << 20  # 4MB float32 per party per round

    @fed.remote
    def contrib(seed):
        return {"g": np.full((n_elem,), float(seed), np.float32)}

    def one_round(r):
        objs = {
            p: contrib.party(p).remote(float(r * 10 + i))
            for i, p in enumerate(_HIER4)
        }
        agg = fed_aggregate(objs, op="mean")
        out = fed.get(agg)
        expect = sum(r * 10 + i for i in range(4)) / 4.0
        assert float(np.asarray(out["g"])[0]) == expect
        return out

    _progress(party, "init done; warmup round")
    one_round(-1)  # warmup (connections, executor)
    _progress(party, "timed rounds")
    dts = []
    for r in range(rounds):
        t0 = time.perf_counter()
        one_round(r)
        dts.append((time.perf_counter() - t0) * 1000)
    _progress(party, "rounds done; shutting down")
    if party == "alice":
        import statistics

        # Mean keeps continuity with earlier rounds' round_ms; the
        # median and [min, max] spread qualify how noisy the stage was
        # (4 parties on a shared VM — a single steal burst can double
        # the mean without touching the median).
        with open(result_path, "w") as f:
            json.dump(
                {
                    "round_ms": sum(dts) / len(dts),
                    "round_ms_median": statistics.median(dts),
                    "round_ms_spread": [min(dts), max(dts)],
                },
                f,
            )
    fed.shutdown()


# --- N-party scale sweep (reactor transport + topology planner) -----------
#
# Spawning 64 real party processes on a shared 1-2 core CI VM measures the
# scheduler, not the transport. Instead the sweep simulates N parties in
# ONE process: each party is a real TcpSenderProxy + TcpReceiverProxy pair
# (real sockets, real frames, real acks — all riding the shared reactor
# loops), and each round executes a planned hierarchical reduction whose
# edges are actual wire transfers. What's simulated is only process
# isolation; the transport path is the production one.

_SCALE_NS = (8, 16, 32, 64)


def _simulated_hier_round(n_parties: int, rounds: int,
                          payload_elems: int = 16384,
                          topology: str = "hier") -> dict:
    """Median round latency for an N-party planned reduction where every
    reduce edge is a real proxy-to-proxy transfer. Returns
    {"round_ms_median", "round_ms_spread", "rounds"}."""
    import statistics
    from concurrent.futures import ThreadPoolExecutor

    import numpy as np

    from rayfed_tpu import topology as topo
    from rayfed_tpu.proxy.tcp.tcp_proxy import (
        TcpReceiverProxy,
        TcpSenderProxy,
    )

    parties = [f"p{i:02d}" for i in range(n_parties)]
    ports = _free_ports(n_parties)
    addresses = {p: f"127.0.0.1:{port}" for p, port in zip(parties, ports)}
    cfg = {
        "timeout_in_ms": 30000,
        "connect_timeout_in_ms": 5000,
        "retry_policy": {
            "max_attempts": 3,
            "initial_backoff_ms": 50,
            "max_backoff_ms": 500,
            "backoff_multiplier": 2.0,
        },
        "num_reactors": 4,
    }
    plan = topo.plan(parties, topology)
    receivers, senders = {}, {}
    try:
        for p in parties:
            rp = TcpReceiverProxy(addresses[p], p, "bench-scale", None,
                                  dict(cfg))
            rp.start()
            ok, err = rp.is_ready()
            if not ok:
                raise RuntimeError(f"receiver for {p} not ready: {err}")
            receivers[p] = rp
        for p in parties:
            sp = TcpSenderProxy(addresses, p, "bench-scale", None, dict(cfg))
            sp.start()
            senders[p] = sp

        base = {
            p: np.full((payload_elems,), float(i + 1), np.float32)
            for i, p in enumerate(parties)
        }
        expect = float(sum(range(1, n_parties + 1))) / n_parties

        def one_round(r: int) -> None:
            held = dict(base)
            for li, level in enumerate(plan.levels):
                def do_step(step):
                    futs = []
                    for s in step.srcs[1:]:
                        seq = f"r{r}L{li}:{s}>{step.dst}"
                        futs.append(
                            (receivers[step.dst].get_data(s, seq, seq),
                             senders[s].send(step.dst, held[s], seq, seq))
                        )
                    acc = held[step.srcs[0]].astype(np.float32)
                    for recv_fut, send_fut in futs:
                        send_fut.result(60)
                        acc = acc + np.asarray(recv_fut.result(60),
                                               np.float32)
                    return step.dst, acc
                with ThreadPoolExecutor(
                    max_workers=max(1, min(32, len(level)))
                ) as pool:
                    for dst, acc in pool.map(do_step, level):
                        held[dst] = acc
            out = held[plan.root] / float(n_parties)
            # Integer-valued contributions: the planned fold is exact, so
            # a wrong aggregate is a transport bug, not float noise.
            assert float(out[0]) == expect, (float(out[0]), expect)

        one_round(-1)  # warmup: dial every edge, prime the reactor rings
        dts = []
        for r in range(rounds):
            t0 = time.perf_counter()
            one_round(r)
            dts.append((time.perf_counter() - t0) * 1000)
        return {
            "round_ms_median": statistics.median(dts),
            "round_ms_spread": [min(dts), max(dts)],
            "rounds": rounds,
        }
    finally:
        for sp in senders.values():
            try:
                sp.stop()
            except Exception:  # noqa: BLE001 - teardown best-effort
                pass
        for rp in receivers.values():
            try:
                rp.stop()
            except Exception:  # noqa: BLE001 - teardown best-effort
                pass


def _run_scale_sweep() -> dict:
    """``hierN_round_ms`` for N in 8/16/32/64 + ``parties_sustained``
    (largest N whose sweep completed). Median-of-rounds (same noise
    treatment as hier4) so the keys are CI-gateable."""
    out = {}
    rounds = int(os.environ.get("FEDTPU_BENCH_SCALE_ROUNDS", 5))
    ns = [
        int(x) for x in os.environ.get(
            "FEDTPU_BENCH_SCALE_NS",
            ",".join(str(n) for n in _SCALE_NS),
        ).split(",") if x
    ]
    sustained = 0
    for n in ns:
        # Small-N rounds are cheap: take more of them so the median the
        # scaling ratio divides by sits in the steady-state regime (a
        # lucky 5-round N=8 window can halve the denominator on this
        # class of shared VM).
        n_rounds = max(rounds, min(160 // max(1, n), 20))
        try:
            res = _simulated_hier_round(n, n_rounds)
        except Exception as e:  # noqa: BLE001 - keep smaller-N keys
            print(f"scale bench skipped at N={n}: {e!r}", file=sys.stderr)
            break
        out[f"hier{n}_round_ms"] = round(res["round_ms_median"], 2)
        out[f"hier{n}_round_ms_spread"] = [
            round(x, 2) for x in res["round_ms_spread"]
        ]
        sustained = n
    if sustained:
        out["parties_sustained"] = sustained
    return out


# --- Federated inference serving plane (docs/serving.md) ------------------


def _serve_bench_entry(result_path, clients, requests_per_client, reps):
    """Spawned child: the serving engine under concurrent client load.

    ``clients`` threads each stream ``requests_per_client`` generate
    requests into one InferenceServer while a publisher thread lands two
    hot swaps strictly mid-window (after 1/3 and 2/3 of completions, so
    requests are always in flight across each swap). The identical
    workload then runs in ``mode='sequential'`` — the same engine
    admitting one request at a time, the naive no-batching baseline —
    for the continuous-batching speedup ratio."""
    import statistics
    import threading

    import jax
    import jax.numpy as jnp
    import numpy as np

    from rayfed_tpu.config import ServingConfig
    from rayfed_tpu.models import transformer as tfm
    from rayfed_tpu.serving.server import InferenceServer

    cfg = tfm.tiny_config(compute_dtype=jnp.float32)
    params = [tfm.init_params(jax.random.PRNGKey(i), cfg) for i in (0, 1)]
    # Long enough that decode dominates prefill: prefill is serialized in
    # the engine thread in BOTH modes, so short generations dilute the
    # batching speedup the gate measures.
    max_new = 48
    total = clients * requests_per_client

    def window(mode, swap):
        srv = InferenceServer(
            cfg,
            ServingConfig(
                max_slots=8, max_len=64, max_new_tokens=max_new,
                max_pending=max(64, 2 * total), mode=mode,
            ),
            params=params[0],
        )
        try:
            # Discarded requests compile the prefill + step programs for
            # EVERY prompt bucket the clients will hit (plen 4..12 spans
            # three buckets) so the timed window measures the scheduler,
            # not XLA.
            for plen in (8, 4, 12):
                srv.submit_and_wait(list(range(1, plen + 1)), max_new_tokens=2)
            warm = srv.stats()["completed"]
            latencies, tokens = [], [0]
            lock = threading.Lock()

            def client(ci):
                rng = np.random.default_rng(1000 + ci)
                for _ in range(requests_per_client):
                    plen = int(rng.integers(4, 13))
                    prompt = [
                        int(t)
                        for t in rng.integers(1, cfg.vocab - 1, size=plen)
                    ]
                    resp = srv.submit_and_wait(
                        prompt, max_new_tokens=max_new
                    )
                    with lock:
                        latencies.append(resp["latency_ms"])
                        tokens[0] += len(resp["tokens"])

            swaps = [0]

            def publisher():
                for thr in (max(1, total // 3), max(2, 2 * total // 3)):
                    while True:
                        done = srv.stats()["completed"] - warm
                        if done >= total:
                            return  # window drained before the swap slot
                        if done >= thr:
                            break
                        time.sleep(0.005)
                    srv.publish(params[(swaps[0] + 1) % 2])
                    swaps[0] += 1

            threads = [
                threading.Thread(target=client, args=(i,))
                for i in range(clients)
            ]
            pub = threading.Thread(target=publisher) if swap else None
            t0 = time.perf_counter()
            for t in threads:
                t.start()
            if pub is not None:
                pub.start()
            for t in threads:
                t.join()
            dt = time.perf_counter() - t0
            if pub is not None:
                pub.join()
            assert len(latencies) == total, (len(latencies), total)
            return {
                "tokens_s": tokens[0] / dt,
                "p50_ms": float(np.percentile(latencies, 50)),
                "p99_ms": float(np.percentile(latencies, 99)),
                "swaps": swaps[0],
            }
        finally:
            srv.stop()

    def stream_ttft_window(n_requests=8):
        """Streaming clients: median ms from submit to FIRST streamed
        token (the latency win streaming buys over waiting for the full
        response)."""
        srv = InferenceServer(
            cfg,
            ServingConfig(
                max_slots=8, max_len=64, max_new_tokens=max_new,
                max_pending=max(64, 2 * total),
            ),
            params=params[0],
        )
        try:
            for plen in (8, 4, 12):  # compile every bucket (see above)
                srv.submit_and_wait(list(range(1, plen + 1)), max_new_tokens=2)
            ttfts = []
            lock = threading.Lock()

            def client(ci):
                rng = np.random.default_rng(7000 + ci)
                plen = int(rng.integers(4, 13))
                prompt = [
                    int(t)
                    for t in rng.integers(1, cfg.vocab - 1, size=plen)
                ]
                t0 = time.perf_counter()
                fut, stream = srv.submit_stream(
                    prompt, max_new_tokens=max_new
                )
                for _ in stream:
                    break  # first token only; the rest streams on
                first = stream.first_token_s
                fut.result(timeout=300)
                with lock:
                    ttfts.append((first - t0) * 1e3)

            threads = [
                threading.Thread(target=client, args=(i,))
                for i in range(n_requests)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            return float(np.percentile(ttfts, 50))
        finally:
            srv.stop()

    def mixed_window(n_short=16, long_len=1024, short_new=16, long_new=8):
        """Fragmentation regression: 16 short requests interleaved with
        one 1024-token prompt. Paged KV admits the shorts while the long
        prompt chunk-prefills under the token budget; the gateable
        number is the short requests' p99."""
        srv = InferenceServer(
            cfg,
            ServingConfig(
                max_slots=8, max_len=long_len + long_new + 8,
                max_new_tokens=max(short_new, long_new),
                max_pending=2 * (n_short + 1),
                prompt_buckets=[16, long_len],
            ),
            params=params[0],
        )
        try:
            # Warm the short bucket AND the chunked-prefill program (a
            # 64-token prompt exceeds prefill_chunk, compiling the chunk
            # step the 1024-token prompt will reuse).
            srv.submit_and_wait(list(range(1, 9)), max_new_tokens=2)
            srv.submit_and_wait(list(range(1, 65)), max_new_tokens=2)
            rng = np.random.default_rng(4242)
            long_prompt = [
                int(t)
                for t in rng.integers(1, cfg.vocab - 1, size=long_len)
            ]
            lat = []
            lock = threading.Lock()

            def short_client(ci):
                r = np.random.default_rng(5000 + ci)
                prompt = [
                    int(t)
                    for t in r.integers(
                        1, cfg.vocab - 1, size=int(r.integers(4, 13))
                    )
                ]
                resp = srv.submit_and_wait(
                    prompt, max_new_tokens=short_new
                )
                with lock:
                    lat.append(resp["latency_ms"])

            long_fut = srv.submit(
                np.asarray(long_prompt, np.int32), max_new_tokens=long_new
            )
            threads = [
                threading.Thread(target=short_client, args=(i,))
                for i in range(n_short)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            long_resp = long_fut.result(timeout=300)
            assert len(long_resp["tokens"]) == long_new
            st = srv.stats()
            return {
                "p99_ms": float(np.percentile(lat, 99)),
                "chunks": st["prefill_chunks"],
            }
        finally:
            srv.stop()

    windows = [window("continuous", swap=True) for _ in range(reps)]
    naive = window("sequential", swap=False)
    ttft_ms = stream_ttft_window()
    mixed = mixed_window()
    tok = [w["tokens_s"] for w in windows]
    p99 = [w["p99_ms"] for w in windows]
    out = {
        "serve_tokens_s": round(statistics.median(tok), 1),
        "serve_tokens_s_spread": [round(min(tok), 1), round(max(tok), 1)],
        "serve_p99_ms": round(statistics.median(p99), 1),
        "serve_p99_ms_spread": [round(min(p99), 1), round(max(p99), 1)],
        "serve_p50_ms": round(
            statistics.median([w["p50_ms"] for w in windows]), 1
        ),
        # min across reps: the gateable "every window swapped" statistic.
        "serve_swaps": min(w["swaps"] for w in windows),
        "serve_clients": clients,
        "serve_requests": total,
        "serve_naive_tokens_s": round(naive["tokens_s"], 1),
        "serve_batching_speedup": round(
            statistics.median(tok) / naive["tokens_s"], 2
        ),
        "serve_stream_ttft_ms": round(ttft_ms, 1),
        "serve_mixed_p99_ms": round(mixed["p99_ms"], 1),
        "serve_mixed_prefill_chunks": mixed["chunks"],
    }
    with open(result_path, "w") as f:
        json.dump(out, f)


def _tenant_bench_entry(result_path, window_s, push_mb, inline_kb):
    """Child-process body of the tenant stage: two jobs share one
    listener (the piggyback path), both keep bulk backlog through the
    weighted-fair gate at weights 4:1, while the victim job's inline
    serving-class round trips are latency-sampled. Emits the two keys
    tools/tenant_check.py gates: ``tenant_fairness_ratio`` (weight-
    normalized bulk byte ratio, 1.0 = perfectly fair) and
    ``multitenant_victim_p99_ms``."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import threading

    import numpy as np

    from rayfed_tpu.proxy.tcp.tcp_proxy import (
        TcpReceiverProxy,
        TcpSenderProxy,
    )
    from rayfed_tpu.tenancy import qos as tenancy_qos
    from rayfed_tpu.tenancy.context import TenancyConfig

    fast = {"retry_policy": {"max_attempts": 10, "initial_backoff_ms": 100}}
    sched = tenancy_qos.get_scheduler()
    sched.register("victim", TenancyConfig(weight=4, fair_window_mb=2))
    sched.register("noisy", TenancyConfig(weight=1, fair_window_mb=2))

    (port,) = _free_ports(1)
    addrs = {"bob": f"127.0.0.1:{port}"}
    receivers = {
        job: TcpReceiverProxy(addrs["bob"], "bob", job, None, dict(fast))
        for job in ("victim", "noisy")
    }
    senders = {
        job: TcpSenderProxy(addrs, "alice", job, None, dict(fast))
        for job in ("victim", "noisy")
    }
    for p in list(receivers.values()) + list(senders.values()):
        p.start()

    deadline = time.monotonic() + window_s
    bulk_payload = np.arange((push_mb << 20) // 4, dtype=np.uint32)
    inline_payload = np.arange((inline_kb << 10), dtype=np.uint8)
    errors = []

    def bulk_loop(job, base):
        try:
            i = 0
            while time.monotonic() < deadline:
                seq = base + 2 * i
                fut = receivers[job].get_data("alice", f"{seq}#0", seq + 1)
                senders[job].send(
                    "bob", bulk_payload, f"{seq}#0", seq + 1
                ).result(60)
                fut.result(60)
                i += 1
        except Exception as e:  # noqa: BLE001 - surfaced below
            errors.append(f"{job} bulk: {e!r}")

    latencies = []

    def inline_loop():
        try:
            i = 0
            while time.monotonic() < deadline:
                seq = 1 + 2 * i  # odd ids: disjoint from the bulk range
                fut = receivers["victim"].get_data(
                    "alice", f"{seq}#0", seq + 1
                )
                t0 = time.monotonic()
                senders["victim"].send(
                    "bob", inline_payload, f"{seq}#0", seq + 1
                )
                fut.result(60)
                latencies.append((time.monotonic() - t0) * 1e3)
                i += 1
        except Exception as e:  # noqa: BLE001 - surfaced below
            errors.append(f"victim inline: {e!r}")

    threads = [
        threading.Thread(target=bulk_loop, args=("noisy", 1_000_000)),
        threading.Thread(target=bulk_loop, args=("victim", 2_000_000)),
        threading.Thread(target=inline_loop),
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=window_s + 120)
    for p in list(senders.values()) + [receivers["noisy"],
                                       receivers["victim"]]:
        try:
            p.stop()
        except Exception:  # noqa: BLE001 - teardown best-effort
            pass
    if errors or not latencies:
        raise RuntimeError(f"tenant bench failed: {errors or 'no samples'}")
    ratio = sched.fairness_ratio("victim", "noisy")
    lat = sorted(latencies)
    out = {
        "tenant_fairness_ratio": round(ratio, 3) if ratio else None,
        "multitenant_victim_p99_ms": round(
            lat[int(0.99 * (len(lat) - 1))], 2
        ),
        "multitenant_victim_p50_ms": round(lat[len(lat) // 2], 2),
        "tenant_inline_samples": len(lat),
        "tenant_bulk_mb": {
            job: round(
                sched.bytes_sent(job, tenancy_qos.TC_BULK) / (1 << 20), 1
            )
            for job in ("victim", "noisy")
        },
    }
    with open(result_path, "w") as f:
        json.dump(out, f)


def _run_tenant_bench() -> dict:
    """Tenant-fairness stage (docs/multitenancy.md); spawned CPU-forced
    child, same isolation rationale as the psum stage."""
    mp = multiprocessing.get_context("spawn")
    with _cpu_forced(), tempfile.TemporaryDirectory() as tmp:
        result_path = os.path.join(tmp, "tenant.json")
        p = mp.Process(
            target=_tenant_bench_entry,
            args=(
                result_path,
                float(os.environ.get("FEDTPU_BENCH_TENANT_WINDOW_S", 6)),
                int(os.environ.get("FEDTPU_BENCH_TENANT_PUSH_MB", 4)),
                int(os.environ.get("FEDTPU_BENCH_TENANT_INLINE_KB", 4)),
            ),
        )
        p.start()
        p.join(timeout=300)
        if p.is_alive():
            p.kill()
            p.join(timeout=30)
            raise RuntimeError("tenant bench child hung")
        if p.exitcode != 0 or not os.path.exists(result_path):
            raise RuntimeError(f"tenant bench child failed rc={p.exitcode}")
        with open(result_path) as f:
            return json.load(f)


def _run_serve_bench() -> dict:
    """``serve_tokens_s`` / ``serve_p99_ms`` (+``_spread``) from >=8
    concurrent clients with hot swaps mid-window, plus the
    continuous-vs-sequential ``serve_batching_speedup`` ratio
    (tools/serve_check.py gates these keys). Spawned CPU-forced child,
    same isolation rationale as the psum stage."""
    mp = multiprocessing.get_context("spawn")
    with _cpu_forced(), tempfile.TemporaryDirectory() as tmp:
        result_path = os.path.join(tmp, "serve.json")
        p = mp.Process(
            target=_serve_bench_entry,
            args=(
                result_path,
                int(os.environ.get("FEDTPU_BENCH_SERVE_CLIENTS", 8)),
                int(os.environ.get("FEDTPU_BENCH_SERVE_REQS", 4)),
                int(os.environ.get("FEDTPU_BENCH_SERVE_REPS", 3)),
            ),
        )
        p.start()
        p.join(timeout=600)
        if p.is_alive():
            p.kill()
            p.join(timeout=30)
            raise RuntimeError("serve bench child hung")
        if p.exitcode != 0 or not os.path.exists(result_path):
            raise RuntimeError(f"serve bench child failed rc={p.exitcode}")
        with open(result_path) as f:
            return json.load(f)


def _cnn_party(party, addresses, transport, result_path, rounds):
    """2-party federated CNN round at CIFAR-10 shapes (BASELINE config
    #5): per-party data shards, local jitted train steps, FedAvg of the
    full parameter tree each round."""
    import numpy as np

    import rayfed_tpu as fed
    from rayfed_tpu.federated import FedAvgTrainer

    fed.init(
        addresses=addresses,
        party=party,
        config={"cross_silo_comm": dict(_FAST_RETRY), "transport": transport},
        job_name=f"bench-cnn-{transport}",
        logging_level="error",
    )

    @fed.remote
    class CnnWorker:
        def __init__(self, seed):
            import jax

            from rayfed_tpu.models.cnn import cnn_loss, init_cnn

            self.params = init_cnn(jax.random.PRNGKey(0))
            rng = np.random.default_rng(seed)
            self.x = rng.normal(size=(32, 32, 32, 3)).astype(np.float32)
            self.y = rng.integers(0, 10, size=(32,))

            def step(params, x, y):
                loss, grads = jax.value_and_grad(cnn_loss)(params, x, y)
                return jax.tree_util.tree_map(
                    lambda p, g: p - 0.05 * g, params, grads
                ), loss

            self._step = jax.jit(step)

        def train(self, global_params):
            if global_params is not None:
                self.params = global_params
            for _ in range(2):  # local steps
                self.params, _ = self._step(self.params, self.x, self.y)
            return self.params

    trainer = FedAvgTrainer(
        CnnWorker, ["alice", "bob"],
        worker_args={"alice": (1,), "bob": (2,)},
    )
    # Warmup round absorbs actor init + the jit compile.
    _progress(party, "init done; warmup round (jit compile)")
    global_params = fed.get(trainer.run(1))
    _progress(party, "timed rounds")
    t0 = time.perf_counter()
    final = fed.get(trainer.run(rounds, global_params))
    dt = time.perf_counter() - t0
    _progress(party, "rounds done; shutting down")
    assert all(
        np.isfinite(np.asarray(leaf)).all()
        for leaf in (final["head"]["w"], final["dense"]["w"])
    )
    if party == "alice":
        with open(result_path, "w") as f:
            json.dump({"round_ms": dt / rounds * 1000}, f)
    fed.shutdown()


_ASYNC3 = ("alice", "bob", "carol")


def _async_party(party, addresses, transport, result_path, rounds):
    """Straggler-proof sustained throughput (docs/async_rounds.md): 3
    parties, every frame carol sends delayed by a seeded fault schedule
    (``resilience.inject``). Each repetition runs the same contribution
    workload through two windows: lock-step ``fed_aggregate`` rounds
    (every round waits out carol's delay — the stall async mode exists
    to remove) and buffered-async rounds (``fed.async_round``,
    buffer_k=2: alice+bob publish immediately; carol's late pushes fold
    in with staleness decay). ``async_rounds_s`` vs ``sync_rounds_s`` is
    the headline ratio tools/async_check.py gates (>= 3x)."""
    import numpy as np

    import rayfed_tpu as fed
    from rayfed_tpu.async_rounds import async_session_stats
    from rayfed_tpu.federated import fed_aggregate

    delay_ms = int(os.environ.get("FEDTPU_BENCH_ASYNC_DELAY_MS", "400"))
    reps = int(os.environ.get("FEDTPU_BENCH_ASYNC_REPS", "2"))
    fed.init(
        addresses=addresses,
        party=party,
        config={
            "cross_silo_comm": dict(_FAST_RETRY),
            "transport": transport,
            "resilience": {
                "fault_schedule": {
                    "seed": 9,
                    "rules": [{
                        "fault": "delay",
                        "src": "carol",
                        "prob": 1.0,
                        "max_delay_ms": delay_ms,
                    }],
                },
            },
        },
        job_name=f"bench-async-{transport}",
        logging_level="error",
    )
    n_elem = 1 << 14  # 64KB float32 gradient tree per contribution
    seeds = {"alice": 1.0, "bob": 2.0, "carol": 3.0}

    @fed.remote
    def contrib(seed, r):
        return {"g": np.full((n_elem,), float(seed + r), np.float32)}

    def sync_window(tag):
        t0 = time.perf_counter()
        for r in range(rounds):
            objs = {
                p: contrib.party(p).remote(seeds[p], r) for p in _ASYNC3
            }
            val = fed.get(fed_aggregate(objs, op="mean"))
            assert np.isfinite(np.asarray(val["g"]).sum())
        return time.perf_counter() - t0

    def async_window(tag):
        session = f"bench{tag}"
        handles = []
        t0 = time.perf_counter()
        for r in range(rounds):
            objs = {
                p: contrib.party(p).remote(seeds[p], r) for p in _ASYNC3
            }
            handles.append(fed.async_round(
                objs, round_tag=r, buffer_k=2, session=session,
                fetch_model=False,
            ))
        # The window ends when `rounds` K-publishes landed — alice+bob
        # fill each buffer without waiting for carol. Every driver polls
        # the SAME broadcast stats, so every driver exits the loop on
        # the same iteration (multi-controller contract).
        deadline = t0 + max(60.0, rounds * delay_ms / 1000.0 * 3)
        while True:
            stats = fed.get(async_session_stats("alice", session))
            if stats["publishes"] >= rounds:
                break
            if time.perf_counter() > deadline:
                raise RuntimeError(f"async window stalled: {stats}")
            time.sleep(0.02)
        dt = time.perf_counter() - t0
        assert stats["version"] >= rounds
        # Drain carol's in-flight straggler offers BEFORE any party
        # reaches fed.shutdown(): the delayed frames ride daemon timer
        # threads, so a party exiting early would strand alice's
        # pending offer tasks on blocked pool workers (exit-time hang).
        # Outside the timed window — the window ends at the K-publish.
        for h in handles:
            fed.get(list(h.offers.values()))
        return dt

    # Warmup round: dial + jit of the fold programs, outside both windows.
    _progress(party, "init done; warmup")
    warm = {p: contrib.party(p).remote(seeds[p], 0) for p in _ASYNC3}
    fed.get(fed_aggregate(warm, op="mean"))
    sync_s, async_s = [], []
    for rep in range(reps):
        _progress(party, f"rep {rep + 1}/{reps}: sync window")
        sync_s.append(rounds / sync_window(rep))
        _progress(party, f"rep {rep + 1}/{reps}: async window")
        async_s.append(rounds / async_window(rep))
    _progress(party, "windows done; shutting down")
    if party == "alice":
        best_async, best_sync = max(async_s), max(sync_s)
        with open(result_path, "w") as f:
            json.dump({
                "async_rounds_s": best_async,
                "sync_rounds_s": best_sync,
                "async_rounds_s_spread": async_s,
                "sync_rounds_s_spread": sync_s,
                "async_vs_sync": best_async / best_sync,
                "straggler_delay_ms": delay_ms,
            }, f)
    fed.shutdown()


_CHURN5 = ("alice", "bob", "carol", "dave", "erin")


def _churn_party(party, addresses, transport, result_path, rounds):
    """Elastic-membership churn lifecycle (docs/membership.md): a
    4-party FedAvg where dave is crash-killed mid-round by an injected
    fault, evicted at the next sync by the liveness monitor's DEAD
    verdict, and erin joins as its replacement mid-training via
    ``fed.join``. Headline metrics tools/churn_check.py gates:

      churn_join_ms    — fed.join() call to the joiner's FIRST completed
                         contribution round (handshake + admission bump
                         + one elastic round).
      churn_rounds_lost — rounds that aggregated zero contributors on
                         the coordinator (must be 0: churn must degrade
                         rounds, never lose them).
    """
    import numpy as np

    import rayfed_tpu as fed
    from rayfed_tpu.ops.aggregate import elastic_weighted_mean
    from rayfed_tpu.resilience.liveness import DEAD

    crash_round = 3  # dave pushes to 3 peers/round; 10th push crashes
    join_trigger = 4  # erin dials in while the eviction is in flight
    marker_dir = os.path.dirname(result_path)
    bases = {"alice": 1.0, "bob": 2.0, "carol": 3.0, "dave": 4.0,
             "erin": 5.0}
    comm = {
        "retry_policy": {
            "max_attempts": 2,
            "initial_backoff_ms": 50,
            "max_backoff_ms": 100,
        },
        "timeout_in_ms": 2000,
        "recv_timeout_in_ms": 2000,
        "send_deadline_in_ms": 4000,
    }
    resilience = {
        "liveness": {
            "interval_ms": 100, "suspect_after": 2, "dead_after": 4,
            "timeout_ms": 300,
        },
    }
    membership = {
        "coordinator": "alice",
        "auth_token": "bench-churn",
        "evict_dead": True,
        "sync_timeout_s": 30.0,
    }
    job_name = f"bench-churn-{transport}"

    @fed.remote
    def contrib(base, r):
        return {"g": np.full((1 << 12,), base * (r + 1), np.float32)}

    def one_round(r, view):
        roster = sorted(view.roster)
        objs = {p: contrib.party(p).remote(bases[p], r) for p in roster}
        got = fed.get([objs[p] for p in roster], timeout=3.0,
                      on_missing="default")
        contribs = dict(zip(roster, got))
        live = fed.liveness_view()
        agg = elastic_weighted_mean(contribs, liveness=live)
        assert np.isfinite(np.asarray(agg["g"]).sum())
        return [p for p in roster
                if contribs[p] is not fed.MISSING and live.get(p) != DEAD]

    if party == "erin":
        trigger = os.path.join(marker_dir, f"round-{join_trigger}")
        deadline = time.monotonic() + 120
        while not os.path.exists(trigger):
            if time.monotonic() > deadline:
                raise RuntimeError("founders never reached the join round")
            time.sleep(0.05)
        from rayfed_tpu.membership.manager import get_membership_manager

        t_join = time.monotonic()
        fed.join(
            address=addresses["erin"],
            party="erin",
            coordinator="alice",
            coordinator_address=addresses["alice"],
            config={
                "cross_silo_comm": dict(comm),
                "transport": transport,
                "resilience": dict(resilience),
                "membership": dict(membership),
            },
            job_name=job_name,
            logging_level="error",
            timeout=90.0,
        )
        entry = get_membership_manager().sync_index() - 1
        join_ms = None
        for r in range(entry, rounds):
            view = (fed.membership_view() if r == entry
                    else fed.membership_sync(timeout=30.0))
            one_round(r, view)
            if join_ms is None:
                join_ms = (time.monotonic() - t_join) * 1e3
            time.sleep(0.25)
        # Sidecar for the coordinator's result merge (atomic: alice may
        # already be polling for it).
        tmp = result_path + ".erin.tmp"
        with open(tmp, "w") as f:
            json.dump({"churn_join_ms": join_ms, "entry": entry}, f)
        os.replace(tmp, result_path + ".erin")
        fed.shutdown()
        return

    founders = {p: a for p, a in addresses.items() if p != "erin"}
    config = {
        "barrier_on_initializing": True,
        "cross_silo_comm": dict(comm),
        "transport": transport,
        "resilience": dict(resilience),
        "membership": dict(membership),
    }
    if party == "dave":
        config["cross_silo_comm"]["exit_on_sending_failure"] = True
        config["resilience"]["fault_schedule"] = {
            "seed": 7,
            "rules": [{"fault": "crash", "src": "dave",
                       "after": 3 * crash_round}],
        }
    fed.init(
        addresses=founders,
        party=party,
        config=config,
        job_name=job_name,
        logging_level="error",
        sending_failure_handler=(
            (lambda e: os._exit(0)) if party == "dave" else None
        ),
    )
    per_round = []
    last_view = None
    try:
        for r in range(rounds):
            view = fed.membership_sync(timeout=30.0)
            last_view = view
            contributors = one_round(r, view)
            per_round.append(contributors)
            if party == "alice":
                with open(os.path.join(marker_dir, f"round-{r}"), "w"):
                    pass
            time.sleep(0.25)
    except BaseException:
        if party == "dave" and len(per_round) >= crash_round - 1:
            os._exit(0)  # expected death throes after the injected crash
        raise
    if party == "dave":
        raise AssertionError("dave survived its own crash schedule")
    if party == "alice":
        erin_path = result_path + ".erin"
        deadline = time.monotonic() + 60
        while not os.path.exists(erin_path):
            if time.monotonic() > deadline:
                raise RuntimeError("joiner never reported its sidecar")
            time.sleep(0.1)
        with open(erin_path) as f:
            erin_res = json.load(f)
        final_roster = sorted(last_view.roster)
        replaced = ("erin" in final_roster and "dave" not in final_roster
                    and "erin" in per_round[-1])
        with open(result_path, "w") as f:
            json.dump({
                "churn_join_ms": erin_res["churn_join_ms"],
                "churn_rounds_lost": sum(
                    1 for c in per_round if not c
                ),
                "churn_replaced": int(replaced),
                "churn_epoch": last_view.epoch,
                "churn_entry_round": erin_res["entry"],
                "churn_rounds": rounds,
            }, f)
    fed.shutdown()


_HA3 = ("alice", "bob", "carol")


def _ha_party(party, addresses, transport, result_path, rounds):
    """Control-plane HA stage (docs/ha.md): a 3-party FedAvg where the
    CONFIGURED COORDINATOR (alice) is crash-killed mid-sync-broadcast by
    an injected fault; the deterministic successor (bob) deposes it on
    the liveness DEAD verdict, adopts term 1, and takes over the sync
    point — re-broadcasting the retained views so the member whose recv
    the crash orphaned (carol) converges on the same roster. Headline
    metrics tools/ha_check.py gates:

      coordinator_failover_ms — the longest membership_sync wait the
                         successor paid across the run: the round stall
                         the takeover cost (DEAD verdict + deterministic
                         election + takeover re-broadcast).
      ha_rounds_lost   — rounds that aggregated zero contributors on the
                         successor (must be 0: failover must degrade
                         rounds, never lose them).
      ha_failed_over   — the successor actually holds the coordinator
                         role at a term >= 1 when the run ends.
    """
    import numpy as np

    import rayfed_tpu as fed
    from rayfed_tpu.membership.manager import get_membership_manager
    from rayfed_tpu.ops.aggregate import elastic_weighted_mean
    from rayfed_tpu.resilience.liveness import DEAD

    crash_round = 2  # alice makes 4 data sends per healthy round (the
    #                  sync broadcast to each member, then its update
    #                  push to each consumer); after=9 kills it MID the
    #                  round-2 sync broadcast — one member holds sync 3,
    #                  the other waits for the takeover re-broadcast.
    bases = {"alice": 1.0, "bob": 2.0, "carol": 3.0}
    comm = {
        "retry_policy": {
            "max_attempts": 2,
            "initial_backoff_ms": 50,
            "max_backoff_ms": 100,
        },
        "timeout_in_ms": 2000,
        "recv_timeout_in_ms": 2000,
        "send_deadline_in_ms": 4000,
    }
    config = {
        "barrier_on_initializing": True,
        "cross_silo_comm": dict(comm),
        "transport": transport,
        "resilience": {
            "liveness": {
                "interval_ms": 100, "suspect_after": 2, "dead_after": 4,
                "timeout_ms": 300,
            },
        },
        "membership": {
            "coordinator": "alice",
            "evict_dead": True,
            "sync_timeout_s": 30.0,
            "failover": {"takeover_timeout_s": 0.5, "resync_window": 8},
        },
    }
    if party == "alice":
        config["cross_silo_comm"]["exit_on_sending_failure"] = True
        config["resilience"]["fault_schedule"] = {
            "seed": 11,
            "rules": [{"fault": "crash", "src": "alice",
                       "after": 4 * crash_round + 1}],
        }
    fed.init(
        addresses=addresses,
        party=party,
        config=config,
        job_name=f"bench-ha-{transport}",
        logging_level="error",
        sending_failure_handler=(
            (lambda e: os._exit(0)) if party == "alice" else None
        ),
    )

    @fed.remote
    def contrib(base, r):
        return {"g": np.full((1 << 12,), base * (r + 1), np.float32)}

    per_round = []
    max_sync_ms = 0.0
    try:
        for r in range(rounds):
            t0 = time.monotonic()
            view = fed.membership_sync(timeout=30.0)
            max_sync_ms = max(max_sync_ms, (time.monotonic() - t0) * 1e3)
            roster = sorted(view.roster)
            objs = {p: contrib.party(p).remote(bases[p], r) for p in roster}
            got = fed.get([objs[p] for p in roster], timeout=3.0,
                          on_missing="default")
            contribs = dict(zip(roster, got))
            live = fed.liveness_view()
            agg = elastic_weighted_mean(contribs, liveness=live)
            assert np.isfinite(np.asarray(agg["g"]).sum())
            per_round.append([
                p for p in roster
                if contribs[p] is not fed.MISSING and live.get(p) != DEAD
            ])
            time.sleep(0.2)
    except BaseException:
        if party == "alice" and len(per_round) >= crash_round - 1:
            os._exit(0)  # expected death throes after the injected crash
        raise
    if party == "alice":
        raise AssertionError("alice survived its own crash schedule")
    if party == "bob":
        mgr = get_membership_manager()
        stats = fed.membership_stats()
        failed_over = (
            mgr.coordinator() == "bob"
            and stats.get("term", 0) >= 1
            and stats.get("takeovers", 0) >= 1
        )
        with open(result_path, "w") as f:
            json.dump({
                "coordinator_failover_ms": max_sync_ms,
                "ha_rounds_lost": sum(1 for c in per_round if not c),
                "ha_failed_over": int(failed_over),
                "ha_rounds": rounds,
            }, f)
    fed.shutdown()


_WAN3 = ("alice", "bob", "carol")


def _wan_party(party, addresses, transport, result_path, rounds):
    """WAN-emulation stage (docs/resilience.md): a 3-party FedAvg where
    every edge rides a netem-style emulated 50ms/100Mbit link (the
    in-proxy LinkProfile shaper — deterministic latency + token-bucket
    pacing, no root netem needed), with frame crc and adaptive deadlines
    on: the self-healing transport's steady-state WAN posture. Headline
    metrics tools/wan_check.py gates:

      wan_round_ms — median FedAvg round latency over the shaped link
                     (floor: ~2 x 50ms one-way latency per round trip).
      link_rtt_ms  — worst per-peer smoothed RTT the LinkHealth
                     estimator converged to (liveness ping round-trips
                     through the shaper): must see the emulated
                     latency, or adaptive deadlines are flying blind.
    """
    import statistics

    import numpy as np

    import rayfed_tpu as fed
    from rayfed_tpu.ops.aggregate import elastic_weighted_mean
    from rayfed_tpu.resilience import linkhealth

    bases = {"alice": 1.0, "bob": 2.0, "carol": 3.0}
    fed.init(
        addresses=addresses,
        party=party,
        config={
            "barrier_on_initializing": True,
            "cross_silo_comm": dict(
                _FAST_RETRY,
                frame_crc=True,
                adaptive_timeouts=True,
                recv_timeout_in_ms=20000,
            ),
            "transport": transport,
            "resilience": {
                "fault_schedule": {
                    "seed": 17,
                    "links": [{"latency_ms": 50, "rate_mbit": 100}],
                },
                "liveness": {
                    "interval_ms": 250, "suspect_after": 4,
                    "dead_after": 8, "timeout_ms": 2000,
                },
            },
        },
        job_name=f"bench-wan-{transport}",
        logging_level="error",
    )

    @fed.remote
    def contrib(base, r):
        # 256KB per contribution: ~2ms of 100Mbit pipe per edge, so the
        # round is latency-bound (the WAN regime), not bandwidth-bound.
        return {"g": np.full((1 << 16,), base * (r + 1), np.float32)}

    per_round_ms = []
    for r in range(rounds):
        t0 = time.perf_counter()
        objs = {p: contrib.party(p).remote(bases[p], r) for p in _WAN3}
        got = fed.get([objs[p] for p in _WAN3], timeout=60.0)
        agg = elastic_weighted_mean(dict(zip(_WAN3, got)))
        assert np.isfinite(np.asarray(agg["g"]).sum())
        if r > 0:  # round 0 pays actor init + first-push setup
            per_round_ms.append((time.perf_counter() - t0) * 1e3)
    _progress(party, "rounds done; shutting down")
    if party == "alice":
        health = linkhealth.get_health().get_stats()
        link_rtt_ms = max(
            (s["srtt_ms"] for s in health.values()), default=0.0
        )
        with open(result_path, "w") as f:
            json.dump({
                "round_ms": statistics.median(per_round_ms),
                "link_rtt_ms": link_rtt_ms,
                "wan_rounds": rounds,
            }, f)
    fed.shutdown()


_OBS3 = ("alice", "bob", "carol")


def _obs_party(party, addresses, transport, result_path, rounds):
    """3-party telemetry-plane stage (docs/observability.md): paired
    telemetry-off / telemetry-on windows of the same tiny-aggregate
    round, toggled at identical program points on every party, measure
    what the metrics registry + agent pushes cost the training loop —
    ``metrics_overhead_pct`` is the median over the pairs, so a host
    regime shift poisons one pair, not the headline. A final
    telemetry-on window lets alice (the collector) scrape its own HTTP
    endpoint: ``fleet_scrape_ms``, the core-series roll call, and the
    cross-party stitched-trace check that tools/obs_check.py gates."""
    import statistics
    import urllib.request

    import numpy as np

    import rayfed_tpu as fed
    from rayfed_tpu import telemetry
    from rayfed_tpu.federated import fed_aggregate
    from rayfed_tpu.telemetry.config import TelemetryConfig

    job = f"bench-obs-{transport}"
    fed.init(
        addresses=addresses,
        party=party,
        config={"cross_silo_comm": dict(_FAST_RETRY), "transport": transport},
        job_name=job,
        logging_level="error",
    )

    @fed.remote
    def contrib(seed, r):
        rng = np.random.default_rng(seed + r)
        return {"w": rng.standard_normal(2048).astype(np.float32)}

    @fed.remote
    def barrier(x):
        return True

    seeds = {p: i for i, p in enumerate(_OBS3)}

    def window(n):
        # Median per-round ms, not window mean: one GC pause or
        # scheduler hiccup in a 100ms window would otherwise swamp the
        # few-percent effect this stage exists to measure.
        times = []
        for r in range(n):
            t0 = time.perf_counter()
            objs = {
                p: contrib.party(p).remote(seeds[p], r) for p in _OBS3
            }
            agg = fed_aggregate(objs, op="mean")
            fed.get(barrier.party("alice").remote(agg))
            times.append((time.perf_counter() - t0) * 1000.0)
        return statistics.median(times)

    cfg = TelemetryConfig(
        collector="alice", push_interval_ms=250, http_port=0
    )

    _progress(party, "warmup")
    window(max(2, rounds // 4))

    # 5 pairs, order alternating OFF-first / ON-first: a monotone host
    # drift (load ramping up or down across the stage) then biases half
    # the pairs each way and the median cancels it, instead of every
    # pair charging the drift to the on-window.
    off_ms, on_ms = [], []
    for i in range(5):
        _progress(party, f"pair {i}")

        def on_window():
            telemetry.start(job, party, dict(addresses), cfg)
            ms = window(rounds)
            telemetry.stop()
            return ms

        if i % 2 == 0:
            off_ms.append(window(rounds))
            on_ms.append(on_window())
        else:
            on_ms.append(on_window())
            off_ms.append(window(rounds))

    # Scrape window: telemetry back on, a short burst of rounds, then a
    # couple of push intervals of settle time so every party's delta
    # lands before the collector is read.
    _progress(party, "scrape window")
    telemetry.start(job, party, dict(addresses), cfg)
    window(max(2, rounds // 4))
    time.sleep(1.0)

    if party == "alice":
        core = [
            "fed_transport_send_ops_total",
            "fed_transport_recv_ops_total",
            "fed_transport_inline_sends_total",
            "fed_telemetry_pushes_total",
            "fed_telemetry_party_stale",
            "fed_telemetry_fleet_epoch",
            "fed_driver_aggregates_total",
        ]
        url = telemetry.http_url()
        t0 = time.perf_counter()
        with urllib.request.urlopen(url + "/fleet", timeout=10) as resp:
            fleet = json.loads(resp.read().decode("utf-8"))
        fleet_scrape_ms = (time.perf_counter() - t0) * 1000.0
        with urllib.request.urlopen(url + "/metrics", timeout=10) as resp:
            text = resp.read().decode("utf-8")
        lines = text.splitlines()
        missing = [
            n for n in core
            if not any(ln.startswith(n) for ln in lines)
        ]
        with urllib.request.urlopen(url + "/trace", timeout=10) as resp:
            trace = json.loads(resp.read().decode("utf-8"))
        stitched = any(
            len({ev["party"] for ev in e["events"]}) >= 2
            for e in trace.get("edges", [])
        )
        overhead = statistics.median(
            (on - off) / off * 100.0 for off, on in zip(off_ms, on_ms)
        )
        with open(result_path, "w") as f:
            json.dump({
                "metrics_overhead_pct": overhead,
                "fleet_scrape_ms": fleet_scrape_ms,
                "obs_off_ms": off_ms,
                "obs_on_ms": on_ms,
                "obs_series_missing": missing,
                "obs_stitched": int(stitched),
                "obs_parties_reporting": len(fleet.get("parties", {})),
            }, f)
    telemetry.stop()
    fed.shutdown()


_SECAGG3 = ("alice", "bob", "carol")


def _secagg_party(party, addresses, transport, result_path, rounds):
    """3-party privacy-plane stage (docs/privacy.md): paired plaintext /
    secure windows of the same integer-valued FedAvg round price the
    masking path (fixed-point encode + pairwise PRNG streams at each
    party, ring unmask at the root) — ``secure_agg_overhead_pct`` is the
    median over the pairs. Every secure round is also bitwise-compared
    against the locally recomputed plaintext fold
    (``secagg_bitwise_equal``: the mask-cancellation witness
    tools/privacy_check.py gates). A final window owner-pushes int8
    error-feedback-quantized trees across the wire and prices them in
    ORIGINAL float bytes per second: ``quantized_push_gbps``."""
    import statistics

    import numpy as np

    import rayfed_tpu as fed
    from rayfed_tpu import topology as topo
    from rayfed_tpu.federated import fed_aggregate
    from rayfed_tpu.ops.aggregate import reduce_by_plan

    fed.init(
        addresses=addresses,
        party=party,
        config={
            "cross_silo_comm": dict(_FAST_RETRY),
            "transport": transport,
            "privacy": {"secure_aggregation": True, "mask_seed": 97},
        },
        job_name=f"bench-secagg-{transport}",
        logging_level="error",
    )

    leafs = 8192

    def local_tree(seed, r):
        rng = np.random.default_rng(seed * 1000 + r)
        return {"w": rng.integers(-1000, 1000, (leafs,)).astype(np.float32)}

    @fed.remote
    def contrib(seed, r):
        return local_tree(seed, r)

    seeds = {p: i + 1 for i, p in enumerate(_SECAGG3)}
    plan = topo.plan(list(_SECAGG3), "flat")
    bitwise_ok = [True]

    def window(n, secure):
        # Median per-round ms (one GC pause must not swamp the few-
        # percent masking cost); every party fed.gets the aggregate, so
        # the fetch doubles as the round barrier in both windows.
        times = []
        for r in range(n):
            t0 = time.perf_counter()
            objs = {
                p: contrib.party(p).remote(seeds[p], r) for p in _SECAGG3
            }
            val = fed.get(fed_aggregate(objs, op="mean", secure=secure))
            times.append((time.perf_counter() - t0) * 1000.0)
            if secure and party == "alice":
                expect = reduce_by_plan(
                    plan, {p: local_tree(seeds[p], r) for p in _SECAGG3}
                )
                if np.asarray(val["w"]).tobytes() != \
                        np.asarray(expect["w"]).tobytes():
                    bitwise_ok[0] = False
        return statistics.median(times)

    _progress(party, "warmup")
    window(max(2, rounds // 4), secure=False)
    window(max(2, rounds // 4), secure=True)  # seed exchange + jit

    # 5 pairs, alternating plain-first / secure-first so a monotone host
    # drift biases half the pairs each way and the median cancels it.
    plain_ms, secure_ms = [], []
    for i in range(5):
        _progress(party, f"pair {i}")
        if i % 2 == 0:
            plain_ms.append(window(rounds, secure=False))
            secure_ms.append(window(rounds, secure=True))
        else:
            secure_ms.append(window(rounds, secure=True))
            plain_ms.append(window(rounds, secure=False))

    # Quantized-push window: int8 error-feedback trees cross the wire
    # (1/4 the bytes), priced in original float bytes per second.
    _progress(party, "quantized push window")
    push_mb = 32
    push_reps = 4

    @fed.remote
    def make_packed(r):
        n = push_mb * (1 << 20) // 4
        rng = np.random.default_rng(r)
        tree = {"w": rng.standard_normal(n).astype(np.float32)}
        return _secagg_quantizer().quantize("alice", tree)

    @fed.remote
    def sink(packed):
        from rayfed_tpu.privacy.quantize import dequantize_tree

        t = dequantize_tree(packed)
        return float(np.asarray(t["w"]).flat[0])

    fed.get(sink.party("bob").remote(make_packed.party("alice").remote(0)))
    t0 = time.perf_counter()
    for r in range(push_reps):
        fed.get(
            sink.party("bob").remote(make_packed.party("alice").remote(r + 1))
        )
    dt = time.perf_counter() - t0
    quant_gbps = push_reps * push_mb * (1 << 20) / dt / 1e9

    if party == "alice":
        overhead = statistics.median(
            (s - p) / p * 100.0 for p, s in zip(plain_ms, secure_ms)
        )
        with open(result_path, "w") as f:
            json.dump({
                "secure_agg_overhead_pct": overhead,
                "secagg_bitwise_equal": int(bitwise_ok[0]),
                "quantized_push_gbps": quant_gbps,
                "plain_round_ms": plain_ms,
                "secure_round_ms": secure_ms,
            }, f)
    fed.shutdown()


# Executor-process singleton for the quantized-push window: the error-
# feedback residual must persist ACROSS make_packed tasks (that is the
# contract being priced), so it cannot live inside the task closure.
# Built lazily — bench.py must stay importable without rayfed_tpu.
_secagg_ef = None


def _secagg_quantizer():
    global _secagg_ef
    if _secagg_ef is None:
        from rayfed_tpu.privacy.quantize import ErrorFeedbackQuantizer

        _secagg_ef = ErrorFeedbackQuantizer()
    return _secagg_ef


def _try_build_fastwire() -> None:
    """Best-effort build of the native C++ IO lane; the transport falls
    back to pure-Python sockets if this fails."""
    import glob
    import subprocess

    here = os.path.dirname(os.path.abspath(__file__))
    if glob.glob(os.path.join(here, "rayfed_tpu", "_fastwire*.so")):
        return
    try:
        subprocess.run(
            [sys.executable, "setup.py", "build_ext", "--inplace"],
            cwd=here, capture_output=True, timeout=120, check=False,
        )
    except Exception:
        pass


def _try_train_mfu():
    """Flagship train-step MFU on the local accelerator (TPU only) —
    recorded alongside the push-throughput headline.

    Runs in a killable subprocess supervised by a progress watchdog
    instead of one flat timeout (rounds 2 and 3 both lost the MFU number
    to a 420s flat budget): the child prints ``BACKEND_UP`` once jax's
    device init returns and ``COMPILED`` when the warmup step finishes.
    A wedged accelerator service (backend init never returns — the
    failure mode that ate both prior rounds) is killed after
    ``_MFU_BACKEND_DEADLINE``; once the backend is up, a cold-cache XLA
    compile may use the full hard cap. A warm persistent compilation
    cache (repo-local .jax_cache) finishes in well under a minute."""
    import subprocess
    import threading

    # Fast pre-probe: spawning the child costs 240s of backend-init
    # watchdog when no accelerator is reachable (the exact stall
    # BENCH_r05 recorded). Skip immediately when the environment says
    # there is nothing to init against; FEDTPU_MFU_FORCE=1 overrides
    # for plugin platforms this heuristic cannot see.
    if not os.environ.get("FEDTPU_MFU_FORCE"):
        plat = os.environ.get("JAX_PLATFORMS", "").strip().lower()
        if plat and "tpu" not in plat and "axon" not in plat:
            print(
                f"train MFU bench skipped: JAX_PLATFORMS={plat!r} "
                "selects no accelerator", file=sys.stderr,
            )
            return None
        import glob as _glob

        if not (
            os.environ.get("PALLAS_AXON_POOL_IPS")
            or _glob.glob("/dev/accel*")
            or _glob.glob("/dev/vfio/*")
        ):
            print(
                "train MFU bench skipped: no accelerator visible (no "
                "PALLAS_AXON_POOL_IPS, no /dev/accel*); set "
                "FEDTPU_MFU_FORCE=1 to attempt anyway", file=sys.stderr,
            )
            return None

    here = os.path.dirname(os.path.abspath(__file__))
    backend_deadline = int(os.environ.get("FEDTPU_MFU_BACKEND_DEADLINE", 240))
    hard_cap = int(os.environ.get("FEDTPU_MFU_HARD_CAP", 900))
    # Flagship MFU configuration. Defaults are the proven round-2
    # measurement config: full per-layer remat + Pallas flash attention
    # at batch 12 (remat='attn' keeps the attention outputs and is
    # faster per step, but compiles pathologically slowly around the
    # Pallas custom_vjp under scan — use only with a pre-warmed cache).
    # A checked-in benchmarks/mfu_config.json (written by
    # tools/mfu_tune.py after an on-hardware sweep) overrides the
    # defaults; FEDTPU_MFU_* env vars override both.
    file_cfg = {}
    cfg_path = os.path.join(here, "benchmarks", "mfu_config.json")
    if os.path.exists(cfg_path):
        try:
            with open(cfg_path) as f:
                file_cfg = json.load(f)
        except Exception:  # noqa: BLE001 - defaults still apply
            file_cfg = {}
        if not isinstance(file_cfg, dict):
            file_cfg = {}
    mfu_cfg = {
        "batch": int(os.environ.get(
            "FEDTPU_MFU_BATCH", file_cfg.get("batch", 12))),
        "steps": int(os.environ.get(
            "FEDTPU_MFU_STEPS", file_cfg.get("steps", 10))),
        "remat": str(os.environ.get(
            "FEDTPU_MFU_REMAT", file_cfg.get("remat", "1"))),
    }
    cache_dir = os.path.join(here, ".jax_cache")
    cache_warm = os.path.isdir(cache_dir) and bool(os.listdir(cache_dir))
    if (
        mfu_cfg["remat"] == "attn"
        and "FEDTPU_MFU_REMAT" not in os.environ
        and not cache_warm
    ):
        # A file-tuned 'attn' winner presumes the warmed compilation
        # cache it was swept with; cold, its compile blows the hard cap
        # (the exact failure the watchdog exists for). Fall back to the
        # safe full-remat config; an explicit env override still wins.
        print(
            "mfu: ignoring remat='attn' from mfu_config.json (compilation "
            "cache is cold); using full remat", file=sys.stderr,
        )
        mfu_cfg["remat"] = "1"
    remat_arg = (
        "'attn'" if mfu_cfg["remat"] == "attn"
        else str(mfu_cfg["remat"] == "1")
    )
    code = (
        "import sys, json\n"
        f"sys.path.insert(0, {os.path.join(here, 'benchmarks')!r})\n"
        "from transformer_train_benchmark import enable_compilation_cache\n"
        "enable_compilation_cache()\n"
        "import jax\n"
        "from rayfed_tpu.utils import is_tpu_backend\n"
        "if not is_tpu_backend():\n"
        "    sys.exit(3)\n"
        "from contextlib import redirect_stdout\n"
        "from transformer_train_benchmark import FLAGSHIP\n"
        "from transformer_train_benchmark import run as train_run\n"
        "with redirect_stdout(sys.stderr):\n"
        "    r = train_run(FLAGSHIP['d_model'], FLAGSHIP['n_layers'], "
        f"FLAGSHIP['seq'], batch={mfu_cfg['batch']}, "
        f"steps={mfu_cfg['steps']}, vocab=FLAGSHIP['vocab'], "
        f"remat={remat_arg})\n"
        "print(json.dumps({'train_tokens_per_s': round(r['tokens_per_s']),"
        "'train_mfu': round(r['mfu'], 4),"
        "'train_n_params': r['n_params'], 'train_seq': r['seq']}))\n"
    )
    try:
        proc = subprocess.Popen(
            [sys.executable, "-u", "-c", code],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True, cwd=here,
        )
        stderr_lines = []

        def _drain():
            for line in proc.stderr:
                stderr_lines.append(line)

        t = threading.Thread(target=_drain, daemon=True)
        t.start()
        t0 = time.monotonic()
        why = None
        while proc.poll() is None:
            elapsed = time.monotonic() - t0
            backend_up = any("BACKEND_UP" in ln for ln in stderr_lines)
            if not backend_up and elapsed > backend_deadline:
                why = f"backend init made no progress in {backend_deadline}s"
                break
            if elapsed > hard_cap:
                why = f"exceeded hard cap {hard_cap}s"
                break
            time.sleep(2.0)
        if why is not None:
            proc.kill()
            proc.wait(timeout=30)
            print(f"train MFU bench skipped: {why}", file=sys.stderr)
            return None
        stdout = proc.stdout.read()
        t.join(timeout=10)
        if proc.returncode != 0:
            tail = "".join(stderr_lines)[-500:]
            print(
                f"train MFU bench skipped (rc={proc.returncode}): {tail}",
                file=sys.stderr,
            )
            return None
        return json.loads(stdout.strip().splitlines()[-1])
    except Exception as e:  # noqa: BLE001 - bench must still print its line
        print(f"train MFU bench skipped: {e!r}", file=sys.stderr)
        return None


def main() -> None:
    _try_build_fastwire()
    mfu = _try_train_mfu()
    # The ceiling is PAIRED: each native rep is preceded by a raw-socket
    # window between the same two party processes (see _party_main), so
    # lane and ceiling samples share the host regime they were measured
    # in. On this class of shared VM, loopback throughput swings 2-3x on
    # a seconds timescale — round 4's bracketing probes (minutes away
    # from the stage they calibrated) produced a 77.5% ratio from regime
    # mismatch alone; the paired median ratio is stable.
    native = run_transport("tcp", pair_ceiling=True)
    baseline = run_transport("grpc")
    # Same-host zero-copy shm lane, same workload/processes layout as
    # the tcp stage so tools/shm_check.py can gate the ratio against
    # tcp_loopback_gbps (both keys from this run, same host regime).
    shm_lane = {}
    try:
        _lane_stats(shm_lane, "shm_push_gbps", run_transport("tcp", shm=True))
    except Exception as e:  # noqa: BLE001 - bench must still print its line
        print(f"shm bench skipped: {e!r}", file=sys.stderr)
    tpu_lanes = _try_tpu_lanes()
    result = {
        "metric": "2-party cross-party push throughput, 100MB float32 tensors",
        "value": round(native["max"], 3),
        "unit": "GB/s",
        "vs_baseline_unpaired": round(native["max"] / baseline["max"], 3),
        "value_median": round(native["median"], 3),
        "baseline_grpc_cloudpickle_gbps": round(baseline["max"], 3),
        "rounds": ROUNDS,
        "payload_mb": PAYLOAD_MB,
    }
    if native.get("raw_median"):
        result["loopback_ceiling_gbps"] = round(native["raw_median"], 3)
        result["loopback_ceiling_spread"] = [
            round(x, 3) for x in native["raw_spread"]
        ]
        result["pct_of_ceiling"] = round(
            100.0 * native["paired_ratio_median"], 1
        )
    # Paired vs_baseline: per-pair tcp/grpc window ratios measured
    # seconds apart in the same processes. Best-effort — on failure the
    # unpaired ratio (max-of-run over max-of-run, windows minutes apart)
    # keeps the key populated for continuity.
    try:
        result.update(_run_paired_baseline())
    except Exception as e:  # noqa: BLE001 - bench must still print its line
        print(f"paired baseline skipped: {e!r}", file=sys.stderr)
    result.setdefault("vs_baseline", result["vs_baseline_unpaired"])
    # The socket-lane number the shm gate normalizes by (median: robust
    # to the one lucky rep "max" keeps for continuity).
    result["tcp_loopback_gbps"] = round(native["median"], 3)
    result.update(shm_lane)
    result.update(tpu_lanes)
    result.update(_try_data_plane())
    if mfu:
        result.update(mfu)
    # BASELINE.json configs #1/#3/#4/#5 as driver keys; #1 and #3 also
    # measured on the reference-parity gRPC lane for the ratio.
    result.update(_bench_stage(
        _tiny_party, "per_task_ms", "FEDTPU_BENCH_TINY_ROUNDS", 300,
        [("tcp", "tiny_task_overhead_ms"),
         ("grpc", "tiny_task_overhead_grpc_ms")],
        digits=3,
    ))
    result.update(_bench_stage(
        _fedavg_party, "round_ms", "FEDTPU_BENCH_FEDAVG_ROUNDS", 20,
        [("tcp", "fedavg_round_ms"), ("grpc", "fedavg_round_grpc_ms")],
        cpu_force=True,
    ))
    result.update(_bench_stage(
        _hier4_party, "round_ms", "FEDTPU_BENCH_HIER4_ROUNDS", 20,
        [("tcp", "hier4_round_ms")], cpu_force=True, parties=_HIER4,
        extra_fields={
            "round_ms_median": "hier4_round_ms_median",
            "round_ms_spread": "hier4_round_ms_spread",
        },
    ))
    result.update(_bench_stage(
        _cnn_party, "round_ms", "FEDTPU_BENCH_CNN_ROUNDS", 5,
        [("tcp", "fedavg_cnn_round_ms")], cpu_force=True, timeout_s=420,
    ))
    # Straggler-proof async rounds (docs/async_rounds.md): carol's sends
    # delayed by a seeded fault schedule; sync stalls, buffered-async
    # sustains. tools/async_check.py gates the ratio.
    result.update(_bench_stage(
        _async_party, "async_rounds_s", "FEDTPU_BENCH_ASYNC_ROUNDS", 12,
        [("tcp", "async_rounds_s")], cpu_force=True, parties=_ASYNC3,
        timeout_s=420,
        extra_fields={
            "sync_rounds_s": "sync_rounds_s",
            "async_rounds_s_spread": "async_rounds_s_spread",
            "sync_rounds_s_spread": "sync_rounds_s_spread",
            "async_vs_sync": "async_vs_sync",
        },
    ))
    # Elastic-membership churn (docs/membership.md): dave crash-killed
    # mid-round, liveness-evicted at the next sync, erin joins as its
    # replacement mid-training. tools/churn_check.py gates join latency
    # and rounds lost.
    result.update(_bench_stage(
        _churn_party, "churn_join_ms", "FEDTPU_BENCH_CHURN_ROUNDS", 12,
        [("tcp", "churn_join_ms")], cpu_force=True, parties=_CHURN5,
        timeout_s=300, digits=1,
        extra_fields={
            "churn_rounds_lost": "churn_rounds_lost",
            "churn_replaced": "churn_replaced",
            "churn_epoch": "churn_epoch",
            "churn_entry_round": "churn_entry_round",
            "churn_rounds": "churn_rounds",
        },
    ))
    # Control-plane HA (docs/ha.md): the configured coordinator is
    # crash-killed mid-sync-broadcast; the deterministic successor
    # deposes it at the liveness verdict and takes over the sync point
    # under term 1. tools/ha_check.py gates the failover stall and
    # rounds lost.
    result.update(_bench_stage(
        _ha_party, "coordinator_failover_ms", "FEDTPU_BENCH_HA_ROUNDS", 8,
        [("tcp", "coordinator_failover_ms")], cpu_force=True, parties=_HA3,
        timeout_s=300, digits=1,
        extra_fields={
            "ha_rounds_lost": "ha_rounds_lost",
            "ha_failed_over": "ha_failed_over",
            "ha_rounds": "ha_rounds",
        },
    ))
    # WAN emulation (docs/resilience.md): 3-party FedAvg over an
    # in-proxy 50ms/100Mbit shaped link with frame crc + adaptive
    # deadlines on. tools/wan_check.py gates the round latency and the
    # LinkHealth estimator's convergence on the emulated RTT.
    result.update(_bench_stage(
        _wan_party, "round_ms", "FEDTPU_BENCH_WAN_ROUNDS", 8,
        [("tcp", "wan_round_ms")], cpu_force=True, parties=_WAN3,
        timeout_s=300, digits=1,
        extra_fields={
            "link_rtt_ms": "link_rtt_ms",
            "wan_rounds": "wan_rounds",
        },
    ))
    # Telemetry plane (docs/observability.md): paired on/off windows
    # price the metrics registry + agent pushes; tools/obs_check.py
    # gates the overhead and the collector's fleet/trace endpoints.
    result.update(_bench_stage(
        _obs_party, "metrics_overhead_pct", "FEDTPU_BENCH_OBS_ROUNDS", 60,
        [("tcp", "metrics_overhead_pct")], cpu_force=True, parties=_OBS3,
        timeout_s=420,
        extra_fields={
            "fleet_scrape_ms": "fleet_scrape_ms",
            "obs_stitched": "obs_stitched",
        },
    ))
    # Privacy plane (docs/privacy.md): paired plaintext/secure FedAvg
    # windows price the masking path, every secure round is bitwise-
    # checked against the plaintext fold, and a quantized-push window
    # prices int8 error-feedback trees on the wire.
    # tools/privacy_check.py gates all three.
    result.update(_bench_stage(
        _secagg_party, "secure_agg_overhead_pct",
        "FEDTPU_BENCH_SECAGG_ROUNDS", 20,
        [("tcp", "secure_agg_overhead_pct")], cpu_force=True,
        parties=_SECAGG3, timeout_s=420,
        extra_fields={
            "secagg_bitwise_equal": "secagg_bitwise_equal",
            "quantized_push_gbps": "quantized_push_gbps",
            "plain_round_ms": "secagg_plain_round_ms",
            "secure_round_ms": "secagg_secure_round_ms",
        },
    ))
    # N-party scale sweep (in-process simulated parties, real wire edges).
    try:
        result.update(_run_scale_sweep())
    except Exception as e:  # noqa: BLE001 - bench must still print its line
        print(f"scale sweep skipped: {e!r}", file=sys.stderr)
    # Serving plane: continuous batching under concurrent clients with
    # hot swaps mid-window (docs/serving.md).
    try:
        result.update(_run_serve_bench())
    except Exception as e:  # noqa: BLE001 - bench must still print its line
        print(f"serve bench skipped: {e!r}", file=sys.stderr)
    # Tenancy plane: weighted-fair sharing between two jobs on one
    # shared listener + the victim's inline p99 under a noisy neighbor
    # (docs/multitenancy.md; tools/tenant_check.py gates both keys).
    try:
        result.update(_run_tenant_bench())
    except Exception as e:  # noqa: BLE001 - bench must still print its line
        print(f"tenant bench skipped: {e!r}", file=sys.stderr)
    if _DIAGNOSTICS:
        result["diagnostics"] = _DIAGNOSTICS
    print(json.dumps(result))


if __name__ == "__main__":
    sys.exit(main())
