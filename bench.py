# Copyright 2026 The rayfed-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Headline benchmark: cross-party push throughput on 100MB tensors.

Prints ONE JSON line:
    {"metric": ..., "value": <GB/s native>, "unit": "GB/s",
     "vs_baseline": <native GB/s / reference-parity gRPC GB/s>}

The baseline is self-measured (the reference publishes no numbers —
BASELINE.md): the same two-party push workload over this repo's
``transport='grpc'`` lane, which reproduces the reference's wire behavior
(one unary RPC per object, payload cloudpickled inside the request,
ref ``fed/proxy/grpc/grpc_proxy.py:193-220``). The native lane is the
binary TCP protocol with the zero-pickle array fast path.

Workload (BASELINE.json config #2): 2 parties on localhost, alice pushes
N x 100MB float32 gradient tensors to bob via ``@fed.remote`` consumers;
bob measures arrival throughput.
"""

from __future__ import annotations

import json
import multiprocessing
import os
import socket
import sys
import tempfile
import time

PAYLOAD_MB = 100
ROUNDS = 5
REPS = 8  # best-of-N inside one job (single-core hosts are noisy)

_FAST_RETRY = {
    "retry_policy": {
        "max_attempts": 20,
        "initial_backoff_ms": 200,
        "max_backoff_ms": 2000,
        "backoff_multiplier": 1.5,
    }
}


def _party_main(party, addresses, transport, result_path, device_dma=False):
    import numpy as np

    import rayfed_tpu as fed

    comm = dict(_FAST_RETRY)
    if os.environ.get("FEDTPU_BENCH_WINDOW"):
        comm["send_window"] = int(os.environ["FEDTPU_BENCH_WINDOW"])
    if device_dma:
        comm["device_dma"] = True
    fed.init(
        addresses=addresses,
        party=party,
        config={"cross_silo_comm": comm, "transport": transport},
        job_name=f"bench-{transport}",
        logging_level="error",
    )

    n_elem = PAYLOAD_MB * 1024 * 1024 // 4

    if device_dma:
        # Device-resident payloads: the DMA lane parks live jax buffers
        # on the transfer server and ships only a descriptor over the
        # socket; the receiver pulls through the transfer engine's bulk
        # transport (ICI/DCN on a pod, its socket transport in CPU sim).
        import jax.numpy as jnp

        @fed.remote
        def produce(i):
            import jax

            return jax.block_until_ready(
                jnp.full((n_elem,), float(i), dtype=jnp.float32)
            )
    else:

        @fed.remote
        def produce(i):
            # Fresh tensor per round (dedup would skip repeat pushes).
            return np.full((n_elem,), float(i), dtype=np.float32)

    @fed.remote
    def consume(x):
        return float(x[0]) + float(x[-1])

    @fed.remote
    def barrier(*xs):
        return len(xs)

    # Warmup round (connection setup, allocator warm).
    w = consume.party("bob").remote(produce.party("alice").remote(-1.0))
    assert fed.get(w) == -2.0

    samples = []
    for rep in range(REPS):
        # Materialize all tensors at alice BEFORE the timed window so the
        # measurement is transport throughput, not producer memset speed.
        base = 100.0 * rep
        tensors = [produce.party("alice").remote(base + i) for i in range(ROUNDS)]
        ready = barrier.party("alice").remote(*tensors)
        assert fed.get(ready) == ROUNDS

        t0 = time.perf_counter()
        outs = [consume.party("bob").remote(t) for t in tensors]
        checks = fed.get(outs)
        dt = time.perf_counter() - t0
        assert checks == [2.0 * (base + i) for i in range(ROUNDS)], checks
        samples.append(ROUNDS * PAYLOAD_MB / 1024 / dt)

    # Peak-of-reps: throughput capability, same rule for both lanes.
    gbps = max(samples)
    if party == "bob":
        with open(result_path, "w") as f:
            json.dump({"gbps": gbps, "samples": samples}, f)
    fed.shutdown()


def _free_ports(n):
    socks = [socket.socket() for _ in range(n)]
    for s in socks:
        s.bind(("127.0.0.1", 0))
    ports = [s.getsockname()[1] for s in socks]
    for s in socks:
        s.close()
    return ports


def run_transport(transport: str, device_dma: bool = False) -> dict:
    p1, p2 = _free_ports(2)
    addresses = {"alice": f"127.0.0.1:{p1}", "bob": f"127.0.0.1:{p2}"}
    mp = multiprocessing.get_context("spawn")
    with tempfile.TemporaryDirectory() as tmp:
        result_path = os.path.join(tmp, "result.json")
        procs = [
            mp.Process(
                target=_party_main,
                args=(party, addresses, transport, result_path, device_dma),
            )
            for party in ("alice", "bob")
        ]
        for p in procs:
            p.start()
        for p in procs:
            p.join(timeout=600)
        hung = [p for p in procs if p.is_alive()]
        for p in hung:
            p.terminate()  # a live non-daemon child would hang exit
            p.join(timeout=30)
        if hung:
            raise RuntimeError(f"{transport} bench party hung; terminated")
        for p in procs:
            if p.exitcode != 0:
                raise RuntimeError(
                    f"{transport} bench party failed (exitcode={p.exitcode})"
                )
        with open(result_path) as f:
            res = json.load(f)
        import statistics

        # max = capability (continuity with earlier rounds); median is
        # robust to the start-clock skew between the two party processes,
        # which can inflate individual short timed windows.
        return {
            "max": res["gbps"],
            "median": statistics.median(res["samples"]),
            "samples": res["samples"],
        }


def _tune(sock) -> None:
    """Apply the transport's own socket tuning to the ceiling probe —
    without this the 'ceiling' uses default buffer sizes and the tuned
    native lane can beat it (a >100% pct_of_ceiling is a measurement
    artifact, not physics)."""
    try:
        from rayfed_tpu.proxy.tcp import sockio

        sockio.tune_socket(sock)
    except Exception:  # noqa: BLE001 - probe still works untuned
        pass


def _ceiling_tx(port: int, n: int, reps: int) -> None:
    """Sender half of the loopback-ceiling probe (own OS process, like a
    bench party)."""
    # Import the tuning helper BEFORE connecting: the first rayfed_tpu
    # import takes seconds on a busy host, and the receiver's first
    # timed window must not absorb it.
    try:
        from rayfed_tpu.proxy.tcp import sockio  # noqa: F401
    except Exception:  # noqa: BLE001
        pass
    buf = bytearray(n)
    s = socket.socket()
    s.connect(("127.0.0.1", port))
    _tune(s)
    with s:
        for _ in range(reps):
            for _ in range(ROUNDS):
                s.sendall(buf)


def _loopback_ceiling() -> dict:
    """The host's raw-socket loopback throughput as {"max", "median"}
    over REPS reps of ROUNDS x payload timed windows (same methodology
    and socket tuning as the transport benchmark; sender in its own
    spawned process, recv_into a pinned buffer, nothing else on the
    wire). The output JSON reports the MEDIAN. The push
    benchmark's number is only meaningful relative to this: on a
    single-core host the ceiling sits far below the NIC-less ideal
    because sender and receiver share the core, and it drifts with
    allocation noise — so it is re-measured at bench time, not quoted
    from a past run (BASELINE.md's 2.8 GB/s was measured on a quieter
    allocation and does not reproduce)."""
    n = PAYLOAD_MB * 1024 * 1024
    samples = []
    srv = socket.socket()
    proc = None
    try:
        srv.bind(("127.0.0.1", 0))
        srv.listen(1)
        port = srv.getsockname()[1]
        mp = multiprocessing.get_context("spawn")
        proc = mp.Process(target=_ceiling_tx, args=(port, n, REPS))
        proc.start()
        srv.settimeout(60)
        conn, _ = srv.accept()
        _tune(conn)
        with conn:
            view = memoryview(bytearray(n))
            for _ in range(REPS):
                t0 = time.perf_counter()
                for _ in range(ROUNDS):
                    got = 0
                    while got < n:
                        k = conn.recv_into(view[got:], n - got)
                        if not k:
                            raise ConnectionError("ceiling sender died")
                        got += k
                samples.append(ROUNDS * n / 2**30 / (time.perf_counter() - t0))
    finally:
        srv.close()
        if proc is not None:
            proc.join(timeout=30)
            if proc.is_alive():
                proc.terminate()
                proc.join(timeout=10)
    if not samples:
        return {"max": 0.0, "median": 0.0}
    import statistics

    return {"max": max(samples), "median": statistics.median(samples)}


def _try_dma_transport() -> Optional[float]:
    """Device-DMA lane throughput (descriptor over the socket lane,
    buffers pulled through the jax transfer engine). Parties are forced
    onto the CPU backend: on this driver there is ONE real chip and two
    party processes cannot share it — the number measures the lane's
    machinery (register/descriptor/pull) end-to-end; on a pod the same
    lane rides ICI/DCN. Best-effort: records nothing when the transfer
    engine is unavailable."""
    scrub = {
        "PALLAS_AXON_POOL_IPS": None,
        "JAX_PLATFORMS": "cpu",
    }
    saved = {k: os.environ.get(k) for k in scrub}
    try:
        for k, v in scrub.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        return run_transport("tpu", device_dma=True)["max"]
    except Exception as e:  # noqa: BLE001 - bench must still print its line
        print(f"dma bench skipped: {e!r}", file=sys.stderr)
        return None
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def _try_build_fastwire() -> None:
    """Best-effort build of the native C++ IO lane; the transport falls
    back to pure-Python sockets if this fails."""
    import glob
    import subprocess

    here = os.path.dirname(os.path.abspath(__file__))
    if glob.glob(os.path.join(here, "rayfed_tpu", "_fastwire*.so")):
        return
    try:
        subprocess.run(
            [sys.executable, "setup.py", "build_ext", "--inplace"],
            cwd=here, capture_output=True, timeout=120, check=False,
        )
    except Exception:
        pass


def _try_train_mfu():
    """Flagship train-step MFU on the local accelerator (TPU only) —
    recorded alongside the push-throughput headline.

    Runs in a killable subprocess supervised by a progress watchdog
    instead of one flat timeout (rounds 2 and 3 both lost the MFU number
    to a 420s flat budget): the child prints ``BACKEND_UP`` once jax's
    device init returns and ``COMPILED`` when the warmup step finishes.
    A wedged accelerator service (backend init never returns — the
    failure mode that ate both prior rounds) is killed after
    ``_MFU_BACKEND_DEADLINE``; once the backend is up, a cold-cache XLA
    compile may use the full hard cap. A warm persistent compilation
    cache (repo-local .jax_cache) finishes in well under a minute."""
    import subprocess
    import threading

    here = os.path.dirname(os.path.abspath(__file__))
    backend_deadline = int(os.environ.get("FEDTPU_MFU_BACKEND_DEADLINE", 240))
    hard_cap = int(os.environ.get("FEDTPU_MFU_HARD_CAP", 900))
    # Flagship MFU configuration. Defaults are the proven round-2
    # measurement config: full per-layer remat + Pallas flash attention
    # at batch 12 (remat='attn' keeps the attention outputs and is
    # faster per step, but compiles pathologically slowly around the
    # Pallas custom_vjp under scan — use only with a pre-warmed cache).
    # A checked-in benchmarks/mfu_config.json (written by
    # tools/mfu_tune.py after an on-hardware sweep) overrides the
    # defaults; FEDTPU_MFU_* env vars override both.
    file_cfg = {}
    cfg_path = os.path.join(here, "benchmarks", "mfu_config.json")
    if os.path.exists(cfg_path):
        try:
            with open(cfg_path) as f:
                file_cfg = json.load(f)
        except Exception:  # noqa: BLE001 - defaults still apply
            file_cfg = {}
        if not isinstance(file_cfg, dict):
            file_cfg = {}
    mfu_cfg = {
        "batch": int(os.environ.get(
            "FEDTPU_MFU_BATCH", file_cfg.get("batch", 12))),
        "steps": int(os.environ.get(
            "FEDTPU_MFU_STEPS", file_cfg.get("steps", 10))),
        "remat": str(os.environ.get(
            "FEDTPU_MFU_REMAT", file_cfg.get("remat", "1"))),
    }
    cache_dir = os.path.join(here, ".jax_cache")
    cache_warm = os.path.isdir(cache_dir) and bool(os.listdir(cache_dir))
    if (
        mfu_cfg["remat"] == "attn"
        and "FEDTPU_MFU_REMAT" not in os.environ
        and not cache_warm
    ):
        # A file-tuned 'attn' winner presumes the warmed compilation
        # cache it was swept with; cold, its compile blows the hard cap
        # (the exact failure the watchdog exists for). Fall back to the
        # safe full-remat config; an explicit env override still wins.
        print(
            "mfu: ignoring remat='attn' from mfu_config.json (compilation "
            "cache is cold); using full remat", file=sys.stderr,
        )
        mfu_cfg["remat"] = "1"
    remat_arg = (
        "'attn'" if mfu_cfg["remat"] == "attn"
        else str(mfu_cfg["remat"] == "1")
    )
    code = (
        "import sys, json\n"
        f"sys.path.insert(0, {os.path.join(here, 'benchmarks')!r})\n"
        "from transformer_train_benchmark import enable_compilation_cache\n"
        "enable_compilation_cache()\n"
        "import jax\n"
        "from rayfed_tpu.utils import is_tpu_backend\n"
        "if not is_tpu_backend():\n"
        "    sys.exit(3)\n"
        "from contextlib import redirect_stdout\n"
        "from transformer_train_benchmark import FLAGSHIP\n"
        "from transformer_train_benchmark import run as train_run\n"
        "with redirect_stdout(sys.stderr):\n"
        "    r = train_run(FLAGSHIP['d_model'], FLAGSHIP['n_layers'], "
        f"FLAGSHIP['seq'], batch={mfu_cfg['batch']}, "
        f"steps={mfu_cfg['steps']}, vocab=FLAGSHIP['vocab'], "
        f"remat={remat_arg})\n"
        "print(json.dumps({'train_tokens_per_s': round(r['tokens_per_s']),"
        "'train_mfu': round(r['mfu'], 4),"
        "'train_n_params': r['n_params'], 'train_seq': r['seq']}))\n"
    )
    try:
        proc = subprocess.Popen(
            [sys.executable, "-u", "-c", code],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True, cwd=here,
        )
        stderr_lines = []

        def _drain():
            for line in proc.stderr:
                stderr_lines.append(line)

        t = threading.Thread(target=_drain, daemon=True)
        t.start()
        t0 = time.monotonic()
        why = None
        while proc.poll() is None:
            elapsed = time.monotonic() - t0
            backend_up = any("BACKEND_UP" in ln for ln in stderr_lines)
            if not backend_up and elapsed > backend_deadline:
                why = f"backend init made no progress in {backend_deadline}s"
                break
            if elapsed > hard_cap:
                why = f"exceeded hard cap {hard_cap}s"
                break
            time.sleep(2.0)
        if why is not None:
            proc.kill()
            proc.wait(timeout=30)
            print(f"train MFU bench skipped: {why}", file=sys.stderr)
            return None
        stdout = proc.stdout.read()
        t.join(timeout=10)
        if proc.returncode != 0:
            tail = "".join(stderr_lines)[-500:]
            print(
                f"train MFU bench skipped (rc={proc.returncode}): {tail}",
                file=sys.stderr,
            )
            return None
        return json.loads(stdout.strip().splitlines()[-1])
    except Exception as e:  # noqa: BLE001 - bench must still print its line
        print(f"train MFU bench skipped: {e!r}", file=sys.stderr)
        return None


def main() -> None:
    _try_build_fastwire()
    mfu = _try_train_mfu()
    # Ceiling probes BRACKET the native measurement: this host's loopback
    # throughput shifts regimes by tens of percent over minutes (observed
    # medians 2.0-3.2 GiB/s across one bench run), so a single probe can
    # land in a different regime than the stage it calibrates; the
    # bracket's mean is the fairest available denominator and its spread
    # is recorded so the ratio's noise is visible.
    def _ceiling_safe():
        try:
            return _loopback_ceiling()
        except Exception:  # noqa: BLE001 - diagnostic only
            return {"max": 0.0, "median": 0.0}

    ceiling_pre = _ceiling_safe()
    native = run_transport("tcp")
    baseline = run_transport("grpc")
    ceiling_post = _ceiling_safe()
    dma = _try_dma_transport()
    mids = [c["median"] for c in (ceiling_pre, ceiling_post) if c["median"]]
    ceiling = {
        "median": sum(mids) / len(mids) if mids else 0.0,
        "spread": mids,
    }
    result = {
        "metric": "2-party cross-party push throughput, 100MB float32 tensors",
        "value": round(native["max"], 3),
        "unit": "GB/s",
        "vs_baseline": round(native["max"] / baseline["max"], 3),
        "value_median": round(native["median"], 3),
        "baseline_grpc_cloudpickle_gbps": round(baseline["max"], 3),
        "rounds": ROUNDS,
        "payload_mb": PAYLOAD_MB,
    }
    if ceiling["median"]:
        # Medians on both sides: peak-of-reps is inflatable by the
        # parties' start-clock skew on short windows, the median is not.
        result["loopback_ceiling_gbps"] = round(ceiling["median"], 3)
        result["loopback_ceiling_spread"] = [
            round(x, 3) for x in ceiling["spread"]
        ]
        result["pct_of_ceiling"] = round(
            100.0 * native["median"] / ceiling["median"], 1
        )
    if dma:
        result["dma_cpu_gbps"] = round(dma, 3)
    if mfu:
        result.update(mfu)
    print(json.dumps(result))


if __name__ == "__main__":
    sys.exit(main())
