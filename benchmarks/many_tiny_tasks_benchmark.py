# Copyright 2026 The rayfed-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Per-task overhead micro-benchmark.

Capability parity: reference ``benchmarks/many_tiny_tasks_benchmark.py``
(2 parties, N rounds of inc + cross-party aggregate on trivial payloads,
prints per-task overhead). The reference's floor is Ray task submission +
actor hops + gRPC per round; ours is a thread-pool future plus one TCP
frame, so this number is where the Ray-free substrate shows up most.

Usage: python benchmarks/many_tiny_tasks_benchmark.py [rounds]
"""

from __future__ import annotations

import multiprocessing
import os
import socket
import sys
import time

# Runnable from a checkout without installation; executes in spawned party
# processes too (they re-import this module).
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _party_main(party, addresses, rounds, q):
    import rayfed_tpu as fed

    fed.init(
        addresses=addresses,
        party=party,
        config={
            "cross_silo_comm": {
                "retry_policy": {"max_attempts": 20, "initial_backoff_ms": 200}
            }
        },
        logging_level="error",
    )

    @fed.remote
    def inc(x):
        return x + 1

    @fed.remote
    def aggregate(a, b):
        return a + b

    # Warmup.
    fed.get(aggregate.party("alice").remote(
        inc.party("alice").remote(0), inc.party("bob").remote(0)))

    t0 = time.perf_counter()
    acc = 0
    for i in range(rounds):
        a = inc.party("alice").remote(acc)
        b = inc.party("bob").remote(acc)
        s = aggregate.party("alice").remote(a, b)
        acc = fed.get(s)
    dt = time.perf_counter() - t0
    # 3 fed tasks + 1 get per round (matches the reference's accounting).
    per_task_ms = dt / rounds / 3 * 1000
    if party == "alice":
        q.put({"rounds": rounds, "seconds": dt, "per_task_ms": per_task_ms})
        print(
            f"[{party}] {rounds} rounds in {dt:.2f}s -> "
            f"{per_task_ms:.3f} ms/task"
        )
    fed.shutdown()


def main(rounds: int = 1000) -> None:
    socks = [socket.socket() for _ in range(2)]
    for s in socks:
        s.bind(("127.0.0.1", 0))
    addresses = {
        "alice": f"127.0.0.1:{socks[0].getsockname()[1]}",
        "bob": f"127.0.0.1:{socks[1].getsockname()[1]}",
    }
    for s in socks:
        s.close()
    mp = multiprocessing.get_context("spawn")
    q = mp.Queue()
    procs = [
        mp.Process(target=_party_main, args=(p, addresses, rounds, q))
        for p in ("alice", "bob")
    ]
    for p in procs:
        p.start()
    for p in procs:
        p.join(timeout=600)
    assert all(p.exitcode == 0 for p in procs), [p.exitcode for p in procs]
    print(q.get(timeout=10))


if __name__ == "__main__":
    main(int(sys.argv[1]) if len(sys.argv) > 1 else 1000)
