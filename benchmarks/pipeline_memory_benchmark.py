# Copyright 2026 The rayfed-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""GPipe vs 1F1B: compiled peak temp memory and step time vs microbatch count.

The 1F1B schedule's reason to exist is its memory bound: in-flight
activations per stage stay O(stage depth) regardless of how many
microbatches fill the pipeline, while GPipe's autodiff-through-the-scan
keeps every microbatch's forward activations alive until its backward
runs — so GPipe's activation high-water grows linearly with the
microbatch count (``rayfed_tpu/parallel/pipeline.py:131-150``).

This benchmark turns that claim into numbers using XLA's own accounting:
``jit(...).lower(...).compile().memory_analysis().temp_size_in_bytes`` is
the compiled program's peak scratch (activation) memory, exact and
deterministic — no device allocator sampling, works identically on the
CPU-sim mesh and on TPU. Step wall time is measured too (CPU sim: treat
as smoke, not as a perf claim).

Usage: XLA_FLAGS=--xla_force_host_platform_device_count=8 \
       JAX_PLATFORMS=cpu python benchmarks/pipeline_memory_benchmark.py
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

if __name__ == "__main__":
    if "xla_force_host_platform_device_count" not in \
            os.environ.get("XLA_FLAGS", ""):
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=8"
        )
    # The axon plugin force-registers a TPU platform whenever
    # PALLAS_AXON_POOL_IPS is set, overriding JAX_PLATFORMS=cpu — and
    # backend init blocks indefinitely when the tunnel is down. This is
    # a CPU-sim benchmark; scrub the var AND pin the platform via config
    # (both needed — same recipe as tests/conftest).
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")


def run(n_stages=4, micro_counts=(4, 8, 16), d_model=64, n_layers=4,
        seq=64, vocab=256, steps=3):
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh

    from rayfed_tpu.models import transformer as tfm
    from rayfed_tpu.parallel.pipeline import (
        make_1f1b_loss_and_grad,
        make_pp_loss_fn,
        schedule_1f1b,
    )

    cfg = tfm.TransformerConfig(
        vocab=vocab, d_model=d_model, n_heads=4, n_layers=n_layers,
        d_ff=d_model * 4, compute_dtype=jnp.float32,
    )
    mesh = Mesh(np.array(jax.devices()[:n_stages]).reshape(n_stages),
                ("stage",))
    params = tfm.init_params(jax.random.PRNGKey(0), cfg)

    rows = []
    for m in micro_counts:
        batch = m  # one sequence per microbatch: isolate schedule memory
        tokens = jax.random.randint(
            jax.random.PRNGKey(1), (batch, seq + 1), 0, cfg.vocab
        )
        inputs, targets = tokens[:, :-1], tokens[:, 1:]

        def measure(fn):
            jitted = jax.jit(fn)
            compiled = jitted.lower(params, inputs, targets).compile()
            mem = compiled.memory_analysis()
            out = jitted(params, inputs, targets)  # warmup
            jax.block_until_ready(out)
            t0 = time.perf_counter()
            for _ in range(steps):
                out = jitted(params, inputs, targets)
            jax.block_until_ready(out)
            dt = (time.perf_counter() - t0) / steps
            return mem.temp_size_in_bytes, dt

        gpipe_mem, gpipe_dt = measure(
            jax.value_and_grad(make_pp_loss_fn(cfg, mesh, n_microbatches=m))
        )
        f1b_mem, f1b_dt = measure(
            make_1f1b_loss_and_grad(cfg, mesh, n_microbatches=m)
        )
        _, _, _, ring = schedule_1f1b(n_stages, m)
        rows.append({
            "micro": m,
            "gpipe_temp_mb": gpipe_mem / 2**20,
            "f1b_temp_mb": f1b_mem / 2**20,
            "ratio": gpipe_mem / f1b_mem,
            "ring": ring,
            "gpipe_ms": gpipe_dt * 1e3,
            "f1b_ms": f1b_dt * 1e3,
        })
        print(
            f"stages={n_stages} micro={m:3d}: "
            f"GPipe temp {rows[-1]['gpipe_temp_mb']:8.1f} MB, "
            f"1F1B temp {rows[-1]['f1b_temp_mb']:8.1f} MB "
            f"(ring={ring}), ratio {rows[-1]['ratio']:.2f}x | "
            f"step {rows[-1]['gpipe_ms']:.0f} / {rows[-1]['f1b_ms']:.0f} ms",
            flush=True,
        )
    return rows


if __name__ == "__main__":
    run()
