# Copyright 2026 The rayfed-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Cross-party push latency/throughput sweep, 1KB -> 100MB
(BASELINE.json config #2's full payload range).

Prints one line per size per transport: median round-trip of
produce-at-alice -> consume-at-bob, and effective GB/s for the large sizes.

Usage: python benchmarks/push_size_sweep.py [transports...]
"""

from __future__ import annotations

import json
import multiprocessing
import os
import socket
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

SIZES = [2**10, 2**14, 2**17, 2**20, 2**23, 10 * 2**20, 100 * 2**20]
REPS = {2**10: 50, 2**14: 50, 2**17: 30, 2**20: 20, 2**23: 10,
        10 * 2**20: 8, 100 * 2**20: 5}


def _party_main(party, addresses, transport, result_path):
    import numpy as np

    import rayfed_tpu as fed

    fed.init(
        addresses=addresses, party=party,
        config={"cross_silo_comm": {
            "retry_policy": {"max_attempts": 20, "initial_backoff_ms": 200}},
            "transport": transport},
        job_name=f"sweep-{transport}", logging_level="error",
    )

    @fed.remote
    def produce(nbytes, tag):
        return np.full((int(nbytes) // 4,), float(tag), dtype=np.float32)

    @fed.remote
    def consume(x):
        return float(x[-1])

    results = {}
    tag = 0.0
    for nbytes in SIZES:
        # Warmup.
        tag += 1
        assert fed.get(consume.party("bob").remote(
            produce.party("alice").remote(nbytes, tag))) == tag
        times = []
        for _ in range(REPS[nbytes]):
            tag += 1
            t0 = time.perf_counter()
            v = fed.get(consume.party("bob").remote(
                produce.party("alice").remote(nbytes, tag)))
            times.append(time.perf_counter() - t0)
            assert v == tag
        med = sorted(times)[len(times) // 2]
        results[nbytes] = {
            "median_ms": med * 1000,
            "gbps": nbytes / (1 << 30) / med,
        }
    if party == "bob":
        with open(result_path, "w") as f:
            json.dump(results, f)
    fed.shutdown()


def run(transport):
    socks = [socket.socket() for _ in range(2)]
    for s in socks:
        s.bind(("127.0.0.1", 0))
    addresses = {p: f"127.0.0.1:{s.getsockname()[1]}"
                 for p, s in zip(("alice", "bob"), socks)}
    for s in socks:
        s.close()
    mp = multiprocessing.get_context("spawn")
    with tempfile.TemporaryDirectory() as tmp:
        path = os.path.join(tmp, "r.json")
        procs = [mp.Process(target=_party_main,
                            args=(p, addresses, transport, path))
                 for p in ("alice", "bob")]
        for p in procs:
            p.start()
        for p in procs:
            p.join(timeout=600)
        for p in procs:
            if p.is_alive():
                p.terminate()
                raise RuntimeError("sweep party hung")
        with open(path) as f:
            return json.load(f)


def fmt_size(n):
    if n >= 2**20:
        return f"{n // 2**20}MB"
    return f"{n // 2**10}KB"


def main(transports):
    for transport in transports:
        results = run(transport)
        for nbytes in SIZES:
            r = results[str(nbytes)]
            line = (f"{transport:>5} {fmt_size(nbytes):>6}: "
                    f"{r['median_ms']:8.2f} ms median")
            if nbytes >= 2**20:
                line += f"  ({r['gbps']:.3f} GB/s)"
            print(line, flush=True)


if __name__ == "__main__":
    main(sys.argv[1:] or ["tcp", "grpc"])
