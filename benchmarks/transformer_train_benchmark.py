# Copyright 2026 The rayfed-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Flagship-model training throughput + MFU on the local accelerator.

Measures tokens/second and model-FLOPs utilization for the transformer LM
train step (bf16 compute, f32 params/optimizer) — the party-local compute
half of federated training, complementing the cross-party transport
benchmarks. On TPU the step uses the Pallas flash-attention kernel and
per-layer rematerialization by default.

Model FLOPs per token = 6*N + 12*L*d_model*S*0.5 (causal attention),
the standard accounting (PaLM appendix B convention). Peak chip FLOPs for
the MFU denominator comes from PEAK_TFLOPS (default 197, TPU v5e bf16).

Usage: python benchmarks/transformer_train_benchmark.py [d_model] [layers] [seq]
Env: REMAT=0/1 (default 1 on TPU), ATTN=auto|flash|xla, BATCH, STEPS,
PEAK_TFLOPS.
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


# The flagship measurement shape shared by bench.py's MFU stage and
# tools/mfu_tune.py — one source of truth so a committed tuning config
# and warmed compilation cache always describe the program bench.py
# actually measures.
FLAGSHIP = {"d_model": 2048, "n_layers": 12, "seq": 2048, "vocab": 32768}


def enable_compilation_cache():
    """Point JAX at the repo-local persistent compilation cache so the
    flagship step compiles once per (program, jaxlib, chip) ever — a
    driver/bench run on a warm cache skips the multi-minute XLA compile
    that previously ate the whole measurement budget (VERDICT r2 #1)."""
    import jax

    cache_dir = os.environ.get("JAX_COMPILATION_CACHE_DIR") or os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        ".jax_cache",
    )
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)


def run(d_model=512, n_layers=8, seq=1024, batch=8, steps=20, remat=None,
        attn="auto", peak_tflops=197.0, vocab=8192):
    enable_compilation_cache()

    import jax
    import numpy as np
    from jax.sharding import Mesh, NamedSharding

    from rayfed_tpu.models import transformer as tfm
    from rayfed_tpu.parallel import sharding as shd
    from rayfed_tpu.parallel.train import make_fed_train_step

    from rayfed_tpu.utils import is_tpu_backend

    on_tpu = is_tpu_backend()
    # Progress marker: a supervising process (bench.py's watchdog) reads
    # this to distinguish "wedged accelerator" from "long XLA compile".
    print(f"BACKEND_UP {jax.default_backend()}", flush=True)
    if remat is None:
        remat = on_tpu  # memory-for-FLOPs is the right default on the chip

    # head_dim 128 fills the TPU's 128-lane tiling exactly — head_dim 64
    # arrays get lane-padded 2x in HBM (memory AND bandwidth waste).
    cfg = tfm.TransformerConfig(
        vocab=vocab, d_model=d_model, n_heads=max(2, d_model // 128),
        n_layers=n_layers, d_ff=int(d_model * 2.75) // 16 * 16,
    )
    devices = jax.devices()
    mesh = Mesh(np.array(devices).reshape(len(devices)), ("data",))
    init_fn, step_fn = make_fed_train_step(
        cfg, mesh, party_axis=None, data_axis="data", lr=1e-3, remat=remat,
        attn=attn,
    )
    tokens = jax.random.randint(
        jax.random.PRNGKey(0), (batch, seq + 1), 0, cfg.vocab
    )
    sharding = NamedSharding(mesh, shd.batch_spec(mesh, party_axis=None))
    inputs = jax.device_put(tokens[:, :-1], sharding)
    targets = jax.device_put(tokens[:, 1:], sharding)
    params, opt_state = init_fn(jax.random.PRNGKey(0), inputs)

    n_params = sum(x.size for x in jax.tree_util.tree_leaves(params))
    # PaLM appendix-B convention: the embedding table is a gather, not a
    # matmul — excluded from the 6N FLOPs term (lm_head stays in).
    n_matmul_params = n_params - params["embed"].size
    # Warmup/compile.
    t_c = time.perf_counter()
    params, opt_state, loss = step_fn(params, opt_state, inputs, targets)
    float(loss)
    print(f"COMPILED {time.perf_counter() - t_c:.1f}s", flush=True)
    t0 = time.perf_counter()
    for _ in range(steps):
        params, opt_state, loss = step_fn(params, opt_state, inputs, targets)
    loss = float(loss)
    dt = time.perf_counter() - t0
    tok_s = steps * batch * seq / dt
    # 6N covers fwd+bwd matmuls on the params; the attention term is
    # 12*L*d*S per token halved for causality.
    flops_per_token = 6 * n_matmul_params + 12 * n_layers * d_model * seq * 0.5
    mfu = tok_s * flops_per_token / (peak_tflops * 1e12 * len(devices))
    result = {
        "backend": jax.default_backend(),
        "devices": len(devices),
        "n_params": n_params,
        "batch": batch,
        "seq": seq,
        "remat": bool(remat),
        "attn": attn,
        "tokens_per_s": tok_s,
        "ms_per_step": dt / steps * 1000,
        "mfu": mfu,
        "peak_tflops": peak_tflops,
        "loss": loss,
    }
    print(
        f"{result['backend']} x{result['devices']}: {n_params/1e6:.1f}M params, "
        f"batch {batch} x seq {seq} (attn={attn}, remat={remat}): "
        f"{tok_s:,.0f} tokens/s ({result['ms_per_step']:.1f} ms/step), "
        f"MFU {mfu*100:.1f}% (peak {peak_tflops} TF/chip), loss {loss:.3f}"
    )
    return result


def main():
    args = [int(a) for a in sys.argv[1:4]]
    remat_env = os.environ.get("REMAT")
    # REMAT accepts 0/1/attn: "attn" = checkpoint layers but save each
    # layer's attention output, so the backward never re-runs the flash
    # kernel (see transformer.hidden_states).
    if remat_env is None:
        remat = None
    elif remat_env == "attn":
        remat = "attn"
    else:
        remat = remat_env == "1"
    run(
        *args,
        batch=int(os.environ.get("BATCH", 8)),
        steps=int(os.environ.get("STEPS", 20)),
        remat=remat,
        attn=os.environ.get("ATTN", "auto"),
        peak_tflops=float(os.environ.get("PEAK_TFLOPS", 197.0)),
        vocab=int(os.environ.get("VOCAB", 8192)),
    )


if __name__ == "__main__":
    main()
