"""Flagship-model training throughput on the local accelerator.

Measures tokens/second for the transformer LM train step (bf16 compute,
f32 params/optimizer) at a configurable size — the party-local compute
half of federated training, complementing the cross-party transport
benchmarks.

Usage: python benchmarks/transformer_train_benchmark.py [d_model] [layers] [seq]
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(d_model=512, n_layers=8, seq=1024, batch=8, steps=20, remat=False):
    import jax
    import numpy as np
    from jax.sharding import Mesh, NamedSharding

    from rayfed_tpu.models import transformer as tfm
    from rayfed_tpu.parallel import sharding as shd
    from rayfed_tpu.parallel.train import make_fed_train_step

    cfg = tfm.TransformerConfig(
        vocab=8192, d_model=d_model, n_heads=max(4, d_model // 64),
        n_layers=n_layers, d_ff=int(d_model * 2.75) // 16 * 16,
    )
    devices = jax.devices()
    mesh = Mesh(np.array(devices).reshape(len(devices)), ("data",))
    init_fn, step_fn = make_fed_train_step(
        cfg, mesh, party_axis=None, data_axis="data", lr=1e-3, remat=remat
    )
    tokens = jax.random.randint(
        jax.random.PRNGKey(0), (batch, seq + 1), 0, cfg.vocab
    )
    sharding = NamedSharding(mesh, shd.batch_spec(mesh, party_axis=None))
    inputs = jax.device_put(tokens[:, :-1], sharding)
    targets = jax.device_put(tokens[:, 1:], sharding)
    params, opt_state = init_fn(jax.random.PRNGKey(0), inputs)

    n_params = sum(x.size for x in jax.tree_util.tree_leaves(params))
    # Warmup/compile.
    params, opt_state, loss = step_fn(params, opt_state, inputs, targets)
    float(loss)
    t0 = time.perf_counter()
    for _ in range(steps):
        params, opt_state, loss = step_fn(params, opt_state, inputs, targets)
    loss = float(loss)
    dt = time.perf_counter() - t0
    tok_s = steps * batch * seq / dt
    print(
        f"{jax.default_backend()} x{len(devices)}: {n_params/1e6:.1f}M params, "
        f"batch {batch} x seq {seq}: {tok_s:,.0f} tokens/s "
        f"({dt/steps*1000:.1f} ms/step), loss {loss:.3f}"
    )


if __name__ == "__main__":
    args = [int(a) for a in sys.argv[1:4]]
    main(*args, remat=os.environ.get("REMAT", "0") == "1")
