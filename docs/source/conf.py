# Copyright 2026 The rayfed-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Sphinx configuration (capability parity: reference
``docs/source/conf.py``). The hand-written guides live as Markdown one
level up (``docs/*.md``); this tree renders them via myst-parser plus
autodoc API pages. Build: ``pip install sphinx myst-parser &&
sphinx-build -b html docs/source docs/_build``."""

import os
import sys

sys.path.insert(0, os.path.abspath(os.path.join("..", "..")))

project = "rayfed-tpu"
copyright = "2026, The rayfed-tpu Authors"
author = "The rayfed-tpu Authors"
release = "0.1.0"

extensions = [
    "sphinx.ext.autodoc",
    "sphinx.ext.napoleon",
    "sphinx.ext.viewcode",
    "myst_parser",
]

# The markdown guides live in docs/ (one level above this source tree);
# include them without duplication.
import shutil  # noqa: E402

_here = os.path.dirname(os.path.abspath(__file__))
_guides = os.path.join(_here, "guides")
# Rebuild the staging dir from scratch (gitignored): a stale copy of a
# renamed/deleted guide must not keep rendering.
shutil.rmtree(_guides, ignore_errors=True)
os.makedirs(_guides)
for _name in os.listdir(os.path.join(_here, "..")):
    if _name.endswith(".md"):
        shutil.copy(os.path.join(_here, "..", _name),
                    os.path.join(_guides, _name))

source_suffix = {".rst": "restructuredtext", ".md": "markdown"}
master_doc = "index"
exclude_patterns = ["_build"]
html_theme = "alabaster"
