# Copyright 2026 The rayfed-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""2-party federated CNN training at CIFAR-10 shapes (BASELINE config #5).

Run the SAME script once per party (different machines or terminals):

    python examples/fedavg_cnn.py alice 127.0.0.1:9103 127.0.0.1:9104
    python examples/fedavg_cnn.py bob   127.0.0.1:9103 127.0.0.1:9104

Each party holds a disjoint shard of (synthetic) 32x32x3 images and
trains the shared convnet locally on its own devices; per-round weight
aggregation crosses the wire on the zero-pickle push lane and is averaged
by a jitted deterministic tree-mean, weighted by per-party sample counts
— so both parties print identical digests.
"""

import sys

import numpy as np

import rayfed_tpu as fed
from rayfed_tpu.federated import FedAvgTrainer

CLASSES, BATCH, LOCAL_STEPS, ROUNDS = 10, 64, 3, 4
SHARD = {"alice": 640, "bob": 384}  # unequal shards: exercises weighting


@fed.remote
class CnnWorker:
    def __init__(self, party, seed):
        import jax

        from rayfed_tpu.models.cnn import cnn_loss, init_cnn

        self.params = init_cnn(jax.random.PRNGKey(0), num_classes=CLASSES)
        rng = np.random.default_rng(seed)
        n = SHARD[party]
        self.x = rng.normal(size=(n, 32, 32, 3)).astype(np.float32)
        self.y = rng.integers(0, CLASSES, size=(n,))
        self._n = n
        self._i = 0

        def step(params, x, y):
            loss, grads = jax.value_and_grad(cnn_loss)(params, x, y)
            return jax.tree_util.tree_map(
                lambda p, g: p - 0.05 * g, params, grads
            ), loss

        self._step = jax.jit(step)

    def train(self, global_params):
        if global_params is not None:
            self.params = global_params
        for _ in range(LOCAL_STEPS):
            lo = self._i % (self._n - BATCH + 1)
            self.params, loss = self._step(
                self.params, self.x[lo: lo + BATCH], self.y[lo: lo + BATCH]
            )
            self._i += BATCH
        self._last_loss = float(loss)
        return self.params

    def num_samples(self):
        return float(self._n)

    def loss(self):
        return self._last_loss


def main():
    party, addr_a, addr_b = sys.argv[1], sys.argv[2], sys.argv[3]
    fed.init(
        addresses={"alice": addr_a, "bob": addr_b},
        party=party,
        config={
            "cross_silo_comm": {
                "retry_policy": {"max_attempts": 30, "initial_backoff_ms": 500}
            }
        },
    )
    trainer = FedAvgTrainer(
        CnnWorker, ["alice", "bob"],
        worker_args={"alice": ("alice", 1), "bob": ("bob", 2)},
        op="wmean",
        weights={p: float(n) for p, n in SHARD.items()},
    )
    final = fed.get(trainer.run(ROUNDS))
    digest = float(np.asarray(final["convs"][0]["w"]).sum())
    my_loss = fed.get(trainer.workers[party].loss.remote())
    print(f"[{party}] final conv0 digest {digest:.6f}, local loss {my_loss:.4f}")
    fed.shutdown()


if __name__ == "__main__":
    main()
