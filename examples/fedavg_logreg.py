# Copyright 2026 The rayfed-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""2-party FedAvg logistic regression at MNIST shapes (BASELINE config #3).

Run the SAME script once per party (different machines or terminals):

    python examples/fedavg_logreg.py alice 127.0.0.1:9101 127.0.0.1:9102
    python examples/fedavg_logreg.py bob   127.0.0.1:9101 127.0.0.1:9102

Each party trains on its own synthetic data shard on its local devices;
weights cross via the zero-pickle push lane; aggregation is a jitted
deterministic tree-mean, so both parties print identical digests.
"""

import sys

import numpy as np

import rayfed_tpu as fed
from rayfed_tpu.federated import FedAvgTrainer

DIM, CLASSES, BATCH, ROUNDS = 784, 10, 128, 5


@fed.remote
class LogRegWorker:
    def __init__(self, seed):
        import jax

        from rayfed_tpu.models.mlp import init_logreg, logreg_loss

        self.params = init_logreg(jax.random.PRNGKey(0), DIM, CLASSES)
        rng = np.random.default_rng(seed)
        self.x = rng.normal(size=(BATCH, DIM)).astype(np.float32)
        self.y = rng.integers(0, CLASSES, size=(BATCH,))

        def step(params, x, y):
            loss, grads = jax.value_and_grad(logreg_loss)(params, x, y)
            return jax.tree_util.tree_map(
                lambda p, g: p - 0.1 * g, params, grads
            ), loss

        self._step = jax.jit(step)

    def train(self, global_params):
        if global_params is not None:
            self.params = global_params
        for _ in range(3):  # local epochs
            self.params, loss = self._step(self.params, self.x, self.y)
        self._last_loss = float(loss)
        return self.params

    def loss(self):
        return self._last_loss


def main():
    party, addr_a, addr_b = sys.argv[1], sys.argv[2], sys.argv[3]
    fed.init(
        addresses={"alice": addr_a, "bob": addr_b},
        party=party,
        config={
            "cross_silo_comm": {
                "retry_policy": {"max_attempts": 30, "initial_backoff_ms": 500}
            }
        },
    )
    trainer = FedAvgTrainer(
        LogRegWorker, ["alice", "bob"],
        worker_args={"alice": (1,), "bob": (2,)},
    )
    final = fed.get(trainer.run(ROUNDS))
    digest = np.asarray(final["w"]).sum()
    my_loss = fed.get(trainer.workers[party].loss.remote())
    print(f"[{party}] final weight digest {digest:.6f}, local loss {my_loss:.4f}")
    fed.shutdown()


if __name__ == "__main__":
    main()
