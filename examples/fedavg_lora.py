# Copyright 2026 The rayfed-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Federated LoRA fine-tuning: only adapter trees cross the wire.

Every party holds the same frozen base LM (distributed once out-of-band)
and fine-tunes low-rank adapters on its private tokens; the FedAvg round
aggregates just the A/B matrices — orders of magnitude smaller than the
base weights (the ratio printed per round is derived from
`rayfed_tpu.models.lora.lora_nbytes`, the adapter's byte size).
The merged model is identical in every party after each round.

    python examples/fedavg_lora.py alice 127.0.0.1:9131 127.0.0.1:9132
    python examples/fedavg_lora.py bob   127.0.0.1:9131 127.0.0.1:9132
"""

import sys

import numpy as np

import rayfed_tpu as fed
from rayfed_tpu.federated import fed_aggregate

ROUNDS = 2


@fed.remote
class LoraWorker:
    def __init__(self, seed):
        import jax

        from rayfed_tpu.models import lora, transformer as tfm

        self.lora = lora
        self.cfg = tfm.tiny_config(vocab=512, d_model=128, n_heads=4,
                                   n_layers=2, d_ff=352)
        # Same base everywhere (same seed); private tokens per party.
        self.params = tfm.init_params(jax.random.PRNGKey(0), self.cfg)
        self.ad = lora.init_lora(jax.random.PRNGKey(1), self.cfg, rank=8)
        tok = jax.random.randint(
            jax.random.PRNGKey(seed), (8, 65), 0, self.cfg.vocab
        )
        self.inputs, self.targets = tok[:, :-1], tok[:, 1:]
        self.step, optimizer = lora.make_lora_train_step(self.cfg, lr=1e-2)
        self.opt = optimizer.init(self.ad["layers"])

    def train(self, global_ab):
        import jax

        if global_ab is not None:
            self.ad = {**self.ad, "layers": global_ab}
        for _ in range(3):  # local steps between aggregation rounds
            self.ad, self.opt, loss = self.step(
                self.params, self.ad, self.opt, self.inputs, self.targets
            )
        self._loss = float(loss)
        return jax.tree_util.tree_map(np.asarray, self.ad["layers"])

    def report(self, global_ab):
        """Loss + merged-model digest (must match across parties)."""
        import jax

        merged = self.lora.merge_lora(
            self.params, {**self.ad, "layers": global_ab}
        )
        digest = float(sum(
            np.asarray(x).astype(np.float64).sum()
            for x in jax.tree_util.tree_leaves(merged)
        ))
        base = sum(x.size * x.dtype.itemsize
                   for x in jax.tree_util.tree_leaves(self.params))
        pushed = self.lora.lora_nbytes({"layers": global_ab})
        return self._loss, digest, base / max(pushed, 1)


def main():
    party, addr_a, addr_b = sys.argv[1], sys.argv[2], sys.argv[3]
    fed.init(
        addresses={"alice": addr_a, "bob": addr_b},
        party=party,
        config={"cross_silo_comm": {
            "retry_policy": {"max_attempts": 30, "initial_backoff_ms": 500}
        }},
    )
    wa = LoraWorker.party("alice").remote(11)
    wb = LoraWorker.party("bob").remote(22)
    g = None
    for rnd in range(ROUNDS):
        g = fed_aggregate(
            {"alice": wa.train.remote(g), "bob": wb.train.remote(g)},
            op="mean",
        )
        # Multi-controller rule: every party issues the SAME calls (the
        # deterministic seq-id DAG requires identical traces) — both
        # reports are requested everywhere, each party prints its own.
        ra, rb = wa.report.remote(g), wb.report.remote(g)
        (loss_a, dig_a, ratio), (loss_b, dig_b, _) = fed.get([ra, rb])
        # Same-platform runs produce bitwise-equal digests; across
        # heterogeneous hardware XLA codegen differs in low-order bits,
        # so compare with a tolerance.
        assert abs(dig_a - dig_b) <= 1e-6 * max(1.0, abs(dig_a)), (
            f"merged models diverged: {dig_a} != {dig_b}")
        loss = loss_a if party == "alice" else loss_b
        print(f"[{party}] round {rnd}: loss {loss:.4f} "
              f"(pushes {ratio:.0f}x smaller than full weights)")
    print(f"[{party}] merged-model digest identical in both parties")
    fed.shutdown()


if __name__ == "__main__":
    main()
