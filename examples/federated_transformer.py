# Copyright 2026 The rayfed-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Federated LM training: each party runs a dp/tp(/sp)-sharded train step
on its own device mesh; weight trees cross per round via the push lane.

Run once per party (CPU simulation shown; on TPU hosts drop the env vars):

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/federated_transformer.py alice 127.0.0.1:9111 127.0.0.1:9112
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/federated_transformer.py bob 127.0.0.1:9111 127.0.0.1:9112
"""

import sys

import numpy as np

import rayfed_tpu as fed
from rayfed_tpu.federated import fed_aggregate

ROUNDS = 3


@fed.remote
class LmWorker:
    def __init__(self, seed):
        import jax
        from jax.sharding import Mesh, NamedSharding

        from rayfed_tpu.models import transformer as tfm
        from rayfed_tpu.parallel import sharding as shd
        from rayfed_tpu.parallel.train import make_fed_train_step

        self.cfg = tfm.tiny_config(vocab=512, d_model=128, n_heads=4,
                                   n_layers=2, d_ff=352)
        # Party-local mesh: all local devices, data x model.
        n = jax.device_count()
        model_par = 2 if n % 2 == 0 else 1
        mesh = Mesh(
            np.array(jax.devices()).reshape(n // model_par, model_par),
            ("data", "model"),
        )
        # donate=False: this worker RETURNS self.params each round for
        # local aggregation (fed_aggregate consumes it in-party by
        # reference); donating those buffers into the next step would
        # invalidate them under the consumer (see make_fed_train_step).
        # fedlint FED003 (donation-aliasing) flags the donate=True
        # variant of this pattern — docs/fedlint.md.
        self._init_fn, self._step_fn = make_fed_train_step(
            self.cfg, mesh, party_axis=None, lr=1e-2, donate=False
        )
        rng = np.random.default_rng(seed)
        tokens = rng.integers(0, self.cfg.vocab, size=(8, 65))
        sharding = NamedSharding(mesh, shd.batch_spec(mesh, party_axis=None))
        self.inputs = jax.device_put(tokens[:, :-1], sharding)
        self.targets = jax.device_put(tokens[:, 1:], sharding)
        import jax.random as jrandom

        self.params, self.opt_state = self._init_fn(
            jrandom.PRNGKey(0), self.inputs
        )

    def train(self, global_params):
        if global_params is not None:
            import jax

            self.params = jax.tree_util.tree_map(
                lambda old, new: jax.device_put(new, old.sharding),
                self.params, global_params,
            )
        self.params, self.opt_state, loss = self._step_fn(
            self.params, self.opt_state, self.inputs, self.targets
        )
        self._loss = float(loss)
        return self.params

    def loss(self):
        return self._loss


def main():
    party, addr_a, addr_b = sys.argv[1], sys.argv[2], sys.argv[3]
    fed.init(
        addresses={"alice": addr_a, "bob": addr_b},
        party=party,
        config={
            "cross_silo_comm": {
                "retry_policy": {"max_attempts": 30, "initial_backoff_ms": 500}
            }
        },
    )
    workers = {p: LmWorker.party(p).remote(seed=i)
               for i, p in enumerate(["alice", "bob"])}
    global_params = None
    for r in range(ROUNDS):
        locals_ = {p: workers[p].train.remote(global_params)
                   for p in workers}
        global_params = fed_aggregate(locals_, op="mean")
        my_loss = fed.get(workers[party].loss.remote())
        print(f"[{party}] round {r}: local loss {my_loss:.4f}")
    final = fed.get(global_params)
    digest = float(sum(np.asarray(x).sum() for x in
                       __import__("jax").tree_util.tree_leaves(final)))
    print(f"[{party}] final aggregate digest {digest:.6f}")
    fed.shutdown()


if __name__ == "__main__":
    main()
