# Copyright 2026 The rayfed-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Split learning: alice holds the feature extractor + raw data, bob holds
the head + labels. Only activations and activation-gradients cross the
boundary — both as ordinary owner-pushes.

    python examples/split_learning.py alice 127.0.0.1:9121 127.0.0.1:9122
    python examples/split_learning.py bob   127.0.0.1:9121 127.0.0.1:9122
"""

import sys

import numpy as np

import rayfed_tpu as fed

STEPS = 10


@fed.remote
class Bottom:
    def __init__(self):
        import jax
        import jax.numpy as jnp

        rng = np.random.default_rng(0)
        self.x = jnp.asarray(rng.normal(size=(64, 32)).astype(np.float32))
        self.w = jnp.asarray(
            rng.normal(size=(32, 16)).astype(np.float32) * 0.1
        )
        self._fwd = jax.jit(lambda x, w: jax.nn.tanh(x @ w))

        def bwd(x, w, h, gh):
            gz = gh * (1 - h**2)  # tanh'
            return w - 0.1 * (x.T @ gz) / x.shape[0]

        self._bwd = jax.jit(bwd)

    def forward(self):
        self.h = self._fwd(self.x, self.w)
        return self.h

    def backward(self, grad_h):
        self.w = self._bwd(self.x, self.w, self.h, grad_h)


@fed.remote
class Head:
    def __init__(self):
        import jax
        import jax.numpy as jnp

        rng = np.random.default_rng(1)
        self.wh = jnp.asarray(rng.normal(size=(16, 1)).astype(np.float32) * 0.1)
        self.y = jnp.asarray(rng.normal(size=(64, 1)).astype(np.float32))

        def step(wh, h, y):
            def loss_fn(wh, h):
                return ((h @ wh - y) ** 2).mean()

            loss, (gwh, gh) = jax.value_and_grad(
                lambda wh, h: loss_fn(wh, h), argnums=(0, 1)
            )(wh, h)
            return wh - 0.1 * gwh, gh, loss

        self._step = jax.jit(step)

    def step(self, h):
        self.wh, grad_h, loss = self._step(self.wh, h, self.y)
        self.loss = float(loss)
        return grad_h

    def get_loss(self):
        return self.loss


def main():
    party, addr_a, addr_b = sys.argv[1], sys.argv[2], sys.argv[3]
    fed.init(
        addresses={"alice": addr_a, "bob": addr_b},
        party=party,
        config={
            "cross_silo_comm": {
                "retry_policy": {"max_attempts": 30, "initial_backoff_ms": 500}
            }
        },
    )
    bottom = Bottom.party("alice").remote()
    head = Head.party("bob").remote()
    first = last = None
    for step in range(STEPS):
        h = bottom.forward.remote()
        grad_h = head.step.remote(h)
        bottom.backward.remote(grad_h)
        loss = fed.get(head.get_loss.remote())
        print(f"[{party}] step {step}: loss {loss:.5f}")
        first = loss if first is None else first
        last = loss
    assert last < first, f"loss did not decrease: {first} -> {last}"
    fed.shutdown()


if __name__ == "__main__":
    main()
