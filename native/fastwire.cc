/* fastwire (C++): GIL-released socket IO for the FTP1 data plane.
 *
 * The role the reference delegates to native dependencies (Ray's C++ core
 * and gRPC's C-core move its bytes; SURVEY.md C14/§2) is filled here by a
 * small CPython extension: vectored sends (writev) of header+payload in one
 * syscall batch and exact-length receives, both with the GIL released and
 * poll()-based timeouts compatible with Python socket timeout semantics
 * (Python puts timed sockets in non-blocking mode, so EAGAIN must poll).
 *
 * Plaintext sockets only — TLS connections stay on the Python ssl path.
 */

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <errno.h>
#include <poll.h>
#include <string.h>
#include <sys/socket.h>
#include <sys/uio.h>

#define MAX_IOV 64

/* Wait for the fd to become ready; returns 0 ok, -1 timeout, errno>0 error. */
static int wait_fd(int fd, short events, long timeout_ms) {
    struct pollfd pfd = {fd, events, 0};
    for (;;) {
        int rc = poll(&pfd, 1, timeout_ms < 0 ? -1 : (int)timeout_ms);
        if (rc > 0) return 0;
        if (rc == 0) return -1;
        if (errno == EINTR) continue;
        return errno;
    }
}

/* sendv(fd, timeout_ms, buffers_sequence) -> None
 * Sends every buffer fully, in order, via writev. */
static PyObject *fastwire_sendv(PyObject *self, PyObject *args) {
    int fd;
    long timeout_ms;
    PyObject *seq;
    if (!PyArg_ParseTuple(args, "ilO", &fd, &timeout_ms, &seq))
        return NULL;

    PyObject *fast = PySequence_Fast(seq, "buffers must be a sequence");
    if (!fast) return NULL;
    Py_ssize_t n = PySequence_Fast_GET_SIZE(fast);
    if (n > MAX_IOV) {
        Py_DECREF(fast);
        PyErr_Format(PyExc_ValueError, "too many buffers (%zd > %d)", n,
                     MAX_IOV);
        return NULL;
    }

    Py_buffer views[MAX_IOV];
    struct iovec iov[MAX_IOV];
    Py_ssize_t nviews = 0;
    size_t total = 0;
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject *item = PySequence_Fast_GET_ITEM(fast, i);
        if (PyObject_GetBuffer(item, &views[nviews], PyBUF_C_CONTIGUOUS) < 0) {
            for (Py_ssize_t j = 0; j < nviews; j++) PyBuffer_Release(&views[j]);
            Py_DECREF(fast);
            return NULL;
        }
        iov[nviews].iov_base = views[nviews].buf;
        iov[nviews].iov_len = (size_t)views[nviews].len;
        total += (size_t)views[nviews].len;
        nviews++;
    }

    int err = 0;        /* errno, or -1 for poll timeout */
    size_t sent = 0;
    Py_BEGIN_ALLOW_THREADS;
    int first = 0;
    while (sent < total) {
        while (first < nviews && iov[first].iov_len == 0) first++;
        ssize_t rc = writev(fd, &iov[first], (int)(nviews - first));
        if (rc < 0) {
            if (errno == EINTR) continue;
            if (errno == EAGAIN || errno == EWOULDBLOCK) {
                int w = wait_fd(fd, POLLOUT, timeout_ms);
                if (w == 0) continue;
                err = (w == -1) ? -1 : w;
                break;
            }
            err = errno;
            break;
        }
        sent += (size_t)rc;
        size_t done = (size_t)rc;
        while (done > 0 && first < nviews) {
            if (done >= iov[first].iov_len) {
                done -= iov[first].iov_len;
                iov[first].iov_len = 0;
                first++;
            } else {
                iov[first].iov_base = (char *)iov[first].iov_base + done;
                iov[first].iov_len -= done;
                done = 0;
            }
        }
    }
    Py_END_ALLOW_THREADS;

    for (Py_ssize_t j = 0; j < nviews; j++) PyBuffer_Release(&views[j]);
    Py_DECREF(fast);

    if (err == -1) {
        PyErr_SetString(PyExc_TimeoutError, "fastwire send timed out");
        return NULL;
    }
    if (err != 0) {
        errno = err;
        return PyErr_SetFromErrno(PyExc_OSError);
    }
    Py_RETURN_NONE;
}

/* recv_exact(fd, timeout_ms, writable_buffer) -> None
 * Fills the buffer completely or raises (ConnectionError on EOF). */
static PyObject *fastwire_recv_exact(PyObject *self, PyObject *args) {
    int fd;
    long timeout_ms;
    Py_buffer buf;
    if (!PyArg_ParseTuple(args, "ilw*", &fd, &timeout_ms, &buf))
        return NULL;

    int err = 0;  /* errno, -1 poll timeout, -2 EOF */
    Py_BEGIN_ALLOW_THREADS;
    char *p = (char *)buf.buf;
    size_t remaining = (size_t)buf.len;
    while (remaining > 0) {
        ssize_t rc = recv(fd, p, remaining, 0);
        if (rc > 0) {
            p += rc;
            remaining -= (size_t)rc;
            continue;
        }
        if (rc == 0) {
            err = -2;
            break;
        }
        if (errno == EINTR) continue;
        if (errno == EAGAIN || errno == EWOULDBLOCK) {
            int w = wait_fd(fd, POLLIN, timeout_ms);
            if (w == 0) continue;
            err = (w == -1) ? -1 : w;
            break;
        }
        err = errno;
        break;
    }
    Py_END_ALLOW_THREADS;
    PyBuffer_Release(&buf);

    if (err == -2) {
        PyErr_SetString(PyExc_ConnectionError,
                        "peer closed connection mid-frame");
        return NULL;
    }
    if (err == -1) {
        PyErr_SetString(PyExc_TimeoutError, "fastwire recv timed out");
        return NULL;
    }
    if (err != 0) {
        errno = err;
        return PyErr_SetFromErrno(PyExc_OSError);
    }
    Py_RETURN_NONE;
}

static PyMethodDef fastwire_methods[] = {
    {"sendv", fastwire_sendv, METH_VARARGS,
     "sendv(fd, timeout_ms, buffers): fully send all buffers via writev."},
    {"recv_exact", fastwire_recv_exact, METH_VARARGS,
     "recv_exact(fd, timeout_ms, buffer): fill the writable buffer."},
    {NULL, NULL, 0, NULL},
};

static struct PyModuleDef fastwire_module = {
    PyModuleDef_HEAD_INIT, "_fastwire",
    "GIL-released vectored socket IO for the rayfed_tpu data plane.", -1,
    fastwire_methods,
};

PyMODINIT_FUNC PyInit__fastwire(void) {
    return PyModule_Create(&fastwire_module);
}
