/* fastwire (C++): the native data-plane engine for the FTP1 wire protocol.
 *
 * The role the reference delegates to native dependencies (Ray's C++ core
 * and gRPC's C-core move its bytes; SURVEY.md C14/§2, ref
 * fed/proxy/grpc/grpc_proxy.py:23) is filled here by a CPython extension
 * that owns the hot receive path end-to-end:
 *
 *   - sendv:              vectored header+payload sends (writev), GIL off
 *   - recv_exact:         exact-length receive into a caller buffer
 *   - recv_prefix_header: frame prefix + header in ONE GIL-released
 *                         window, with magic/version/size-cap validation
 *                         BEFORE any allocation
 *   - recv_scatter:       the whole payload scatter-read into C-pooled
 *                         buffers via readv — one GIL window, one
 *                         syscall batch across segment boundaries
 *   - a C-side buffer pool (PooledBuf) recycling large receive blocks
 *     across frames, replacing the Python-side refcount-scanning pool on
 *     the native path (fresh 100MB allocations cost page faults + munmap
 *     per frame; the pool makes steady-state receives allocation-free)
 *
 * Plaintext sockets only — TLS connections stay on the Python ssl path.
 */

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <errno.h>
#include <fcntl.h>
#include <poll.h>
#include <stdint.h>
#include <stdlib.h>
#include <string.h>
#include <sys/epoll.h>
#include <sys/mman.h>
#include <sys/socket.h>
#include <sys/stat.h>
#include <sys/uio.h>
#include <unistd.h>

#include <mutex>
#include <vector>

#define MAX_IOV 64

/* ------------------------------------------------------------------ */
/* fd polling                                                          */
/* ------------------------------------------------------------------ */

/* Wait for the fd to become ready; returns 0 ok, -1 timeout, errno>0 error. */
static int wait_fd(int fd, short events, long timeout_ms) {
    struct pollfd pfd = {fd, events, 0};
    for (;;) {
        int rc = poll(&pfd, 1, timeout_ms < 0 ? -1 : (int)timeout_ms);
        if (rc > 0) return 0;
        if (rc == 0) return -1;
        if (errno == EINTR) continue;
        return errno;
    }
}

/* Receive exactly n bytes into p. Returns 0 ok, -1 timeout, -2 EOF,
 * errno>0 error. Caller must NOT hold the GIL. */
static int recv_all(int fd, char *p, size_t n, long timeout_ms) {
    while (n > 0) {
        ssize_t rc = recv(fd, p, n, 0);
        if (rc > 0) {
            p += rc;
            n -= (size_t)rc;
            continue;
        }
        if (rc == 0) return -2;
        if (errno == EINTR) continue;
        if (errno == EAGAIN || errno == EWOULDBLOCK) {
            int w = wait_fd(fd, POLLIN, timeout_ms);
            if (w == 0) continue;
            return w;
        }
        return errno;
    }
    return 0;
}

/* Raise the Python exception matching a recv_all/err code. */
static PyObject *raise_io(int err, const char *what) {
    if (err == -2) {
        PyErr_Format(PyExc_ConnectionError,
                     "peer closed connection mid-%s", what);
        return NULL;
    }
    if (err == -1) {
        PyErr_Format(PyExc_TimeoutError, "fastwire %s timed out", what);
        return NULL;
    }
    errno = err;
    return PyErr_SetFromErrno(PyExc_OSError);
}

/* ------------------------------------------------------------------ */
/* C-side buffer pool                                                  */
/* ------------------------------------------------------------------ */

struct Block {
    char *p;
    size_t size;
};

/* Free blocks, oldest first; total tracks free bytes only (a block handed
 * to a PooledBuf is accounted by that object, not the pool). All pool
 * state is touched with the GIL held (take/put run from Python-visible
 * entry/exit points), so the mutex guards only against future no-GIL
 * builds and direct C callers. */
static std::mutex pool_mu;
static std::vector<Block> pool_blocks;
static size_t pool_free_bytes = 0;
static size_t pool_cap = (size_t)2 << 30; /* overridden at module init */
static const size_t POOL_MIN = (size_t)1 << 20;
static const size_t POOL_ALIGN = 64;

static char *block_alloc(size_t n) {
    void *p = NULL;
    if (posix_memalign(&p, POOL_ALIGN, n) != 0) return NULL;
    return (char *)p;
}

/* Best-fit take: smallest free block with n <= size <= 4n (a huge block
 * must not be burned on a small frame). Returns {NULL, n} when the pool
 * has no candidate and the caller should allocate. */
static Block pool_take(size_t n) {
    Block out = {NULL, n};
    if (n < POOL_MIN || pool_cap == 0) return out;
    std::lock_guard<std::mutex> g(pool_mu);
    size_t best = (size_t)-1;
    size_t best_size = 0;
    for (size_t i = 0; i < pool_blocks.size(); i++) {
        size_t sz = pool_blocks[i].size;
        if (sz >= n && sz <= (n << 2) &&
            (best == (size_t)-1 || sz < best_size)) {
            best = i;
            best_size = sz;
        }
    }
    if (best != (size_t)-1) {
        out = pool_blocks[best];
        pool_blocks.erase(pool_blocks.begin() + best);
        pool_free_bytes -= out.size;
    }
    return out;
}

static void pool_put(Block b) {
    if (b.size < POOL_MIN || pool_cap == 0) {
        free(b.p);
        return;
    }
    std::vector<Block> evicted;
    {
        std::lock_guard<std::mutex> g(pool_mu);
        pool_blocks.push_back(b);
        pool_free_bytes += b.size;
        /* Evict oldest-first until the cap holds — including down to an
         * empty pool, so a single block larger than FEDTPU_RECV_POOL_MB
         * is freed instead of retained forever. */
        while (pool_free_bytes > pool_cap && !pool_blocks.empty()) {
            evicted.push_back(pool_blocks.front());
            pool_free_bytes -= pool_blocks.front().size;
            pool_blocks.erase(pool_blocks.begin());
        }
    }
    for (auto &e : evicted) free(e.p);
}

/* ------------------------------------------------------------------ */
/* PooledBuf: a writable buffer-protocol object returning its block to  */
/* the pool on dealloc (all consumer views hold a strong reference, so  */
/* dealloc implies no live exports).                                    */
/* ------------------------------------------------------------------ */

typedef struct {
    PyObject_HEAD
    char *ptr;
    size_t alloc_size; /* underlying block size (pool key) */
    Py_ssize_t len;    /* exposed length */
} PooledBuf;

static void PooledBuf_dealloc(PyObject *self) {
    PooledBuf *pb = (PooledBuf *)self;
    if (pb->ptr) {
        Block b = {pb->ptr, pb->alloc_size};
        pool_put(b);
        pb->ptr = NULL;
    }
    Py_TYPE(self)->tp_free(self);
}

static int PooledBuf_getbuffer(PyObject *self, Py_buffer *view, int flags) {
    PooledBuf *pb = (PooledBuf *)self;
    if (pb->ptr == NULL) {
        PyErr_SetString(PyExc_ValueError, "PooledBuf is released");
        return -1;
    }
    return PyBuffer_FillInfo(view, self, pb->ptr, pb->len, 0, flags);
}

static PyBufferProcs PooledBuf_as_buffer = {
    PooledBuf_getbuffer,
    NULL,
};

static Py_ssize_t PooledBuf_length(PyObject *self) {
    return ((PooledBuf *)self)->len;
}

static PySequenceMethods PooledBuf_as_sequence = {
    PooledBuf_length, /* sq_length — len(buf) == payload bytes */
};

static PyTypeObject PooledBuf_Type = {
    PyVarObject_HEAD_INIT(NULL, 0)
    "rayfed_tpu._fastwire.PooledBuf", /* tp_name */
    sizeof(PooledBuf),                /* tp_basicsize */
};

/* New PooledBuf of n bytes (pool hit or fresh aligned allocation).
 * Caller must hold the GIL. Returns NULL with an exception set. */
static PyObject *pooledbuf_new(size_t n) {
    Block b = pool_take(n);
    if (b.p == NULL) {
        b.size = n;
        b.p = block_alloc(n ? n : 1);
        if (b.p == NULL) return PyErr_NoMemory();
    }
    PooledBuf *pb = PyObject_New(PooledBuf, &PooledBuf_Type);
    if (pb == NULL) {
        pool_put(b);
        return NULL;
    }
    pb->ptr = b.p;
    pb->alloc_size = b.size;
    pb->len = (Py_ssize_t)n;
    return (PyObject *)pb;
}

/* pool_trim() -> None: drop every free block (transport stop hook). */
static PyObject *fastwire_pool_trim(PyObject *self, PyObject *args) {
    std::vector<Block> dropped;
    {
        std::lock_guard<std::mutex> g(pool_mu);
        dropped.swap(pool_blocks);
        pool_free_bytes = 0;
    }
    for (auto &b : dropped) free(b.p);
    Py_RETURN_NONE;
}

/* ------------------------------------------------------------------ */
/* sendv                                                               */
/* ------------------------------------------------------------------ */

/* sendv(fd, timeout_ms, buffers_sequence) -> None
 * Sends every buffer fully, in order, via writev — any number of
 * buffers; the syscalls batch MAX_IOV iovecs at a time (a model
 * pytree's frame can easily carry hundreds of leaf buffers). */
static PyObject *fastwire_sendv(PyObject *self, PyObject *args) {
    int fd;
    long timeout_ms;
    PyObject *seq;
    if (!PyArg_ParseTuple(args, "ilO", &fd, &timeout_ms, &seq))
        return NULL;

    PyObject *fast = PySequence_Fast(seq, "buffers must be a sequence");
    if (!fast) return NULL;
    Py_ssize_t n = PySequence_Fast_GET_SIZE(fast);

    std::vector<Py_buffer> views;
    std::vector<struct iovec> iov;
    views.reserve((size_t)n);
    iov.reserve((size_t)n);
    size_t total = 0;
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject *item = PySequence_Fast_GET_ITEM(fast, i);
        Py_buffer view;
        if (PyObject_GetBuffer(item, &view, PyBUF_C_CONTIGUOUS) < 0) {
            for (auto &v : views) PyBuffer_Release(&v);
            Py_DECREF(fast);
            return NULL;
        }
        views.push_back(view);
        struct iovec v;
        v.iov_base = view.buf;
        v.iov_len = (size_t)view.len;
        total += (size_t)view.len;
        iov.push_back(v);
    }

    int err = 0;        /* errno, or -1 for poll timeout */
    size_t sent = 0;
    Py_BEGIN_ALLOW_THREADS;
    size_t first = 0;
    while (sent < total) {
        while (first < iov.size() && iov[first].iov_len == 0) first++;
        int cnt = (int)(iov.size() - first);
        if (cnt > MAX_IOV) cnt = MAX_IOV;
        ssize_t rc = writev(fd, &iov[first], cnt);
        if (rc < 0) {
            if (errno == EINTR) continue;
            if (errno == EAGAIN || errno == EWOULDBLOCK) {
                int w = wait_fd(fd, POLLOUT, timeout_ms);
                if (w == 0) continue;
                err = (w == -1) ? -1 : w;
                break;
            }
            err = errno;
            break;
        }
        sent += (size_t)rc;
        size_t done = (size_t)rc;
        while (done > 0 && first < iov.size()) {
            if (done >= iov[first].iov_len) {
                done -= iov[first].iov_len;
                iov[first].iov_len = 0;
                first++;
            } else {
                iov[first].iov_base = (char *)iov[first].iov_base + done;
                iov[first].iov_len -= done;
                done = 0;
            }
        }
    }
    Py_END_ALLOW_THREADS;

    for (auto &v : views) PyBuffer_Release(&v);
    Py_DECREF(fast);

    if (err == -1) {
        PyErr_SetString(PyExc_TimeoutError, "fastwire send timed out");
        return NULL;
    }
    if (err != 0) {
        errno = err;
        return PyErr_SetFromErrno(PyExc_OSError);
    }
    Py_RETURN_NONE;
}

/* ------------------------------------------------------------------ */
/* recv_exact                                                          */
/* ------------------------------------------------------------------ */

/* recv_exact(fd, timeout_ms, writable_buffer) -> None
 * Fills the buffer completely or raises (ConnectionError on EOF). */
static PyObject *fastwire_recv_exact(PyObject *self, PyObject *args) {
    int fd;
    long timeout_ms;
    Py_buffer buf;
    if (!PyArg_ParseTuple(args, "ilw*", &fd, &timeout_ms, &buf))
        return NULL;

    int err;
    Py_BEGIN_ALLOW_THREADS;
    err = recv_all(fd, (char *)buf.buf, (size_t)buf.len, timeout_ms);
    Py_END_ALLOW_THREADS;
    PyBuffer_Release(&buf);
    if (err != 0) return raise_io(err, "recv");
    Py_RETURN_NONE;
}

/* ------------------------------------------------------------------ */
/* recv_prefix_header                                                  */
/* ------------------------------------------------------------------ */

/* recv_prefix_header(fd, timeout_ms, magic4, version, max_header,
 *                    max_payload) -> (ftype, plen, header_bytes)
 *
 * Reads the fixed FTP1 prefix (4s magic, u8 version, u8 ftype, u32 hlen,
 * u64 plen, big-endian — proxy/tcp/wire.py frame layout) and the msgpack
 * header blob in one GIL-released window. Magic, version and both size
 * caps are validated BEFORE any allocation, so a hostile frame costs no
 * memory (ValueError; the Python layer maps it to WireError and tears
 * the connection down). */
static PyObject *fastwire_recv_prefix_header(PyObject *self, PyObject *args) {
    int fd;
    long timeout_ms;
    const char *magic;
    Py_ssize_t magic_len;
    int version;
    unsigned long long max_header, max_payload;
    if (!PyArg_ParseTuple(args, "ily#iKK", &fd, &timeout_ms, &magic,
                          &magic_len, &version, &max_header, &max_payload))
        return NULL;
    if (magic_len != 4) {
        PyErr_SetString(PyExc_ValueError, "magic must be 4 bytes");
        return NULL;
    }

    unsigned char prefix[18]; /* 4 + 1 + 1 + 4 + 8 */
    char *hdr = NULL;
    int err = 0;            /* recv_all code */
    int bad = 0;            /* 1 magic, 2 version, 3 hlen, 4 plen, 5 oom */
    unsigned int hlen = 0;
    unsigned long long plen = 0;
    unsigned int ftype = 0;
    unsigned int ver = 0;

    Py_BEGIN_ALLOW_THREADS;
    err = recv_all(fd, (char *)prefix, 18, timeout_ms);
    if (err == 0) {
        ver = prefix[4];
        ftype = prefix[5];
        hlen = ((unsigned int)prefix[6] << 24) |
               ((unsigned int)prefix[7] << 16) |
               ((unsigned int)prefix[8] << 8) | (unsigned int)prefix[9];
        plen = 0;
        for (int i = 0; i < 8; i++)
            plen = (plen << 8) | (unsigned long long)prefix[10 + i];
        if (memcmp(prefix, magic, 4) != 0) {
            bad = 1;
        } else if (ver != (unsigned int)version) {
            bad = 2;
        } else if ((unsigned long long)hlen > max_header) {
            bad = 3;
        } else if (plen > max_payload) {
            bad = 4;
        } else {
            hdr = (char *)malloc(hlen ? hlen : 1);
            if (hdr == NULL) {
                bad = 5;
            } else {
                err = recv_all(fd, hdr, hlen, timeout_ms);
            }
        }
    }
    Py_END_ALLOW_THREADS;

    if (err != 0) {
        free(hdr);
        return raise_io(err, "recv");
    }
    switch (bad) {
    case 1:
        PyErr_Format(PyExc_ValueError, "bad magic %.4s", (char *)prefix);
        return NULL;
    case 2:
        PyErr_Format(PyExc_ValueError, "unsupported wire version %u", ver);
        return NULL;
    case 3:
        PyErr_Format(PyExc_ValueError, "header length %u exceeds cap", hlen);
        return NULL;
    case 4:
        PyErr_Format(PyExc_ValueError,
                     "payload length %llu exceeds cap %llu", plen,
                     max_payload);
        return NULL;
    case 5:
        return PyErr_NoMemory();
    }
    PyObject *hbytes = PyBytes_FromStringAndSize(hdr, (Py_ssize_t)hlen);
    free(hdr);
    if (hbytes == NULL) return NULL;
    PyObject *out = Py_BuildValue("IKN", ftype, plen, hbytes);
    return out;
}

/* ------------------------------------------------------------------ */
/* recv_frame_small                                                    */
/* ------------------------------------------------------------------ */

/* recv_frame_small(fd, timeout_ms, magic4, version, max_header,
 *                  max_payload, small_max)
 *     -> (ftype, plen, header_bytes, payload | None)
 *
 * The latency-path sibling of recv_prefix_header: when the frame's
 * payload fits within small_max, the prefix, header AND payload are all
 * received inside ONE GIL-released window — a small frame costs a single
 * GIL round-trip instead of three (prefix+header, sizes, scatter).
 * Validation order and error codes match recv_prefix_header exactly.
 * For plen > small_max the payload slot is None and the caller falls
 * through to the scatter/pooled machinery unchanged. The payload comes
 * back as a writable bytearray (consumers build numpy views on it). */
static PyObject *fastwire_recv_frame_small(PyObject *self, PyObject *args) {
    int fd;
    long timeout_ms;
    const char *magic;
    Py_ssize_t magic_len;
    int version;
    unsigned long long max_header, max_payload, small_max;
    if (!PyArg_ParseTuple(args, "ily#iKKK", &fd, &timeout_ms, &magic,
                          &magic_len, &version, &max_header, &max_payload,
                          &small_max))
        return NULL;
    if (magic_len != 4) {
        PyErr_SetString(PyExc_ValueError, "magic must be 4 bytes");
        return NULL;
    }

    unsigned char prefix[18]; /* 4 + 1 + 1 + 4 + 8 */
    char *hdr = NULL;
    char *pay = NULL;
    int err = 0;            /* recv_all code */
    int bad = 0;            /* 1 magic, 2 version, 3 hlen, 4 plen, 5 oom */
    unsigned int hlen = 0;
    unsigned long long plen = 0;
    unsigned int ftype = 0;
    unsigned int ver = 0;
    int inlined = 0;        /* payload received in this window */

    Py_BEGIN_ALLOW_THREADS;
    err = recv_all(fd, (char *)prefix, 18, timeout_ms);
    if (err == 0) {
        ver = prefix[4];
        ftype = prefix[5];
        hlen = ((unsigned int)prefix[6] << 24) |
               ((unsigned int)prefix[7] << 16) |
               ((unsigned int)prefix[8] << 8) | (unsigned int)prefix[9];
        plen = 0;
        for (int i = 0; i < 8; i++)
            plen = (plen << 8) | (unsigned long long)prefix[10 + i];
        if (memcmp(prefix, magic, 4) != 0) {
            bad = 1;
        } else if (ver != (unsigned int)version) {
            bad = 2;
        } else if ((unsigned long long)hlen > max_header) {
            bad = 3;
        } else if (plen > max_payload) {
            bad = 4;
        } else {
            hdr = (char *)malloc(hlen ? hlen : 1);
            if (hdr == NULL) {
                bad = 5;
            } else {
                err = recv_all(fd, hdr, hlen, timeout_ms);
                if (err == 0 && plen <= small_max) {
                    inlined = 1;
                    pay = (char *)malloc(plen ? (size_t)plen : 1);
                    if (pay == NULL) {
                        bad = 5;
                    } else {
                        err = recv_all(fd, pay, (size_t)plen, timeout_ms);
                    }
                }
            }
        }
    }
    Py_END_ALLOW_THREADS;

    if (err != 0) {
        free(hdr);
        free(pay);
        return raise_io(err, "recv");
    }
    switch (bad) {
    case 1:
        PyErr_Format(PyExc_ValueError, "bad magic %.4s", (char *)prefix);
        return NULL;
    case 2:
        PyErr_Format(PyExc_ValueError, "unsupported wire version %u", ver);
        return NULL;
    case 3:
        PyErr_Format(PyExc_ValueError, "header length %u exceeds cap", hlen);
        return NULL;
    case 4:
        PyErr_Format(PyExc_ValueError,
                     "payload length %llu exceeds cap %llu", plen,
                     max_payload);
        return NULL;
    case 5:
        free(hdr);
        free(pay);
        return PyErr_NoMemory();
    }
    PyObject *hbytes = PyBytes_FromStringAndSize(hdr, (Py_ssize_t)hlen);
    free(hdr);
    if (hbytes == NULL) {
        free(pay);
        return NULL;
    }
    PyObject *pobj;
    if (inlined) {
        pobj = PyByteArray_FromStringAndSize(pay, (Py_ssize_t)plen);
        free(pay);
        if (pobj == NULL) {
            Py_DECREF(hbytes);
            return NULL;
        }
    } else {
        pobj = Py_None;
        Py_INCREF(pobj);
    }
    return Py_BuildValue("IKNN", ftype, plen, hbytes, pobj);
}

/* ------------------------------------------------------------------ */
/* recv_scatter                                                        */
/* ------------------------------------------------------------------ */

/* recv_scatter(fd, timeout_ms, sizes) -> [PooledBuf, ...]
 *
 * Allocates one pooled buffer per size and fills them all in a single
 * GIL-released window with readv batched across segment boundaries —
 * a segmented tree payload costs the same GIL/syscall structure as a
 * contiguous one. Caller is responsible for size validation (the frame's
 * plen was already capped by recv_prefix_header). */
static PyObject *fastwire_recv_scatter(PyObject *self, PyObject *args) {
    int fd;
    long timeout_ms;
    PyObject *sizes;
    if (!PyArg_ParseTuple(args, "ilO", &fd, &timeout_ms, &sizes))
        return NULL;

    PyObject *fast = PySequence_Fast(sizes, "sizes must be a sequence");
    if (!fast) return NULL;
    Py_ssize_t n = PySequence_Fast_GET_SIZE(fast);
    PyObject *out = PyList_New(n);
    if (!out) {
        Py_DECREF(fast);
        return NULL;
    }

    std::vector<struct iovec> iov;
    iov.reserve((size_t)n);
    int failed = 0;
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject *item = PySequence_Fast_GET_ITEM(fast, i);
        size_t sz = (size_t)PyLong_AsUnsignedLongLong(item);
        if (PyErr_Occurred()) {
            failed = 1;
            break;
        }
        PyObject *pb = pooledbuf_new(sz);
        if (pb == NULL) {
            failed = 1;
            break;
        }
        PyList_SET_ITEM(out, i, pb); /* steals */
        struct iovec v;
        v.iov_base = ((PooledBuf *)pb)->ptr;
        v.iov_len = sz;
        iov.push_back(v);
    }
    Py_DECREF(fast);
    if (failed) {
        Py_DECREF(out);
        return NULL;
    }

    int err = 0;
    Py_BEGIN_ALLOW_THREADS;
    size_t first = 0;
    while (first < iov.size()) {
        if (iov[first].iov_len == 0) {
            first++;
            continue;
        }
        int cnt = (int)(iov.size() - first);
        if (cnt > MAX_IOV) cnt = MAX_IOV;
        ssize_t rc = readv(fd, &iov[first], cnt);
        if (rc < 0) {
            if (errno == EINTR) continue;
            if (errno == EAGAIN || errno == EWOULDBLOCK) {
                int w = wait_fd(fd, POLLIN, timeout_ms);
                if (w == 0) continue;
                err = (w == -1) ? -1 : w;
                break;
            }
            err = errno;
            break;
        }
        if (rc == 0) {
            err = -2;
            break;
        }
        size_t done = (size_t)rc;
        while (done > 0 && first < iov.size()) {
            if (done >= iov[first].iov_len) {
                done -= iov[first].iov_len;
                iov[first].iov_len = 0;
                first++;
            } else {
                iov[first].iov_base = (char *)iov[first].iov_base + done;
                iov[first].iov_len -= done;
                done = 0;
            }
        }
    }
    Py_END_ALLOW_THREADS;

    if (err != 0) {
        Py_DECREF(out);
        return raise_io(err, "recv");
    }
    return out;
}

/* ------------------------------------------------------------------ */
/* epoll reactor core                                                  */
/* ------------------------------------------------------------------ */

/* reactor_new() -> epfd (close-on-exec). The Python reactor thread owns
 * the fd and closes it with reactor_close(). */
static PyObject *fastwire_reactor_new(PyObject *self, PyObject *args) {
    int epfd = epoll_create1(EPOLL_CLOEXEC);
    if (epfd < 0) return PyErr_SetFromErrno(PyExc_OSError);
    return PyLong_FromLong(epfd);
}

static PyObject *fastwire_reactor_close(PyObject *self, PyObject *args) {
    int epfd;
    if (!PyArg_ParseTuple(args, "i", &epfd)) return NULL;
    close(epfd);
    Py_RETURN_NONE;
}

/* reactor_ctl(epfd, op, fd, events) -> None
 * op: 1 add, 2 del, 3 mod (the kernel EPOLL_CTL_* values). ``events`` is
 * the raw epoll event mask (select.EPOLLIN|...). Level-triggered on
 * purpose: interest management (read always on, write only while the
 * send ring is non-empty) lives in Python, and level semantics make a
 * missed edge impossible. */
static PyObject *fastwire_reactor_ctl(PyObject *self, PyObject *args) {
    int epfd, op, fd;
    unsigned int events;
    if (!PyArg_ParseTuple(args, "iiiI", &epfd, &op, &fd, &events))
        return NULL;
    struct epoll_event ev;
    memset(&ev, 0, sizeof(ev));
    ev.events = events;
    ev.data.fd = fd;
    if (epoll_ctl(epfd, op, fd, &ev) < 0)
        return PyErr_SetFromErrno(PyExc_OSError);
    Py_RETURN_NONE;
}

#define REACTOR_MAX_EVENTS 128

/* reactor_wait(epfd, timeout_ms) -> list[(fd, events)]
 * One GIL-released epoll_wait, whole ready set in one call. */
static PyObject *fastwire_reactor_wait(PyObject *self, PyObject *args) {
    int epfd;
    long timeout_ms;
    if (!PyArg_ParseTuple(args, "il", &epfd, &timeout_ms)) return NULL;

    struct epoll_event evs[REACTOR_MAX_EVENTS];
    int n, err = 0;
    Py_BEGIN_ALLOW_THREADS;
    for (;;) {
        n = epoll_wait(epfd, evs, REACTOR_MAX_EVENTS,
                       timeout_ms < 0 ? -1 : (int)timeout_ms);
        if (n >= 0) break;
        if (errno == EINTR) continue;
        err = errno;
        break;
    }
    Py_END_ALLOW_THREADS;
    if (err != 0) {
        errno = err;
        return PyErr_SetFromErrno(PyExc_OSError);
    }
    PyObject *out = PyList_New(n);
    if (!out) return NULL;
    for (int i = 0; i < n; i++) {
        PyObject *t = Py_BuildValue("(iI)", evs[i].data.fd,
                                    (unsigned int)evs[i].events);
        if (!t) {
            Py_DECREF(out);
            return NULL;
        }
        PyList_SET_ITEM(out, i, t);
    }
    return out;
}

/* Nonblocking vectored write of one connection's ready chunks.
 * Returns bytes written (>= 0; 0 means EAGAIN before any byte), or
 * -errno on a hard socket error. Never raises for socket errors — the
 * caller maps negatives to its break machinery. Caller must NOT hold
 * buffer views it mutates concurrently. */
static ssize_t writev_nb(int fd, std::vector<struct iovec> &iov) {
    size_t first = 0, sent = 0;
    while (first < iov.size()) {
        while (first < iov.size() && iov[first].iov_len == 0) first++;
        if (first >= iov.size()) break;
        int cnt = (int)(iov.size() - first);
        if (cnt > MAX_IOV) cnt = MAX_IOV;
        ssize_t rc = writev(fd, &iov[first], cnt);
        if (rc < 0) {
            if (errno == EINTR) continue;
            if (errno == EAGAIN || errno == EWOULDBLOCK) break;
            return -(ssize_t)errno;
        }
        sent += (size_t)rc;
        size_t done = (size_t)rc;
        while (done > 0 && first < iov.size()) {
            if (done >= iov[first].iov_len) {
                done -= iov[first].iov_len;
                iov[first].iov_len = 0;
                first++;
            } else {
                iov[first].iov_base = (char *)iov[first].iov_base + done;
                iov[first].iov_len -= done;
                done = 0;
            }
        }
    }
    return (ssize_t)sent;
}

/* Collect buffer views for one job into (views, iov). Returns 0 ok. */
static int collect_iov(PyObject *bufseq, std::vector<Py_buffer> &views,
                       std::vector<struct iovec> &iov) {
    PyObject *fast = PySequence_Fast(bufseq, "buffers must be a sequence");
    if (!fast) return -1;
    Py_ssize_t n = PySequence_Fast_GET_SIZE(fast);
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject *item = PySequence_Fast_GET_ITEM(fast, i);
        Py_buffer view;
        if (PyObject_GetBuffer(item, &view, PyBUF_C_CONTIGUOUS) < 0) {
            Py_DECREF(fast);
            return -1;
        }
        views.push_back(view);
        struct iovec v;
        v.iov_base = view.buf;
        v.iov_len = (size_t)view.len;
        iov.push_back(v);
    }
    Py_DECREF(fast);
    return 0;
}

/* sendv_nb(fd, buffers) -> int
 * One nonblocking gather-write; partial writes are the caller's problem
 * (it advances its send ring by the return value). */
static PyObject *fastwire_sendv_nb(PyObject *self, PyObject *args) {
    int fd;
    PyObject *seq;
    if (!PyArg_ParseTuple(args, "iO", &fd, &seq)) return NULL;

    std::vector<Py_buffer> views;
    std::vector<struct iovec> iov;
    if (collect_iov(seq, views, iov) < 0) {
        for (auto &v : views) PyBuffer_Release(&v);
        return NULL;
    }
    ssize_t rc;
    Py_BEGIN_ALLOW_THREADS;
    rc = writev_nb(fd, iov);
    Py_END_ALLOW_THREADS;
    for (auto &v : views) PyBuffer_Release(&v);
    return PyLong_FromSsize_t(rc);
}

/* flush_many(jobs) -> list[int]
 * Batched submission: jobs is a sequence of (fd, buffers); every ready
 * connection's pending chunks are flushed inside ONE GIL window — N
 * writable peers cost one GIL round-trip, not N. Per-job result is
 * bytes written or -errno (a dead peer must not fail its neighbours'
 * flushes). */
static PyObject *fastwire_flush_many(PyObject *self, PyObject *args) {
    PyObject *jobs;
    if (!PyArg_ParseTuple(args, "O", &jobs)) return NULL;
    PyObject *fast = PySequence_Fast(jobs, "jobs must be a sequence");
    if (!fast) return NULL;
    Py_ssize_t njobs = PySequence_Fast_GET_SIZE(fast);

    struct JobIov {
        int fd;
        size_t viv_start, viv_len; /* slice into the shared views vector */
        std::vector<struct iovec> iov;
        ssize_t result;
    };
    std::vector<Py_buffer> views;
    std::vector<JobIov> parsed;
    parsed.reserve((size_t)njobs);
    for (Py_ssize_t i = 0; i < njobs; i++) {
        PyObject *item = PySequence_Fast_GET_ITEM(fast, i);
        int fd;
        PyObject *bufseq;
        if (!PyArg_ParseTuple(item, "iO", &fd, &bufseq)) {
            for (auto &v : views) PyBuffer_Release(&v);
            Py_DECREF(fast);
            return NULL;
        }
        JobIov j;
        j.fd = fd;
        j.viv_start = views.size();
        if (collect_iov(bufseq, views, j.iov) < 0) {
            for (auto &v : views) PyBuffer_Release(&v);
            Py_DECREF(fast);
            return NULL;
        }
        j.viv_len = views.size() - j.viv_start;
        j.result = 0;
        parsed.push_back(std::move(j));
    }

    Py_BEGIN_ALLOW_THREADS;
    for (auto &j : parsed) j.result = writev_nb(j.fd, j.iov);
    Py_END_ALLOW_THREADS;

    for (auto &v : views) PyBuffer_Release(&v);
    Py_DECREF(fast);

    PyObject *out = PyList_New(njobs);
    if (!out) return NULL;
    for (Py_ssize_t i = 0; i < njobs; i++) {
        PyObject *v = PyLong_FromSsize_t(parsed[(size_t)i].result);
        if (!v) {
            Py_DECREF(out);
            return NULL;
        }
        PyList_SET_ITEM(out, i, v);
    }
    return out;
}

/* recv_into_nb(fd, writable_buffer) -> int
 * Nonblocking drain: recv repeatedly into the buffer until it is full
 * or the socket would block — one GIL window for the whole burst.
 * Returns bytes read (0 = would block before any byte), -2 on EOF with
 * nothing read this call, or -errno on a hard error with nothing read
 * (partial reads return the partial count; the condition resurfaces on
 * the next call). */
static PyObject *fastwire_recv_into_nb(PyObject *self, PyObject *args) {
    int fd;
    Py_buffer buf;
    if (!PyArg_ParseTuple(args, "iw*", &fd, &buf)) return NULL;

    ssize_t got = 0, result = 0;
    Py_BEGIN_ALLOW_THREADS;
    char *p = (char *)buf.buf;
    size_t n = (size_t)buf.len;
    while ((size_t)got < n) {
        ssize_t rc = recv(fd, p + got, n - (size_t)got, 0);
        if (rc > 0) {
            got += rc;
            continue;
        }
        if (rc == 0) {
            result = (got > 0) ? got : -2;
            goto done;
        }
        if (errno == EINTR) continue;
        if (errno == EAGAIN || errno == EWOULDBLOCK) {
            result = got;
            goto done;
        }
        result = (got > 0) ? got : -(ssize_t)errno;
        goto done;
    }
    result = got;
done:;
    Py_END_ALLOW_THREADS;
    PyBuffer_Release(&buf);
    return PyLong_FromSsize_t(result);
}

/* ------------------------------------------------------------------ */
/* shm ring: same-host zero-copy bulk lane                             */
/* ------------------------------------------------------------------ */

/* One single-producer ring in a /dev/shm-backed file. The sender
 * (creator) serializes payloads directly into it and ships only a tiny
 * descriptor frame (ring name + offset + length) over the socket lane;
 * the receiver maps the same file and adopts the bytes zero-copy as a
 * ShmBuf that the pooled decode path consumes like any other buffer.
 *
 * Layout: a 4096-byte file header {u64 magic, u64 cap}, then a cap-byte
 * data region of 64-byte-aligned chunks. Each chunk starts with a
 * 64-byte header {u32 magic, u32 state, u64 size} (size = whole chunk,
 * header + payload + padding, a multiple of 64), so adopted payloads are
 * 64-byte aligned — the same alignment the receive pool guarantees.
 *
 * Concurrency contract: head/tail live in the CREATOR's ShmRing struct,
 * not in shared memory — the receiver never scans the ring, it only maps
 * explicit offsets named by descriptor frames. The one cross-process
 * mutation is the chunk ``state`` flag: the receiver's ShmBuf dealloc
 * atomically flips it to RELEASED, and the creator lazily reclaims
 * contiguous released chunks from head on each push. A push that cannot
 * find room returns None and the Python layer falls back to the socket
 * lane — the ring can stall a push, never lose one. */

#define SHM_FILE_HDR 4096
#define SHM_CHUNK_HDR 64
#define SHM_ALIGN 64
#define SHM_FILE_MAGIC 0x4645445450534852ULL /* "FEDTPSHR" */
#define SHM_CHUNK_MAGIC 0x46435348u          /* "FCSH" */
#define SHM_STATE_INFLIGHT 0u
#define SHM_STATE_RELEASED 1u

typedef struct {
    uint32_t magic;
    uint32_t state;
    uint64_t size;
    char pad[SHM_CHUNK_HDR - 16];
} ShmChunkHdr;

typedef struct {
    uint64_t magic;
    uint64_t cap;
} ShmFileHdr;

typedef struct {
    PyObject_HEAD
    char *base;        /* mmap base (file offset 0); NULL once unmapped */
    size_t cap;        /* data-region capacity in bytes */
    int fd;
    int creator;
    int closed;
    uint64_t head;     /* creator-side cumulative reclaim counter */
    uint64_t tail;     /* creator-side cumulative write counter */
    char path[256];
} ShmRing;

static char *shm_data(ShmRing *r) { return r->base + SHM_FILE_HDR; }

#if defined(__x86_64__) || defined(_M_X64)
#include <emmintrin.h>
/* Bulk copy with non-temporal stores: a pushed payload is read next by
 * the RECEIVER process, so filling the sender's cache with it is pure
 * waste — streaming stores skip the read-for-ownership and raise copy
 * bandwidth ~25% on this class of host. Head/tail fragments and small
 * copies go through plain memcpy. */
static void shm_copy(char *dst, const char *src, size_t n) {
    const size_t NT_MIN = (size_t)1 << 20;
    if (n < NT_MIN) {
        memcpy(dst, src, n);
        return;
    }
    size_t head = ((uintptr_t)dst) & 15 ? 16 - (((uintptr_t)dst) & 15) : 0;
    if (head) {
        memcpy(dst, src, head);
        dst += head;
        src += head;
        n -= head;
    }
    size_t i = 0;
    for (; i + 64 <= n; i += 64) {
        __m128i a = _mm_loadu_si128((const __m128i *)(src + i));
        __m128i b = _mm_loadu_si128((const __m128i *)(src + i + 16));
        __m128i c = _mm_loadu_si128((const __m128i *)(src + i + 32));
        __m128i d = _mm_loadu_si128((const __m128i *)(src + i + 48));
        _mm_stream_si128((__m128i *)(dst + i), a);
        _mm_stream_si128((__m128i *)(dst + i + 16), b);
        _mm_stream_si128((__m128i *)(dst + i + 32), c);
        _mm_stream_si128((__m128i *)(dst + i + 48), d);
    }
    _mm_sfence();
    if (i < n) memcpy(dst + i, src + i, n - i);
}
#else
static void shm_copy(char *dst, const char *src, size_t n) {
    memcpy(dst, src, n);
}
#endif

static void ShmRing_dealloc(PyObject *self) {
    ShmRing *r = (ShmRing *)self;
    if (r->creator && !r->closed && r->path[0]) unlink(r->path);
    if (r->base) munmap(r->base, SHM_FILE_HDR + r->cap);
    if (r->fd >= 0) close(r->fd);
    Py_TYPE(self)->tp_free(self);
}

static PyTypeObject ShmRing_Type = {
    PyVarObject_HEAD_INIT(NULL, 0)
    "rayfed_tpu._fastwire.ShmRing", /* tp_name */
    sizeof(ShmRing),                /* tp_basicsize */
};

/* ShmBuf: a read-through view of one adopted chunk's payload. Dealloc
 * flips the chunk's release flag so the creator can reclaim the space —
 * the exact PooledBuf contract, with the pool replaced by the ring. */
typedef struct {
    PyObject_HEAD
    ShmRing *ring;     /* strong ref keeps the mapping alive */
    char *ptr;
    Py_ssize_t len;
    ShmChunkHdr *chunk;
} ShmBuf;

static void ShmBuf_dealloc(PyObject *self) {
    ShmBuf *sb = (ShmBuf *)self;
    if (sb->chunk) {
        __atomic_store_n(&sb->chunk->state, SHM_STATE_RELEASED,
                         __ATOMIC_RELEASE);
        sb->chunk = NULL;
    }
    Py_XDECREF((PyObject *)sb->ring);
    Py_TYPE(self)->tp_free(self);
}

static int ShmBuf_getbuffer(PyObject *self, Py_buffer *view, int flags) {
    ShmBuf *sb = (ShmBuf *)self;
    if (sb->ptr == NULL) {
        PyErr_SetString(PyExc_ValueError, "ShmBuf is released");
        return -1;
    }
    return PyBuffer_FillInfo(view, self, sb->ptr, sb->len, 0, flags);
}

static PyBufferProcs ShmBuf_as_buffer = {
    ShmBuf_getbuffer,
    NULL,
};

static Py_ssize_t ShmBuf_length(PyObject *self) {
    return ((ShmBuf *)self)->len;
}

static PySequenceMethods ShmBuf_as_sequence = {
    ShmBuf_length, /* sq_length — len(buf) == payload bytes */
};

static PyTypeObject ShmBuf_Type = {
    PyVarObject_HEAD_INIT(NULL, 0)
    "rayfed_tpu._fastwire.ShmBuf", /* tp_name */
    sizeof(ShmBuf),                /* tp_basicsize */
};

/* Ring names come off the wire (descriptor frames), so they are
 * validated as a single flat filename before touching the filesystem. */
static int shm_name_ok(const char *name) {
    size_t n = strlen(name);
    if (n == 0 || n > 200) return 0;
    for (size_t i = 0; i < n; i++) {
        char c = name[i];
        if (!((c >= 'a' && c <= 'z') || (c >= 'A' && c <= 'Z') ||
              (c >= '0' && c <= '9') || c == '-' || c == '_' || c == '.'))
            return 0;
    }
    if (name[0] == '.') return 0;
    return 1;
}

static PyObject *shm_ring_alloc(void) {
    ShmRing *r = PyObject_New(ShmRing, &ShmRing_Type);
    if (r == NULL) return NULL;
    r->base = NULL;
    r->cap = 0;
    r->fd = -1;
    r->creator = 0;
    r->closed = 0;
    r->head = 0;
    r->tail = 0;
    r->path[0] = '\0';
    return (PyObject *)r;
}

/* shm_ring_create(name, capacity) -> ShmRing
 * Creates /dev/shm/<name> (0600, O_EXCL) sized header + capacity and
 * maps it. The creator owns head/tail and unlinks the file on close. */
static PyObject *fastwire_shm_ring_create(PyObject *self, PyObject *args) {
    const char *name;
    unsigned long long cap_arg;
    if (!PyArg_ParseTuple(args, "sK", &name, &cap_arg)) return NULL;
    if (!shm_name_ok(name)) {
        PyErr_Format(PyExc_ValueError, "bad shm ring name %.220s", name);
        return NULL;
    }
    size_t cap = (size_t)cap_arg;
    if (cap < SHM_ALIGN) cap = SHM_ALIGN;
    cap = (cap + SHM_ALIGN - 1) & ~((size_t)SHM_ALIGN - 1);

    ShmRing *r = (ShmRing *)shm_ring_alloc();
    if (r == NULL) return NULL;
    snprintf(r->path, sizeof(r->path), "/dev/shm/%s", name);
    r->fd = open(r->path, O_RDWR | O_CREAT | O_EXCL | O_CLOEXEC, 0600);
    if (r->fd < 0) {
        PyErr_SetFromErrnoWithFilename(PyExc_OSError, r->path);
        r->path[0] = '\0';
        Py_DECREF(r);
        return NULL;
    }
    if (ftruncate(r->fd, (off_t)(SHM_FILE_HDR + cap)) != 0) {
        PyErr_SetFromErrnoWithFilename(PyExc_OSError, r->path);
        unlink(r->path);
        r->path[0] = '\0';
        Py_DECREF(r);
        return NULL;
    }
    r->base = (char *)mmap(NULL, SHM_FILE_HDR + cap,
                           PROT_READ | PROT_WRITE, MAP_SHARED, r->fd, 0);
    if (r->base == MAP_FAILED) {
        r->base = NULL;
        PyErr_SetFromErrnoWithFilename(PyExc_OSError, r->path);
        unlink(r->path);
        r->path[0] = '\0';
        Py_DECREF(r);
        return NULL;
    }
    r->cap = cap;
    r->creator = 1;
    ShmFileHdr *h = (ShmFileHdr *)r->base;
    h->cap = (uint64_t)cap;
    /* Magic last: an attacher that races creation sees zero magic and
     * fails attach instead of reading a half-written header. */
    __atomic_store_n(&h->magic, SHM_FILE_MAGIC, __ATOMIC_RELEASE);
    return (PyObject *)r;
}

/* shm_ring_attach(name) -> ShmRing
 * Maps an existing ring read-write (the release flags are written
 * through this mapping). Never unlinks. */
static PyObject *fastwire_shm_ring_attach(PyObject *self, PyObject *args) {
    const char *name;
    if (!PyArg_ParseTuple(args, "s", &name)) return NULL;
    if (!shm_name_ok(name)) {
        PyErr_Format(PyExc_ValueError, "bad shm ring name %.220s", name);
        return NULL;
    }
    ShmRing *r = (ShmRing *)shm_ring_alloc();
    if (r == NULL) return NULL;
    snprintf(r->path, sizeof(r->path), "/dev/shm/%s", name);
    r->fd = open(r->path, O_RDWR | O_CLOEXEC);
    if (r->fd < 0) {
        PyErr_SetFromErrnoWithFilename(PyExc_OSError, r->path);
        Py_DECREF(r);
        return NULL;
    }
    struct stat st;
    if (fstat(r->fd, &st) != 0) {
        PyErr_SetFromErrnoWithFilename(PyExc_OSError, r->path);
        Py_DECREF(r);
        return NULL;
    }
    if (st.st_size < (off_t)SHM_FILE_HDR) {
        PyErr_Format(PyExc_ValueError, "shm ring %.220s truncated", name);
        Py_DECREF(r);
        return NULL;
    }
    r->base = (char *)mmap(NULL, (size_t)st.st_size,
                           PROT_READ | PROT_WRITE, MAP_SHARED, r->fd, 0);
    if (r->base == MAP_FAILED) {
        r->base = NULL;
        PyErr_SetFromErrnoWithFilename(PyExc_OSError, r->path);
        Py_DECREF(r);
        return NULL;
    }
    ShmFileHdr *h = (ShmFileHdr *)r->base;
    uint64_t magic = __atomic_load_n(&h->magic, __ATOMIC_ACQUIRE);
    uint64_t cap = h->cap;
    if (magic != SHM_FILE_MAGIC || cap == 0 ||
        (uint64_t)st.st_size < SHM_FILE_HDR + cap) {
        munmap(r->base, (size_t)st.st_size);
        r->base = NULL;
        PyErr_Format(PyExc_ValueError,
                     "shm ring %.220s has bad header (magic/cap)", name);
        Py_DECREF(r);
        return NULL;
    }
    r->cap = (size_t)cap;
    return (PyObject *)r;
}

static int shm_check_ring(PyObject *obj, const char **why) {
    if (!PyObject_TypeCheck(obj, &ShmRing_Type)) {
        *why = "expected a ShmRing";
        return -1;
    }
    ShmRing *r = (ShmRing *)obj;
    if (r->base == NULL || r->closed) {
        *why = "ring is closed";
        return -1;
    }
    return 0;
}

/* Advance head over contiguous released chunks. Creator only. */
static void shm_reclaim(ShmRing *r) {
    while (r->head < r->tail) {
        size_t pos = (size_t)(r->head % r->cap);
        ShmChunkHdr *c = (ShmChunkHdr *)(shm_data(r) + pos);
        if (c->magic != SHM_CHUNK_MAGIC) break; /* corrupted: stop */
        if (__atomic_load_n(&c->state, __ATOMIC_ACQUIRE) !=
            SHM_STATE_RELEASED)
            break;
        uint64_t size = c->size;
        if (size < SHM_CHUNK_HDR || size % SHM_ALIGN != 0 ||
            r->head + size > r->tail)
            break; /* corrupted size: stop reclaiming, ring degrades */
        r->head += size;
    }
}

/* shm_ring_push(ring, buffers) -> payload offset | None
 * Copies the buffers back-to-back into one chunk (GIL released for the
 * byte work) and returns the data-region offset of the payload, or None
 * when the ring has no contiguous room (caller waits or falls back). */
static PyObject *fastwire_shm_ring_push(PyObject *self, PyObject *args) {
    PyObject *ring_obj, *seq;
    if (!PyArg_ParseTuple(args, "OO", &ring_obj, &seq)) return NULL;
    const char *why = NULL;
    if (shm_check_ring(ring_obj, &why) < 0) {
        PyErr_SetString(PyExc_ValueError, why);
        return NULL;
    }
    ShmRing *r = (ShmRing *)ring_obj;
    if (!r->creator) {
        PyErr_SetString(PyExc_ValueError,
                        "only the creating side may push into a shm ring");
        return NULL;
    }

    PyObject *fast = PySequence_Fast(seq, "buffers must be a sequence");
    if (!fast) return NULL;
    Py_ssize_t nbufs = PySequence_Fast_GET_SIZE(fast);
    std::vector<Py_buffer> views;
    views.reserve((size_t)nbufs);
    size_t total = 0;
    for (Py_ssize_t i = 0; i < nbufs; i++) {
        PyObject *item = PySequence_Fast_GET_ITEM(fast, i);
        Py_buffer view;
        if (PyObject_GetBuffer(item, &view, PyBUF_C_CONTIGUOUS) < 0) {
            for (auto &v : views) PyBuffer_Release(&v);
            Py_DECREF(fast);
            return NULL;
        }
        views.push_back(view);
        total += (size_t)view.len;
    }
    Py_DECREF(fast);

    size_t need = (SHM_CHUNK_HDR + total + SHM_ALIGN - 1) &
                  ~((size_t)SHM_ALIGN - 1);
    int fits = 0;
    size_t pos = 0;
    if (need <= r->cap) {
        shm_reclaim(r);
        pos = (size_t)(r->tail % r->cap);
        size_t wrem = (pos + need > r->cap) ? r->cap - pos : 0;
        size_t free_bytes = r->cap - (size_t)(r->tail - r->head);
        if (free_bytes >= wrem + need) {
            fits = 1;
            if (wrem) {
                /* Wrap marker: a pre-released chunk covering the unusable
                 * region tail, so reclaim walks past it naturally. */
                ShmChunkHdr *w = (ShmChunkHdr *)(shm_data(r) + pos);
                w->magic = SHM_CHUNK_MAGIC;
                w->size = (uint64_t)wrem;
                __atomic_store_n(&w->state, SHM_STATE_RELEASED,
                                 __ATOMIC_RELEASE);
                r->tail += wrem;
                pos = 0;
            }
        }
    }
    if (!fits) {
        for (auto &v : views) PyBuffer_Release(&v);
        Py_RETURN_NONE;
    }

    char *dst = shm_data(r) + pos + SHM_CHUNK_HDR;
    Py_BEGIN_ALLOW_THREADS;
    for (auto &v : views) {
        shm_copy(dst, (const char *)v.buf, (size_t)v.len);
        dst += (size_t)v.len;
    }
    Py_END_ALLOW_THREADS;
    for (auto &v : views) PyBuffer_Release(&v);

    ShmChunkHdr *c = (ShmChunkHdr *)(shm_data(r) + pos);
    c->magic = SHM_CHUNK_MAGIC;
    c->size = (uint64_t)need;
    __atomic_store_n(&c->state, SHM_STATE_INFLIGHT, __ATOMIC_RELEASE);
    r->tail += need;
    return PyLong_FromSize_t(pos + SHM_CHUNK_HDR);
}

/* shm_ring_adopt(ring, offset, nbytes) -> ShmBuf
 * Zero-copy view of a pushed payload; validated against the chunk header
 * so a bad descriptor raises instead of exposing arbitrary ring bytes. */
static PyObject *fastwire_shm_ring_adopt(PyObject *self, PyObject *args) {
    PyObject *ring_obj;
    unsigned long long off, nbytes;
    if (!PyArg_ParseTuple(args, "OKK", &ring_obj, &off, &nbytes))
        return NULL;
    const char *why = NULL;
    if (shm_check_ring(ring_obj, &why) < 0) {
        PyErr_SetString(PyExc_ValueError, why);
        return NULL;
    }
    ShmRing *r = (ShmRing *)ring_obj;
    if (off < SHM_CHUNK_HDR || off % SHM_ALIGN != 0 || off > r->cap ||
        nbytes > r->cap - off) {
        PyErr_Format(PyExc_ValueError,
                     "shm descriptor out of range (off=%llu len=%llu "
                     "cap=%zu)", off, nbytes, r->cap);
        return NULL;
    }
    ShmChunkHdr *c =
        (ShmChunkHdr *)(shm_data(r) + (size_t)off - SHM_CHUNK_HDR);
    if (c->magic != SHM_CHUNK_MAGIC ||
        __atomic_load_n(&c->state, __ATOMIC_ACQUIRE) !=
            SHM_STATE_INFLIGHT ||
        (uint64_t)SHM_CHUNK_HDR + nbytes > c->size) {
        PyErr_SetString(PyExc_ValueError,
                        "shm descriptor does not name a live chunk");
        return NULL;
    }
    ShmBuf *sb = PyObject_New(ShmBuf, &ShmBuf_Type);
    if (sb == NULL) return NULL;
    Py_INCREF(r);
    sb->ring = r;
    sb->ptr = shm_data(r) + (size_t)off;
    sb->len = (Py_ssize_t)nbytes;
    sb->chunk = c;
    return (PyObject *)sb;
}

/* shm_ring_cancel(ring, offset) -> None
 * Release a pushed chunk whose descriptor frame was never delivered
 * (sender-side fallback path) so its space is reclaimable. */
static PyObject *fastwire_shm_ring_cancel(PyObject *self, PyObject *args) {
    PyObject *ring_obj;
    unsigned long long off;
    if (!PyArg_ParseTuple(args, "OK", &ring_obj, &off)) return NULL;
    const char *why = NULL;
    if (shm_check_ring(ring_obj, &why) < 0) {
        PyErr_SetString(PyExc_ValueError, why);
        return NULL;
    }
    ShmRing *r = (ShmRing *)ring_obj;
    if (off < SHM_CHUNK_HDR || off % SHM_ALIGN != 0 || off > r->cap) {
        PyErr_SetString(PyExc_ValueError, "shm cancel offset out of range");
        return NULL;
    }
    ShmChunkHdr *c =
        (ShmChunkHdr *)(shm_data(r) + (size_t)off - SHM_CHUNK_HDR);
    if (c->magic != SHM_CHUNK_MAGIC) {
        PyErr_SetString(PyExc_ValueError, "shm cancel offset not a chunk");
        return NULL;
    }
    __atomic_store_n(&c->state, SHM_STATE_RELEASED, __ATOMIC_RELEASE);
    Py_RETURN_NONE;
}

/* shm_ring_occupancy(ring) -> (used_bytes, capacity)
 * Creator-side view after a reclaim pass (telemetry + wait-for-space). */
/* shm_ring_chunk_state(ring, off) -> int
 * Atomic read of a chunk's state word (0 inflight, 1 released) so the
 * sender can reclaim ONLY still-inflight chunks after a peer death —
 * cancelling a chunk the receiver already released would be a
 * double-release (the sanitizer treats it as one). */
static PyObject *fastwire_shm_ring_chunk_state(PyObject *self,
                                               PyObject *args) {
    PyObject *ring_obj;
    unsigned long long off;
    if (!PyArg_ParseTuple(args, "OK", &ring_obj, &off)) return NULL;
    const char *why = NULL;
    if (shm_check_ring(ring_obj, &why) < 0) {
        PyErr_SetString(PyExc_ValueError, why);
        return NULL;
    }
    ShmRing *r = (ShmRing *)ring_obj;
    if (off < SHM_CHUNK_HDR || off % SHM_ALIGN != 0 || off > r->cap) {
        PyErr_SetString(PyExc_ValueError, "shm chunk offset out of range");
        return NULL;
    }
    ShmChunkHdr *c =
        (ShmChunkHdr *)(shm_data(r) + (size_t)off - SHM_CHUNK_HDR);
    if (c->magic != SHM_CHUNK_MAGIC) {
        PyErr_SetString(PyExc_ValueError, "shm offset not a chunk");
        return NULL;
    }
    return PyLong_FromUnsignedLong(
        __atomic_load_n(&c->state, __ATOMIC_ACQUIRE));
}

static PyObject *fastwire_shm_ring_occupancy(PyObject *self, PyObject *args) {
    PyObject *ring_obj;
    if (!PyArg_ParseTuple(args, "O", &ring_obj)) return NULL;
    const char *why = NULL;
    if (shm_check_ring(ring_obj, &why) < 0) {
        PyErr_SetString(PyExc_ValueError, why);
        return NULL;
    }
    ShmRing *r = (ShmRing *)ring_obj;
    if (r->creator) shm_reclaim(r);
    return Py_BuildValue("(KK)", (unsigned long long)(r->tail - r->head),
                         (unsigned long long)r->cap);
}

/* shm_ring_close(ring) -> None
 * Creator: unlink the file (new attaches fail, live mappings survive).
 * Both sides: refuse further push/adopt. The mapping itself is unmapped
 * at dealloc, AFTER the last adopted ShmBuf is gone — ShmBufs hold a
 * strong ring reference, so close can never pull bytes out from under a
 * consumer. */
static PyObject *fastwire_shm_ring_close(PyObject *self, PyObject *args) {
    PyObject *ring_obj;
    if (!PyArg_ParseTuple(args, "O", &ring_obj)) return NULL;
    if (!PyObject_TypeCheck(ring_obj, &ShmRing_Type)) {
        PyErr_SetString(PyExc_TypeError, "expected a ShmRing");
        return NULL;
    }
    ShmRing *r = (ShmRing *)ring_obj;
    if (!r->closed) {
        if (r->creator && r->path[0]) unlink(r->path);
        if (r->fd >= 0) {
            close(r->fd);
            r->fd = -1;
        }
        r->closed = 1;
    }
    Py_RETURN_NONE;
}

/* ------------------------------------------------------------------ */
/* crc32c (Castagnoli) — frame-integrity fast path                     */
/* ------------------------------------------------------------------ */

static uint32_t crc32c_table[256];

static void crc32c_init_table(void) {
    for (uint32_t i = 0; i < 256; i++) {
        uint32_t crc = i;
        for (int j = 0; j < 8; j++)
            crc = (crc >> 1) ^ (0x82F63B78u & (uint32_t)(-(int32_t)(crc & 1)));
        crc32c_table[i] = crc;
    }
}

/* crc32c(data, crc=0) -> int
 * Streaming CRC-32C over one contiguous buffer; pass the previous
 * return value as `crc` to accumulate across buffers (zlib.crc32
 * calling convention). GIL released while crunching. */
static PyObject *fastwire_crc32c(PyObject *self, PyObject *args) {
    Py_buffer view;
    unsigned int crc_in = 0;
    if (!PyArg_ParseTuple(args, "y*|I", &view, &crc_in)) return NULL;
    uint32_t crc = crc_in ^ 0xFFFFFFFFu;
    const unsigned char *p = (const unsigned char *)view.buf;
    Py_ssize_t n = view.len;
    Py_BEGIN_ALLOW_THREADS
    for (Py_ssize_t k = 0; k < n; k++)
        crc = crc32c_table[(crc ^ p[k]) & 0xFF] ^ (crc >> 8);
    Py_END_ALLOW_THREADS
    PyBuffer_Release(&view);
    return PyLong_FromUnsignedLong(crc ^ 0xFFFFFFFFu);
}

/* ------------------------------------------------------------------ */
/* module                                                              */
/* ------------------------------------------------------------------ */

static PyMethodDef fastwire_methods[] = {
    {"crc32c", fastwire_crc32c, METH_VARARGS,
     "crc32c(data, crc=0) -> int: streaming CRC-32C (Castagnoli)."},
    {"sendv", fastwire_sendv, METH_VARARGS,
     "sendv(fd, timeout_ms, buffers): fully send all buffers via writev."},
    {"recv_exact", fastwire_recv_exact, METH_VARARGS,
     "recv_exact(fd, timeout_ms, buffer): fill the writable buffer."},
    {"recv_prefix_header", fastwire_recv_prefix_header, METH_VARARGS,
     "recv_prefix_header(fd, timeout_ms, magic, version, max_header, "
     "max_payload) -> (ftype, plen, header_bytes)."},
    {"recv_frame_small", fastwire_recv_frame_small, METH_VARARGS,
     "recv_frame_small(fd, timeout_ms, magic, version, max_header, "
     "max_payload, small_max) -> (ftype, plen, header_bytes, "
     "payload|None): whole small frame in one GIL-released window."},
    {"recv_scatter", fastwire_recv_scatter, METH_VARARGS,
     "recv_scatter(fd, timeout_ms, sizes) -> list of pooled buffers."},
    {"pool_trim", fastwire_pool_trim, METH_NOARGS,
     "pool_trim(): free every idle pooled receive block."},
    {"reactor_new", fastwire_reactor_new, METH_NOARGS,
     "reactor_new() -> epoll fd (close-on-exec)."},
    {"reactor_close", fastwire_reactor_close, METH_VARARGS,
     "reactor_close(epfd): close the epoll fd."},
    {"reactor_ctl", fastwire_reactor_ctl, METH_VARARGS,
     "reactor_ctl(epfd, op, fd, events): EPOLL_CTL_{ADD=1,DEL=2,MOD=3}."},
    {"reactor_wait", fastwire_reactor_wait, METH_VARARGS,
     "reactor_wait(epfd, timeout_ms) -> [(fd, events)] in one "
     "GIL-released epoll_wait."},
    {"sendv_nb", fastwire_sendv_nb, METH_VARARGS,
     "sendv_nb(fd, buffers) -> bytes written (0 on EAGAIN, -errno on "
     "error); one nonblocking writev batch."},
    {"flush_many", fastwire_flush_many, METH_VARARGS,
     "flush_many([(fd, buffers), ...]) -> [bytes|-errno]: flush many "
     "connections' send rings in one GIL window."},
    {"recv_into_nb", fastwire_recv_into_nb, METH_VARARGS,
     "recv_into_nb(fd, buffer) -> bytes read (0 would-block, -2 EOF, "
     "-errno error); drains a burst in one GIL window."},
    {"shm_ring_create", fastwire_shm_ring_create, METH_VARARGS,
     "shm_ring_create(name, capacity) -> ShmRing under /dev/shm."},
    {"shm_ring_attach", fastwire_shm_ring_attach, METH_VARARGS,
     "shm_ring_attach(name) -> ShmRing mapping an existing ring."},
    {"shm_ring_push", fastwire_shm_ring_push, METH_VARARGS,
     "shm_ring_push(ring, buffers) -> payload offset, or None when the "
     "ring has no room (caller waits or falls back to the socket lane)."},
    {"shm_ring_adopt", fastwire_shm_ring_adopt, METH_VARARGS,
     "shm_ring_adopt(ring, offset, nbytes) -> ShmBuf zero-copy view; "
     "its dealloc releases the chunk back to the creator."},
    {"shm_ring_cancel", fastwire_shm_ring_cancel, METH_VARARGS,
     "shm_ring_cancel(ring, offset): release an undelivered chunk."},
    {"shm_ring_chunk_state", fastwire_shm_ring_chunk_state, METH_VARARGS,
     "shm_ring_chunk_state(ring, offset) -> 0 inflight / 1 released."},
    {"shm_ring_occupancy", fastwire_shm_ring_occupancy, METH_VARARGS,
     "shm_ring_occupancy(ring) -> (used_bytes, capacity)."},
    {"shm_ring_close", fastwire_shm_ring_close, METH_VARARGS,
     "shm_ring_close(ring): unlink (creator) and refuse further ops; "
     "live ShmBufs keep the mapping alive until they are dropped."},
    {NULL, NULL, 0, NULL},
};

static struct PyModuleDef fastwire_module = {
    PyModuleDef_HEAD_INIT, "_fastwire",
    "Native (C++) data-plane engine for the rayfed_tpu FTP1 protocol.", -1,
    fastwire_methods,
};

PyMODINIT_FUNC PyInit__fastwire(void) {
    crc32c_init_table();
    PooledBuf_Type.tp_dealloc = PooledBuf_dealloc;
    PooledBuf_Type.tp_flags = Py_TPFLAGS_DEFAULT;
    PooledBuf_Type.tp_doc = "Pooled receive buffer (writable, buffer protocol)";
    PooledBuf_Type.tp_as_buffer = &PooledBuf_as_buffer;
    PooledBuf_Type.tp_as_sequence = &PooledBuf_as_sequence;
    PooledBuf_Type.tp_new = NULL; /* C-internal construction only */
    if (PyType_Ready(&PooledBuf_Type) < 0) return NULL;

    ShmRing_Type.tp_dealloc = ShmRing_dealloc;
    ShmRing_Type.tp_flags = Py_TPFLAGS_DEFAULT;
    ShmRing_Type.tp_doc = "Same-host shared-memory ring (/dev/shm file)";
    ShmRing_Type.tp_new = NULL; /* C-internal construction only */
    if (PyType_Ready(&ShmRing_Type) < 0) return NULL;

    ShmBuf_Type.tp_dealloc = ShmBuf_dealloc;
    ShmBuf_Type.tp_flags = Py_TPFLAGS_DEFAULT;
    ShmBuf_Type.tp_doc =
        "Adopted shm chunk payload (buffer protocol); dealloc releases "
        "the chunk back to the ring's creator";
    ShmBuf_Type.tp_as_buffer = &ShmBuf_as_buffer;
    ShmBuf_Type.tp_as_sequence = &ShmBuf_as_sequence;
    ShmBuf_Type.tp_new = NULL; /* C-internal construction only */
    if (PyType_Ready(&ShmBuf_Type) < 0) return NULL;

    const char *cap_mb = getenv("FEDTPU_RECV_POOL_MB");
    if (cap_mb != NULL) {
        char *end = NULL;
        long v = strtol(cap_mb, &end, 10);
        if (end != cap_mb && *end == '\0' && v >= 0)
            pool_cap = (size_t)v << 20;
    }

    PyObject *m = PyModule_Create(&fastwire_module);
    if (m == NULL) return NULL;
    Py_INCREF(&PooledBuf_Type);
    if (PyModule_AddObject(m, "PooledBuf", (PyObject *)&PooledBuf_Type) < 0) {
        Py_DECREF(&PooledBuf_Type);
        Py_DECREF(m);
        return NULL;
    }
    Py_INCREF(&ShmRing_Type);
    if (PyModule_AddObject(m, "ShmRing", (PyObject *)&ShmRing_Type) < 0) {
        Py_DECREF(&ShmRing_Type);
        Py_DECREF(m);
        return NULL;
    }
    Py_INCREF(&ShmBuf_Type);
    if (PyModule_AddObject(m, "ShmBuf", (PyObject *)&ShmBuf_Type) < 0) {
        Py_DECREF(&ShmBuf_Type);
        Py_DECREF(m);
        return NULL;
    }
    return m;
}
