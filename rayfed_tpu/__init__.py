# Copyright 2026 The rayfed-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""rayfed_tpu: a TPU-native multi-party federated execution framework.

Same capability surface as ray-project/rayfed (reference
``fed/__init__.py:15-30``): ``init``, ``remote``, ``get``, ``kill``,
``shutdown``, ``send``, ``recv``, ``FedObject``, ``FedRemoteError`` — on a
brand-new substrate: party-local JAX execution over device meshes, a native
TCP/TLS data plane with a zero-pickle array fast path, and federated
aggregation that lowers to XLA collectives (see SURVEY.md §7).
"""

from rayfed_tpu import tree_util  # noqa: F401  (must precede api import)
from rayfed_tpu.api import (  # noqa: F401
    get,
    init,
    is_party_leader,
    join,
    kill,
    leave,
    membership_stats,
    membership_sync,
    membership_view,
    privacy_ledger,
    remote,
    shutdown,
)
from rayfed_tpu.exceptions import (  # noqa: F401
    FedRemoteError,
    StaleCoordinatorError,
)
from rayfed_tpu.fed_object import FedObject  # noqa: F401
from rayfed_tpu.proxy.barriers import recv, send  # noqa: F401
from rayfed_tpu.resilience import (  # noqa: F401
    MISSING,
    fault_trace,
    liveness_view,
    party_state,
)
from rayfed_tpu.serving import (  # noqa: F401
    ServeHandle,
    serve,
    submit_request,
)
from rayfed_tpu.async_rounds import (  # noqa: F401  (after api import)
    AsyncRoundHandle,
    async_handoff,
    async_rebuild,
    async_round,
)
from rayfed_tpu.checkpoint import (  # noqa: F401
    restore_job_state,
    save_job_state,
)
from rayfed_tpu.telemetry import (  # noqa: F401
    export_fleet_trace,
    telemetry_snapshot,
)

__version__ = "0.1.0"

__all__ = [
    "init",
    "remote",
    "get",
    "kill",
    "shutdown",
    "send",
    "is_party_leader",
    "recv",
    "FedObject",
    "FedRemoteError",
    "MISSING",
    "fault_trace",
    "liveness_view",
    "party_state",
    "join",
    "leave",
    "membership_stats",
    "membership_sync",
    "membership_view",
    "privacy_ledger",
    "StaleCoordinatorError",
    "serve",
    "submit_request",
    "ServeHandle",
    "async_round",
    "async_handoff",
    "async_rebuild",
    "AsyncRoundHandle",
    "save_job_state",
    "restore_job_state",
    "telemetry_snapshot",
    "export_fleet_trace",
    "__version__",
]
