# Copyright 2026 The rayfed-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""FedCallHolder: the per-call-site dispatch node.

Capability parity: reference ``fed/_private/fed_call_holder.py:31-110`` —
the single place where the local-vs-remote decision is made:

 - my party == node party: resolve dependency FedObjects into value futures
   (issuing ``recv`` for foreign ones), submit the task to the local
   executor, wrap outputs in bound FedObjects.
 - otherwise: for every *own-party* FedObject argument not yet pushed to the
   node party, fire an owner-initiated push addressed by
   (producer task id, this call's task id); return placeholder FedObject(s).

The owner-push branch is the data perimeter: data leaves a party only
because its owner's driver reached the same call site (ref README.md:28-30).
"""

from __future__ import annotations

import logging
from typing import Any, Callable, Dict, Optional

from rayfed_tpu import tree_util
from rayfed_tpu._private.global_context import get_global_context
from rayfed_tpu.fed_object import FedObject
from rayfed_tpu.proxy.barriers import send
from rayfed_tpu.utils import resolve_dependencies

logger = logging.getLogger(__name__)


class FedCallHolder:
    def __init__(
        self,
        node_party: str,
        submit_task_func: Callable,
        options: Optional[Dict[str, Any]] = None,
    ) -> None:
        ctx = get_global_context()
        if ctx is None:
            raise RuntimeError(
                "rayfed_tpu is not initialized; call fed.init() first."
            )
        self._party = ctx.get_current_party()
        self._node_party = node_party
        self._options = options or {}
        self._submit_task_func = submit_task_func

    def options(self, **options):
        self._options = options
        return self

    def internal_remote(self, *args, **kwargs):
        if not self._node_party:
            raise ValueError("You should specify a party name on the fed task/actor.")

        fed_task_id = get_global_context().next_seq_id()
        if self._party == self._node_party:
            resolved_args, resolved_kwargs = resolve_dependencies(
                self._party, fed_task_id, *args, **kwargs
            )
            out = self._submit_task_func(resolved_args, resolved_kwargs)
            if isinstance(out, list):
                return [
                    FedObject(self._node_party, fed_task_id, fut, i)
                    for i, fut in enumerate(out)
                ]
            return FedObject(self._node_party, fed_task_id, out)

        # Consumer side of a push (or an unrelated party): push own data.
        flattened_args, _ = tree_util.tree_flatten((args, kwargs))
        for arg in flattened_args:
            if isinstance(arg, FedObject) and arg.get_party() == self._party:
                if arg._was_sending_or_sent_to_party(self._node_party):
                    # Deduplicated: already pushed for an earlier edge
                    # (ref fed_call_holder.py:87-90).
                    continue
                arg._mark_is_sending_to_party(self._node_party)
                send(
                    dest_party=self._node_party,
                    data=arg.get_value_future(),
                    upstream_seq_id=arg.get_fed_task_id(),
                    downstream_seq_id=fed_task_id,
                )
        num_returns = self._options.get("num_returns", 1)
        if num_returns > 1:
            return [
                FedObject(self._node_party, fed_task_id, None, i)
                for i in range(num_returns)
            ]
        return FedObject(self._node_party, fed_task_id, None)
