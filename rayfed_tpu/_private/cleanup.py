# Copyright 2026 The rayfed-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Asynchronous send-drain + failure-propagation manager.

Capability parity: reference ``fed/cleanup.py:29-203``. Cross-party pushes
are fire-and-forget at the call site; their completion futures are drained
here by daemon threads. A failed data send substitutes a
:class:`FedRemoteError` envelope *under the same (upstream, downstream) seq
ids* the peer is already waiting on (ref ``cleanup.py:160-172``) so the peer
fails fast instead of hanging, then optionally SIGINTs this process
(``exit_on_sending_failure``, ref ``cleanup.py:112-128,176-183``).

Differences from the reference: the drained handle is a
``concurrent.futures.Future`` from our sender proxy (no Ray ObjectRefs), and
a producer-task failure is distinguished from a transport failure by the
:class:`FedLocalError` wrapper instead of ``ray.exceptions.RayError``.
"""

from __future__ import annotations

import logging
import os
import signal
import threading
from typing import Callable, Optional

from rayfed_tpu._private.message_queue import MessageQueueManager
from rayfed_tpu.exceptions import FedLocalError, FedRemoteError

logger = logging.getLogger(__name__)


class CleanupManager:
    def __init__(self, current_party: str, acquire_shutdown_flag: Callable[[], bool]):
        self._sending_data_q = MessageQueueManager(
            self._process_data_send, thread_name="fedtpu-data-send-drain"
        )
        self._sending_error_q = MessageQueueManager(
            self._process_error_send, thread_name="fedtpu-error-send-drain"
        )
        self._current_party = current_party
        self._acquire_shutdown_flag = acquire_shutdown_flag
        self._last_sending_error: Optional[Exception] = None
        # Data sends not yet discharged (future -> queued message). Happy
        # paths never touch the drain thread; see push_to_sending.
        self._inflight: dict = {}
        self._inflight_lock = threading.Lock()
        self._exit_on_sending_failure = False
        self._expose_error_trace = False
        # Fast-fail drain (entered by stop(wait_for_sending=False)): pending
        # sends get a short bounded wait instead of blocking forever, and
        # ones that cannot complete are substituted by error envelopes so
        # peers parked on their rendezvous keys unblock instead of hanging
        # (liveness the reference lacks: its non-graceful stop just drops
        # queued sends, ref message_queue.py:84-99).
        self._fast_fail = False

    def start(self, exit_on_sending_failure: bool = False,
              expose_error_trace: bool = False) -> None:
        self._exit_on_sending_failure = exit_on_sending_failure
        self._expose_error_trace = expose_error_trace
        self._sending_data_q.start()
        self._sending_error_q.start()

    def stop(self, wait_for_sending: bool = False) -> None:
        if not wait_for_sending:
            self._fast_fail = True
        # In-flight data sends first: wait for each to resolve (bounded in
        # fast-fail mode) and discharge it — failures land in the data
        # queue as envelope jobs before the stop symbol does.
        timeout_each = 2.0 if self._fast_fail else None
        while True:
            with self._inflight_lock:
                item = next(iter(self._inflight.items()), None)
            if item is None:
                break
            f, msg = item
            try:
                f.result(timeout=timeout_each)
            except BaseException:  # noqa: BLE001 - discharge decides
                pass
            self._discharge_data_send(f, msg)
        # Data queue first: its failure handling may enqueue error sends
        # (same ordering constraint as ref cleanup.py:71-76). Both queues
        # always drain gracefully — in fast-fail mode each item's wait is
        # bounded, so "graceful" stays prompt while guaranteeing that every
        # queued edge either completes or gets an error envelope.
        self._sending_data_q.stop(graceful=True)
        self._sending_error_q.stop(graceful=True)

    def push_to_sending(
        self,
        send_future,
        dest_party: Optional[str] = None,
        upstream_seq_id: int = -1,
        downstream_seq_id: int = -1,
        is_error: bool = False,
    ) -> None:
        """Track a pending cross-party send. ``send_future`` resolves when the
        peer acknowledged the payload (or raises)."""
        msg = (send_future, dest_party, upstream_seq_id, downstream_seq_id)
        if is_error:
            self._sending_error_q.append(msg)
            return
        # Successful sends must not wake the drain thread (one context
        # switch per ack adds up on small-message rounds): the discharge
        # runs as a done-callback on whichever thread resolves the ack,
        # and only *failed* sends become drain-queue jobs (for error
        # enveloping). stop() sweeps whatever is still in flight.
        with self._inflight_lock:
            self._inflight[send_future] = msg
        send_future.add_done_callback(
            lambda f, _msg=msg: self._discharge_data_send(f, _msg)
        )

    def _discharge_data_send(self, f, msg) -> None:
        """At-most-once per send (callback and stop() both call this; the
        inflight map arbitrates): drop a successful send, queue an
        error-envelope job for a failed or still-pending one."""
        with self._inflight_lock:
            if self._inflight.pop(f, None) is None:
                return
        if f.done() and not f.cancelled():
            try:
                failed = f.exception() is not None
            except BaseException:  # noqa: BLE001
                failed = True
        else:
            failed = True  # cancelled, or stop() gave up waiting
        if failed:
            self._sending_data_q.append(msg)

    def get_last_sending_error(self) -> Optional[Exception]:
        return self._last_sending_error

    def _signal_exit(self) -> None:
        """SIGINT ourselves so the main thread runs the unintended-shutdown
        path. The shutdown flag must be won *before* signalling to avoid the
        signal-handler deadlock documented at ref cleanup.py:117-128."""
        if self._acquire_shutdown_flag():
            logger.warning("Signaling SIGINT to exit on sending failure.")
            os.kill(os.getpid(), signal.SIGINT)

    def _process_data_send(self, message) -> bool:
        send_future, dest_party, upstream_seq_id, downstream_seq_id = message
        try:
            timeout = 2.0 if self._fast_fail else None
            res = send_future.result(timeout=timeout)
        except Exception as e:  # noqa: BLE001 - every failure must be handled
            logger.warning(
                "Failed to send to %s (upstream_seq_id=%s downstream_seq_id=%s): %s",
                dest_party, upstream_seq_id, downstream_seq_id, e,
            )
            self._last_sending_error = e
            # Substitute an error envelope under the same seq ids the
            # peer's recv is parked on, for EVERY failure mode (the
            # reference does the same for any RayError, cleanup.py:160-172):
            # producer raised (FedLocalError), payload rejected (strict
            # mode / size caps), or transport down — in the last case the
            # envelope send fails too and is just logged by the error
            # queue, but in the first two the transport is healthy and the
            # envelope is what keeps the peer from hanging.
            from rayfed_tpu.proxy.barriers import send

            error_trace = None
            if self._expose_error_trace:
                # Producer exceptions cross as objects (reference parity;
                # whitelist them on the receiver). Transport/validation
                # exceptions cross as strings — their classes (ssl.SSLError,
                # wire errors) would just fail the peer's whitelist.
                error_trace = (
                    e.cause if isinstance(e, FedLocalError) else repr(e)
                )
            send(
                dest_party,
                FedRemoteError(self._current_party, error_trace),
                upstream_seq_id,
                downstream_seq_id,
                is_error=True,
            )
            res = False

        if not res and self._exit_on_sending_failure and not self._fast_fail:
            self._signal_exit()
            return False  # stop this drain thread; main thread cleans up
        # In fast-fail teardown keep draining so every queued edge gets its
        # envelope before the process exits.
        return True

    def _process_error_send(self, message) -> bool:
        send_future, dest_party, upstream_seq_id, downstream_seq_id = message
        try:
            # Bounded even in normal mode: an unreachable peer must not
            # wedge shutdown behind the full transport retry budget.
            res = send_future.result(timeout=10.0 if self._fast_fail else 120.0)
        except Exception:  # noqa: BLE001
            res = False
        if not res:
            logger.warning(
                "Failed to send error to %s (upstream_seq_id=%s "
                "downstream_seq_id=%s); the peer may not sense this error.",
                dest_party, upstream_seq_id, downstream_seq_id,
            )
        return True  # keep draining remaining error sends (ref cleanup.py:202)
