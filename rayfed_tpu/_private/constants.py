# Copyright 2026 The rayfed-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Internal constants.

Mirrors the role of the reference's ``fed/_private/constants.py`` (key names
for the job-scoped KV, logging format) with our own naming.
"""

KEY_OF_CLUSTER_CONFIG = "CLUSTER_CONFIG"
KEY_OF_JOB_CONFIG = "JOB_CONFIG"

KEY_OF_CLUSTER_ADDRESSES = "CLUSTER_ADDRESSES"
KEY_OF_CURRENT_PARTY_NAME = "CURRENT_PARTY_NAME"
KEY_OF_TLS_CONFIG = "TLS_CONFIG"
KEY_OF_CROSS_SILO_COMM_CONFIG_DICT = "CROSS_SILO_COMM_CONFIG_DICT"

KV_NAMESPACE_PREFIX = "FEDTPU"

# Logging format: party and job name injected via logging.Filter, matching
# the observability surface of the reference (``fed/_private/constants.py:30``).
LOG_FORMAT = (
    "%(asctime)s %(levelname)s %(filename)s:%(lineno)s"
    " [%(party)s] -- [%(jobname)s] %(message)s"
)

DEFAULT_JOB_NAME = "default"

# Wire protocol (see rayfed_tpu/proxy/tcp/wire.py).
WIRE_MAGIC = b"FTP1"
WIRE_VERSION = 1

# Response codes on the data plane — kept numerically compatible with the
# reference's HTTP-flavored codes (``fed/proxy/grpc/grpc_proxy.py:311-320``).
CODE_OK = 200
CODE_FORBIDDEN = 403  # peer cert does not attest the claimed src party
CODE_PICKLE_FORBIDDEN = 415  # strict arrays-only mode rejected the frame
CODE_JOB_MISMATCH = 417
# Receiver could not attach/adopt a same-host shared-memory descriptor
# (ring unlinked, cross-host misconfiguration, map failure). The sender
# treats it as a per-push demotion signal: resend on the socket lane and
# stop offering shm frames to this peer (proxy/lanes.py).
CODE_SHM_UNAVAILABLE = 424
# Frame-integrity NACK: the receiver's payload checksum (header "crc",
# algorithm id "crca") did not match the received bytes. The sender
# treats it as retryable — requeue the SAME frame for retransmission
# through the resend machinery (bounded by max_attempts); never a
# demotion signal and never a poisoned-decode crash.
CODE_DATA_CORRUPT = 409
CODE_INTERNAL_ERROR = 500

# Seq id used by the ping_others readiness barrier for both the upstream
# and downstream ids of a ping send — matches the reference's literal
# "ping"/"ping" pair on the wire (ref fed/proxy/barriers.py:497-523).
PING_SEQ_ID = "ping"
