# Copyright 2026 The rayfed-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Party-local task execution engine.

This replaces the reference's L0 substrate — Ray tasks and actors
(`ray.remote(...).remote()` submission at ref ``fed/api.py:413-417`` and the
actor machinery at ``fed/_private/fed_actor.py``) — with an in-process
dataflow thread pool. Rationale (TPU-first): party-local "tasks" are mostly
jit-compiled JAX calls; XLA dispatch is already asynchronous and releases the
GIL during device execution, so threads give real overlap without Ray's
per-task IPC + serialization overhead (the reference's dominant cost in the
many-tiny-tasks benchmark, ``benchmarks/many_tiny_tasks_benchmark.py``).

Dataflow contract:
 - ``submit`` returns one (or ``num_returns``) ``concurrent.futures.Future``.
 - Arguments may contain Futures nested in pytrees; the worker resolves them
   before invoking the function — mirroring Ray's ObjectRef dereferencing as
   used via ``resolve_dependencies`` (ref ``fed/utils.py:48-83``).
 - Because every dependency Future is created before any task that consumes
   it, and the pool queue is FIFO, blocking waits inside workers cannot
   deadlock: a blocked task's dependency has always already been dequeued.
 - ``SerialLane`` provides actor semantics: one dedicated thread, methods
   execute one-at-a-time in submission order (Ray actor ordering guarantee).
"""

from __future__ import annotations

import contextvars
import threading
from concurrent.futures import Future, ThreadPoolExecutor
from queue import Queue
from typing import Any, Callable, List, Optional, Sequence, Union

from rayfed_tpu import tree_util


def _resolve(obj: Any) -> Any:
    """Replace every Future leaf in a pytree with its result (blocking;
    steals the producing task inline when it has not started yet)."""
    def leaf(x: Any) -> Any:
        if isinstance(x, Future):
            if not x.done():
                steal(x)
            return x.result()
        return x

    return tree_util.tree_map(leaf, obj)


def _deps_ready(obj: Any) -> bool:
    """True when no Future leaf in the pytree is still pending (failed
    futures count as ready — _resolve will surface their exception)."""
    ready = True

    def leaf(x: Any) -> Any:
        nonlocal ready
        if ready and isinstance(x, Future) and not x.done():
            ready = False
        return x

    tree_util.tree_map(leaf, obj)
    return ready


def try_resolved(obj: Any) -> "tuple[bool, Any]":
    """Non-blocking companion to :func:`_resolve` for the send fast path:
    (True, value) when ``obj`` is a plain value or an already-successful
    Future — the caller may proceed inline without a pool hop — else
    (False, None), meaning the value still needs the blocking dataflow
    path (pending, or failed: the worker path owns error enveloping)."""
    if isinstance(obj, Future):
        if obj.done() and obj.exception() is None:
            return True, obj.result()
        return False, None
    return True, obj


class _StealableTask:
    """A pool task a *blocked consumer* may claim and run on its own
    thread. On a busy (or single-core) host the pool-worker wake-up is a
    full context switch on the critical path; a consumer that is about to
    block in ``Future.result`` runs the producer inline instead. The
    claim flag makes pool worker and thief mutually exclusive — whoever
    claims first runs, the other does nothing."""

    __slots__ = ("fn", "args", "kwargs", "out", "num_returns",
                 "_lock", "_claimed", "_ctx")

    def __init__(self, fn, args, kwargs, out, num_returns, ctx=None):
        self.fn = fn
        self.args = args
        self.kwargs = kwargs
        self.out = out
        self.num_returns = num_returns
        self._lock = threading.Lock()
        self._claimed = False
        # Submitter's contextvar snapshot: pool workers (and thieves on
        # foreign drivers) must resolve the same FedContext the task was
        # submitted under, or a co-tenant's JobScoped state would leak in.
        self._ctx = ctx

    def claim(self) -> bool:
        with self._lock:
            if self._claimed:
                return False
            self._claimed = True
            return True

    def run_if_unclaimed(self) -> None:
        if self.claim():
            self._execute()

    def _execute(self) -> None:
        if self._ctx is not None:
            self._ctx.run(_run_task, self.fn, self.args, self.kwargs,
                          self.out, self.num_returns)
        else:
            _run_task(self.fn, self.args, self.kwargs, self.out,
                      self.num_returns)
        # Drop payload refs promptly: the out-futures keep this shell
        # alive via their steal attribute until they are collected.
        self.fn = self.args = self.kwargs = self.out = self._ctx = None


_steal_depth = threading.local()
# Each inline steal nests _run_task/_resolve frames on the thief's stack;
# cap the nesting so a long dependency chain blocks (pool workers make
# progress independently) instead of hitting the recursion limit.
_STEAL_DEPTH_MAX = 20


def steal(fut: Future) -> None:
    """If ``fut`` belongs to a queued-but-unstarted pool task, run that
    task on the calling thread. No-op for lane (actor) tasks, transport
    futures, started/claimed tasks, or past the nesting cap."""
    task = getattr(fut, "_fedtpu_steal", None)
    if task is None:
        return
    depth = getattr(_steal_depth, "v", 0)
    if depth >= _STEAL_DEPTH_MAX or not task.claim():
        return
    _steal_depth.v = depth + 1
    try:
        task._execute()
    finally:
        _steal_depth.v = depth


def result_stealing(fut: Future, timeout: Optional[float] = None) -> Any:
    """``fut.result(timeout)`` preceded by an inline steal attempt — the
    entry point for API-level consumers (``fed.get``)."""
    if not fut.done():
        steal(fut)
    return fut.result(timeout)


def _run_task(
    fn: Callable,
    args: Sequence[Any],
    kwargs: Optional[dict],
    out: Union[Future, List[Future]],
    num_returns: int,
) -> None:
    try:
        rargs = _resolve(list(args))
        rkwargs = _resolve(kwargs or {})
        result = fn(*rargs, **rkwargs)
    except BaseException as e:  # noqa: BLE001 - stored, not swallowed
        if num_returns == 1:
            out.set_exception(e)
        else:
            for f in out:
                f.set_exception(e)
        return
    if num_returns == 1:
        out.set_result(result)
    else:
        try:
            items = list(result)
            if len(items) != num_returns:
                raise ValueError(
                    f"task declared num_returns={num_returns} but returned "
                    f"{len(items)} values"
                )
        except BaseException as e:  # noqa: BLE001
            for f in out:
                f.set_exception(e)
            return
        for f, item in zip(out, items):
            f.set_result(item)


class SerialLane:
    """A single-threaded execution lane preserving submission order —
    the actor execution model (ref ``fed/_private/fed_actor.py``)."""

    def __init__(self, name: str = "fedtpu-actor-lane"):
        self._q: "Queue[Optional[Callable[[], None]]]" = Queue()
        self._lock = threading.Lock()
        self.killed = False
        self._thread = threading.Thread(target=self._loop, name=name, daemon=True)
        self._thread.start()

    def _loop(self) -> None:
        while True:
            item = self._q.get()
            if item is None:
                return
            item()

    def submit_thunk(self, thunk: Callable[[], None]) -> bool:
        """Enqueue; False if the lane was killed (caller must fail the
        task's futures itself — nothing will ever dequeue them)."""
        with self._lock:
            if self.killed:
                return False
            self._q.put(thunk)
            return True

    def kill(self) -> None:
        """Fail-fast teardown: queued-but-unexecuted thunks observe
        ``killed`` and fail their futures instead of silently vanishing."""
        with self._lock:
            self.killed = True
            self._q.put(None)

    def stop(self) -> None:
        self._q.put(None)


class LocalExecutor:
    """The party-local scheduler: a FIFO thread pool plus serial lanes."""

    def __init__(self, max_workers: int = 32):
        self._pool = ThreadPoolExecutor(
            max_workers=max_workers, thread_name_prefix="fedtpu-exec"
        )
        self._lanes: List[SerialLane] = []
        self._lock = threading.Lock()

    def submit(
        self,
        fn: Callable,
        args: Sequence[Any] = (),
        kwargs: Optional[dict] = None,
        *,
        num_returns: int = 1,
        lane: Optional[SerialLane] = None,
        eager: bool = True,
    ) -> Union[Future, List[Future]]:
        if num_returns == 1:
            out: Union[Future, List[Future]] = Future()
        else:
            out = [Future() for _ in range(num_returns)]

        def fail_all(exc: BaseException) -> None:
            for f in out if isinstance(out, list) else [out]:
                f.set_exception(exc)

        def _charge_slot() -> Optional[str]:
            # Tenant quota on pool/lane occupancy ("executor_tasks"):
            # eager-inline tasks run on the caller's own thread and are
            # exempt — the quota caps how much of the SHARED worker pool
            # one tenant may hold. Raises TenantQuotaExceeded loudly.
            from rayfed_tpu.tenancy.context import current_job
            from rayfed_tpu.tenancy.qos import get_ledger

            job = current_job()
            get_ledger().charge(job, "executor_tasks", 1)
            first = out[0] if isinstance(out, list) else out
            first.add_done_callback(
                lambda _f: get_ledger().release(job, "executor_tasks", 1)
            )
            return job

        if lane is not None:
            from rayfed_tpu.exceptions import FedActorKilledError

            _charge_slot()
            task_ctx = contextvars.copy_context()

            def thunk() -> None:
                if lane.killed:
                    fail_all(FedActorKilledError("actor was killed"))
                    return
                task_ctx.run(_run_task, fn, args, kwargs, out, num_returns)

            if not lane.submit_thunk(thunk):
                fail_all(FedActorKilledError("actor was killed"))
        elif eager and _deps_ready(list(args)) and _deps_ready(kwargs or {}):
            # Eager inline execution: every dependency is already
            # resolved, so the task has nothing to block on — running it
            # on the caller's thread skips the pool-dispatch wake-up AND
            # the consumer's wait wake-up (the future resolves before
            # submit returns). This cannot deadlock the driver: every
            # future in this system is created at submission time (task,
            # actor call, or transport recv), so anything a task could
            # wait on internally is already in flight and resolves
            # without the caller's help. The latency-critical chains
            # (small federated rounds) are exactly the ones whose tiny
            # tasks land here. Tasks submitted with ``eager=False`` opt
            # out: a task that BLOCKS until other submissions make
            # progress (e.g. a serving submit waiting on the batched
            # decode engine) must not occupy the caller's thread, or the
            # driver could never issue the concurrent work it waits on.
            _run_task(fn, args, kwargs, out, num_returns)
        else:
            _charge_slot()
            task = _StealableTask(
                fn, args, kwargs, out, num_returns,
                ctx=contextvars.copy_context(),
            )
            for f in out if isinstance(out, list) else [out]:
                f._fedtpu_steal = task
            self._pool.submit(task.run_if_unclaimed)
        return out

    def new_lane(self, name: str = "fedtpu-actor-lane") -> SerialLane:
        lane = SerialLane(name)
        with self._lock:
            self._lanes.append(lane)
        return lane

    def shutdown(self, wait: bool = True) -> None:
        with self._lock:
            lanes, self._lanes = self._lanes, []
        for lane in lanes:
            lane.stop()
        self._pool.shutdown(wait=wait)
