# Copyright 2026 The rayfed-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Party-local stateful actors.

Capability parity: reference ``fed/_private/fed_actor.py`` — a
``FedActorHandle`` mirrors the actor API; the real object is instantiated
only in its own party (ref ``fed_actor.py:78-91``); method access resolves
through ``__getattr__`` (ref ``fed_actor.py:44-76``) and every method call
goes through a FedCallHolder (ref ``fed_actor.py:115-145``).

TPU-native substrate: instead of a Ray actor process, the instance lives on
a :class:`~rayfed_tpu._private.executor.SerialLane` — a dedicated thread
that executes constructor + methods one-at-a-time in submission order (the
actor ordering guarantee). For model actors this is exactly right: state is
a pytree of device arrays on the party mesh; methods are jit calls whose
device work overlaps via XLA's async dispatch even though Python-side entry
is serialized.
"""

from __future__ import annotations

import logging
from typing import Any, Dict

from rayfed_tpu._private.call_holder import FedCallHolder
from rayfed_tpu._private.global_context import get_global_context

logger = logging.getLogger(__name__)


class FedActorHandle:
    def __init__(
        self,
        fed_class_task_id: int,
        addresses: Dict[str, str],
        cls,
        party: str,
        node_party: str,
        options: Dict[str, Any],
    ) -> None:
        self._fed_class_task_id = fed_class_task_id
        self._addresses = addresses
        self._body = cls
        self._party = party
        self._node_party = node_party
        self._options = options
        self._lane = None
        self._instance_future = None

    def __getattr__(self, method_name: str):
        # `__getattr__` is only invoked for *missing* attributes, so actor
        # internals resolve normally (ref fed_actor.py:44-54).
        if method_name.startswith("_"):
            raise AttributeError(method_name)
        if self._node_party == self._party and self._instance_future is None:
            raise AttributeError(
                f"actor {self._body} is not instantiated in party {self._party}"
            )
        return FedActorMethod(
            self._addresses, self._party, self._node_party, self, method_name
        )

    def _execute_impl(self, cls_args, cls_kwargs):
        """Instantiate the real object — own party only (routed by the
        creation FedCallHolder, ref fed_actor.py:78-91)."""
        executor = get_global_context().get_executor()
        self._lane = executor.new_lane(
            name=f"fedtpu-actor-{getattr(self._body, '__name__', 'actor')}"
        )
        self._instance_future = executor.submit(
            self._body, cls_args, cls_kwargs, lane=self._lane
        )
        return self._instance_future

    def _execute_remote_method(self, method_name, options, args, kwargs):
        """Run a method on the actor's serial lane — own party only."""
        num_returns = (options or {}).get("num_returns", 1)
        executor = get_global_context().get_executor()
        instance_future = self._instance_future

        def call(*a, **k):
            instance = instance_future.result()
            return getattr(instance, method_name)(*a, **k)

        return executor.submit(call, args, kwargs, num_returns=num_returns,
                               lane=self._lane)

    def _kill(self) -> None:
        """Forcefully drop the instance (fed.kill, ref ``fed/api.py:611-623``).
        Pending method futures fail fast with FedActorKilledError rather than
        hanging their consumers."""
        if self._lane is not None:
            self._lane.kill()


class FedActorMethod:
    def __init__(self, addresses, party, node_party, fed_actor_handle,
                 method_name) -> None:
        self._addresses = addresses
        self._party = party
        self._node_party = node_party
        self._fed_actor_handle = fed_actor_handle
        self._method_name = method_name
        self._options: Dict[str, Any] = {}
        self._fed_call_holder = FedCallHolder(node_party, self._execute_impl)

    def remote(self, *args, **kwargs):
        return self._fed_call_holder.internal_remote(*args, **kwargs)

    def options(self, **options):
        self._options = options
        self._fed_call_holder.options(**options)
        return self

    def _execute_impl(self, args, kwargs):
        return self._fed_actor_handle._execute_remote_method(
            self._method_name, self._options, args, kwargs
        )
