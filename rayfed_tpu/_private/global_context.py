# Copyright 2026 The rayfed-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Per-process global context: job identity, deterministic sequence ids,
shutdown-once flag, and the cleanup (send-drain) manager.

Capability parity: reference ``fed/_private/global_context.py:22-120``.
The monotonically increasing ``next_seq_id`` is THE cross-party ordering
mechanism — every party runs the same driver program, so every party numbers
every call site identically (reference ``fed_call_holder.py:67``); the pair
(producer seq id, consumer seq id) addresses each data-flow edge on the wire.
"""

from __future__ import annotations

import threading
from typing import Callable, Optional


class GlobalContext:
    def __init__(
        self,
        job_name: str,
        current_party: str,
        sending_failure_handler: Optional[Callable[[Exception], None]] = None,
        exit_on_sending_failure: bool = False,
        continue_waiting_for_data_sending_on_error: bool = False,
        party_process_id: int = 0,
        party_num_processes: int = 1,
    ) -> None:
        self._job_name = job_name
        self._current_party = current_party
        # A party spanning several host processes elects process 0 the
        # leader: it alone owns the wire (proxies, sends); received
        # cross-party values are relayed to follower hosts over the
        # party's coordination service so every host can feed them into
        # the jitted multi-host computation.
        self._party_process_id = party_process_id
        self._party_num_processes = party_num_processes
        self._seq_count = 0
        self._seq_lock = threading.Lock()
        self._sending_failure_handler = sending_failure_handler
        self._exit_on_sending_failure = exit_on_sending_failure
        self._continue_waiting_for_data_sending_on_error = (
            continue_waiting_for_data_sending_on_error
        )
        self._atomic_shutdown_flag_lock = threading.Lock()
        self._atomic_shutdown_flag = True
        # The last *sending* error lives on the CleanupManager (the drain
        # thread records it); only received errors are tracked here.
        self._last_received_error: Optional[Exception] = None

        # Imported lazily to avoid a cycle (cleanup → barriers → context).
        from rayfed_tpu._private.cleanup import CleanupManager
        from rayfed_tpu._private.executor import LocalExecutor

        self._cleanup_manager = CleanupManager(
            current_party, self.acquire_shutdown_flag
        )
        # The party-local task engine (replaces Ray task submission,
        # ref fed/api.py:413-417).
        self._executor = LocalExecutor()

    # -- identity ---------------------------------------------------------
    def get_job_name(self) -> str:
        return self._job_name

    def get_current_party(self) -> str:
        return self._current_party

    def get_party_process_id(self) -> int:
        return self._party_process_id

    def get_party_num_processes(self) -> int:
        return self._party_num_processes

    def is_party_leader(self) -> bool:
        return self._party_process_id == 0

    # -- deterministic DAG numbering (ref global_context.py:45-47) --------
    def next_seq_id(self) -> int:
        with self._seq_lock:
            self._seq_count += 1
            return self._seq_count

    def peek_seq_id(self) -> int:
        """Current DAG position WITHOUT advancing it — advancing outside a
        call site would desynchronize this party from its peers."""
        with self._seq_lock:
            return self._seq_count

    def reset_seq_id(self, value: int = 0) -> None:
        """Restart the DAG numbering — ONLY safe at a membership epoch
        bump, where every party resets at the same program point and the
        barrier layer's epoch stamp keeps old-numbered in-flight frames
        in a disjoint key space."""
        with self._seq_lock:
            self._seq_count = value

    # -- cleanup / failure bookkeeping ------------------------------------
    def get_cleanup_manager(self):
        return self._cleanup_manager

    def get_executor(self):
        return self._executor

    def get_sending_failure_handler(self):
        return self._sending_failure_handler

    def get_exit_on_sending_failure(self) -> bool:
        return self._exit_on_sending_failure

    def get_continue_waiting_for_data_sending_on_error(self) -> bool:
        return self._continue_waiting_for_data_sending_on_error

    def set_last_received_error(self, err: Exception) -> None:
        self._last_received_error = err

    def get_last_received_error(self) -> Optional[Exception]:
        return self._last_received_error

    def acquire_shutdown_flag(self) -> bool:
        """Return True exactly once — the caller that wins performs shutdown.

        Reference ``global_context.py:70-87``: uses a non-blocking acquire so
        a signal handler re-entering during shutdown cannot deadlock.
        """
        if not self._atomic_shutdown_flag_lock.acquire(blocking=False):
            return False
        try:
            if not self._atomic_shutdown_flag:
                return False
            self._atomic_shutdown_flag = False
            return True
        finally:
            self._atomic_shutdown_flag_lock.release()


# Tenancy: one GlobalContext per job, resolved through the ambient
# FedContext (tenancy/context.py) so concurrent fed.init jobs in one
# process each see their own seq counters, cleanup manager and executor.
from rayfed_tpu.tenancy.context import JobScoped

_contexts: "JobScoped[GlobalContext]" = JobScoped("global_context")
_context_lock = threading.Lock()  # fedlint: disable=global-mutable-singleton (guards per-job context slots; cleared by clear_global_context at shutdown)


def init_global_context(
    job_name: str,
    current_party: str,
    sending_failure_handler: Optional[Callable[[Exception], None]] = None,
    exit_on_sending_failure: bool = False,
    continue_waiting_for_data_sending_on_error: bool = False,
    party_process_id: int = 0,
    party_num_processes: int = 1,
) -> GlobalContext:
    from rayfed_tpu.tenancy.context import current_job

    with _context_lock:
        job = current_job() or job_name
        existing = _contexts.peek(job)
        if existing is None:
            existing = GlobalContext(
                job_name,
                current_party,
                sending_failure_handler=sending_failure_handler,
                exit_on_sending_failure=exit_on_sending_failure,
                continue_waiting_for_data_sending_on_error=(
                    continue_waiting_for_data_sending_on_error
                ),
                party_process_id=party_process_id,
                party_num_processes=party_num_processes,
            )
            _contexts.set(existing, job=job)
        return existing


def get_global_context() -> Optional[GlobalContext]:
    return _contexts.peek()


def clear_global_context(wait_for_sending: bool = False) -> None:
    with _context_lock:
        ctx = _contexts.pop()
        if ctx is not None:
            ctx.get_cleanup_manager().stop(wait_for_sending=wait_for_sending)
            ctx.get_executor().shutdown(wait=False)
