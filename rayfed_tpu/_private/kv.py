# Copyright 2026 The rayfed-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Job-scoped internal key-value store.

Capability parity: the reference stores cluster/job config in Ray's GCS
internal KV under job-scoped keys ``RAYFED#{job_name}#{key}``
(ref ``fed/_private/compatible_utils.py:68-74,106-139``) so proxy actors in
other processes can read them. Our proxies are threads in the party process,
so the default store is an in-process dict with the same prefixed-key
contract and lifecycle (init once per job, ``reset`` on shutdown — behavior
pinned by ``fed/tests/test_internal_kv.py``).

A party spanning several host processes configures the **file backend**
(``fed.init(config={"kv_store": {"backend": "file", "path": ...}})``): keys
live as files in a shared directory, so every host of the party reads the
same cluster/job config, and only the party leader clears it on shutdown.
"""

from __future__ import annotations

import hashlib
import os
import threading
from typing import Dict, Optional

_lock = threading.Lock()  # fedlint: disable=global-mutable-singleton (process KV backend; kv_reset() clears it at shutdown)
_initialized_jobs: set = set()  # fedlint: disable=global-mutable-singleton (process KV backend; kv_reset() clears it at shutdown)


class _MemoryBackend:
    def __init__(self) -> None:
        self._store: Dict[str, bytes] = {}

    def put(self, key: str, value: bytes) -> None:
        self._store[key] = value

    def get(self, key: str) -> Optional[bytes]:
        return self._store.get(key)

    def delete(self, key: str) -> None:
        self._store.pop(key, None)

    def clear(self, key_prefix: Optional[str] = None) -> None:
        if key_prefix is None:
            self._store.clear()
            return
        # Scope to one job's keys — several jobs share this process store.
        for key in [k for k in self._store if k.startswith(key_prefix)]:
            self._store.pop(key, None)


class _FileBackend:
    """One file per key in a shared directory; writes are atomic
    (tmp + rename) so concurrent host processes never read torn values.
    File names encode the full (job-prefixed) key so ``clear`` can scope
    itself to one job — several jobs may share the directory."""

    def __init__(self, root: str, clear_on_reset: bool = True) -> None:
        self._root = root
        self._clear_on_reset = clear_on_reset
        os.makedirs(root, exist_ok=True)

    def _path(self, key: str) -> str:
        import base64

        name = base64.urlsafe_b64encode(key.encode()).decode()
        if len(name) > 200:  # filesystem name cap; fall back to a digest
            name = hashlib.sha256(key.encode()).hexdigest()
        return os.path.join(self._root, name + ".kv")

    def put(self, key: str, value: bytes) -> None:
        path = self._path(key)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "wb") as f:
            f.write(value)
        os.replace(tmp, path)

    def get(self, key: str) -> Optional[bytes]:
        try:
            with open(self._path(key), "rb") as f:
                return f.read()
        except FileNotFoundError:
            return None

    def delete(self, key: str) -> None:
        try:
            os.remove(self._path(key))
        except FileNotFoundError:
            pass

    def clear(self, key_prefix: Optional[str] = None) -> None:
        if not self._clear_on_reset:
            return  # follower hosts leave the shared store to the leader
        import base64

        try:
            names = os.listdir(self._root)
        except FileNotFoundError:
            return
        for name in names:
            if not name.endswith(".kv"):
                continue
            if key_prefix is not None:
                try:
                    key = base64.urlsafe_b64decode(name[:-3]).decode()
                except Exception:  # noqa: BLE001 - digest-named file
                    key = None
                # Only delete THIS job's keys; other jobs may share the
                # directory. Digest-named (over-long) keys can't be
                # attributed, so they are left behind.
                if key is None or not key.startswith(key_prefix):
                    continue
            try:
                os.remove(os.path.join(self._root, name))
            except FileNotFoundError:
                pass


_backend = _MemoryBackend()  # fedlint: disable=global-mutable-singleton (process KV backend; kv_reset() clears it at shutdown)


def kv_configure(backend: str = "memory", path: Optional[str] = None,
                 clear_on_reset: bool = True) -> None:
    """Select the KV backend (call before/at ``fed.init``)."""
    global _backend
    with _lock:
        if backend == "memory":
            _backend = _MemoryBackend()
        elif backend == "file":
            assert path, "file KV backend needs a path"
            _backend = _FileBackend(path, clear_on_reset=clear_on_reset)
        else:
            raise ValueError(f"unknown kv backend {backend!r}")


def wrap_kv_key(job_name: str, key: str) -> str:
    """``FEDTPU#{job_name}#{key}`` (ref ``compatible_utils.py:68-74``)."""
    from rayfed_tpu._private.constants import KV_NAMESPACE_PREFIX

    return f"{KV_NAMESPACE_PREFIX}#{job_name}#{key}"


def kv_initialize(job_name: str) -> bool:
    with _lock:
        _initialized_jobs.add(job_name)
        return True


def kv_initialized() -> bool:
    return bool(_initialized_jobs)


def kv_put(job_name: str, key: str, value: bytes) -> bool:
    with _lock:
        _backend.put(wrap_kv_key(job_name, key), value)
        return True


def kv_get(job_name: str, key: str) -> Optional[bytes]:
    with _lock:
        return _backend.get(wrap_kv_key(job_name, key))


def kv_delete(job_name: str, key: str) -> bool:
    with _lock:
        _backend.delete(wrap_kv_key(job_name, key))
        return True


def kv_reset() -> None:
    """Clear the current job's keys; revert to the in-process backend
    only once no initialized job remains (ref ``compatible_utils.py:
    179-186``) — rebinding the backend under a live co-tenant would nuke
    its keys."""
    from rayfed_tpu.tenancy.context import current_job

    global _backend
    with _lock:
        job = current_job()
        if job is None and len(_initialized_jobs) == 1:
            job = next(iter(_initialized_jobs))
        if job is not None:
            # Scoped to the resolved job even when it is no longer
            # initialized (idempotent re-run) — falling back to "some
            # other job" here would nuke a live co-tenant's keys.
            _backend.clear(wrap_kv_key(job, ""))
            _initialized_jobs.discard(job)
        else:
            _backend.clear(None)
            _initialized_jobs.clear()
        if not _initialized_jobs:
            _backend = _MemoryBackend()
