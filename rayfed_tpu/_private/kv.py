"""Job-scoped internal key-value store.

Capability parity: the reference stores cluster/job config in Ray's GCS
internal KV under job-scoped keys ``RAYFED#{job_name}#{key}``
(ref ``fed/_private/compatible_utils.py:68-74,106-139``) so proxy actors in
other processes can read them. Our proxies are threads in the party process,
so the store is an in-process dict with the same prefixed-key contract and
lifecycle (init once per job, ``reset`` on shutdown — behavior pinned by
``fed/tests/test_internal_kv.py``).
"""

from __future__ import annotations

import threading
from typing import Dict, Optional

_store: Dict[str, bytes] = {}
_lock = threading.Lock()
_initialized_job: Optional[str] = None


def wrap_kv_key(job_name: str, key: str) -> str:
    """``FEDTPU#{job_name}#{key}`` (ref ``compatible_utils.py:68-74``)."""
    from rayfed_tpu._private.constants import KV_NAMESPACE_PREFIX

    return f"{KV_NAMESPACE_PREFIX}#{job_name}#{key}"


def kv_initialize(job_name: str) -> bool:
    global _initialized_job
    with _lock:
        _initialized_job = job_name
        return True


def kv_initialized() -> bool:
    return _initialized_job is not None


def kv_put(job_name: str, key: str, value: bytes) -> bool:
    with _lock:
        _store[wrap_kv_key(job_name, key)] = value
        return True


def kv_get(job_name: str, key: str) -> Optional[bytes]:
    with _lock:
        return _store.get(wrap_kv_key(job_name, key))


def kv_delete(job_name: str, key: str) -> bool:
    with _lock:
        _store.pop(wrap_kv_key(job_name, key), None)
        return True


def kv_reset() -> None:
    """Clear everything for this process (ref ``compatible_utils.py:179-186``)."""
    global _initialized_job
    with _lock:
        _store.clear()
        _initialized_job = None
