# Copyright 2026 The rayfed-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Single-consumer polling queue backing the send-drain threads.

Capability parity: reference ``fed/_private/message_queue.py:28-105`` — a
daemon thread pops callables off a deque; ``stop()`` enqueues a stop symbol
so in-flight sends drain first; a non-graceful stop from a signal-handler
context must not join the thread it is running on (reference
``message_queue.py:84-99``).
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from typing import Callable, Optional

logger = logging.getLogger(__name__)

_STOP = object()


class MessageQueueManager:
    def __init__(self, msg_handler: Callable, failure_handler: Optional[Callable] = None,
                 thread_name: str = "fedtpu-msg-queue"):
        # One handler per message; returning False marks a handling failure.
        self._msg_handler = msg_handler
        self._failure_handler = failure_handler
        self._thread_name = thread_name
        self._queue: deque = deque()
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()

    def start(self) -> None:
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return

            def _loop() -> None:
                while True:
                    try:
                        msg = self._queue.popleft()
                    except IndexError:
                        time.sleep(0.05)
                        continue
                    if msg is _STOP:
                        break
                    try:
                        ok = self._msg_handler(msg)
                    except Exception:  # noqa: BLE001 - drain must survive
                        logger.exception("message handler raised")
                        ok = False
                    if ok is False:
                        if self._failure_handler is not None:
                            try:
                                self._failure_handler()
                            except Exception:  # noqa: BLE001
                                logger.exception("failure handler raised")
                        break
                logger.debug("message queue %s exited", self._thread_name)

            self._thread = threading.Thread(
                target=_loop, name=self._thread_name, daemon=True
            )
            self._thread.start()

    def append(self, msg) -> None:
        self._queue.append(msg)

    def appendleft(self, msg) -> None:
        self._queue.appendleft(msg)

    def size(self) -> int:
        return len(self._queue)

    def is_started(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def stop(self, graceful: bool = True) -> None:
        """Graceful: let queued sends drain, then join. Non-graceful: ask the
        thread to stop at the next pop without joining (safe from signal
        handlers running on arbitrary threads)."""
        if not self.is_started():
            return
        if threading.current_thread() is self._thread:
            # A handler asked its own queue to stop; just mark it.
            self._queue.appendleft(_STOP) if not graceful else self._queue.append(_STOP)
            return
        if graceful:
            self._queue.append(_STOP)
            self._thread.join()
        else:
            self._queue.appendleft(_STOP)
