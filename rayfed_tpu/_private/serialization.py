# Copyright 2026 The rayfed-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Payload serialization: array fast path + whitelisted unpickling.

Two lanes (SURVEY.md C15 + §7 "mixed payloads"):

1. ``tree`` — the TPU-native fast path. A pytree whose containers are
   msgpack-encodable and whose leaves are arrays / simple scalars is encoded
   as a msgpack skeleton plus the raw array bytes concatenated — **zero
   pickle on either end**. This is what 100MB gradient pushes ride; the
   reference instead cloudpickles every payload
   (ref ``fed/proxy/grpc/grpc_proxy.py:202,289-293``), which is both slower
   and a security liability.
2. ``pickle`` — fallback for arbitrary Python objects, guarded on the
   receiver by a module/class whitelist unpickler, mirroring
   ``fed/_private/serialization_utils.py:24-83`` (behavior pinned by
   ``fed/tests/serializations_tests/test_unpickle_with_whitelist.py``).
"""

from __future__ import annotations

import io
import pickle
from typing import Any, Dict, List, Optional, Tuple

import cloudpickle
import msgpack
import numpy as np

from rayfed_tpu import tree_util

# Modules that are always unpicklable even under a whitelist: our own error
# envelope must be able to cross (the peer re-raises it), and the exception
# *types* it may wrap. Note: unlike a blanket ``builtins`` pass-through,
# builtins are only admitted when the resolved object is an exception class —
# ``builtins.eval``/``getattr`` stay forbidden.
_ALWAYS_ALLOWED = {
    "rayfed_tpu.exceptions": {"FedRemoteError", "FedLocalError"},
}


def dumps(obj: Any) -> bytes:
    return cloudpickle.dumps(obj)


class _WhitelistUnpickler(pickle.Unpickler):
    def __init__(self, file, allowed: Dict[str, set]):
        super().__init__(file)
        self._allowed = allowed

    def find_class(self, module: str, name: str):
        for table in (self._allowed, _ALWAYS_ALLOWED):
            names = table.get(module)
            if names is not None and ("*" in names or name in names):
                return super().find_class(module, name)
        if module == "builtins":
            obj = getattr(__import__("builtins"), name, None)
            if isinstance(obj, type) and issubclass(obj, BaseException):
                return obj
        raise pickle.UnpicklingError(
            f"global '{module}.{name}' is forbidden by the serialization "
            "whitelist (serializing_allowed_list)."
        )


def restricted_loads(
    data: bytes, allowed_list: Optional[Dict[str, List[str]]]
) -> Any:
    """Unpickle; if a whitelist is configured, only whitelisted globals load.

    Accepts the reference's config forms (``serialization_utils.py:66-83``):
    a top-level ``"*"`` key disables the whitelist entirely, and a ``None``
    (or ``["*"]``) value allows every name in that module.
    """
    if allowed_list is None or "*" in allowed_list:
        return cloudpickle.loads(data)
    allowed = {
        m: {"*"} if ns is None else set(ns) for m, ns in allowed_list.items()
    }
    return _WhitelistUnpickler(io.BytesIO(data), allowed).load()


# ---------------------------------------------------------------------------
# Array-tree fast path
# ---------------------------------------------------------------------------

_MSGPACK_SCALARS = (bool, int, float, str, bytes, type(None))


class SegmentedPayload:
    """A frame payload received as several buffers instead of one.

    The receiver scatter-reads large ``tree`` payloads into leaf/shard-
    aligned buffers (sized from the frame's meta) so no host buffer of the
    whole payload — for a sharded array, of the global array — is ever
    allocated. Consumers address it by the same absolute ``(offset, n)``
    ranges the tree meta records.
    """

    def __init__(self, segments):
        # segments: list of (absolute_offset, buffer), ascending, contiguous.
        self._segments = [(off, memoryview(buf)) for off, buf in segments]
        self._starts = [off for off, _ in self._segments]
        self.nbytes = sum(v.nbytes for _, v in self._segments)

    def range(self, off: int, n: int) -> memoryview:
        import bisect

        i = bisect.bisect_right(self._starts, off) - 1
        if i >= 0:
            seg_off, view = self._segments[i]
            if off + n <= seg_off + view.nbytes:
                return view[off - seg_off: off - seg_off + n]
        raise ValueError(
            f"range ({off}, {n}) does not fall inside one received segment"
        )

    def tobytes(self) -> bytes:
        return b"".join(bytes(v) for _, v in self._segments)

    def segments(self):
        """The (absolute_offset, view) pairs — for re-basing a stripe's
        local segments into the reassembled payload's address space."""
        return list(self._segments)


def payload_nbytes(payload) -> int:
    if payload is None:
        return 0
    if isinstance(payload, SegmentedPayload):
        return payload.nbytes
    return memoryview(payload).nbytes


def payload_range(payload, off: int, n: int) -> memoryview:
    if isinstance(payload, SegmentedPayload):
        return payload.range(off, n)
    return memoryview(payload)[off: off + n]


def payload_bytes(payload) -> bytes:
    if isinstance(payload, SegmentedPayload):
        return payload.tobytes()
    return bytes(payload)


# Extents below this are coalesced with their neighbors: scatter-reading
# only pays off at shard scale, and thousands of tiny-leaf recv calls
# would regress small-tree throughput.
_MIN_SEGMENT = 256 * 1024


def tree_segment_lengths(meta_bytes: bytes, plen: int):
    """Buffer-aligned segment lengths for scatter-reading a ``tree``
    payload, or None when the meta doesn't contiguously cover it.

    Consecutive extents smaller than ``_MIN_SEGMENT`` are merged (a
    leaf's range always stays inside one segment — merging only widens
    segments), so a many-tiny-leaf tree still reads in big chunks while
    shard-scale extents get their own buffers.
    """
    try:
        meta = msgpack.unpackb(meta_bytes, raw=False)
        extents = []
        for d in meta["leaves"]:
            if d["t"] == "arr":
                extents.append((d["off"], d["n"]))
            elif d["t"] == "sharr":
                extents.extend((s["off"], s["n"]) for s in d["shards"])
        extents.sort()
        lengths = []
        pos = 0
        for off, n in extents:
            if n < 0 or off != pos:
                return None
            if n:
                if (
                    lengths
                    and lengths[-1] < _MIN_SEGMENT
                    and n < _MIN_SEGMENT
                ):
                    lengths[-1] += n
                else:
                    lengths.append(n)
                pos += n
        if pos != plen:
            return None
        return lengths
    except Exception:  # noqa: BLE001 - malformed meta -> single-buffer read
        return None


def _coalesce_sizes(sizes):
    """Apply the ``_MIN_SEGMENT`` coalescing rule to a list of extent
    sizes (shared between :func:`tree_segment_lengths` and the per-stripe
    segment plans — extents always stay inside one segment)."""
    lengths = []
    for n in sizes:
        if not n:
            continue
        if lengths and lengths[-1] < _MIN_SEGMENT and n < _MIN_SEGMENT:
            lengths[-1] += n
        else:
            lengths.append(n)
    return lengths


# ---------------------------------------------------------------------------
# Shard striping (multi-stream data plane): a large multi-buffer ``tree``
# payload is split at buffer boundaries into K contiguous byte-balanced
# stripes, each shipped as its own frame (``pkind: "stripe"``) over its
# own wire lane; the receiver reassembles them into one SegmentedPayload
# whose segments stay leaf/shard-aligned. Stripe frames carry a stripe
# descriptor ``sd``: {"i": stripe index, "n": stripe count, "off":
# absolute byte offset, "tot": total payload bytes, "segs": this
# stripe's scatter-read segment lengths}; stripe 0 additionally carries
# the original pkind/pmeta (``pk``/``pm`` live in the outer header as
# pkind/pmeta of the reassembled offer).
# ---------------------------------------------------------------------------

# A payload below this never stripes: lane-parallelism only pays at
# frame-pipelining scale, and the receive path's segment machinery wants
# shard-scale buffers.
STRIPE_MIN_BYTES = 1 << 20


def plan_stripes(buffers, k: int):
    """Partition ordered payload buffers into up to ``k`` contiguous,
    byte-balanced stripes, split only at buffer boundaries (so every
    leaf/shard extent stays inside one stripe and, within it, one
    scatter segment). Returns [(soff, bufs, nbytes, segs), ...] or None
    when striping is pointless (fewer than 2 non-empty buffers, or
    k <= 1)."""
    entries = []
    off = 0
    for b in buffers:
        n = buffer_nbytes(b)
        if n:
            entries.append((off, b, n))
        off += n
    total = off
    k = min(k, len(entries))
    if k <= 1 or total < STRIPE_MIN_BYTES:
        return None
    stripes = []
    i = 0
    done = 0
    for si in range(k):
        left = k - si
        target = (total - done + left - 1) // left
        soff = entries[i][0]
        bufs = []
        nbytes = 0
        while i < len(entries):
            # Leave at least one buffer for every remaining stripe.
            if bufs and (len(entries) - i) <= (left - 1):
                break
            if bufs and nbytes >= target:
                break
            bufs.append(entries[i][1])
            nbytes += entries[i][2]
            i += 1
        stripes.append((
            soff, bufs, nbytes,
            _coalesce_sizes([buffer_nbytes(b) for b in bufs]),
        ))
        done += nbytes
    return stripes


def stripe_segment_lengths(sd, plen: int):
    """Validated scatter-read segment lengths from a stripe frame's
    descriptor, or None for a single contiguous read. Shared by the
    Python and native receive paths, like :func:`tree_segment_lengths`."""
    try:
        segs = sd.get("segs")
        if not isinstance(segs, list) or len(segs) < 2:
            return None
        total = 0
        for n in segs:
            if not isinstance(n, int) or n <= 0:
                return None
            total += n
        if total != plen:
            return None
        return segs
    except Exception:  # noqa: BLE001 - malformed descriptor -> single read
        return None


def _array_buffer(arr: np.ndarray):
    """A bytes-like for the raw contents of a C-contiguous array. Zero-copy
    (memoryview) when the buffer protocol supports the dtype; ml_dtypes
    dtypes (bfloat16, float8) are reinterpreted as a same-width integer
    view (the buffer protocol rejects them directly, and ``tobytes`` would
    copy); only 0-d/empty arrays fall back to a copy."""
    if arr.nbytes == 0:
        return b""
    try:
        return memoryview(arr).cast("B")
    except (ValueError, TypeError):
        pass
    if arr.ndim and arr.dtype.itemsize in (1, 2, 4):
        view = arr.view({1: np.uint8, 2: np.uint16, 4: np.uint32}[
            arr.dtype.itemsize
        ])
        return memoryview(view).cast("B")
    return arr.tobytes()


def buffer_nbytes(buf) -> int:
    return memoryview(buf).nbytes


def concat_buffers(buffers) -> bytes:
    return b"".join(bytes(memoryview(b)) if not isinstance(b, bytes) else b
                    for b in buffers)


def _np_dtype(name: str) -> np.dtype:
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes  # registers bfloat16/float8 etc.

        return np.dtype(getattr(ml_dtypes, name))


def _is_array_leaf(x: Any) -> bool:
    # Covers numpy, jax.Array, torch.Tensor without importing any of them.
    return hasattr(x, "shape") and hasattr(x, "dtype") and hasattr(x, "__array__")


def _normalize_index(index, shape):
    """A shard's global slice as [[start, stop], ...] per dimension."""
    out = []
    for sl, dim in zip(index, shape):
        if sl.step not in (None, 1):
            return None
        start = 0 if sl.start is None else int(sl.start)
        stop = dim if sl.stop is None else int(sl.stop)
        out.append([start, stop])
    return out


def _spec_entry_to_wire(entry):
    if entry is None:
        return None
    if isinstance(entry, str):
        return entry
    return [str(a) for a in entry]


def try_encode_sharded(leaf, offset: int):
    """Encode a multi-shard ``jax.Array`` as per-shard buffers (SURVEY §7
    stage 4: sharded arrays cross the wire as shards, replacing the
    device->host gather of the whole global array).

    Returns (desc, buffers, nbytes) or None when the leaf is not a
    partitioned, fully-addressable jax.Array on a named mesh (those fall
    back to the dense ``arr`` path).
    """
    sharding = getattr(leaf, "sharding", None)
    if sharding is None or not hasattr(leaf, "addressable_shards"):
        return None
    try:
        from jax.sharding import NamedSharding
    except Exception:  # noqa: BLE001 - no jax in this process
        return None
    if not isinstance(sharding, NamedSharding):
        return None
    if not getattr(leaf, "is_fully_addressable", True):
        return None
    shape = tuple(int(d) for d in leaf.shape)
    # One copy per distinct global slice (replica_id>0 hold the same data).
    uniq = [s for s in leaf.addressable_shards if s.replica_id == 0]
    if len(uniq) <= 1:
        return None  # single shard / fully replicated: dense path is right
    shard_entries = []
    for s in uniq:
        idx = _normalize_index(s.index, shape)
        if idx is None:
            return None
        shard_entries.append((idx, s))
    shard_entries.sort(key=lambda e: tuple(a for ab in e[0] for a in ab))
    mesh = sharding.mesh
    spec = list(sharding.spec) + [None] * (len(shape) - len(sharding.spec))
    # Kick off every shard's device->host copy before materializing any of
    # them: the transfers overlap each other (and, on real TPUs, the wire
    # work of earlier shards) instead of serializing one np.asarray at a
    # time. No-op on backends without async transfer.
    for _, s in shard_entries:
        start = getattr(s.data, "copy_to_host_async", None)
        if start is not None:
            try:
                start()
            except Exception:  # noqa: BLE001 - overlap is best-effort
                break
    descs = []
    buffers = []
    total = 0
    for idx, s in shard_entries:
        arr = np.asarray(s.data)  # device->host of ONE shard only
        if not arr.flags["C_CONTIGUOUS"]:
            arr = np.ascontiguousarray(arr)
        buffers.append(_array_buffer(arr))
        descs.append({"i": idx, "off": offset + total, "n": arr.nbytes})
        total += arr.nbytes
    desc = {
        "t": "sharr",
        "dtype": np.dtype(leaf.dtype).name,
        "shape": list(shape),
        "mesh": {
            "axes": [str(a) for a in mesh.axis_names],
            "shape": [int(d) for d in mesh.devices.shape],
        },
        "spec": [_spec_entry_to_wire(e) for e in spec],
        "shards": descs,
    }
    return desc, buffers, total


def _spec_to_wire(spec: tree_util.TreeSpec) -> Optional[dict]:
    if spec.kind == "namedtuple":
        return None  # type object not wire-encodable -> caller falls back
    meta = spec.meta
    if spec.kind in ("dict", "odict"):
        if not all(isinstance(k, (str, int)) for k in meta):
            return None
        meta = list(meta)
    children = []
    for c in spec.children:
        w = _spec_to_wire(c)
        if w is None:
            return None
        children.append(w)
    return {"k": spec.kind, "m": meta, "c": children}


def _spec_from_wire(w: dict) -> tree_util.TreeSpec:
    return tree_util.TreeSpec(
        w["k"], w["m"], tuple(_spec_from_wire(c) for c in w["c"])
    )


# Lossy wire precision (config ``payload_wire_dtype``): accepted knob
# values -> canonical numpy dtype names. bf16 keeps float32's exponent
# range (safe for gradients); fp16 halves mantissa error but overflows
# past 65504 — callers pick their poison explicitly. int8 is the privacy
# plane's quantized tier (4x fewer bulk bytes than fp32): symmetric
# per-leaf uniform quantization, the scale rides the leaf descriptor
# (``qs``) — gated at fed.init on config["privacy"]["quantize"]="int8"
# (privacy/config.validate_wire_dtype_gate).
WIRE_DTYPES = {
    "bf16": "bfloat16",
    "bfloat16": "bfloat16",
    "fp16": "float16",
    "float16": "float16",
    "int8": "int8",
}


def wire_dtype_name(knob: Optional[str]) -> Optional[str]:
    """Canonical dtype name for the ``payload_wire_dtype`` knob (None
    passes through); unknown values raise at send time, like the
    compression knobs."""
    if knob is None:
        return None
    try:
        return WIRE_DTYPES[str(knob).lower()]
    except KeyError:
        raise ValueError(
            f"unknown payload_wire_dtype {knob!r}; supported: "
            f"{sorted(set(WIRE_DTYPES))}"
        ) from None


def try_encode_tree(
    data: Any, wire_dtype: Optional[str] = None
) -> Optional[Tuple[bytes, List[Any]]]:
    """Attempt the zero-pickle encoding.

    Returns (meta_bytes, buffers) or None if the payload needs pickling.
    ``meta_bytes`` is the msgpack-packed meta dict; ``buffers`` is a list of
    byte-like objects to be written after the header (no concatenation of
    large arrays).

    ``wire_dtype`` (canonical name from :func:`wire_dtype_name`) downcasts
    wide-float dense array leaves on the wire — LOSSY, opt-in; each leaf's
    original dtype rides the meta (``odt``) and is restored on decode.
    Sharded (``sharr``) leaves ship at native dtype (their buffers are
    zero-copy device-shard views; a downcast would force a host copy of
    every shard).
    """
    leaves, spec = tree_util.tree_flatten(data)
    wire_spec = _spec_to_wire(spec)
    if wire_spec is None:
        return None
    descs = []
    buffers: List[Any] = []
    offset = 0
    for leaf in leaves:
        if _is_array_leaf(leaf):
            sharded = try_encode_sharded(leaf, offset)
            if sharded is not None:
                desc, shard_bufs, total = sharded
                descs.append(desc)
                buffers.extend(shard_bufs)
                offset += total
                continue
            arr = np.asarray(leaf)  # device->host for jax arrays
            if arr.dtype == object:
                return None
            if not arr.dtype.isnative:
                # The wire declares endianness-less dtype NAMES and the
                # receiver reads native order — a big-endian source array
                # shipped raw would decode to garbage values.
                arr = arr.astype(arr.dtype.newbyteorder("="))
            odt = None
            qscale = None
            if (
                wire_dtype is not None
                and arr.dtype.kind == "f"
                and arr.dtype.itemsize > 2
            ):
                odt = arr.dtype.name
                if wire_dtype == "int8":
                    # Quantized tier: symmetric per-leaf int8, scale in
                    # the descriptor. The savings counter feeds the
                    # privacy plane's telemetry (lazy import: the wire
                    # path must work even in processes that never
                    # touched the privacy package).
                    from rayfed_tpu.privacy.quantize import quantize_leaf

                    saved = arr.nbytes
                    arr, qscale = quantize_leaf(arr)
                    saved -= arr.nbytes
                    if saved > 0:
                        try:
                            from rayfed_tpu.privacy.manager import (
                                record_quantized_bytes_saved,
                            )

                            record_quantized_bytes_saved(saved)
                        except Exception:  # noqa: BLE001 - stats only
                            pass
                else:
                    arr = arr.astype(_np_dtype(wire_dtype))
            if not arr.flags["C_CONTIGUOUS"]:
                arr = np.ascontiguousarray(arr)
            buf = _array_buffer(arr)
            desc = {
                "t": "arr",
                "dtype": arr.dtype.name,
                "shape": list(arr.shape),
                "off": offset,
                "n": arr.nbytes,
            }
            if odt is not None:
                desc["odt"] = odt
            if qscale is not None:
                desc["qs"] = float(qscale)
            descs.append(desc)
            buffers.append(buf)
            offset += arr.nbytes
        elif isinstance(leaf, _MSGPACK_SCALARS):
            if isinstance(leaf, int) and abs(leaf) >= 2**63:
                return None
            descs.append({"t": "obj", "v": leaf})
        else:
            return None
    meta = {"spec": wire_spec, "leaves": descs}
    try:
        meta_bytes = msgpack.packb(meta, use_bin_type=True)
    except Exception:  # noqa: BLE001 - any unpackable meta -> pickle lane
        return None
    return meta_bytes, buffers


def shard_view(desc: dict, shard: dict, payload) -> np.ndarray:
    """A zero-copy numpy view of one received shard's data."""
    dtype = _np_dtype(desc["dtype"])
    shape = [b - a for a, b in shard["i"]]
    raw = payload_range(payload, shard["off"], shard["n"])
    return np.frombuffer(raw, dtype=dtype).reshape(shape)


def region_volume(region) -> int:
    v = 1
    for a, b in region:
        v *= max(0, b - a)
    return v


def regions_cover_exactly(regions, target) -> bool:
    """True iff ``regions`` (clipped to ``target``) tile ``target`` exactly:
    full coverage, zero overlap. Guards against hostile/buggy shard metas
    whose byte counts add up while leaving holes (which would surface
    uninitialized receiver memory as array contents)."""
    clipped = []
    for r in regions:
        c = [
            [max(a, ta), min(b, tb)]
            for (a, b), (ta, tb) in zip(r, target)
        ]
        if region_volume(c) > 0:
            clipped.append(c)
    if sum(region_volume(c) for c in clipped) != region_volume(target):
        return False
    for i in range(len(clipped)):
        for j in range(i + 1, len(clipped)):
            inter = [
                [max(a1, a2), min(b1, b2)]
                for (a1, b1), (a2, b2) in zip(clipped[i], clipped[j])
            ]
            if region_volume(inter) > 0:
                return False
    return True


def extract_region(desc: dict, payload, region) -> np.ndarray:
    """Host array for one requested slice of a ``sharr`` leaf's global
    array: a zero-copy view when the region matches a received shard
    exactly, otherwise assembled from the overlapping shards. ``region``
    is [[start, stop], ...] per dimension. The received shards must
    exactly tile the requested region (no holes, no double-writes) — the
    guard against hostile/buggy metas surfacing uninitialized memory."""
    for shard in desc["shards"]:
        if shard["i"] == region:
            return shard_view(desc, shard, payload)
    if not regions_cover_exactly([s["i"] for s in desc["shards"]], region):
        raise ValueError(
            f"received shards do not exactly tile requested region {region}"
        )
    shape = [b - a for a, b in region]
    out = np.empty(shape, _np_dtype(desc["dtype"]))
    for shard in desc["shards"]:
        inter = [
            [max(sa, ra), min(sb, rb)]
            for (sa, sb), (ra, rb) in zip(shard["i"], region)
        ]
        if any(a >= b for a, b in inter):
            continue
        src = shard_view(desc, shard, payload)
        src_sl = tuple(
            slice(a - sa, b - sa)
            for (a, b), (sa, _) in zip(inter, shard["i"])
        )
        dst_sl = tuple(
            slice(a - ra, b - ra)
            for (a, b), (ra, _) in zip(inter, region)
        )
        out[dst_sl] = src[src_sl]
    return out


def assemble_global(desc: dict, payload) -> np.ndarray:
    """Reassemble a ``sharr`` leaf into one dense host array (fallback for
    receivers without a device mesh; the TPU lane reassembles per-device
    instead, see ``proxy/tpu/tpu_proxy.py``)."""
    return extract_region(
        desc, payload, [[0, int(d)] for d in desc["shape"]]
    )


def decode_tree(meta: dict, payload, sharded_fn=None) -> Any:
    """Inverse of :func:`try_encode_tree`. ``payload`` is a bytes-like (or
    :class:`SegmentedPayload`) of the concatenated buffers; array leaves are
    materialized as numpy views (zero-copy). ``sharded_fn(desc, payload)``
    lets a transport place ``sharr`` leaves directly onto devices; without
    it they are assembled into dense host arrays."""
    spec = _spec_from_wire(meta["spec"])
    leaves = []
    for d in meta["leaves"]:
        if d["t"] == "arr":
            dtype = _np_dtype(d["dtype"])
            raw = payload_range(payload, d["off"], d["n"])
            arr = np.frombuffer(raw, dtype=dtype).reshape(d["shape"])
            odt = d.get("odt")
            qs = d.get("qs")
            if qs is not None:
                # Quantized-tier leaf: dequantize through the shipped
                # per-leaf scale back to the producer's dtype (values
                # carry the int8 grid's rounding).
                arr = (arr.astype(np.float64) * qs).astype(
                    _np_dtype(odt or "float32")
                )
            elif odt:
                # Lossy-wire leaf: restore the producer's dtype so the
                # consumer sees the type it sent (values carry the
                # wire dtype's rounding).
                arr = arr.astype(_np_dtype(odt))
            leaves.append(arr)
        elif d["t"] == "sharr":
            if sharded_fn is not None:
                leaves.append(sharded_fn(d, payload))
            else:
                leaves.append(assemble_global(d, payload))
        else:
            leaves.append(d["v"])
    return tree_util.tree_unflatten(leaves, spec)


# ---------------------------------------------------------------------------
# Small-message compact lane ("mp"): scalars and plain containers of
# scalars cross as a single msgpack blob — no tree walk, no per-leaf meta,
# no pickle on either end. Type fidelity is strict: anything msgpack would
# round-trip as a *different* Python type (tuples, namedtuples, subclasses,
# numpy scalars) falls through to the tree/pickle lanes.
# ---------------------------------------------------------------------------

_MP_EXACT_SCALARS = frozenset(
    (bool, int, float, str, bytes, type(None))
)
_MP_MAX_DEPTH = 32


def _msgpack_clean(x: Any, depth: int = 0) -> bool:
    """True iff ``x`` round-trips through msgpack with exact types: only
    the exact builtin scalar types (int within 64 bits), lists, and dicts
    with str/int keys. Subclasses and tuples are rejected — msgpack would
    return them as base types / lists."""
    if depth > _MP_MAX_DEPTH:
        return False
    t = type(x)
    if t in _MP_EXACT_SCALARS:
        return t is not int or -(2**63) <= x < 2**64
    if t is list:
        return all(_msgpack_clean(v, depth + 1) for v in x)
    if t is dict:
        return all(
            type(k) in (str, int) and _msgpack_clean(v, depth + 1)
            for k, v in x.items()
        )
    return False


def try_encode_compact(data: Any, max_bytes: int) -> Optional[bytes]:
    """Encode ``data`` as one msgpack blob when it is msgpack-clean and the
    blob fits in ``max_bytes``; None otherwise (caller falls through to the
    tree/pickle lanes)."""
    if max_bytes <= 0 or not _msgpack_clean(data):
        return None
    try:
        blob = msgpack.packb(data, use_bin_type=True, strict_types=True)
    except Exception:  # noqa: BLE001 - anything unpackable -> normal lanes
        return None
    if len(blob) > max_bytes:
        return None
    return blob


def decode_compact(payload) -> Any:
    return msgpack.unpackb(
        payload_bytes(payload), raw=False, strict_map_key=False
    )


def quick_payload_bound(data: Any, limit: int) -> bool:
    """Conservative constant-ish-time probe: True only when the encoded
    payload for ``data`` is guaranteed to fit within ``limit`` bytes.
    False means "don't know / too big" — callers fall back to the normal
    queued path, so under-estimation is the only correctness hazard and
    every unknown leaf type declines. Used by the send fast path to decide
    *before* encoding whether a payload may ride the inline small lane."""
    if limit <= 0:
        return False
    budget = _quick_bound(data, 0)
    return budget is not None and budget <= limit


_QUICK_ITEM_CAP = 256


def _quick_bound(x: Any, depth: int) -> Optional[int]:
    if depth > _MP_MAX_DEPTH:
        return None
    t = type(x)
    if t in _MP_EXACT_SCALARS:
        if t is str:
            return 8 + 4 * len(x)  # worst-case UTF-8 expansion
        if t is bytes:
            return 8 + len(x)
        return 16
    if t in (list, tuple):
        if len(x) > _QUICK_ITEM_CAP:
            return None
        total = 8
        for v in x:
            b = _quick_bound(v, depth + 1)
            if b is None:
                return None
            total += b
        return total
    if t is dict:
        if len(x) > _QUICK_ITEM_CAP:
            return None
        total = 8
        for k, v in x.items():
            kb = _quick_bound(k, depth + 1)
            vb = _quick_bound(v, depth + 1)
            if kb is None or vb is None:
                return None
            total += kb + vb
        return total
    nbytes = getattr(x, "nbytes", None)
    if isinstance(nbytes, int):
        # Array-like leaf: raw bytes + generous per-leaf meta margin.
        return nbytes + 256
    return None


def encode_payload(
    data: Any,
    wire_dtype: Optional[str] = None,
    small_threshold: Optional[int] = None,
) -> Tuple[str, bytes, List[Any]]:
    """Encode any payload for the wire.

    Returns (kind, meta_bytes, buffers): kind in {"mp", "tree", "pickle"};
    meta_bytes is msgpack (tree) or empty (mp/pickle); buffers are written
    after the frame header in order. ``wire_dtype`` — see
    :func:`try_encode_tree` (tree lane only; the pickle lane ships
    objects verbatim). ``small_threshold`` (> 0) enables the compact
    ``mp`` lane for msgpack-clean payloads whose blob fits within it.
    """
    if small_threshold:
        blob = try_encode_compact(data, small_threshold)
        if blob is not None:
            return "mp", b"", [blob]
    enc = try_encode_tree(data, wire_dtype=wire_dtype)
    if enc is not None:
        meta_bytes, buffers = enc
        return "tree", meta_bytes, buffers
    return "pickle", b"", [dumps(data)]


def decode_payload(
    kind: str,
    meta_bytes: bytes,
    payload,
    allowed_list: Optional[Dict[str, List[str]]] = None,
    sharded_fn=None,
) -> Any:
    if kind == "tree":
        return decode_tree(
            msgpack.unpackb(meta_bytes, raw=False), payload, sharded_fn
        )
    if kind == "mp":
        # Pure msgpack — no unpickling, so no whitelist concerns.
        return decode_compact(payload)
    if kind == "pickle":
        return restricted_loads(payload_bytes(payload), allowed_list)
    raise ValueError(f"unknown payload kind: {kind}")


# ---------------------------------------------------------------------------
# Optional wire compression (native lanes only; the reference wire has no
# equivalent field, so the gRPC parity lane never compresses)
# ---------------------------------------------------------------------------

COMPRESSION_SCHEMES = ("zlib", "zstd")

# Valid compression_level range per scheme (zstd: negative = fast modes).
_LEVEL_RANGES = {"zlib": (-1, 9), "zstd": (-22, 22)}


def _check_scheme_level(scheme: str, level: int, knob: str) -> None:
    if scheme not in COMPRESSION_SCHEMES:
        raise ValueError(
            f"unknown {knob} {scheme!r}; supported: {COMPRESSION_SCHEMES}"
        )
    lo, hi = _LEVEL_RANGES[scheme]
    if not lo <= level <= hi:
        raise ValueError(
            f"compression_level must be in [{lo}, {hi}] for "
            f"{scheme}, got {level}"
        )


def compress_buffers(buffers, scheme: str, level: int = 1):
    """Compress the payload buffers into one stream (zlib or zstd).

    Returns (blob, raw_len) — or None when compression does not shrink the
    payload (incompressible data ships raw; the header then carries no
    ``comp`` flag, so the receive path is unchanged). Buffers are fed to
    the compressor incrementally — the payload is never concatenated, so
    peak send-side memory is payload + blob, not 2x payload.

    ``zstd`` is the codec of choice for gradient/weight data: at level 1-3
    it compresses comparably to zlib-6 at several times the speed
    (zlib stays supported for deployments pinning the earlier wire).

    Wire compatibility: a ``comp`` frame is only decodable by a
    compression-aware build supporting that scheme (the receiver fails
    the frame with a clear error otherwise), so ``payload_compression``
    requires every receiving party to run one; it is opt-in config,
    never negotiated silently.
    """
    _check_scheme_level(scheme, level, "payload_compression")
    if scheme == "zstd":
        import zstandard

        c = zstandard.ZstdCompressor(level=level).compressobj()
    else:
        import zlib

        c = zlib.compressobj(level)
    raw_len = 0
    parts = []
    for b in buffers:
        view = memoryview(b).cast("B")
        raw_len += view.nbytes
        chunk = c.compress(view)
        if chunk:
            parts.append(chunk)
    parts.append(c.flush())
    blob = b"".join(parts)
    if len(blob) >= raw_len:
        return None
    return blob, raw_len


def decompress_payload(payload, scheme: str, raw_len: int,
                       max_bytes: Optional[int]) -> memoryview:
    """Inverse of :func:`compress_buffers`, with decompression-bomb
    protection: output is bounded by ``max_bytes`` (and must match the
    header's declared ``rawlen``) BEFORE a full-size buffer can be
    produced."""
    if scheme not in COMPRESSION_SCHEMES:
        raise ValueError(f"unknown compression scheme on wire: {scheme!r}")
    if raw_len < 0:
        raise ValueError("compressed frame is missing its rawlen header")
    if max_bytes is not None and raw_len > max_bytes:
        raise ValueError(
            f"compressed payload declares rawlen {raw_len} past the "
            f"allowed size ({max_bytes} bytes)"
        )
    # Chunked inflate: a bomb is caught at the first chunk that overflows
    # the declared rawlen, and the bytearray keeps the receiver's
    # writable-view promise (numpy leaves decoded from raw frames come
    # from the recv pool). rawlen is only trusted for preallocation after
    # the cap validated it; in explicit no-cap deployments the buffer
    # grows with the actual inflated bytes instead, so a forged header
    # can never trigger a large allocation by itself.
    bounded = max_bytes is not None
    out = bytearray(raw_len if bounded else 0)
    pos = 0

    def put(chunk):
        nonlocal pos
        if not chunk:
            return
        if pos + len(chunk) > raw_len:
            raise ValueError(
                f"compressed payload inflates past its declared size "
                f"({raw_len} bytes)"
            )
        if bounded:
            out[pos: pos + len(chunk)] = chunk
        else:
            out.extend(chunk)
        pos += len(chunk)

    src = memoryview(payload_bytes(payload))
    step = 4 << 20
    if scheme == "zstd":
        import zstandard

        # stream_reader bounds OUTPUT per read call, so a bomb never
        # materialises more than one step past the declared size no
        # matter how extreme the ratio of a single compressed block.
        # Trailing bytes after the frame are rejected too: the reader
        # parses them as a following frame — garbage fails the frame
        # header, a real second frame overflows the declared rawlen
        # (both pinned in tests). The one undetectable tail is a valid
        # zero-output empty frame, which contributes no bytes.
        reader = zstandard.ZstdDecompressor().stream_reader(src)
        try:
            while True:
                want = min(step, raw_len - pos + 1)
                chunk = reader.read(max(1, want))
                if not chunk:
                    break
                put(chunk)
        except zstandard.ZstdError as e:
            raise ValueError(f"corrupt zstd stream: {e}") from None
        if pos != raw_len:
            raise ValueError(
                f"decompressed size {pos} != declared rawlen {raw_len}"
            )
        return memoryview(out)

    import zlib

    d = zlib.decompressobj()
    for i in range(0, len(src), step):
        put(d.decompress(src[i: i + step], raw_len - pos + 1))
    put(d.flush())
    if d.unused_data:
        raise ValueError("trailing bytes after the compressed stream")
    if not d.eof or pos != raw_len:
        raise ValueError(
            f"decompressed size {pos} != declared rawlen {raw_len}"
        )
    return memoryview(out)
