"""Payload serialization: array fast path + whitelisted unpickling.

Two lanes (SURVEY.md C15 + §7 "mixed payloads"):

1. ``tree`` — the TPU-native fast path. A pytree whose containers are
   msgpack-encodable and whose leaves are arrays / simple scalars is encoded
   as a msgpack skeleton plus the raw array bytes concatenated — **zero
   pickle on either end**. This is what 100MB gradient pushes ride; the
   reference instead cloudpickles every payload
   (ref ``fed/proxy/grpc/grpc_proxy.py:202,289-293``), which is both slower
   and a security liability.
2. ``pickle`` — fallback for arbitrary Python objects, guarded on the
   receiver by a module/class whitelist unpickler, mirroring
   ``fed/_private/serialization_utils.py:24-83`` (behavior pinned by
   ``fed/tests/serializations_tests/test_unpickle_with_whitelist.py``).
"""

from __future__ import annotations

import io
import pickle
from typing import Any, Dict, List, Optional, Tuple

import cloudpickle
import msgpack
import numpy as np

from rayfed_tpu import tree_util

# Modules that are always unpicklable even under a whitelist: our own error
# envelope must be able to cross (the peer re-raises it), and the exception
# *types* it may wrap. Note: unlike a blanket ``builtins`` pass-through,
# builtins are only admitted when the resolved object is an exception class —
# ``builtins.eval``/``getattr`` stay forbidden.
_ALWAYS_ALLOWED = {
    "rayfed_tpu.exceptions": {"FedRemoteError", "FedLocalError"},
}


def dumps(obj: Any) -> bytes:
    return cloudpickle.dumps(obj)


class _WhitelistUnpickler(pickle.Unpickler):
    def __init__(self, file, allowed: Dict[str, set]):
        super().__init__(file)
        self._allowed = allowed

    def find_class(self, module: str, name: str):
        for table in (self._allowed, _ALWAYS_ALLOWED):
            names = table.get(module)
            if names is not None and ("*" in names or name in names):
                return super().find_class(module, name)
        if module == "builtins":
            obj = getattr(__import__("builtins"), name, None)
            if isinstance(obj, type) and issubclass(obj, BaseException):
                return obj
        raise pickle.UnpicklingError(
            f"global '{module}.{name}' is forbidden by the serialization "
            "whitelist (serializing_allowed_list)."
        )


def restricted_loads(
    data: bytes, allowed_list: Optional[Dict[str, List[str]]]
) -> Any:
    """Unpickle; if a whitelist is configured, only whitelisted globals load
    (ref ``serialization_utils.py:66-83``)."""
    if allowed_list is None:
        return cloudpickle.loads(data)
    allowed = {m: set(ns) for m, ns in allowed_list.items()}
    return _WhitelistUnpickler(io.BytesIO(data), allowed).load()


# ---------------------------------------------------------------------------
# Array-tree fast path
# ---------------------------------------------------------------------------

_MSGPACK_SCALARS = (bool, int, float, str, bytes, type(None))


def _array_buffer(arr: np.ndarray):
    """A bytes-like for the raw contents of a C-contiguous array. Zero-copy
    (memoryview) when the buffer protocol supports the dtype; falls back to
    a copy for exotic dtypes (bfloat16, float8) and 0-d/empty arrays."""
    if arr.nbytes == 0:
        return b""
    try:
        return memoryview(arr).cast("B")
    except (ValueError, TypeError):
        return arr.tobytes()


def buffer_nbytes(buf) -> int:
    return memoryview(buf).nbytes


def concat_buffers(buffers) -> bytes:
    return b"".join(bytes(memoryview(b)) if not isinstance(b, bytes) else b
                    for b in buffers)


def _np_dtype(name: str) -> np.dtype:
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes  # registers bfloat16/float8 etc.

        return np.dtype(getattr(ml_dtypes, name))


def _is_array_leaf(x: Any) -> bool:
    # Covers numpy, jax.Array, torch.Tensor without importing any of them.
    return hasattr(x, "shape") and hasattr(x, "dtype") and hasattr(x, "__array__")


def _spec_to_wire(spec: tree_util.TreeSpec) -> Optional[dict]:
    if spec.kind == "namedtuple":
        return None  # type object not wire-encodable -> caller falls back
    meta = spec.meta
    if spec.kind in ("dict", "odict"):
        if not all(isinstance(k, (str, int)) for k in meta):
            return None
        meta = list(meta)
    children = []
    for c in spec.children:
        w = _spec_to_wire(c)
        if w is None:
            return None
        children.append(w)
    return {"k": spec.kind, "m": meta, "c": children}


def _spec_from_wire(w: dict) -> tree_util.TreeSpec:
    return tree_util.TreeSpec(
        w["k"], w["m"], tuple(_spec_from_wire(c) for c in w["c"])
    )


def try_encode_tree(data: Any) -> Optional[Tuple[dict, List[Any]]]:
    """Attempt the zero-pickle encoding.

    Returns (meta, buffers) or None if the payload needs pickling. ``meta``
    is msgpack-encodable; ``buffers`` is a list of byte-like objects to be
    written after the header (no concatenation of large arrays).
    """
    leaves, spec = tree_util.tree_flatten(data)
    wire_spec = _spec_to_wire(spec)
    if wire_spec is None:
        return None
    descs = []
    buffers: List[Any] = []
    offset = 0
    for leaf in leaves:
        if _is_array_leaf(leaf):
            arr = np.asarray(leaf)  # device->host for jax arrays
            if arr.dtype == object:
                return None
            if not arr.flags["C_CONTIGUOUS"]:
                arr = np.ascontiguousarray(arr)
            buf = _array_buffer(arr)
            descs.append(
                {
                    "t": "arr",
                    "dtype": arr.dtype.name,
                    "shape": list(arr.shape),
                    "off": offset,
                    "n": arr.nbytes,
                }
            )
            buffers.append(buf)
            offset += arr.nbytes
        elif isinstance(leaf, _MSGPACK_SCALARS):
            if isinstance(leaf, int) and abs(leaf) >= 2**63:
                return None
            descs.append({"t": "obj", "v": leaf})
        else:
            return None
    meta = {"spec": wire_spec, "leaves": descs}
    try:
        msgpack.packb(meta, use_bin_type=True)
    except Exception:  # noqa: BLE001 - any unpackable meta -> pickle lane
        return None
    return meta, buffers


def decode_tree(meta: dict, payload) -> Any:
    """Inverse of :func:`try_encode_tree`. ``payload`` is a bytes-like of the
    concatenated buffers; array leaves are materialized as numpy views
    (zero-copy) — the TPU transport then ``jax.device_put``s them onto the
    party mesh."""
    view = memoryview(payload)
    spec = _spec_from_wire(meta["spec"])
    leaves = []
    for d in meta["leaves"]:
        if d["t"] == "arr":
            dtype = _np_dtype(d["dtype"])
            raw = view[d["off"]: d["off"] + d["n"]]
            arr = np.frombuffer(raw, dtype=dtype).reshape(d["shape"])
            leaves.append(arr)
        else:
            leaves.append(d["v"])
    return tree_util.tree_unflatten(leaves, spec)


def encode_payload(data: Any) -> Tuple[str, bytes, List[Any]]:
    """Encode any payload for the wire.

    Returns (kind, meta_bytes, buffers): kind in {"tree", "pickle"};
    meta_bytes is msgpack (tree) or empty (pickle); buffers are written
    after the frame header in order.
    """
    enc = try_encode_tree(data)
    if enc is not None:
        meta, buffers = enc
        return "tree", msgpack.packb(meta, use_bin_type=True), buffers
    return "pickle", b"", [dumps(data)]


def decode_payload(
    kind: str,
    meta_bytes: bytes,
    payload,
    allowed_list: Optional[Dict[str, List[str]]] = None,
) -> Any:
    if kind == "tree":
        return decode_tree(msgpack.unpackb(meta_bytes, raw=False), payload)
    if kind == "pickle":
        return restricted_loads(bytes(payload), allowed_list)
    raise ValueError(f"unknown payload kind: {kind}")
