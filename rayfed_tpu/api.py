# Copyright 2026 The rayfed-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Public API: init / remote / get / kill / shutdown.

Capability parity: reference ``fed/api.py`` —
``init`` (api.py:67-297), ``shutdown``/``_shutdown`` (299-361),
``remote`` decorator + FedRemoteFunction/FedRemoteClass (384-528),
``get`` (531-608), ``kill`` (611-623), SIGINT hook (53-64,233).

Differences (TPU-native substrate, SURVEY.md §7):
 - no Ray: tasks run on the party-local executor, actors on serial lanes;
 - default transport is the native TCP data plane with the array fast path
   (``transport='tcp'``); ``transport='tpu'`` additionally places received
   arrays onto the party's device mesh; ``transport='grpc'`` is the
   reference-parity lane kept for benchmarking;
 - ``init`` may bind the party to a TPU sub-mesh via
   ``config['party_mesh']`` (device_ids / mesh_shape / axis_names).
"""

from __future__ import annotations

import inspect
import logging
import pickle
import signal
import threading
import sys
from typing import Any, Callable, Dict, List, Optional, Union

import rayfed_tpu._private.constants as constants
import rayfed_tpu.config as fed_config
import rayfed_tpu.utils as fed_utils
from rayfed_tpu import sanitize
from rayfed_tpu._private import executor
from rayfed_tpu._private import kv as internal_kv
from rayfed_tpu._private.call_holder import FedCallHolder
from rayfed_tpu._private.fed_actor import FedActorHandle
from rayfed_tpu._private.global_context import (
    clear_global_context,
    get_global_context,
    init_global_context,
)
from rayfed_tpu.config import CrossSiloMessageConfig
from rayfed_tpu.exceptions import FedRemoteError
from rayfed_tpu.fed_object import FedObject
from rayfed_tpu.proxy import barriers
from rayfed_tpu.utils import setup_logger

logger = logging.getLogger(__name__)

#: Machine-readable anchors for the static analyzer (``rayfed_tpu.lint``):
#: the public API entry points whose multi-controller contracts fedlint
#: machine-checks, mapped to the rule ids that guard them (rule catalogue
#: in docs/fedlint.md). Keep in sync with ``rayfed_tpu.lint.rules``; the
#: pairing is pinned by ``tests/test_fedlint.py``.
FEDLINT_ANCHORS = {
    "get": ("FED001", "FED002"),  # owner-push perimeter; seq-consistent gets
    "remote": ("FED002", "FED004"),  # identical call sequence; consumed edges
    "aggregate": ("FED006",),  # privacy plane on -> aggregate securely
}

original_sigint = signal.getsignal(signal.SIGINT)


def _signal_handler(signum, frame):
    if signum == signal.SIGINT:
        signal.signal(signal.SIGINT, original_sigint)
        logger.warning(
            "Interrupt caught - draining pending cross-party sends before "
            "exit; interrupt again to abort the drain."
        )
        _shutdown(intended=False)


def init(
    addresses: Optional[Dict[str, str]] = None,
    party: Optional[str] = None,
    config: Optional[Dict] = None,
    tls_config: Optional[Dict] = None,
    logging_level: str = "info",
    sender_proxy_cls=None,
    receiver_proxy_cls=None,
    receiver_sender_proxy_cls=None,
    job_name: Optional[str] = None,
    sending_failure_handler: Optional[Callable[[Exception], None]] = None,
    transport: Optional[str] = None,
):
    """Initialize this party's fed runtime.

    Args:
        addresses: ``{party: "host:port"}`` for every party in the job.
        party: this party's name (must be a key of ``addresses``).
        config: job configuration dict; supported keys:
            ``cross_silo_comm`` (see :class:`CrossSiloMessageConfig` /
            :class:`~rayfed_tpu.config.TcpCrossSiloMessageConfig`),
            ``barrier_on_initializing`` (bool: block until all parties are
            reachable), ``party_mesh`` (TPU device topology for this party,
            see :class:`~rayfed_tpu.config.PartyMeshConfig`), ``privacy``
            (secure aggregation / DP / quantized pushes, see
            :class:`~rayfed_tpu.privacy.PrivacyConfig` and
            docs/privacy.md; keys are validated strictly — a typo
            rejects init).
        tls_config: ``{ca_cert, cert, key}`` file paths for mutual TLS.
        logging_level: root logging level.
        sender_proxy_cls / receiver_proxy_cls: custom transport classes
            (the pluggable seam, ref api.py:73-75).
        receiver_sender_proxy_cls: a combined transport serving both
            directions behind the party's single advertised port (ref
            api.py:239-248); overrides the separate sender/receiver
            classes. ``cross_silo_comm.use_global_proxy=False`` registers
            proxies under job-suffixed names so several jobs' proxies
            coexist in one process (ref barriers.py:31-85).
        job_name: multi-job isolation name; peers in other jobs get 417.
        sending_failure_handler: called with the last sending error on
            unintended shutdown.
        transport: 'tcp' (default), 'tpu', or 'grpc'.
    """
    assert addresses, "fed.init needs addresses={party: 'host:port', ...}"
    assert party, "fed.init needs party=<this party's name>"
    assert party in addresses, (
        f"party {party!r} has no entry in addresses ({sorted(addresses)})"
    )
    config = config or {}

    if job_name is None:
        job_name = constants.DEFAULT_JOB_NAME

    fed_utils.validate_addresses(addresses)

    cross_silo_comm_dict = config.get("cross_silo_comm", {})
    cross_silo_comm_config = CrossSiloMessageConfig.from_dict(cross_silo_comm_dict)

    # Validate transport-dependent config BEFORE any state is built, so a
    # rejected init leaves nothing behind. The privacy block is STRICT:
    # a typo'd privacy.* key rejects init (a job must not silently run
    # without the protection it asked for), and the int8 wire tier is
    # refused unless the privacy plane's quantizer is on.
    privacy_dict = config.get("privacy")
    privacy_cfg = None
    if privacy_dict is not None:
        from rayfed_tpu.privacy.config import PrivacyConfig

        privacy_cfg = PrivacyConfig.from_dict(privacy_dict)
    from rayfed_tpu.privacy.config import validate_wire_dtype_gate

    validate_wire_dtype_gate(
        cross_silo_comm_dict.get("payload_wire_dtype"), privacy_dict
    )
    # The checkpoint section is STRICT for the same reason: a typo'd
    # retention key must reject init here, not the round-N save_job_state
    # that was supposed to make the job restartable. Validated only at
    # this point; the defaults are installed later, after the runtime
    # exists, so a rejected init leaves no module state behind.
    checkpoint_dict = config.get("checkpoint")
    if checkpoint_dict is not None:
        from rayfed_tpu.checkpoint import CheckpointConfig

        CheckpointConfig.from_dict(checkpoint_dict)
    # The tenancy section is STRICT too: a typo'd quota key must reject
    # init here — a tenant silently running unbounded defeats the whole
    # QoS/quota contract (docs/multitenancy.md).
    from rayfed_tpu.tenancy.context import TenancyConfig

    tenancy_dict = config.get("tenancy")
    tenancy_cfg = (
        TenancyConfig.from_dict(tenancy_dict)
        if tenancy_dict is not None
        else None
    )
    transport = transport or config.get("transport", "tcp")
    if (
        transport == "grpc"
        and cross_silo_comm_config.allow_pickle_payloads is False
    ):
        raise ValueError(
            "allow_pickle_payloads=False is incompatible with "
            "transport='grpc': the gRPC parity lane pickles every payload "
            "by design. Use the native 'tcp'/'tpu' transports for strict "
            "arrays-only mode."
        )

    # Multi-host party: config['jax_distributed'] = {coordinator_address,
    # num_processes, process_id} joins THIS party's hosts into one jax
    # process group. Process 0 is the party leader — it alone owns the
    # wire; followers run the same program for the jitted multi-host
    # computation (SURVEY §2 "party = JAX multi-controller process group").
    jax_dist = config.get("jax_distributed")
    party_process_id = int(jax_dist.get("process_id", 0)) if jax_dist else 0
    party_num_processes = (
        int(jax_dist.get("num_processes", 1)) if jax_dist else 1
    )

    # Tenancy plane first: the FedContext is the per-job home every other
    # plane's JobScoped state resolves through, so it must exist (and be
    # bound to this thread) before anything below builds state. Also
    # registers the job with the weighted-fair transport scheduler.
    from rayfed_tpu.tenancy import context as tenancy_context
    from rayfed_tpu.tenancy import qos as tenancy_qos

    fed_ctx = tenancy_context.create_context(
        job_name, party, tenancy=tenancy_cfg
    )
    tenancy_context.activate(fed_ctx)
    tenancy_qos.get_scheduler().register(job_name, fed_ctx.tenancy)

    init_global_context(
        job_name=job_name,
        current_party=party,
        sending_failure_handler=sending_failure_handler,
        exit_on_sending_failure=cross_silo_comm_config.exit_on_sending_failure,
        continue_waiting_for_data_sending_on_error=(
            cross_silo_comm_config.continue_waiting_for_data_sending_on_error
        ),
        party_process_id=party_process_id,
        party_num_processes=party_num_processes,
    )

    tls_config = {} if tls_config is None else tls_config
    if tls_config:
        assert (
            "cert" in tls_config and "key" in tls_config
        ), "Cert or key are not in tls_config."

    kv_store = config.get("kv_store")
    if kv_store is not None:
        # Shared (file-backed) KV so every host process of a multi-host
        # party reads the same cluster/job config; only the leader clears
        # it on shutdown.
        internal_kv.kv_configure(
            backend=kv_store.get("backend", "memory"),
            path=kv_store.get("path"),
            clear_on_reset=party_process_id == 0,
        )
    internal_kv.kv_initialize(job_name)
    cluster_config = {
        constants.KEY_OF_CLUSTER_ADDRESSES: addresses,
        constants.KEY_OF_CURRENT_PARTY_NAME: party,
        constants.KEY_OF_TLS_CONFIG: tls_config,
    }
    internal_kv.kv_put(
        job_name, constants.KEY_OF_CLUSTER_CONFIG, pickle.dumps(cluster_config)
    )
    job_config = {
        constants.KEY_OF_CROSS_SILO_COMM_CONFIG_DICT: cross_silo_comm_dict,
    }
    internal_kv.kv_put(
        job_name, constants.KEY_OF_JOB_CONFIG, pickle.dumps(job_config)
    )

    setup_logger(
        logging_level=logging_level,
        logging_format=constants.LOG_FORMAT,
        party_val=party,
        job_name=job_name,
    )
    logger.info("Started rayfed_tpu with %s", cluster_config)

    # Signal handlers can only be installed from the main thread; a
    # secondary job initialized from a worker thread (multi-tenant
    # process) simply shares the handler the first job installed.
    if threading.current_thread() is threading.main_thread():
        signal.signal(signal.SIGINT, _signal_handler)
    get_global_context().get_cleanup_manager().start(
        exit_on_sending_failure=cross_silo_comm_config.exit_on_sending_failure,
        expose_error_trace=cross_silo_comm_config.expose_error_trace,
    )

    # Optional TPU binding: establish the party's device mesh before any
    # task is jit-compiled on it (SURVEY.md §3.1 "In a TPU build `init`
    # additionally establishes the party-slice mesh"). A multi-host party
    # first joins its jax.distributed process group.
    if jax_dist is not None:
        from rayfed_tpu.mesh import init_distributed

        init_distributed(**jax_dist)
    party_mesh_dict = config.get("party_mesh")
    if party_mesh_dict is not None or transport == "tpu":
        from rayfed_tpu.mesh import init_party_mesh

        init_party_mesh(fed_config.PartyMeshConfig.from_dict(party_mesh_dict))
    use_global_proxy = cross_silo_comm_dict.get("use_global_proxy", True)
    if party_process_id != 0:
        # Follower host of a multi-host party: the leader owns the wire
        # (listen port, sends, receives); this process only executes the
        # party's jitted computation.
        logger.info(
            "Joined party %s as follower host %d; proxies stay on the "
            "leader.", party, party_process_id,
        )
    elif receiver_sender_proxy_cls is not None:
        barriers.start_sender_receiver_proxy(
            addresses=addresses,
            party=party,
            job_name=job_name,
            tls_config=tls_config,
            proxy_cls=receiver_sender_proxy_cls,
            proxy_config=cross_silo_comm_dict,
            ready_timeout_s=cross_silo_comm_config.timeout_in_ms / 1000,
            use_global_proxy=use_global_proxy,
        )
    else:
        default_sender_cls, default_receiver_cls = (
            barriers._default_transport_classes(transport)
        )
        receiver_proxy_cls = receiver_proxy_cls or default_receiver_cls
        sender_proxy_cls = sender_proxy_cls or default_sender_cls

        barriers.start_receiver_proxy(
            addresses=addresses,
            party=party,
            job_name=job_name,
            tls_config=tls_config,
            proxy_cls=receiver_proxy_cls,
            proxy_config=cross_silo_comm_dict,
            ready_timeout_s=cross_silo_comm_config.timeout_in_ms / 1000,
            use_global_proxy=use_global_proxy,
        )
        barriers.start_sender_proxy(
            addresses=addresses,
            party=party,
            job_name=job_name,
            tls_config=tls_config,
            proxy_cls=sender_proxy_cls,
            proxy_config=cross_silo_comm_dict,
            use_global_proxy=use_global_proxy,
        )

    # Opt-in cross-party collective lane: all parties join one
    # jax.distributed group so FedAvg can lower to a cross-process psum
    # (collective.fed_collective_mean), gated per-collective on the
    # control plane. AFTER the proxies: the join blocks on every party
    # arriving, and this party must stay reachable meanwhile.
    collective_dict = config.get("collective")
    if collective_dict is not None and party_num_processes > 1:
        raise ValueError(
            "config['collective'] and a multi-host party "
            "(config['jax_distributed']) cannot share a process: the "
            "party's private process group would be mistaken for the "
            "joint all-parties group. Aggregate multi-host parties over "
            "the push lane."
        )
    if collective_dict is not None:
        from rayfed_tpu import collective as _collective

        _collective.init_joint_collective(
            addresses,
            party,
            coordinator_address=collective_dict["coordinator"],
            inner_axes=tuple(collective_dict.get("inner_axes", ("data",))),
            inner_shape=collective_dict.get("inner_shape"),
            init_timeout_s=collective_dict.get("init_timeout_s", 120.0),
        )

    # Resilience wiring (docs/resilience.md), leader-only — followers own
    # no proxies to inject into or probe from. The fault injector wraps
    # the just-started sender proxy BEFORE the readiness barrier so a
    # schedule can exercise init-time faults too; the liveness monitor
    # starts last, its heartbeats riding the same (possibly injected)
    # sender, so a partitioned link takes the heartbeats down with the
    # data.
    # Aggregation topology default (rayfed_tpu/topology.py): every driver
    # reads the same config, so every party plans the identical reduction
    # DAG (multi-controller contract).
    aggregation_dict = config.get("aggregation") or {}
    if aggregation_dict:
        from rayfed_tpu import topology as _topology

        _topology.set_default(
            aggregation_dict.get("topology", "auto"),
            group_size=aggregation_dict.get("group_size"),
        )
        # Async-mode job defaults (aggregation.async_* keys,
        # docs/async_rounds.md) — validated eagerly so a typo'd key or
        # out-of-range value rejects init, not the first async round.
        from rayfed_tpu import async_rounds as _async_rounds

        _async_rounds.set_default_async_config(aggregation_dict)

    # Serving-plane job defaults (docs/serving.md): stored like the
    # aggregation topology default — every driver reads the same dict, so
    # a later fed.serve() builds the identical engine on every party.
    serving_dict = config.get("serving")
    if serving_dict is not None:
        # Validate eagerly so a bad key rejects init, not the first serve.
        fed_config.ServingConfig.from_dict(serving_dict)
        from rayfed_tpu.serving import client as _serving_client

        _serving_client.set_default_serving_config(serving_dict)

    resilience_dict = config.get("resilience") or {}
    if resilience_dict and party_process_id == 0:
        from rayfed_tpu.resilience import inject as _inject
        from rayfed_tpu.resilience import liveness as _liveness

        schedule_dict = resilience_dict.get("fault_schedule")
        if schedule_dict is not None:
            _inject.install(
                _inject.FaultSchedule.from_dict(schedule_dict), party
            )
        liveness_dict = resilience_dict.get("liveness")
        if liveness_dict is not None:
            monitor = _liveness.start_monitor(
                [p for p in addresses if p != party],
                _liveness.LivenessConfig.from_dict(liveness_dict),
            )
            # A DEAD peer never acks its shm descriptor frames, so its
            # in-flight ring chunks would leak until ring close: reclaim
            # them on the DEAD edge. Additive subscription — membership
            # (wired below, after this block) owns the set_on_dead slot.
            # The monitor's thread never inherited this job's contextvar,
            # so the callback re-binds it explicitly.
            def _reclaim_dead_peer(*args, _ctx=fed_ctx, **kwargs):
                with tenancy_context.use_context(_ctx):
                    return barriers.cancel_peer_inflight(*args, **kwargs)

            monitor.add_on_dead(_reclaim_dead_peer)

    # Elastic membership (docs/membership.md): every founding party builds
    # the same epoch-0 view from the init addresses and installs the
    # manager's engine hooks (seq-id epoch stamp, rendezvous roster,
    # coordinator control handler + liveness DEAD escalation). AFTER the
    # resilience block — the coordinator's manager wires itself onto the
    # just-started monitor. Leader-only, like the proxies it governs.
    membership_dict = config.get("membership")
    if membership_dict is not None and party_process_id == 0:
        from rayfed_tpu.membership import (
            MembershipConfig,
            MembershipManager,
            MembershipView,
            set_membership_manager,
        )

        membership_manager = MembershipManager(
            job_name,
            party,
            MembershipView(
                epoch=0,
                roster=tuple(sorted(addresses)),
                addresses=dict(addresses),
            ),
            MembershipConfig.from_dict(membership_dict),
        )
        membership_manager.install()
        set_membership_manager(membership_manager)

    # Job-checkpoint defaults (docs/ha.md): already validated before any
    # state was built; installing them cannot fail at this point.
    if checkpoint_dict is not None:
        from rayfed_tpu import checkpoint as _checkpoint

        _checkpoint.set_default_checkpoint_config(checkpoint_dict)

    # Privacy plane (docs/privacy.md): the manager owns the pairwise
    # seed store and the ``prv:`` control handler, the DP ledger, and
    # the error-feedback quantizer. AFTER membership (dropout recovery
    # consults the roster) and BEFORE telemetry (the collector's first
    # scrape sees the fed_privacy_* series registered). Leader-only,
    # like the control handlers it registers.
    if privacy_cfg is not None and party_process_id == 0:
        from rayfed_tpu.privacy.manager import install_privacy

        install_privacy(job_name, party, privacy_cfg)

    # Telemetry plane (docs/observability.md): per-party metrics agent +
    # the collector/HTTP endpoint at the collector party. AFTER the
    # membership block so the collector's fleet view can consult the
    # installed manager from its first scrape. Leader-only, like the
    # proxies the agent pushes through.
    telemetry_dict = config.get("telemetry")
    if telemetry_dict is not None and party_process_id == 0:
        from rayfed_tpu import telemetry as _telemetry
        from rayfed_tpu.telemetry.config import TelemetryConfig

        _telemetry.start(
            job_name,
            party,
            dict(addresses),
            TelemetryConfig.from_dict(telemetry_dict),
        )

    if config.get("barrier_on_initializing", False) and party_process_id == 0:
        barriers.ping_others(addresses=addresses, self_party=party, max_retries=3600)


def shutdown():
    """Intended shutdown (ref api.py:299-306): wins the shutdown-once flag,
    drains pending sends, then tears the runtime down."""
    ctx = get_global_context()
    if ctx is not None and ctx.acquire_shutdown_flag():
        _shutdown(True)


def _shutdown(intended: bool = True):
    if get_global_context() is None:
        return
    # Bind the job being shut down to this thread for the whole teardown:
    # every plane's JobScoped lookups below must resolve THIS job even
    # when shutdown is called from a thread that never ran fed.init (or
    # while other jobs are live in the process).
    from rayfed_tpu.tenancy import context as tenancy_context

    fed_ctx = tenancy_context.get_context(
        get_global_context().get_job_name()
    )
    if fed_ctx is not None:
        with tenancy_context.use_context(fed_ctx):
            _shutdown_impl(intended)
    else:
        _shutdown_impl(intended)


def _shutdown_impl(intended: bool = True):
    if get_global_context() is None:
        return

    if intended:
        logger.info("Shutting down rayfed_tpu intendedly...")
    else:
        logger.warning("Shutting down rayfed_tpu unintendedly...")
    ctx = get_global_context()
    last_sending_error = ctx.get_cleanup_manager().get_last_sending_error()
    last_received_error = ctx.get_last_received_error()
    if last_sending_error is not None:
        logger.error("Cross-silo sending error occurred. %s", last_sending_error)

    wait_for_sending = True
    if (
        last_sending_error is not None or last_received_error is not None
    ) and not ctx.get_continue_waiting_for_data_sending_on_error():
        wait_for_sending = False
    logger.info(
        "%s for data sending.", "Wait" if wait_for_sending else "No wait"
    )

    exit_on_sending_failure = False
    if not intended:
        failure_handler = ctx.get_sending_failure_handler()
        if failure_handler is not None:
            logger.info("Executing failure handler %s ...", failure_handler)
            failure_handler(last_sending_error)
        exit_on_sending_failure = ctx.get_exit_on_sending_failure()

    # Telemetry stops first of all, while the proxies are still up: the
    # agent's final flush rides the inline lane, and the collector's
    # control handler unregisters before the rendezvous store goes away.
    # No-op when init never started it.
    _telemetry = sys.modules.get("rayfed_tpu.telemetry")
    if _telemetry is not None:
        try:
            _telemetry.stop(flush=intended)
        except Exception:  # noqa: BLE001 - telemetry must not block teardown
            logger.warning("telemetry shutdown failed", exc_info=True)
    # Resilience teardown FIRST — before the send drain and long before
    # the proxies go away: a heartbeat tick landing mid-teardown would
    # count misses against peers that are merely shutting down too (and
    # log spurious SUSPECT verdicts), and uninstalling the injector
    # restores the real proxy so stop_proxies stops what init started.
    # The modules are always importable here (config.py pulls the package
    # in), and both calls are no-ops when init never enabled them.
    from rayfed_tpu.resilience import inject as _inject
    from rayfed_tpu.resilience import liveness as _liveness

    _liveness.stop_monitor()
    _inject.uninstall()
    # Membership hooks next (seq-id epoch stamp, rendezvous control
    # handler/roster): the drain below must run against the bare engine.
    # An in-flight coordinator takeover finishes (bounded) against live
    # proxies first — tearing the plane down mid-broadcast would strand
    # survivors parked on a sync that will never come (docs/ha.md).
    _membership = sys.modules.get("rayfed_tpu.membership.manager")
    if _membership is not None:
        _mbr_mgr = _membership.get_membership_manager()
        if _mbr_mgr is not None:
            try:
                _mbr_mgr.drain_takeover(2.0)
            except Exception:  # noqa: BLE001 - must not block teardown
                logger.warning("membership drain failed", exc_info=True)
        _membership.clear_membership_manager()
    # Privacy plane: unregister the prv: control handler while the
    # rendezvous store is still up, and drop seeds/ledger — a new job
    # must not aggregate under an old job's masks or epsilon budget.
    _privacy = sys.modules.get("rayfed_tpu.privacy.manager")
    if _privacy is not None:
        try:
            _privacy.uninstall_privacy()
        except Exception:  # noqa: BLE001 - must not block teardown
            logger.warning("privacy-plane teardown failed", exc_info=True)
    internal_kv.kv_reset()
    clear_global_context(wait_for_sending=wait_for_sending)
    from rayfed_tpu import topology as _topology

    _topology.reset_default()
    # Async aggregation sessions hold buffered contribution trees and
    # per-session version counters; a new job must not fold into them.
    # Drain any mid-adopt aggregator handoff first (docs/ha.md).
    _async_rounds = sys.modules.get("rayfed_tpu.async_rounds")
    if _async_rounds is not None:
        try:
            _async_rounds.drain_handoffs(2.0)
        except Exception:  # noqa: BLE001 - must not block teardown
            logger.warning("async handoff drain failed", exc_info=True)
        _async_rounds.reset_sessions()
        _async_rounds.reset_default_async_config()
    _checkpoint = sys.modules.get("rayfed_tpu.checkpoint")
    if _checkpoint is not None:
        _checkpoint.reset_default_checkpoint_config()
    # Serving engines hold jitted programs and a live thread; stop them
    # before the proxies so a submit task in flight fails loudly instead
    # of wedging teardown. Only touch the module if something imported it
    # (keeps jax out of control-plane-only processes).
    _serving_server = sys.modules.get("rayfed_tpu.serving.server")
    if _serving_server is not None:
        _serving_server.stop_all_servers()
    _serving_client = sys.modules.get("rayfed_tpu.serving.client")
    if _serving_client is not None:
        _serving_client.set_default_serving_config(None)
    barriers.stop_proxies(job_name=ctx.get_job_name())
    # Only touch the collective lane if it was ever imported — keeps jax
    # out of control-plane-only processes.
    _collective = sys.modules.get("rayfed_tpu.collective")
    if _collective is not None:
        _collective.clear_joint_collective()
    fed_config.reset_config_cache()
    # FedSanitizer probe state is per-job: a new job's seq ids start over,
    # so the monotonicity watermarks (and the other probe maps) must not
    # carry across or the first send of the next job trips spuriously.
    sanitize.reset()
    # Completeness sweep (docs/multitenancy.md): every reset hook in the
    # singleton-inventory table runs for this job — the ordered teardown
    # above covers the drains that need arguments; the sweep guarantees
    # no plane's per-job state survives, including planes init never
    # touched. GLOBAL-scope hooks (party mesh, DMA server, tracing
    # buffers, the QoS arbiter itself) only fire when this was the last
    # live job. Then the job leaves the scheduler and context registry.
    from rayfed_tpu.tenancy import context as tenancy_context
    from rayfed_tpu.tenancy import reset as tenancy_reset

    job = ctx.get_job_name()
    last = len(tenancy_context.contexts()) <= 1
    tenancy_reset.run_all_reset_hooks(job, last=last)
    tenancy_context.remove_context(job)
    logger.info("Shutdown rayfed_tpu.")
    if threading.current_thread() is threading.main_thread():
        signal.signal(signal.SIGINT, original_sigint)
    if exit_on_sending_failure:
        logger.critical("Exit now due to the previous error.")
        sys.exit(1)


def join(
    address: str,
    party: str,
    coordinator: str,
    coordinator_address: str,
    config: Optional[Dict] = None,
    tls_config: Optional[Dict] = None,
    logging_level: str = "info",
    job_name: Optional[str] = None,
    transport: Optional[str] = None,
    timeout: Optional[float] = None,
) -> Any:
    """Join a RUNNING membership-enabled job mid-training.

    Boots a minimal two-party runtime ({this party, the coordinator}),
    then runs the join handshake: authenticate with the coordinator
    (``config['membership']['auth_token']`` must match the job's), park
    on the JoinAccept the coordinator emits at its next
    ``fed.membership_sync()``, install the received view (full roster,
    addresses, ghost tables, sync index), re-key the seq-id space to the
    admitting epoch, and warm-dial every peer.

    Returns the bootstrap state the coordinator attached to the accept —
    ``{"kind": "provider"|"checkpoint"|"model_bank", ...}`` or None —
    which the driver uses to enter the training loop at the current
    round. The joiner already holds the view of the sync that admitted
    it, so its driver SKIPS the membership_sync of its entry round and
    resumes the per-round sync with everyone else from the next round on.

    Args:
        address: this party's listen address ("host:port").
        party: this party's name (must not collide with a roster member).
        coordinator: the coordinator party's name.
        coordinator_address: the coordinator's listen address.
        config: job config dict, as in :func:`init`. The
            ``membership`` sub-dict configures the handshake
            (``auth_token``, ``join_timeout_s``); ``barrier_on_initializing``
            is ignored (the handshake is the readiness barrier).
        timeout: handshake deadline in seconds; defaults to
            ``membership.join_timeout_s``.
    """
    from rayfed_tpu.membership import MembershipConfig
    from rayfed_tpu.membership import manager as _mbr_manager

    config = dict(config or {})
    membership_config = MembershipConfig.from_dict(
        config.pop("membership", None) or {}
    )
    if membership_config.coordinator is None:
        membership_config.coordinator = coordinator
    # The handshake below IS the readiness barrier (the request's ack
    # proves the coordinator is up); the ping barrier would deadlock on
    # roster members that are past init.
    config.pop("barrier_on_initializing", None)
    init(
        addresses={party: address, coordinator: coordinator_address},
        party=party,
        config=config,
        tls_config=tls_config,
        logging_level=logging_level,
        job_name=job_name,
        transport=transport,
    )
    job = get_global_context().get_job_name()
    _, bootstrap = _mbr_manager.join_handshake(
        job, party, address, coordinator, membership_config, timeout=timeout
    )
    return bootstrap


def leave(timeout: Optional[float] = None) -> None:
    """Gracefully depart a membership-enabled job: notify the coordinator
    (it removes this party from the roster at its next sync), then run
    the ordinary intended shutdown — which drains in-flight sends and
    releases this party's rendezvous entries with the proxies. Peers drop
    the departed party at the eviction bump instead of waiting out a
    liveness DEAD verdict."""
    from rayfed_tpu.membership import manager as _mbr_manager

    manager = _mbr_manager.get_membership_manager()
    if manager is None:
        raise RuntimeError(
            "fed.leave() needs a membership-enabled job: pass "
            "config={'membership': {...}} to fed.init, or enter via "
            "fed.join"
        )
    manager.leave(timeout=timeout)
    shutdown()


def membership_sync(timeout: Optional[float] = None):
    """One membership sync point — call at the SAME program point (a
    round boundary) on every roster party. The coordinator folds pending
    joins/leaves/evictions into the next view and broadcasts it; everyone
    else receives and applies it. Returns the (possibly unchanged)
    :class:`~rayfed_tpu.membership.MembershipView` now in force.
    Consumes no data seq ids."""
    from rayfed_tpu.membership import manager as _mbr_manager

    manager = _mbr_manager.get_membership_manager()
    if manager is None:
        raise RuntimeError(
            "fed.membership_sync() needs a membership-enabled job: pass "
            "config={'membership': {...}} to fed.init, or enter via "
            "fed.join"
        )
    return manager.membership_sync(timeout=timeout)


def membership_view():
    """This party's current membership view, or None on membership-free
    jobs."""
    from rayfed_tpu.membership import manager as _mbr_manager

    manager = _mbr_manager.get_membership_manager()
    return None if manager is None else manager.view()


def membership_stats() -> Dict[str, int]:
    """This party's membership HA counters (the ``get_stats()`` mirror
    of the ``fed_membership_*`` telemetry series, docs/ha.md): adopted
    ``term``, ``failovers`` (depositions adopted), ``takeovers`` (times
    THIS party won the election), ``stale_syncs_rejected``, plus —
    on the coordinator — the fold counters (``epoch_bumps``,
    ``joins_accepted``, ...). Empty on membership-free jobs."""
    from rayfed_tpu.membership import manager as _mbr_manager

    manager = _mbr_manager.get_membership_manager()
    if manager is None:
        return {}
    out = manager.ha_stats()
    out["term"] = manager.term()
    coordinator = manager.get_coordinator_state()
    if coordinator is not None:
        out.update(coordinator.stats)
    return out


def privacy_ledger() -> Dict[str, Dict[str, float]]:
    """The DP ledger THIS process has accumulated: ``{party:
    {"epsilon", "delta", "rounds"}}`` for every party charged by a noisy
    secure aggregation this session (docs/privacy.md). Epsilon accrues
    at the aggregation ROOT (where the noise is added); other parties see
    it through the ``fed_privacy_ledger_epsilon`` telemetry gauge. Empty
    when the privacy plane is off, ``noise_multiplier`` is unset, or no
    noisy round has folded yet."""
    from rayfed_tpu.privacy.manager import get_privacy_manager

    manager = get_privacy_manager()
    return {} if manager is None else manager.ledger_snapshot()


def _get_addresses(job_name: str) -> Dict[str, str]:
    cfg = fed_config.get_cluster_config(job_name)
    return cfg.cluster_addresses if cfg else {}


def _get_party(job_name: str) -> str:
    cfg = fed_config.get_cluster_config(job_name)
    return cfg.current_party if cfg else ""


def _get_tls(job_name: str) -> Dict:
    cfg = fed_config.get_cluster_config(job_name)
    return cfg.tls_config if cfg else {}


class FedRemoteFunction:
    """`@fed.remote` over a function (ref api.py:384-417)."""

    def __init__(self, func_or_class) -> None:
        self._node_party = None
        self._func_body = func_or_class
        self._options: Dict[str, Any] = {}
        self._fed_call_holder = None

    def party(self, party: str):
        self._node_party = party
        self._fed_call_holder = FedCallHolder(
            self._node_party, self._execute_impl, self._options
        )
        return self

    def options(self, **options):
        self._options = options
        if self._fed_call_holder:
            self._fed_call_holder.options(**options)
        return self

    def remote(self, *args, **kwargs):
        if not self._node_party:
            raise ValueError(
                "call .party(<name>) before .remote(): a fed task needs an "
                "executing party"
            )
        return self._fed_call_holder.internal_remote(*args, **kwargs)

    def _execute_impl(self, args, kwargs):
        return get_global_context().get_executor().submit(
            self._func_body,
            args,
            kwargs,
            num_returns=self._options.get("num_returns", 1),
            eager=self._options.get("eager", True),
        )


class FedRemoteClass:
    """`@fed.remote` over a class (ref api.py:433-448)."""

    def __init__(self, func_or_class) -> None:
        self._party = None
        self._cls = func_or_class
        self._options: Dict[str, Any] = {}

    def party(self, party: str):
        self._party = party
        return self

    def options(self, **options):
        self._options = options
        return self

    def remote(self, *cls_args, **cls_kwargs) -> FedActorHandle:
        fed_class_task_id = get_global_context().next_seq_id()
        job_name = get_global_context().get_job_name()
        fed_actor_handle = FedActorHandle(
            fed_class_task_id,
            _get_addresses(job_name),
            self._cls,
            _get_party(job_name),
            self._party,
            self._options,
        )
        fed_call_holder = FedCallHolder(
            self._party, fed_actor_handle._execute_impl, self._options
        )
        fed_call_holder.internal_remote(*cls_args, **cls_kwargs)
        return fed_actor_handle


def remote(*args, **kwargs):
    """Define a fed task or fed actor (ref api.py:452-528).

    Usable bare (``@fed.remote``) or with options
    (``@fed.remote(num_returns=2)``).
    """

    def _make_fed_remote(function_or_class, **options):
        if inspect.isfunction(function_or_class) or fed_utils_is_cython(
            function_or_class
        ):
            return FedRemoteFunction(function_or_class).options(**options)
        if inspect.isclass(function_or_class):
            return FedRemoteClass(function_or_class).options(**options)
        raise TypeError(
            f"@fed.remote expects a function or class, got "
            f"{type(function_or_class).__name__}"
        )

    if len(args) == 1 and len(kwargs) == 0 and callable(args[0]):
        return _make_fed_remote(args[0])
    assert not args and kwargs, (
        "use @fed.remote bare or with keyword options only, e.g. "
        "@fed.remote(num_returns=2)"
    )
    return lambda fn_or_cls: _make_fed_remote(fn_or_cls, **kwargs)


def fed_utils_is_cython(obj) -> bool:
    """Cython callables are functions too (ref ``fed/utils.py:166-179``)."""
    def check(x):
        return (
            hasattr(x, "__func__")
            and "cython" in type(x.__func__).__name__.lower()
        ) or "cython" in type(x).__name__.lower()

    return check(obj)


def get(
    fed_objects: Union[FedObject, List[FedObject]],
    *,
    timeout: Optional[float] = None,
    on_missing: str = "raise",
    default: Any = None,
) -> Any:
    """Resolve FedObjects to real values; the owner broadcasts to every
    other party (ref api.py:531-608 — `get` is itself a DAG node with a
    fresh seq id so all parties address the same edges).

    Degraded-mode keywords (docs/resilience.md; all keyword-only so the
    reference-shaped positional call keeps meaning what it always did):

    - ``timeout``: wall-clock budget in seconds shared across ALL the
      requested objects (a round with several missing contributors costs
      one timeout, not one each). None = wait forever (legacy).
    - ``on_missing``: what a missing value — recv deadline expired,
      retries exhausted, injected fault — turns into. ``"raise"``
      (default) propagates the failure; ``"drop"`` removes missing
      entries from a list result (a single missing FedObject resolves
      to ``fed.MISSING``, there being no list to drop it from);
      ``"default"`` substitutes ``default`` (``fed.MISSING`` if left at
      None). A ``FedRemoteError`` envelope always re-raises regardless:
      the peer was alive and its task *failed*, which no aggregation
      should silently average over.
    - ``default``: the substitute under ``on_missing="default"``. None
      means the :data:`rayfed_tpu.MISSING` sentinel, which
      ``ops.aggregate.elastic_weighted_mean`` skips natively.

    Multi-controller caveat: like every fed API, the SAME call (same
    keywords) must run on every party — a party that drops while another
    raises diverges the program.
    """
    from rayfed_tpu.resilience.degraded import (
        MISSING,
        resolve_with_policy,
        validate_on_missing,
    )

    validate_on_missing(on_missing)
    if default is None:
        default = MISSING
    # get() is itself a node in the DAG: it burns one seq id so every
    # party addresses the broadcast edges identically.
    consumer_seq_id = get_global_context().next_seq_id()
    job_name = get_global_context().get_job_name()
    addresses = _get_addresses(job_name)
    current_party = _get_party(job_name)
    single = isinstance(fed_objects, FedObject)
    if single:
        fed_objects = [fed_objects]

    futures = []
    for fed_object in fed_objects:
        if fed_object.get_party() == current_party:
            fut = fed_object.get_value_future()
            assert fut is not None
            futures.append(fut)
            for party_name in addresses:
                if party_name == current_party:
                    continue
                if fed_object._was_sending_or_sent_to_party(party_name):
                    continue
                fed_object._mark_is_sending_to_party(party_name)
                barriers.send(
                    dest_party=party_name,
                    data=fut,
                    upstream_seq_id=fed_object.get_fed_task_id(),
                    downstream_seq_id=consumer_seq_id,
                )
        else:
            if fed_object.get_value_future() is not None:
                fut = fed_object.get_value_future()
            else:
                fut = barriers.recv(
                    current_party,
                    fed_object.get_party(),
                    fed_object.get_fed_task_id(),
                    consumer_seq_id,
                )
                fed_object._cache_value_future(fut)
            futures.append(fut)

    try:
        if timeout is None and on_missing == "raise":
            # Legacy fast path, bit-for-bit: block forever per future
            # (stealing a not-yet-started producer inline instead of
            # waiting for a pool worker to wake).
            values = [executor.result_stealing(f) for f in futures]
        else:
            values, missing = resolve_with_policy(
                futures, timeout, on_missing, default
            )
            if on_missing == "drop":
                gone = set(missing)
                values = [v for i, v in enumerate(values) if i not in gone]
        if sanitize.enabled():
            for value in values:
                sanitize.probe_donation_alias(value)
        if single:
            # A dropped single object leaves nothing to index: it
            # resolves to the MISSING sentinel instead (the ergonomic
            # twin of on_missing="default" with the default default).
            return values[0] if values else MISSING
        return values
    except FedRemoteError as e:
        logger.warning(
            "A peer party's task failed; re-raising its error envelope: %s",
            e.cause,
        )
        if get_global_context() is not None:
            get_global_context().set_last_received_error(e)
        raise


def is_party_leader() -> bool:
    """True on the host that owns this party's wire (host 0 of a
    multi-host party; always True for single-process parties).

    Raises if the fed runtime is not initialized — silently answering
    True on every host before ``fed.init`` (or after shutdown) would send
    all hosts down leader-only code paths."""
    ctx = get_global_context()
    if ctx is None:
        raise RuntimeError(
            "is_party_leader() needs an initialized fed runtime "
            "(call fed.init() first)"
        )
    return ctx.is_party_leader()


def kill(actor: FedActorHandle, *, no_restart: bool = True):
    """Kill a fed actor in its party (ref api.py:611-623)."""
    job_name = get_global_context().get_job_name()
    current_party = _get_party(job_name)
    if actor._node_party == current_party:
        actor._kill()
