# Copyright 2026 The rayfed-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Asynchronous buffered aggregation (FedBuff-style) + round pipelining.

Synchronous ``fed_aggregate`` is lock-step: a round completes only when
every party arrives, so one straggler stalls the job. This module adds
the buffered alternative (Nguyen et al. 2022, "Federated Learning with
Buffered Asynchronous Aggregation"): contributions fold into a buffer on
the receiving party *as they arrive*, each weighted by how stale its
base round is, and a new global model is published every K accepted
contributions. Stragglers cost themselves staleness decay instead of
costing the job wall-clock.

Division of labor:

- :class:`BufferedAggregator` — the pure, transport-free server state
  (buffer, staleness weighting, K-publish, liveness filtering). Unit
  tests drive it directly; determinism is its contract: a fixed arrival
  order folds through the same topology plans ``fed_aggregate`` lowers
  to (``ops.aggregate.reduce_by_plan`` stepwise, ``psum_by_plan`` when
  the buffered parties compose onto one registered mesh), so replaying
  the same arrivals reproduces the aggregate bitwise.
- ``_async_offer`` / ``_async_current`` — ordinary ``@fed.remote`` POOL
  tasks executing at the aggregating (root) party. Deliberately not an
  actor: actor lanes resolve arguments inside one serial thread, so a
  straggler's in-flight push would head-of-line-block every later offer
  and degenerate async back to sync. Pool tasks each park on their own
  worker while their contribution is in flight.
- :func:`async_round` / ``fed_aggregate(mode="async")`` — the driver
  surface. Every driver lays out the identical calls (multi-controller
  contract); each party's contribution owner-pushes to the root, and
  the returned handle is non-blocking so round t+1 compute starts while
  the round-t push is still on the wire. The aggregator SNAPSHOTS each
  contribution's mutable leaves when the offer lands (a buffered tree
  may sit un-folded across several rounds — without the copy, a driver
  reusing its gradient buffer in place would poison the pending fold).

Staleness is measured in *round tags*: the driver stamps every
contribution with its round index (auto-incremented per session when not
given), and a contribution's staleness is how many tags the aggregator
has seen beyond it at fold time. Tags ride the offer task's arguments —
identical on every driver, so no party's local clock leaks into the
fold. DEAD parties (the root's ``fed.liveness_view()``) are dropped from
the buffer; SUSPECT ones are down-weighted by ``async_suspect_factor``.
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

from rayfed_tpu import api as fed
from rayfed_tpu import tracing
from rayfed_tpu.config import AsyncAggregationConfig
from rayfed_tpu.fed_object import FedObject
from rayfed_tpu.telemetry import metrics as telemetry_metrics

logger = logging.getLogger(__name__)


# ---------------------------------------------------------------------------
# Staleness decay
# ---------------------------------------------------------------------------

#: Named staleness-decay families (``aggregation.async_staleness``).
STALENESS_FNS = ("poly", "constant", "exp")


def resolve_staleness_fn(
    spec: Any, exp: float = 0.5
) -> Callable[[int], float]:
    """Resolve a staleness spec to ``f(s) -> weight multiplier``.

    ``"poly"`` (FedBuff's default): ``(1 + s) ** -exp`` — gentle decay,
    a one-round-stale update still carries most of its weight.
    ``"constant"``: 1.0 regardless of staleness (pure FedAsync buffer).
    ``"exp"``: ``exp ** s`` for ``0 < exp <= 1`` — aggressive decay.
    A callable passes through unchanged (local/unit-test use only: a
    callable cannot ride the wire to the aggregating party).
    """
    if callable(spec):
        return spec
    if spec == "poly":
        return lambda s: (1.0 + float(s)) ** -float(exp)
    if spec == "constant":
        return lambda s: 1.0
    if spec == "exp":
        if not (0.0 < float(exp) <= 1.0):
            raise ValueError(
                f"staleness='exp' needs 0 < async_staleness_exp <= 1 "
                f"(the per-round multiplier), got {exp}"
            )
        return lambda s: float(exp) ** float(s)
    raise ValueError(
        f"unknown staleness fn {spec!r}; expected one of {STALENESS_FNS} "
        f"or a callable"
    )


# ---------------------------------------------------------------------------
# The buffered aggregator (pure server-side state)
# ---------------------------------------------------------------------------


@dataclass
class _Contribution:
    slot: str          # unique buffer label ("party#arrival_idx")
    party: str
    round_tag: int
    staleness: int
    tree: Any
    weight: float      # base * staleness decay * liveness factor


def _snapshot_tree(tree: Any) -> Any:
    """Copy mutable (numpy) leaves so the buffered contribution is
    immune to the offering driver reusing its buffer in place while the
    fold is still pending. jax arrays are immutable; everything else
    small (scalars, lists) is left alone — the buffer never hands leaves
    back out for mutation."""
    import numpy as np

    from rayfed_tpu import tree_util

    def leaf(x):
        return x.copy() if isinstance(x, np.ndarray) else x

    return tree_util.tree_map(leaf, tree)


class BufferedAggregator:
    """FedBuff server state for one async session.

    ``offer()`` is the only mutating entry point: it applies the
    liveness verdict (DEAD drops, SUSPECT down-weights), stamps the
    contribution's staleness against the newest round tag seen, and —
    every ``buffer_k`` accepted contributions — folds the buffer into a
    staleness-weighted mean, mixes it into the current global model at
    ``server_lr``, bumps the version, and fires ``publish_cb``.

    Determinism contract (asserted in tests/test_async_rounds.py): the
    fold consumes the buffer in arrival order through
    ``ops.aggregate.reduce_by_plan`` over a flat plan whose slots are
    the arrival sequence — the same premultiply/fold/scale association
    ``fed_aggregate`` produces on the wire — so a fixed arrival order
    yields a bitwise-identical aggregate on every replay. When the
    buffered parties are distinct and compose onto the registered party
    mesh (``mesh.compose_party_mesh``), the fold lowers to
    ``psum_by_plan`` in registered-mesh order instead: one collective,
    same bit contract for a fixed arrival *set*.
    """

    def __init__(
        self,
        cfg: AsyncAggregationConfig,
        *,
        liveness_fn: Optional[Callable[[], Dict[str, str]]] = None,
        publish_cb: Optional[Callable[[int, Any], None]] = None,
        staleness_fn: Optional[Callable[[int], float]] = None,
        session: str = "default",
    ):
        self.cfg = cfg
        self.session = session
        self._staleness_fn = staleness_fn or resolve_staleness_fn(
            cfg.staleness, cfg.staleness_exp
        )
        self._liveness_fn = liveness_fn
        self._publish_cb = publish_cb
        self._lock = threading.Lock()
        self._buffer: List[_Contribution] = []
        #: Pending secure-aggregation groups, keyed by mask round:
        #: {round: {party: masked envelope}} (docs/privacy.md). A group
        #: folds when every party named in its envelopes has arrived —
        #: or when the missing parties are DEAD/evicted and every
        #: survivor's recovery seed has been re-offered.
        self._secure_groups: Dict[int, Dict[str, Any]] = {}
        self._arrivals = 0
        self._latest_tag = -1
        self._current: Any = None
        self.version = 0
        self.stats: Dict[str, int] = {
            "accepted": 0,
            "dropped_dead": 0,
            "dropped_ghost": 0,
            "dropped_stale": 0,
            "publishes": 0,
            "publish_errors": 0,
            "handoffs": 0,
        }
        # Mirror every stats bump into the process-global telemetry
        # registry so the fleet view sees aggregator health without
        # polling snapshot_stats() (docs/observability.md).
        _reg = telemetry_metrics.get_registry()
        _offers = _reg.counter(
            "fed_async_offers_total",
            "Buffered-aggregator offers by outcome.",
            labels=("session", "result"),
        )
        self._m_offers = {
            k: _offers.labels(session=session, result=k)
            for k in ("accepted", "dropped_dead", "dropped_ghost",
                      "dropped_stale")
        }
        self._m_publishes = _reg.counter(
            "fed_async_publishes_total", "K-publishes folded and installed.",
            labels=("session",),
        ).labels(session=session)
        self._m_publish_errors = _reg.counter(
            "fed_async_publish_errors_total",
            "Publish hooks that raised (aggregation itself unaffected).",
            labels=("session",),
        ).labels(session=session)
        self._m_depth = _reg.gauge(
            "fed_async_buffer_depth", "Contributions currently buffered.",
            labels=("session",),
        ).labels(session=session)
        self._m_version = _reg.gauge(
            "fed_async_version", "Published model version.",
            labels=("session",),
        ).labels(session=session)
        self._m_latest_tag = _reg.gauge(
            "fed_async_latest_round_tag",
            "Newest round tag seen across offers.",
            labels=("session",),
        ).labels(session=session)
        self._m_handoffs = _reg.counter(
            "fed_async_handoffs_total",
            "Aggregator states this party adopted from a handed-off or "
            "checkpointed predecessor.",
            labels=("session",),
        ).labels(session=session)

    def _bump_stat_locked(self, key: str) -> None:
        self.stats[key] += 1
        m = self._m_offers.get(key)
        if m is not None:
            m.inc()
        elif key == "publishes":
            self._m_publishes.inc()
        elif key == "publish_errors":
            self._m_publish_errors.inc()

    def _sync_gauges_locked(self) -> None:
        self._m_depth.set(len(self._buffer))
        self._m_version.set(self.version)
        self._m_latest_tag.set(self._latest_tag)

    # -- queries ------------------------------------------------------------

    def current(self) -> Dict[str, Any]:
        """The newest published global model: ``{"version", "params"}``
        (version 0 / params None before the first K-publish)."""
        with self._lock:
            return {"version": self.version, "params": self._current}

    def snapshot_stats(self) -> Dict[str, int]:
        with self._lock:
            out = dict(self.stats)
            out["version"] = self.version
            out["buffered"] = len(self._buffer)
            out["latest_round_tag"] = self._latest_tag
            return out

    # -- state handoff (HA, docs/ha.md) -------------------------------------

    def export_state(self) -> Dict[str, Any]:
        """One consistent snapshot of everything a successor aggregator
        needs to continue this session bitwise: the un-folded buffer in
        arrival order, the arrival counter (slot labels must not
        collide), the staleness ledger (latest round tag), the pending
        secure groups, the published model, and the counters. The
        returned dict is wire-clean — it rides a normal fed push to the
        successor, or a checkpoint to disk."""
        with self._lock:
            return {
                "session": self.session,
                "cfg": self.cfg.as_dict(),
                "buffer": [
                    {
                        "slot": c.slot, "party": c.party,
                        "round_tag": c.round_tag, "staleness": c.staleness,
                        "tree": c.tree, "weight": c.weight,
                    }
                    for c in self._buffer
                ],
                "arrivals": self._arrivals,
                "latest_tag": self._latest_tag,
                "secure_groups": {
                    int(r): dict(g) for r, g in self._secure_groups.items()
                },
                "current": self._current,
                "version": self.version,
                "stats": dict(self.stats),
            }

    def adopt_state(self, state: Dict[str, Any]) -> Dict[str, Any]:
        """Install a predecessor's :meth:`export_state` snapshot,
        REPLACING this aggregator's state (the handoff target is a
        fresh/empty successor; a checkpoint restore starts from an empty
        registry). Counts one handoff. Returns ``snapshot_stats()``."""
        with self._lock:
            self._buffer = [
                _Contribution(
                    c["slot"], c["party"], int(c["round_tag"]),
                    int(c["staleness"]), _snapshot_tree(c["tree"]),
                    float(c["weight"]),
                )
                for c in state.get("buffer") or []
            ]
            self._arrivals = int(state.get("arrivals") or 0)
            self._latest_tag = int(state.get("latest_tag", -1))
            self._secure_groups = {
                int(r): dict(g)
                for r, g in (state.get("secure_groups") or {}).items()
            }
            self._current = state.get("current")
            self.version = int(state.get("version") or 0)
            prior = state.get("stats") or {}
            for k in self.stats:
                if k in prior:
                    self.stats[k] = int(prior[k])
            self.stats["handoffs"] += 1
            self._m_handoffs.inc()
            self._sync_gauges_locked()
        tracing.record(
            "failover", "", f"async:{self.session}", f"v{self.version}",
            0, time.perf_counter(), event="handoff",
            buffered=len(self._buffer),
        )
        logger.info(
            "async session %r adopted handed-off state: v%d, %d buffered, "
            "latest tag %d", self.session, self.version,
            len(self._buffer), self._latest_tag,
        )
        return self.snapshot_stats()

    # -- the one mutating entry point ---------------------------------------

    def offer(
        self,
        party: str,
        tree: Any,
        *,
        round_tag: int,
        weight: float = 1.0,
        epoch: Optional[int] = None,
    ) -> Dict[str, Any]:
        """Fold one contribution into the buffer; publish on the Kth.

        ``epoch`` is the membership epoch the offering driver stamped at
        send time (None on membership-free jobs). On a membership-enabled
        job, an offer from outside the current roster — or stamped with
        an epoch predating the party's current incarnation (a pre-crash
        ghost of a since-rejoined party) — is dropped before it can fold
        into the buffer.

        Returns a small status dict (msgpack-clean scalars only — it
        rides the inline small-message lane back to the offering party):
        ``accepted``, ``reason`` (when not), ``staleness``, ``weight``
        (the effective post-decay weight), ``buffered``, ``version``.
        """
        from rayfed_tpu.membership.manager import get_membership_manager
        from rayfed_tpu.resilience.liveness import DEAD, state_weight

        t0 = time.perf_counter()
        view = self._liveness_fn() if self._liveness_fn else {}
        state = view.get(party)
        membership = get_membership_manager()
        if isinstance(tree, dict) and tree.get("__secagg__"):
            return self._offer_secure(
                party, tree, round_tag=round_tag, epoch=epoch,
                t0=t0, view=view, membership=membership,
            )
        tree = _snapshot_tree(tree)
        with self._lock:
            self._latest_tag = max(self._latest_tag, int(round_tag))
            self._m_latest_tag.set(self._latest_tag)
            staleness = self._latest_tag - int(round_tag)
            if membership is not None and membership.is_ghost(party, epoch):
                self._bump_stat_locked("dropped_ghost")
                return {
                    "accepted": False, "reason": "ghost",
                    "staleness": staleness, "weight": 0.0,
                    "buffered": len(self._buffer), "version": self.version,
                }
            if state == DEAD:
                self._bump_stat_locked("dropped_dead")
                return {
                    "accepted": False, "reason": "dead",
                    "staleness": staleness, "weight": 0.0,
                    "buffered": len(self._buffer), "version": self.version,
                }
            if (
                self.cfg.max_staleness is not None
                and staleness > self.cfg.max_staleness
            ):
                self._bump_stat_locked("dropped_stale")
                return {
                    "accepted": False, "reason": "stale",
                    "staleness": staleness, "weight": 0.0,
                    "buffered": len(self._buffer), "version": self.version,
                }
            eff = (
                float(weight)
                * float(self._staleness_fn(staleness))
                * state_weight(state, self.cfg.suspect_factor)
            )
            slot = f"{party}#{self._arrivals}"
            self._arrivals += 1
            self._buffer.append(
                _Contribution(slot, party, int(round_tag), staleness,
                              tree, eff)
            )
            self._bump_stat_locked("accepted")
            published = None
            if len(self._buffer) >= self.cfg.buffer_k:
                published = self._fold_and_publish_locked(t0)
            self._sync_gauges_locked()
            return {
                "accepted": True, "staleness": staleness, "weight": eff,
                "buffered": len(self._buffer), "version": self.version,
                **({"published": published} if published else {}),
            }

    # -- internals ----------------------------------------------------------

    def _fold_and_publish_locked(self, t0: float) -> int:
        from rayfed_tpu.ops.aggregate import (
            psum_by_plan,
            reduce_by_plan,
        )

        buf, self._buffer = self._buffer, []
        parties = [c.party for c in buf]
        plan = self._plan_for(parties)
        if plan is not None:
            # Same-mesh fast path: one collective over the composed
            # party mesh, folding in registered-mesh order.
            by_party = {c.party: c for c in buf}
            mean = psum_by_plan(
                plan,
                {p: by_party[p].tree for p in plan.parties},
                weights={p: by_party[p].weight for p in plan.parties},
            )
            path = "psum"
        else:
            from rayfed_tpu import topology as topo

            slot_plan = topo.plan_buffer([c.slot for c in buf])
            mean = reduce_by_plan(
                slot_plan,
                {c.slot: c.tree for c in buf},
                weights={c.slot: c.weight for c in buf},
            )
            path = "fold"
        return self._install_locked(
            mean, t0, path=path, k=len(buf),
            round_tags=[c.round_tag for c in buf],
        )

    def _install_locked(self, mean, t0, *, path, k, round_tags) -> int:
        """Mix a folded mean into the global model, bump the version,
        and fire the publish hook (shared by the plaintext and secure
        folds)."""
        from rayfed_tpu.ops.aggregate import tree_mix

        self._current = tree_mix(self._current, mean, self.cfg.server_lr)
        self.version += 1
        self._bump_stat_locked("publishes")
        tracing.record(
            "fold", "", f"async:{self.session}", f"v{self.version}",
            0, t0,
            path=path, k=k,
            round_tags=round_tags,
        )
        if self._publish_cb is not None:
            tp = time.perf_counter()
            try:
                self._publish_cb(self.version, self._current)
                tracing.record(
                    "publish", "", f"async:{self.session}",
                    f"v{self.version}", 0, tp,
                )
            except Exception as e:  # noqa: BLE001 - a failed downstream
                # publish must not poison the aggregation itself
                self._bump_stat_locked("publish_errors")
                tracing.record(
                    "publish", "", f"async:{self.session}",
                    f"v{self.version}", 0, tp, ok=False,
                )
                logger.warning(
                    "async session %r publish hook failed at v%d: %r",
                    self.session, self.version, e,
                )
        return self.version

    # -- secure groups (privacy plane, docs/privacy.md) ---------------------

    def _offer_secure(
        self, party, env, *, round_tag, epoch, t0, view, membership
    ) -> Dict[str, Any]:
        """Buffer one MASKED contribution. Masked envelopes group by
        their mask round (not by arrival count): an individual envelope
        is a one-time-pad — only the complete group's modular sum means
        anything — so the effective ``buffer_k`` of a secure session is
        the contributing group's size. The uniform group staleness
        factor cancels in the mean, which is what keeps the secure fold
        bit-comparable to the plaintext one (docs/privacy.md)."""
        from rayfed_tpu.resilience.liveness import DEAD

        with self._lock:
            self._latest_tag = max(self._latest_tag, int(round_tag))
            self._m_latest_tag.set(self._latest_tag)
            staleness = self._latest_tag - int(round_tag)
            buffered = sum(len(g) for g in self._secure_groups.values())
            if membership is not None and membership.is_ghost(party, epoch):
                self._bump_stat_locked("dropped_ghost")
                return {
                    "accepted": False, "reason": "ghost",
                    "staleness": staleness, "weight": 0.0,
                    "buffered": buffered, "version": self.version,
                }
            if view.get(party) == DEAD:
                self._bump_stat_locked("dropped_dead")
                return {
                    "accepted": False, "reason": "dead",
                    "staleness": staleness, "weight": 0.0,
                    "buffered": buffered, "version": self.version,
                }
            if (
                self.cfg.max_staleness is not None
                and staleness > self.cfg.max_staleness
            ):
                self._bump_stat_locked("dropped_stale")
                return {
                    "accepted": False, "reason": "stale",
                    "staleness": staleness, "weight": 0.0,
                    "buffered": buffered, "version": self.version,
                }
            rnd = int(env["round"])
            group = self._secure_groups.setdefault(rnd, {})
            group[party] = env
            self._bump_stat_locked("accepted")
            published = self._try_fold_secure_locked(rnd, t0)
            self._sync_gauges_locked()
            w = 1.0 if env.get("w") is None else float(env["w"])
            return {
                "accepted": True, "secure": True, "staleness": staleness,
                "weight": w,
                "buffered": sum(len(g) for g in self._secure_groups.values()),
                "version": self.version,
                **({"published": published} if published else {}),
            }

    def _try_fold_secure_locked(self, rnd: int, t0: float) -> Optional[int]:
        """Fold the round's secure group if it is completable: every
        expected party arrived, or every missing one is DEAD/evicted AND
        every survivor's recovery seed has been re-offered
        (``prv:recover``). Returns the new version, or None to keep the
        group pending (re-tried on the next offer and on every
        :func:`poke_secure_sessions`)."""
        from rayfed_tpu.privacy.manager import get_privacy_manager

        group = self._secure_groups.get(rnd)
        if not group:
            return None
        mgr = get_privacy_manager()
        if mgr is None:
            logger.warning(
                "masked offers buffered at a party without a privacy "
                "plane; session %r round %s cannot fold", self.session, rnd,
            )
            return None
        first = next(iter(group.values()))
        expected = list(first["parties"])
        missing = [p for p in expected if p not in group]
        if missing:
            from rayfed_tpu.membership.manager import get_membership_manager

            from rayfed_tpu.resilience.liveness import DEAD

            view = self._liveness_fn() if self._liveness_fn else {}
            membership = get_membership_manager()
            roster = (
                set(membership.roster()) if membership is not None else None
            )
            survivors = [p for p in expected if p in group]
            for p in missing:
                gone = view.get(p) == DEAD or (
                    roster is not None and p not in roster
                )
                if not gone:
                    return None  # still expecting its envelope
                if mgr.recovery_seeds(p, survivors) is None:
                    return None  # survivors' re-offers still in flight
        weights = None
        op = "mean"
        if first.get("w") is not None:
            op = "wmean"
            weights = {p: float(e["w"]) for p, e in group.items()}
        try:
            mean = mgr.secure_reduce(
                op, expected, first["domain"], rnd, weights, dict(group)
            )
        except Exception:  # noqa: BLE001 - fold stays pending, retried
            logger.warning(
                "secure fold for session %r round %s not completable yet",
                self.session, rnd, exc_info=True,
            )
            return None
        del self._secure_groups[rnd]
        return self._install_locked(
            mean, t0, path="secure", k=len(group), round_tags=[rnd]
        )

    def poke_secure(self) -> None:
        """Re-try every pending secure group (called when a recovery
        seed lands — the fold it was blocking may now be completable)."""
        with self._lock:
            for rnd in sorted(self._secure_groups):
                self._try_fold_secure_locked(rnd, time.perf_counter())
            self._sync_gauges_locked()

    def _plan_for(self, parties: List[str]):
        """A flat plan in registered-mesh order when the buffered parties
        are distinct and exactly the composed party mesh; else None (the
        arrival-order reduce_by_plan path)."""
        import sys as _sys

        mesh_mod = _sys.modules.get("rayfed_tpu.mesh")
        if mesh_mod is None:
            return None  # no mesh was ever composed in this process
        registered = mesh_mod.get_composed_parties()
        if registered is None:
            return None
        if len(set(parties)) != len(parties):
            return None  # duplicate contributor: slots are not parties
        if set(parties) != set(registered):
            return None
        from rayfed_tpu import topology as topo

        plan = topo.plan(list(registered), "flat")
        if mesh_mod.composed_mesh_for(plan.parties) is None:
            return None
        return plan


# ---------------------------------------------------------------------------
# Process-local session registry (lives at the aggregating party)
# ---------------------------------------------------------------------------

from rayfed_tpu.tenancy.context import JobScoped

_sessions: JobScoped = JobScoped("async_rounds.sessions", default_factory=dict)
_sessions_lock = threading.Lock()  # fedlint: disable=global-mutable-singleton (guards the per-job session registries)


def _serve_publish_cb(serve_name: str) -> Callable[[int, Any], None]:
    def cb(version: int, params: Any) -> None:
        from rayfed_tpu.serving.server import get_server

        get_server(serve_name).publish(params)

    return cb


def _get_or_create_session(
    name: str, cfg_dict: Dict[str, Any], serve_name: Optional[str]
) -> BufferedAggregator:
    with _sessions_lock:
        sessions = _sessions.get()
        agg = sessions.get(name)
        if agg is None:
            from rayfed_tpu.resilience.liveness import liveness_view

            agg = BufferedAggregator(
                AsyncAggregationConfig(**cfg_dict),
                liveness_fn=liveness_view,
                publish_cb=(
                    _serve_publish_cb(serve_name) if serve_name else None
                ),
                session=name,
            )
            sessions[name] = agg
        return agg


def get_session(name: str = "default") -> Optional[BufferedAggregator]:
    """The named session's aggregator in THIS process (None when this
    process is not the aggregating party, or nothing arrived yet)."""
    with _sessions_lock:
        return _sessions.get().get(name)


def reset_sessions() -> None:
    """Drop all aggregator state and driver-side round counters (called
    by ``fed.shutdown`` — a new job must not fold into an old buffer)."""
    with _sessions_lock:
        _sessions.pop()
    with _tags_lock:
        _driver_round_tags.pop()
        _last_rounds.pop()


def poke_secure_sessions() -> None:
    """Re-try every session's pending secure folds (the privacy manager
    calls this when a ``prv:recover`` seed lands — a dropout-blocked
    group may now be completable)."""
    with _sessions_lock:
        aggs = list(_sessions.get().values())
    for agg in aggs:
        agg.poke_secure()


# ---------------------------------------------------------------------------
# Remote surface (pool tasks at the root — see module docstring for why
# these are deliberately not an actor)
# ---------------------------------------------------------------------------


@fed.remote
def _async_offer(
    name, cfg_dict, serve_name, party, round_tag, weight, epoch, tree
):
    agg = _get_or_create_session(name, cfg_dict, serve_name)
    return agg.offer(
        party, tree, round_tag=round_tag, weight=weight, epoch=epoch
    )


@fed.remote
def _async_secure_mask(tree, party, parties, domain, round_index, weight):
    # Party-side masking for a secure async offer (the async twin of
    # federated._secagg_mask): only the masked envelope rides to the
    # root's buffer.
    from rayfed_tpu.privacy.manager import require_privacy_manager

    mgr = require_privacy_manager("async_round(secure=True)")
    return mgr.mask_contribution(
        tree, party=party, parties=list(parties), domain=domain,
        round_index=round_index, weight=weight,
    )


@fed.remote
def _async_current(name, cfg_dict, serve_name):
    agg = _get_or_create_session(name, cfg_dict, serve_name)
    return agg.current()


@fed.remote
def _async_export(name, cfg_dict, serve_name):
    agg = _get_or_create_session(name, cfg_dict, serve_name)
    return agg.export_state()


@fed.remote
def _async_adopt(name, cfg_dict, serve_name, state):
    agg = _get_or_create_session(name, cfg_dict, serve_name)
    _handoff_begin()
    try:
        return agg.adopt_state(state)
    finally:
        _handoff_end()


# In-flight handoff adoption counter: ``fed.shutdown`` drains it so a
# job shutting down during an aggregator handoff finishes installing the
# adopted state before the session registry is cleared.
_handoff_lock = threading.Lock()  # fedlint: disable=global-mutable-singleton (per-job async state; reset_sessions()/reset_default_async_config() clear at shutdown)
_handoff_cond = threading.Condition(_handoff_lock)  # fedlint: disable=global-mutable-singleton (per-job async state; reset_sessions()/reset_default_async_config() clear at shutdown)
_handoffs_inflight = 0  # fedlint: disable=global-mutable-singleton (per-job async state; reset_sessions()/reset_default_async_config() clear at shutdown)


def _handoff_begin() -> None:
    global _handoffs_inflight
    with _handoff_lock:
        _handoffs_inflight += 1


def _handoff_end() -> None:
    global _handoffs_inflight
    with _handoff_lock:
        _handoffs_inflight -= 1
        _handoff_cond.notify_all()


def drain_handoffs(timeout: float = 2.0) -> bool:
    """Block until no aggregator handoff is mid-adopt (or the timeout
    lapses). Returns True when quiescent."""
    deadline = time.monotonic() + max(0.0, timeout)
    with _handoff_lock:
        while _handoffs_inflight > 0:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return False
            _handoff_cond.wait(remaining)
        return True


@fed.remote
def _async_stats(name, cfg_dict, serve_name):
    agg = _get_or_create_session(name, cfg_dict, serve_name)
    return agg.snapshot_stats()


# ---------------------------------------------------------------------------
# Driver surface
# ---------------------------------------------------------------------------

# Job default (config['aggregation']['async_*'] from fed.init), following
# the topology.set_default pattern: every driver reads the same config,
# so every driver ships the identical cfg to the root.
_default_cfgs: "JobScoped[AsyncAggregationConfig]" = JobScoped(
    "async_rounds.default_cfg"
)

# Driver-side auto round tags, per session name. Every driver runs the
# same program, so the counters advance identically on all parties.
_tags_lock = threading.Lock()  # fedlint: disable=global-mutable-singleton (guards the per-job round-tag counters)
_driver_round_tags: JobScoped = JobScoped(
    "async_rounds.round_tags", default_factory=dict
)

# Driver-side memory of the last async_round call per session — the
# survivor re-offer source for :func:`async_rebuild` when the root died
# without handing its buffer off. Identical on every driver (same calls,
# same arguments), so a rebuild lays out the same DAG everywhere.
_last_rounds: JobScoped = JobScoped(
    "async_rounds.last_rounds", default_factory=dict
)


def set_default_async_config(aggregation_dict: Dict[str, Any]) -> None:
    """Validate and install the ``aggregation.async_*`` job defaults
    (called by ``fed.init``; raises on unknown keys or bad values so a
    typo'd config rejects init, not the first round)."""
    cfg = AsyncAggregationConfig.from_aggregation_dict(aggregation_dict)
    resolve_staleness_fn(cfg.staleness, cfg.staleness_exp)  # validate combo
    _default_cfgs.set(cfg)


def get_default_async_config() -> AsyncAggregationConfig:
    return _default_cfgs.peek() or AsyncAggregationConfig()


def reset_default_async_config() -> None:
    _default_cfgs.pop()


def _next_round_tag(session: str) -> int:
    with _tags_lock:
        tags = _driver_round_tags.get()
        tag = tags.get(session, 0)
        tags[session] = tag + 1
        return tag


@dataclass
class AsyncRoundHandle:
    """Non-blocking view of one async round: the per-party offer-status
    FedObjects and the newest global model at the root.

    Nothing here blocks — pull ``model`` with ``fed.get(handle.model,
    timeout=..., on_missing=...)`` for a bounded wait; ``params`` may be
    None (version 0) until the buffer first fills to K."""

    round_tag: int
    root: str
    session: str
    offers: Dict[str, FedObject] = field(default_factory=dict)
    model: Optional[FedObject] = None


def async_round(
    objs: Dict[str, Any],
    *,
    round_tag: Optional[int] = None,
    root: Optional[str] = None,
    weights: Optional[Dict[str, float]] = None,
    buffer_k: Optional[int] = None,
    staleness_fn: Optional[str] = None,
    server_lr: Optional[float] = None,
    session: str = "default",
    publish_to: Any = None,
    fetch_model: bool = True,
    secure: bool = False,
) -> AsyncRoundHandle:
    """Offer ``{party: FedObject-of-pytree}`` into the session's buffer
    at the root and return without waiting for anything.

    Every driver must make the identical call (multi-controller
    contract — offers and the model fetch burn seq ids). Each party's
    contribution owner-pushes to ``root`` when that party's driver
    reaches this call; the aggregator folds arrivals as they land and
    publishes every ``buffer_k``. The returned handle's ``model`` is the
    root's newest published global model *at the time the root executes
    the fetch* — it may or may not include this round's contributions;
    that is the async contract (docs/async_rounds.md).

    ``round_tag`` stamps the contributions' staleness bucket; when None,
    a per-``session`` driver-side counter auto-increments (identically
    on every driver). ``buffer_k`` / ``staleness_fn`` (a name from
    :data:`STALENESS_FNS`) / ``server_lr`` override the
    ``aggregation.async_*`` job defaults. ``publish_to`` (a
    ``ServeHandle`` hosted at the root party) hot-publishes each
    K-publish into the serving plane in-process. ``fetch_model=False``
    skips the model fetch (pipelined inner rounds that only push).

    ``secure=True`` masks each contribution AT its party before it is
    offered (privacy plane, docs/privacy.md): the root buffers masked
    envelopes per round and folds a round's group only once every
    contributor has arrived (or dropped out and been recovered), so the
    effective ``buffer_k`` is the group size. Requires
    ``config["privacy"]["secure_aggregation"] = True``.
    """
    assert objs, "need at least one party's contribution"
    if secure:
        from rayfed_tpu.privacy.manager import require_privacy_manager

        mgr = require_privacy_manager("async_round(secure=True)")
        if not mgr.config.secure_aggregation:
            raise ValueError(
                "async_round(secure=True) needs "
                'config["privacy"]["secure_aggregation"] = True at fed.init'
            )
    if root is None:
        root = next(iter(objs))
    cfg = get_default_async_config()
    overrides: Dict[str, Any] = {}
    if buffer_k is not None:
        overrides["buffer_k"] = int(buffer_k)
    if staleness_fn is not None:
        if callable(staleness_fn):
            raise TypeError(
                "async staleness_fn must be a name from STALENESS_FNS "
                "here (a callable cannot ride the wire to the root); "
                "pass callables to BufferedAggregator directly"
            )
        overrides["staleness"] = staleness_fn
    if server_lr is not None:
        overrides["server_lr"] = float(server_lr)
    if overrides:
        import dataclasses as _dc

        cfg = _dc.replace(cfg, **overrides)
    cfg_dict = cfg.as_dict()
    resolve_staleness_fn(cfg.staleness, cfg.staleness_exp)

    serve_name = None
    if publish_to is not None:
        if publish_to.party != root:
            raise ValueError(
                f"publish_to must be hosted at the aggregating root "
                f"(serving party {publish_to.party!r} != root {root!r}): "
                f"the K-publish hook installs versions in-process"
            )
        serve_name = publish_to.name
    if round_tag is None:
        round_tag = _next_round_tag(session)

    handle = AsyncRoundHandle(
        round_tag=int(round_tag), root=root, session=session
    )
    # Stamp each offer with this driver's membership epoch (None on
    # membership-free jobs): the root's aggregator rejects offers whose
    # stamp predates the offering party's current incarnation.
    from rayfed_tpu.membership.manager import current_epoch_or_none

    epoch = current_epoch_or_none()
    secure_parties = tuple(sorted(objs)) if secure else None
    for party in objs:
        w = 1.0 if weights is None else float(weights[party])
        contribution = objs[party]
        if secure:
            # Mask at the contributing party; the envelope carries the
            # wmean weight (premultiplied), so the offer itself rides
            # weight 1.0. The mask round is the round tag — identical on
            # every driver, so both pair members derive the same streams.
            contribution = _async_secure_mask.party(party).remote(
                objs[party], party, secure_parties, f"async:{session}",
                int(round_tag), None if weights is None else w,
            )
            w = 1.0
        handle.offers[party] = _async_offer.party(root).remote(
            session, cfg_dict, serve_name, party, int(round_tag), w, epoch,
            contribution,
        )
    if fetch_model:
        handle.model = _async_current.party(root).remote(
            session, cfg_dict, serve_name
        )
    with _tags_lock:
        _last_rounds.get()[session] = {
            "objs": dict(objs),
            "round_tag": int(round_tag),
            "weights": None if weights is None else dict(weights),
            "secure": bool(secure),
        }
    return handle


def async_handoff(
    old_root: str, new_root: str, session: str = "default"
) -> FedObject:
    """Hand the session's aggregator state from ``old_root`` to
    ``new_root``: the old root exports one consistent snapshot (buffer,
    staleness ledger, secure groups, published model), the snapshot
    rides a normal fed push, and the successor adopts it wholesale.
    Every driver must make the identical call (multi-controller
    contract). Returns a FedObject of the successor's post-adopt stats
    at ``new_root`` — ``fed.get`` it for a bounded wait. Use when the
    old root is still reachable (planned migration, drain); when it is
    DEAD, use :func:`async_rebuild` instead."""
    cfg_dict = get_default_async_config().as_dict()
    state = _async_export.party(old_root).remote(session, cfg_dict, None)
    return _async_adopt.party(new_root).remote(
        session, cfg_dict, None, state
    )


def async_rebuild(
    new_root: str,
    session: str = "default",
    parties: Optional[Any] = None,
) -> AsyncRoundHandle:
    """Rebuild the session's buffer at ``new_root`` from survivor
    re-offers — the ``prv:recover`` pattern applied to the aggregator:
    when the root died WITH its buffer, each surviving driver re-offers
    its own last contribution (remembered from the most recent
    :func:`async_round`) at the same round tag, and the successor's
    fresh aggregator refolds them in re-arrival order. In-flight
    contributions from dead parties are lost — the round DEGRADES to
    the survivor set rather than disappearing with the root.

    ``parties`` restricts the re-offer to the surviving roster (default:
    every party of the remembered round). Every driver must make the
    identical call."""
    with _tags_lock:
        last = _last_rounds.get().get(session)
    if last is None:
        raise RuntimeError(
            f"async_rebuild({session!r}): no prior async_round to re-offer "
            f"from on this driver"
        )
    keep = None if parties is None else set(parties)
    objs = {
        p: o for p, o in last["objs"].items()
        if keep is None or p in keep
    }
    if not objs:
        raise RuntimeError(
            f"async_rebuild({session!r}): no surviving contributor to "
            f"re-offer from (parties={sorted(keep or ())})"
        )
    weights = last["weights"]
    if weights is not None:
        weights = {p: w for p, w in weights.items() if p in objs}
    return async_round(
        objs,
        round_tag=last["round_tag"],
        root=new_root,
        weights=weights,
        session=session,
        secure=last["secure"],
    )


def async_session_stats(
    root: str, session: str = "default"
) -> FedObject:
    """FedObject of the session's counters at the root (accepted /
    dropped_dead / dropped_stale / publishes / version / buffered)."""
    return _async_stats.party(root).remote(
        session, get_default_async_config().as_dict(), None
    )
