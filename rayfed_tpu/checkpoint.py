# Copyright 2026 The rayfed-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Party-local checkpoint / resume, and the full-job consistent cut.

The reference has NO checkpointing (SURVEY.md §5.4); job-level restart is
only feasible there because seq ids are deterministic across re-runs. This
module supplies the missing piece for long federated training: each party
snapshots its local state (model/optimizer pytrees of — possibly sharded —
jax Arrays, plus the engine's seq-id counter) with orbax, and on restart
every party restores its own snapshot and replays the driver program; the
deterministic DAG numbering then lines the parties back up without any
cross-party coordination.

Two layers (docs/ha.md):

- :func:`save_party_state` / :func:`restore_party_state` — the original
  pytree-only snapshot (arrays via orbax, engine metadata alongside).
- :func:`save_job_state` / :func:`restore_job_state` — one CONSISTENT
  CUT of the whole control plane at a round boundary: model + optimizer
  state (orbax), every async aggregator session's exported state
  (buffer, staleness ledger, secure groups, published model), the
  membership epoch/sync-index/term, the privacy ledger, and the
  driver-side round-tag counters. The consistency contract: call it at
  a round boundary AFTER resolving the round's handles on every party —
  nothing is then in flight, so restoring the cut and replaying from
  round N+1 continues aggregates bitwise (pinned by tests/test_ha.py).
"""

from __future__ import annotations

import dataclasses
import json
import os
import pickle
import shutil
import threading
from typing import Any, Dict, Optional

from rayfed_tpu._private.global_context import get_global_context

_META_FILE = "fed_meta.json"
_CONTROL_FILE = "fed_control.pkl"


@dataclasses.dataclass
class CheckpointConfig:
    """Job-checkpoint knobs (``config['checkpoint']``, validated at
    ``fed.init`` like every other section; docs/ha.md).

    Attributes:
        base_dir: default directory :func:`save_job_state` /
            :func:`restore_job_state` operate on when the caller passes
            none. Each cut lands in ``<base_dir>/step_<N>``. None =
            job-level checkpointing is explicit-path only.
        keep: how many newest step dirs to retain after each save (older
            complete cuts are pruned). 0 = keep everything.
    """

    base_dir: Optional[str] = None
    keep: int = 3

    def __post_init__(self) -> None:
        if int(self.keep) < 0:
            raise ValueError(
                f"checkpoint.keep must be >= 0, got {self.keep}"
            )

    @classmethod
    def from_dict(cls, data: Optional[Dict[str, Any]]) -> "CheckpointConfig":
        data = data or {}
        field_names = {f.name for f in dataclasses.fields(cls)}
        unknown = sorted(set(data) - field_names)
        if unknown:
            raise ValueError(
                f"unknown checkpoint config key(s) {unknown}; known keys: "
                f"{sorted(field_names)}"
            )
        return cls(**data)

    def as_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)


from rayfed_tpu.tenancy.context import JobScoped

_default_cfgs: "JobScoped[CheckpointConfig]" = JobScoped("checkpoint.default")


def set_default_checkpoint_config(data: Optional[Dict[str, Any]]) -> None:
    """Validate and install ``config['checkpoint']`` (called by
    ``fed.init``; raises on unknown keys so a typo rejects init)."""
    _default_cfgs.set(CheckpointConfig.from_dict(data))


def get_default_checkpoint_config() -> CheckpointConfig:
    return _default_cfgs.peek() or CheckpointConfig()


def reset_default_checkpoint_config() -> None:
    _default_cfgs.pop()


def _checkpointer():
    import orbax.checkpoint as ocp

    return ocp.StandardCheckpointer()


def save_party_state(path: str, state: Any, step: int = 0) -> None:
    """Snapshot ``state`` (a pytree of arrays) plus engine metadata.

    ``path`` is a directory; one snapshot per path (use step-suffixed paths
    or a CheckpointManager for retention policies).
    """
    path = os.path.abspath(path)
    ctx = get_global_context()
    meta = {
        "step": step,
        "party": ctx.get_current_party() if ctx else None,
        "job": ctx.get_job_name() if ctx else None,
        # Snapshot of the deterministic DAG position: informational — on
        # restart the driver replays from the top and re-derives ids.
        # (peek, never next: advancing the counter here would desync this
        # party's rendezvous keys from its peers'.)
        "seq_id_watermark": ctx.peek_seq_id() if ctx else None,
    }
    ckpt = _checkpointer()
    ckpt.save(os.path.join(path, "state"), state, force=True)
    # StandardCheckpointer commits asynchronously; the snapshot is only
    # durable (and the meta file only truthful) after the barrier.
    ckpt.wait_until_finished()
    with open(os.path.join(path, _META_FILE), "w") as f:
        json.dump(meta, f)


def restore_party_state(path: str, template: Optional[Any] = None) -> Any:
    """Restore a snapshot. ``template`` (a pytree of arrays or
    ShapeDtypeStructs with shardings) restores leaves onto the same
    shardings/devices; without it, arrays restore to host."""
    path = os.path.abspath(path)
    state_path = os.path.join(path, "state")
    ckpt = _checkpointer()
    if template is not None:
        import jax
        import orbax.checkpoint as ocp

        targets = jax.tree_util.tree_map(
            lambda x: ocp.utils.to_shape_dtype_struct(x)
            if hasattr(x, "shape")
            else x,
            template,
        )
        return ckpt.restore(state_path, targets)
    return ckpt.restore(state_path)


def load_meta(path: str) -> dict:
    with open(os.path.join(os.path.abspath(path), _META_FILE)) as f:
        return json.load(f)


def latest_step(base_dir: str) -> Optional[int]:
    """Scan ``base_dir`` for step-suffixed snapshot dirs (``step_<N>``) and
    return the newest complete one."""
    if not os.path.isdir(base_dir):
        return None
    steps = []
    for name in os.listdir(base_dir):
        if name.startswith("step_"):
            full = os.path.join(base_dir, name)
            if os.path.exists(os.path.join(full, _META_FILE)):
                try:
                    steps.append(int(name[5:]))
                except ValueError:
                    continue
    return max(steps) if steps else None


def step_dir(base_dir: str, step: int) -> str:
    return os.path.join(base_dir, f"step_{step}")


# ---------------------------------------------------------------------------
# Full-job consistent cut (docs/ha.md)
# ---------------------------------------------------------------------------


def _resolve_base_dir(base_dir: Optional[str]) -> str:
    base = base_dir or get_default_checkpoint_config().base_dir
    if not base:
        raise ValueError(
            "no checkpoint directory: pass base_dir= or set "
            'config["checkpoint"]["base_dir"] at fed.init'
        )
    return os.path.abspath(base)


def _prune_steps(base: str, keep: int) -> None:
    if keep <= 0:
        return
    steps = sorted(
        int(name[5:])
        for name in os.listdir(base)
        if name.startswith("step_") and name[5:].isdigit()
    )
    for s in steps[:-keep]:
        shutil.rmtree(os.path.join(base, f"step_{s}"), ignore_errors=True)


def save_job_state(
    base_dir: Optional[str] = None,
    *,
    step: int,
    model: Any = None,
    opt_state: Any = None,
    extra: Any = None,
) -> str:
    """One consistent cut of this party's whole job state at round
    boundary ``step``, under ``<base_dir>/step_<step>``.

    The cut bundles: ``model`` + ``opt_state`` (+ JSON-free ``extra``
    pytree) via orbax; every async aggregator session this party hosts
    (exported buffer, staleness ledger, secure groups, published model);
    the driver-side round-tag counters; the membership view / sync index
    / term (when a manager is installed); and the privacy ledger (when a
    privacy plane is installed). CONSISTENCY CONTRACT: call at a round
    boundary after resolving the round's handles on EVERY party — with
    nothing in flight, each party's local cut composes into one global
    cut, and a restart resumes bitwise (tests/test_ha.py).

    Returns the step directory written."""
    import rayfed_tpu.async_rounds as async_rounds
    from rayfed_tpu.membership.manager import get_membership_manager
    from rayfed_tpu.privacy.manager import get_privacy_manager

    base = _resolve_base_dir(base_dir)
    path = step_dir(base, int(step))
    os.makedirs(path, exist_ok=True)

    arrays = {}
    if model is not None:
        arrays["model"] = model
    if opt_state is not None:
        arrays["opt_state"] = opt_state
    if extra is not None:
        arrays["extra"] = extra
    if arrays:
        ckpt = _checkpointer()
        ckpt.save(os.path.join(path, "state"), arrays, force=True)
        ckpt.wait_until_finished()

    with async_rounds._sessions_lock:
        session_map = dict(async_rounds._sessions.get())
    sessions = {
        name: agg.export_state() for name, agg in session_map.items()
    }
    with async_rounds._tags_lock:
        round_tags = dict(async_rounds._driver_round_tags.get())
    membership = get_membership_manager()
    privacy = get_privacy_manager()
    control = {
        "async_sessions": sessions,
        "round_tags": round_tags,
        "membership": (
            membership.export_snapshot() if membership is not None else None
        ),
        "privacy_ledger": (
            privacy.ledger_snapshot() if privacy is not None else None
        ),
    }
    with open(os.path.join(path, _CONTROL_FILE), "wb") as f:
        pickle.dump(control, f)

    ctx = get_global_context()
    meta = {
        "step": int(step),
        "party": ctx.get_current_party() if ctx else None,
        "job": ctx.get_job_name() if ctx else None,
        "seq_id_watermark": ctx.peek_seq_id() if ctx else None,
        "kind": "job",
        "has_arrays": sorted(arrays),
        "sessions": sorted(sessions),
        "membership_epoch": (
            membership.current_epoch() if membership is not None else None
        ),
        "membership_term": (
            membership.term() if membership is not None else None
        ),
    }
    with open(os.path.join(path, _META_FILE), "w") as f:
        json.dump(meta, f)
    _prune_steps(base, int(get_default_checkpoint_config().keep))
    return path


def restore_job_state(
    base_dir: Optional[str] = None,
    *,
    step: Optional[int] = None,
    template: Any = None,
    install: bool = True,
) -> Dict[str, Any]:
    """Reload a :func:`save_job_state` cut (the newest step when
    ``step`` is None) and — with ``install=True`` — fast-forward the
    running engine to it: every checkpointed aggregator session is
    adopted into this party's registry, the driver round-tag counters
    resume where they left off, the membership manager (when installed)
    fast-forwards to the cut's epoch/sync index/term, and the privacy
    ledger reloads its spent budget.

    ``template`` restores the orbax arrays onto matching shardings; it
    must mirror the saved ``{"model": ..., "opt_state": ...}`` shape.
    Returns ``{"step", "path", "model", "opt_state", "extra", "meta"}``
    (array entries None when the cut carried none)."""
    import rayfed_tpu.async_rounds as async_rounds
    from rayfed_tpu.membership.manager import get_membership_manager
    from rayfed_tpu.privacy.manager import get_privacy_manager

    base = _resolve_base_dir(base_dir)
    if step is None:
        step = latest_step(base)
        if step is None:
            raise FileNotFoundError(
                f"no complete job checkpoint under {base!r}"
            )
    path = step_dir(base, int(step))
    meta = load_meta(path)

    arrays: Dict[str, Any] = {}
    if meta.get("has_arrays"):
        arrays = restore_party_state(path, template)

    control: Dict[str, Any] = {}
    control_path = os.path.join(path, _CONTROL_FILE)
    if os.path.exists(control_path):
        with open(control_path, "rb") as f:
            control = pickle.load(f)

    if install and control:
        for name, state in (control.get("async_sessions") or {}).items():
            agg = async_rounds._get_or_create_session(
                name, state["cfg"], None
            )
            agg.adopt_state(state)
        with async_rounds._tags_lock:
            async_rounds._driver_round_tags.get().update(
                control.get("round_tags") or {}
            )
        membership = get_membership_manager()
        if membership is not None and control.get("membership"):
            membership.restore_snapshot(control["membership"])
        privacy = get_privacy_manager()
        if privacy is not None and control.get("privacy_ledger"):
            privacy.ledger_restore(control["privacy_ledger"])

    return {
        "step": int(step),
        "path": path,
        "model": arrays.get("model"),
        "opt_state": arrays.get("opt_state"),
        "extra": arrays.get("extra"),
        "meta": meta,
        "control": control,
    }
