# Copyright 2026 The rayfed-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Party-local checkpoint / resume.

The reference has NO checkpointing (SURVEY.md §5.4); job-level restart is
only feasible there because seq ids are deterministic across re-runs. This
module supplies the missing piece for long federated training: each party
snapshots its local state (model/optimizer pytrees of — possibly sharded —
jax Arrays, plus the engine's seq-id counter) with orbax, and on restart
every party restores its own snapshot and replays the driver program; the
deterministic DAG numbering then lines the parties back up without any
cross-party coordination.
"""

from __future__ import annotations

import json
import os
from typing import Any, Optional

from rayfed_tpu._private.global_context import get_global_context

_META_FILE = "fed_meta.json"


def _checkpointer():
    import orbax.checkpoint as ocp

    return ocp.StandardCheckpointer()


def save_party_state(path: str, state: Any, step: int = 0) -> None:
    """Snapshot ``state`` (a pytree of arrays) plus engine metadata.

    ``path`` is a directory; one snapshot per path (use step-suffixed paths
    or a CheckpointManager for retention policies).
    """
    path = os.path.abspath(path)
    ctx = get_global_context()
    meta = {
        "step": step,
        "party": ctx.get_current_party() if ctx else None,
        "job": ctx.get_job_name() if ctx else None,
        # Snapshot of the deterministic DAG position: informational — on
        # restart the driver replays from the top and re-derives ids.
        # (peek, never next: advancing the counter here would desync this
        # party's rendezvous keys from its peers'.)
        "seq_id_watermark": ctx.peek_seq_id() if ctx else None,
    }
    ckpt = _checkpointer()
    ckpt.save(os.path.join(path, "state"), state, force=True)
    # StandardCheckpointer commits asynchronously; the snapshot is only
    # durable (and the meta file only truthful) after the barrier.
    ckpt.wait_until_finished()
    with open(os.path.join(path, _META_FILE), "w") as f:
        json.dump(meta, f)


def restore_party_state(path: str, template: Optional[Any] = None) -> Any:
    """Restore a snapshot. ``template`` (a pytree of arrays or
    ShapeDtypeStructs with shardings) restores leaves onto the same
    shardings/devices; without it, arrays restore to host."""
    path = os.path.abspath(path)
    state_path = os.path.join(path, "state")
    ckpt = _checkpointer()
    if template is not None:
        import jax
        import orbax.checkpoint as ocp

        targets = jax.tree_util.tree_map(
            lambda x: ocp.utils.to_shape_dtype_struct(x)
            if hasattr(x, "shape")
            else x,
            template,
        )
        return ckpt.restore(state_path, targets)
    return ckpt.restore(state_path)


def load_meta(path: str) -> dict:
    with open(os.path.join(os.path.abspath(path), _META_FILE)) as f:
        return json.load(f)


def latest_step(base_dir: str) -> Optional[int]:
    """Scan ``base_dir`` for step-suffixed snapshot dirs (``step_<N>``) and
    return the newest complete one."""
    if not os.path.isdir(base_dir):
        return None
    steps = []
    for name in os.listdir(base_dir):
        if name.startswith("step_"):
            full = os.path.join(base_dir, name)
            if os.path.exists(os.path.join(full, _META_FILE)):
                try:
                    steps.append(int(name[5:]))
                except ValueError:
                    continue
    return max(steps) if steps else None


def step_dir(base_dir: str, step: int) -> str:
    return os.path.join(base_dir, f"step_{step}")
