"""Cross-party collective lanes: federated aggregation as XLA collectives.

SURVEY.md §7 stage 5 and the BASELINE.json north star: FedAvg weight
aggregation lowers to a cross-slice ``psum`` over a *joint* mesh whose
leading axis enumerates parties, instead of point-to-point pushes.

Two deployment shapes:

 - **Joint-process lane** (this module): every party's shard lives in one
   JAX process group (a real multi-slice pod with ``jax.distributed``, the
   driver's multi-chip dry-run, or CPU simulation). ``cross_party_mean``
   runs one ``shard_map`` program where each party's sub-mesh holds its own
   weights and one ``psum`` over the party axis produces the aggregate —
   bitwise-identical on every party because XLA reduces in a fixed ring
   order.
 - **Push lane** (the default engine path): parties in separate processes
   push weight trees over the data plane and reduce with
   :func:`rayfed_tpu.ops.aggregate.tree_mean` — same math, pinned
   accumulation dtype, deterministic fold order.

The data-perimeter asymmetry (owner pushes, SURVEY.md §7 "hard parts") is
preserved at the API layer: a party enters ``cross_party_mean`` only by
executing the same program line — exactly the multi-controller opt-in the
push lane has.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from jax import shard_map  # requires jax >= 0.7


def party_axis_mesh(n_parties: int, devices=None, inner_axes=("data",),
                    inner_shape=None):
    """Build a joint mesh with a leading ``party`` axis.

    Default: shape (n_parties, n_devices/n_parties) with one inner axis,
    e.g. 8 devices, 2 parties -> ('party': 2, 'data': 4). For multi-axis
    party sub-meshes pass matching ``inner_axes`` and ``inner_shape``, e.g.
    ``inner_axes=("data", "model"), inner_shape=(2, 2)``. Each party's
    slice is ``mesh.devices[p]``.
    """
    import math

    import numpy as np

    devices = devices if devices is not None else jax.devices()
    n = len(devices)
    if n % n_parties != 0:
        raise ValueError(f"{n} devices not divisible by {n_parties} parties")
    inner_total = n // n_parties
    if inner_shape is None:
        if len(inner_axes) != 1:
            raise ValueError(
                "inner_shape is required when inner_axes has more than one axis"
            )
        inner_shape = (inner_total,)
    if len(inner_shape) != len(inner_axes):
        raise ValueError(f"{inner_axes=} does not match {inner_shape=}")
    if math.prod(inner_shape) != inner_total:
        raise ValueError(
            f"inner_shape {inner_shape} must cover {inner_total} devices/party"
        )
    dev = np.array(devices).reshape((n_parties,) + tuple(inner_shape))
    return Mesh(dev, ("party",) + tuple(inner_axes))


@functools.partial(
    jax.jit, static_argnames=("mesh", "party_axis", "op", "acc_dtype")
)
def _cross_party_reduce(tree, mesh: Mesh, party_axis: str, op: str,
                        acc_dtype: Optional[str]):
    n_parties = mesh.shape[party_axis]
    other_axes = tuple(a for a in mesh.axis_names if a != party_axis)

    def body(local_tree):
        def leaf(x):
            orig = x.dtype
            if acc_dtype is not None:
                x = x.astype(acc_dtype)
            s = jax.lax.psum(x, axis_name=party_axis)
            if op == "mean":
                s = s / n_parties
            return s.astype(orig)

        return jax.tree_util.tree_map(leaf, local_tree)

    # Party-sharded in, party-sharded (replicated value) out: every party's
    # sub-mesh ends up holding the identical aggregate.
    spec = P(party_axis)
    return shard_map(
        body,
        mesh=mesh,
        in_specs=(spec,),
        out_specs=spec,
    )(tree)


def cross_party_reduce(tree, mesh: Mesh, party_axis: str = "party",
                       op: str = "mean", acc_dtype: Optional[str] = "float32"):
    """Reduce a pytree whose leaves carry a leading party dimension sharded
    over ``party_axis``; each party's slot receives the aggregate.

    Leaves must have shape ``(n_parties, ...)`` with the leading dim sharded
    on the party axis (use :func:`stack_party_tree` to build them).
    """
    assert op in ("mean", "sum"), op
    return _cross_party_reduce(tree, mesh, party_axis, op, acc_dtype)


def stack_party_tree(per_party_trees, mesh: Mesh, party_axis: str = "party"):
    """Stack per-party weight trees along a new leading axis and shard that
    axis over the party sub-meshes (host staging lane, used in simulation
    and tests; on a real pod each party's shard is already device-resident)."""
    stacked = jax.tree_util.tree_map(
        lambda *xs: jnp.stack([jnp.asarray(x) for x in xs]), *per_party_trees
    )
    sharding = NamedSharding(mesh, P(party_axis))
    return jax.tree_util.tree_map(
        lambda x: jax.device_put(x, sharding), stacked
    )


def cross_party_mean(per_party_trees, mesh: Optional[Mesh] = None,
                     party_axis: str = "party"):
    """One-call FedAvg over the joint mesh: stack, psum, unstack.

    Returns the aggregate tree (identical content in every party slot).
    """
    if mesh is None:
        mesh = party_axis_mesh(len(per_party_trees))
    stacked = stack_party_tree(per_party_trees, mesh, party_axis)
    reduced = cross_party_reduce(stacked, mesh, party_axis, op="mean")
    # Every party slot now holds the aggregate; slot 0 is representative.
    return jax.tree_util.tree_map(lambda x: x[0], reduced)
