# Copyright 2026 The rayfed-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Cross-party collective lanes: federated aggregation as XLA collectives.

SURVEY.md §7 stage 5 and the BASELINE.json north star: FedAvg weight
aggregation lowers to a cross-slice ``psum`` over a *joint* mesh whose
leading axis enumerates parties, instead of point-to-point pushes.

Two deployment shapes:

 - **Joint-process lane** (this module): every party's shard lives in one
   JAX process group (a real multi-slice pod with ``jax.distributed``, the
   driver's multi-chip dry-run, or CPU simulation). ``cross_party_mean``
   runs one ``shard_map`` program where each party's sub-mesh holds its own
   weights and one ``psum`` over the party axis produces the aggregate —
   bitwise-identical on every party because XLA reduces in a fixed ring
   order.
 - **Push lane** (the default engine path): parties in separate processes
   push weight trees over the data plane and reduce with
   :func:`rayfed_tpu.ops.aggregate.tree_mean` — same math, pinned
   accumulation dtype, deterministic fold order.

The data-perimeter asymmetry (owner pushes, SURVEY.md §7 "hard parts") is
preserved at the API layer: a party enters ``cross_party_mean`` only by
executing the same program line — exactly the multi-controller opt-in the
push lane has.
"""

# fedlint: disable-file=seq-divergence
# Role-divergent control flow is this plane's contract: the root
# party reduces while leaves push, so fed.get/send calls are
# deliberately conditioned on party identity. The wire protocol
# (one seq id per collective op, burned on every party) keeps the
# DAG aligned; FED002's same-shape-everywhere rule targets
# drivers, not this engine.

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:  # jax >= 0.7 promotes shard_map to the top-level namespace
    from jax import shard_map
except ImportError:  # jax 0.4.x: same callable, experimental home
    from jax.experimental.shard_map import shard_map


def party_axis_mesh(n_parties: int, devices=None, inner_axes=("data",),
                    inner_shape=None):
    """Build a joint mesh with a leading ``party`` axis.

    Default: shape (n_parties, n_devices/n_parties) with one inner axis,
    e.g. 8 devices, 2 parties -> ('party': 2, 'data': 4). For multi-axis
    party sub-meshes pass matching ``inner_axes`` and ``inner_shape``, e.g.
    ``inner_axes=("data", "model"), inner_shape=(2, 2)``. Each party's
    slice is ``mesh.devices[p]``.
    """
    import math

    import numpy as np

    devices = devices if devices is not None else jax.devices()
    n = len(devices)
    if n % n_parties != 0:
        raise ValueError(f"{n} devices not divisible by {n_parties} parties")
    inner_total = n // n_parties
    if inner_shape is None:
        if len(inner_axes) != 1:
            raise ValueError(
                "inner_shape is required when inner_axes has more than one axis"
            )
        inner_shape = (inner_total,)
    if len(inner_shape) != len(inner_axes):
        raise ValueError(f"{inner_axes=} does not match {inner_shape=}")
    if math.prod(inner_shape) != inner_total:
        raise ValueError(
            f"inner_shape {inner_shape} must cover {inner_total} devices/party"
        )
    dev = np.array(devices).reshape((n_parties,) + tuple(inner_shape))
    return Mesh(dev, ("party",) + tuple(inner_axes))


@functools.partial(
    jax.jit,
    static_argnames=("mesh", "party_axis", "op", "acc_dtype", "specs"),
)
def _cross_party_reduce(tree, mesh: Mesh, party_axis: str, op: str,
                        acc_dtype: Optional[str], specs):
    n_parties = mesh.shape[party_axis]

    def body(local_tree):
        def leaf(x):
            orig = x.dtype
            if acc_dtype is not None:
                x = x.astype(acc_dtype)
            s = jax.lax.psum(x, axis_name=party_axis)
            if op == "mean":
                s = s / n_parties
            return s.astype(orig)

        return jax.tree_util.tree_map(leaf, local_tree)

    # Party-sharded in, party-sharded (replicated value) out: every party's
    # sub-mesh ends up holding the identical aggregate. Leaves keep their
    # inner-axis sharding through the reduce.
    treedef = jax.tree_util.tree_structure(tree)
    spec_tree = jax.tree_util.tree_unflatten(treedef, list(specs))
    return shard_map(
        body,
        mesh=mesh,
        in_specs=(spec_tree,),
        out_specs=spec_tree,
    )(tree)


def _leaf_spec(x, mesh: Mesh, party_axis: str):
    s = getattr(x, "sharding", None)
    if (
        isinstance(s, NamedSharding)
        and len(s.spec) > 0
        and s.spec[0] == party_axis
        and all(
            n in mesh.axis_names
            for e in s.spec
            for n in ([] if e is None else [e] if isinstance(e, str) else e)
        )
    ):
        return P(*s.spec)
    return P(party_axis)


def cross_party_reduce(tree, mesh: Mesh, party_axis: str = "party",
                       op: str = "mean", acc_dtype: Optional[str] = "float32"):
    """Reduce a pytree whose leaves carry a leading party dimension sharded
    over ``party_axis``; each party's slot receives the aggregate.

    Leaves must have shape ``(n_parties, ...)`` with the leading dim sharded
    on the party axis (use :func:`stack_party_tree` to build them); inner
    dims may additionally be sharded over the mesh's other axes, and that
    layout is preserved through the reduce.
    """
    assert op in ("mean", "sum"), op
    specs = tuple(
        _leaf_spec(x, mesh, party_axis)
        for x in jax.tree_util.tree_leaves(tree)
    )
    return _cross_party_reduce(tree, mesh, party_axis, op, acc_dtype, specs)


def stack_party_tree(per_party_trees, mesh: Mesh, party_axis: str = "party"):
    """Stack per-party weight trees along a new leading axis and shard that
    axis over the party sub-meshes (host staging lane, used in simulation
    and tests; on a real pod each party's shard is already device-resident)."""
    stacked = jax.tree_util.tree_map(
        lambda *xs: jnp.stack([jnp.asarray(x) for x in xs]), *per_party_trees
    )
    sharding = NamedSharding(mesh, P(party_axis))
    return jax.tree_util.tree_map(
        lambda x: jax.device_put(x, sharding), stacked
    )


def cross_party_mean(per_party_trees, mesh: Optional[Mesh] = None,
                     party_axis: str = "party"):
    """One-call FedAvg over the joint mesh: stack, psum, unstack.

    Returns the aggregate tree (identical content in every party slot).
    """
    if mesh is None:
        mesh = party_axis_mesh(len(per_party_trees))
    stacked = stack_party_tree(per_party_trees, mesh, party_axis)
    reduced = cross_party_reduce(stacked, mesh, party_axis, op="mean")
    # Every party slot now holds the aggregate; slot 0 is representative.
    return jax.tree_util.tree_map(lambda x: x[0], reduced)


# ---------------------------------------------------------------------------
# Cross-process joint collective (VERDICT r1 #4 / SURVEY §7 hard part #1)
# ---------------------------------------------------------------------------
#
# Real parties are separate OS processes. Opt-in via
# ``fed.init(config={"collective": {"coordinator": "host:port"}})``: all
# parties join ONE jax.distributed process group (process ranks follow the
# sorted party order), after which ``fed_collective_mean`` lowers FedAvg to
# a cross-process psum over DCN/ICI — gated on a control-plane rendezvous
# so the owner-push perimeter survives: a party's shard enters the
# collective only after every peer announced the same collective id over
# the ordinary data plane, and peers that never announce fail the call
# instead of wedging XLA inside a half-entered collective.

import itertools
import threading as _threading

_joint_lock = _threading.Lock()  # fedlint: disable=global-mutable-singleton (joint collective state; clear_joint_collective() at shutdown)
_joint_mesh: Optional[Mesh] = None  # fedlint: disable=global-mutable-singleton (joint collective state; clear_joint_collective() at shutdown)
_joint_party_order = None  # fedlint: disable=global-mutable-singleton (joint collective state; clear_joint_collective() at shutdown)
_joint_self_party: Optional[str] = None  # fedlint: disable=global-mutable-singleton (joint collective state; clear_joint_collective() at shutdown)
# True iff THIS module created the jax.distributed group (the process
# group outlives fed shutdown; repeat inits may reuse it, foreign groups
# must not be mistaken for it).
_joint_group_owned = False  # fedlint: disable=global-mutable-singleton (joint collective state; clear_joint_collective() at shutdown)
_collective_seq = itertools.count(1)


def init_joint_collective(
    addresses,
    self_party: str,
    coordinator_address: str,
    inner_axes=("data",),
    inner_shape=None,
    init_timeout_s: float = 120.0,
) -> Optional[Mesh]:
    """Join the cross-party jax.distributed group and build the joint
    party mesh. Process rank = index in sorted party order; jax assigns
    global device ids by rank, so the mesh's leading ``party`` rows line
    up with each process's local devices.

    Best-effort: if the group cannot form within ``init_timeout_s`` (a
    party missing the collective config, network issues), this logs a
    warning and returns None — ``fed_collective_mean`` then negotiates
    everyone onto the push lane instead of half the parties wedging.
    """
    import logging
    import time

    global _joint_mesh, _joint_party_order, _joint_self_party
    party_order = sorted(addresses)
    rank = party_order.index(self_party)
    log = logging.getLogger(__name__)

    # Pre-flight over OUR control plane before touching jax.distributed:
    # its join (and the first jax.devices()) can block without honoring
    # timeouts when a party never arrives, so nobody enters it until every
    # party confirmed it is about to. A party missing the collective
    # config simply never confirms, and the others degrade cleanly here.
    from rayfed_tpu.proxy import barriers

    peers = [p for p in party_order if p != self_party]
    for p in peers:
        barriers.send(
            p, {"join": "collective"},
            upstream_seq_id=f"coljoin:{self_party}",
            downstream_seq_id="coljoin",
        )
    deadline = time.monotonic() + init_timeout_s
    for p in peers:
        fut = barriers.receiver_proxy().get_data(p, f"coljoin:{p}", "coljoin")
        try:
            fut.result(timeout=max(0.0, deadline - time.monotonic()))
        except Exception:  # noqa: BLE001 - degrade to push lane
            log.warning(
                "party %s did not confirm joining the collective group "
                "within %.0fs; FedAvg stays on the push lane.",
                p, init_timeout_s,
            )
            return None

    global _joint_group_owned
    try:
        if jax.distributed.is_initialized():
            # A pre-existing process group is only trustworthy if WE
            # formed it (repeat fed.init in one process). Anything else —
            # a multi-host party's private group, a user's own group,
            # even one whose size coincidentally matches the party count
            # — is NOT the joint all-parties group; psumming over it
            # would silently aggregate the wrong set of processes.
            if not _joint_group_owned:
                log.warning(
                    "jax.distributed was initialized outside the "
                    "collective lane; refusing to treat it as the joint "
                    "all-parties group — FedAvg stays on the push lane.",
                )
                return None
        else:
            jax.distributed.initialize(
                coordinator_address=coordinator_address,
                num_processes=len(party_order),
                process_id=rank,
                initialization_timeout=max(1, int(init_timeout_s)),
            )
            _joint_group_owned = True
    except Exception as e:  # noqa: BLE001 - degrade to push lane
        log.warning(
            "joint collective group did not form (%s); FedAvg stays on "
            "the push lane.", e,
        )
        return None
    with _joint_lock:
        _joint_mesh = party_axis_mesh(
            len(party_order), devices=jax.devices(),
            inner_axes=tuple(inner_axes), inner_shape=inner_shape,
        )
        _joint_party_order = party_order
        _joint_self_party = self_party
    return _joint_mesh


def joint_collective_ready() -> bool:
    return _joint_mesh is not None


def clear_joint_collective() -> None:
    """Forget the joint mesh (fed.shutdown). The jax.distributed group
    itself outlives the fed runtime — platform/process-group choices are
    process-wide and irreversible (see mesh.init_distributed)."""
    global _joint_mesh, _joint_party_order, _joint_self_party
    with _joint_lock:
        _joint_mesh = None
        _joint_party_order = None
        _joint_self_party = None


def _stack_local_shard(leaf, mesh: Mesh, party_axis: str):
    """Global (n_parties, ...) array whose party slots are each process's
    local tree — built from THIS process's data only (other parties' slots
    live on their devices).

    When the leaf is already a ``jax.Array`` sharded on axes the joint
    mesh shares, its tiles are re-used device-to-device and the stacked
    array keeps that inner sharding (no host round-trip, no per-device
    replication of a leaf that only fits sharded). Host/numpy leaves are
    staged once and replicated across the party's local devices.
    """
    import numpy as np

    n_parties = mesh.shape[party_axis]
    global_shape = (n_parties,) + tuple(int(d) for d in leaf.shape)

    sharding_in = getattr(leaf, "sharding", None)
    if isinstance(sharding_in, NamedSharding) and getattr(
        leaf, "is_fully_addressable", False
    ):
        inner_spec = tuple(sharding_in.spec)
        sizes = dict(zip(mesh.axis_names, mesh.devices.shape))

        def spec_ok(entry):
            names = (
                [] if entry is None
                else [entry] if isinstance(entry, str) else list(entry)
            )
            return all(
                n in sizes and sizes[n] == sharding_in.mesh.shape[n]
                for n in names
            )

        if all(spec_ok(e) for e in inner_spec):
            target = NamedSharding(mesh, P(party_axis, *inner_spec))

            def norm(idx, shape):
                return tuple(
                    (0 if s.start is None else int(s.start),
                     dim if s.stop is None else int(s.stop))
                    for s, dim in zip(idx, shape)
                )

            tiles = {
                norm(s.index, leaf.shape): s.data
                for s in leaf.addressable_shards
            }
            arrays = []
            for d, idx in target.addressable_devices_indices_map(
                global_shape
            ).items():
                tile = tiles.get(norm(idx[1:], leaf.shape))
                if tile is None:
                    arrays = None
                    break  # layouts disagree -> host path below
                arrays.append(jax.device_put(tile[None], d))
            if arrays is not None:
                return jax.make_array_from_single_device_arrays(
                    global_shape, target, arrays
                )

    local = np.asarray(leaf)
    sharding = NamedSharding(mesh, P(party_axis))
    slab = local[None]
    arrays = [
        jax.device_put(slab, d) for d in sharding.addressable_devices
    ]
    return jax.make_array_from_single_device_arrays(
        global_shape, sharding, arrays
    )


def fed_collective_mean(
    local_tree,
    collective_id: Optional[str] = None,
    timeout_s: float = 120.0,
    party_axis: str = "party",
    device_out: bool = False,
):
    """Cross-party FedAvg over the joint process group.

    Every party calls this with its own local tree (multi-controller, same
    program line). Control-plane gating is TWO-PHASE (announce -> all-ack
    -> enter), so entering the psum implies every peer has *committed*,
    not merely expressed intent:

      1. announce: push an intent frame for ``collective_id`` to every
         peer and wait (``timeout_s``) for all peers' intents. A peer that
         never opts in raises TimeoutError here, on the control plane,
         instead of a hang inside the collective.
      2. commit: having seen every announcement, push a commit-ack and
         wait (a fresh ``timeout_s``) for every peer's ack. A party whose
         phase-1 wait expired never acks, so a *late announcer* — one
         whose intent arrived after a peer's deadline — fails here rather
         than stranding itself inside an XLA collective the timed-out
         peer will never join. (The residual window is an ack frame
         delayed > ``timeout_s`` between two live parties that both saw
         all announcements — network-only, no application latency.)

    Without a joint group the call falls back to the push lane
    (``federated.fed_aggregate`` + broadcast), same math.

    Returns the aggregate tree (identical bytes in every party, XLA's
    fixed reduction order). With ``device_out=True`` the psum lane keeps
    each leaf as a sharded ``jax.Array`` on this party's sub-mesh (no
    host round-trip for a consumer that immediately trains on the
    aggregate); the push-lane fallback returns host arrays regardless.
    """
    from rayfed_tpu._private.global_context import get_global_context

    ctx = get_global_context()
    assert ctx is not None, "fed.init() first"
    if collective_id is None:
        collective_id = f"auto{next(_collective_seq)}"

    from rayfed_tpu.api import _get_addresses

    addresses = _get_addresses(ctx.get_job_name())
    self_party = ctx.get_current_party()
    peers = sorted(p for p in addresses if p != self_party)
    my_lane = "psum" if joint_collective_ready() else "push"

    # Phase 1 (announce): edge key (col:<id>:<sender>, col:<id>) is
    # unique per sender; both sides may arrive in any order (rendezvous
    # store). Exchanging the LANE too keeps mixed deployments convergent:
    # if any party lacks the joint group, everyone takes the push lane
    # rather than half the parties wedging inside a psum.
    acks = _gate_exchange(
        peers, "col", collective_id, self_party,
        {"collective": collective_id, "lane": my_lane},
        "collective", timeout_s,
        "never announced collective {id!r}; not entering the psum "
        "(control-plane gate)",
    )
    lanes = {self_party: my_lane}
    lanes.update(
        (p, ack.get("lane", "psum")) for p, ack in acks.items()
    )

    if any(lane != "psum" for lane in lanes.values()):
        return _push_lane_mean(local_tree)

    # Phase 2 (commit): every peer announced; tell them we are committed
    # and wait for their commitment. All parties compute the same uniform
    # lane decision, so ack frames flow iff the decision was psum.
    _gate_exchange(
        peers, "colack", collective_id, self_party,
        {"collective_ack": collective_id},
        "collective_ack", timeout_s,
        "announced but never committed to collective {id!r} (its "
        "announce wait likely timed out); not entering the psum "
        "(two-phase gate)",
    )

    mesh = _joint_mesh
    rank = _joint_party_order.index(self_party)
    stacked = jax.tree_util.tree_map(
        lambda x: _stack_local_shard(x, mesh, party_axis), local_tree
    )
    reduced = cross_party_reduce(stacked, mesh, party_axis, op="mean")
    if device_out:
        return jax.tree_util.tree_map(
            lambda x: _local_aggregate_device(x, mesh, party_axis, rank),
            reduced,
        )
    return jax.tree_util.tree_map(_local_aggregate, reduced)


def _gate_exchange(peers, prefix, collective_id, self_party, payload,
                   id_field, timeout_s, timeout_msg):
    """One gate phase: push ``payload`` to every peer under the
    (``{prefix}:<id>:<sender>``, ``{prefix}:<id>``) edge, then wait (one
    shared ``timeout_s`` deadline across all peers) for every peer's
    frame. Returns {peer: frame}; raises TimeoutError (message from
    ``timeout_msg``) or RuntimeError on id mismatch (program
    divergence)."""
    import time

    from rayfed_tpu.proxy import barriers

    for p in peers:
        barriers.send(
            p, payload,
            upstream_seq_id=f"{prefix}:{collective_id}:{self_party}",
            downstream_seq_id=f"{prefix}:{collective_id}",
        )
    waits = {
        p: barriers.receiver_proxy().get_data(
            p, f"{prefix}:{collective_id}:{p}", f"{prefix}:{collective_id}"
        )
        for p in peers
    }
    deadline = time.monotonic() + timeout_s
    frames = {}
    for p, fut in waits.items():
        try:
            frame = fut.result(timeout=max(0.0, deadline - time.monotonic()))
        except Exception as e:  # noqa: BLE001 - surfaced with context
            raise TimeoutError(
                f"party {p} " + timeout_msg.format(id=collective_id)
            ) from e
        if frame.get(id_field) != collective_id:
            raise RuntimeError(
                f"party {p} sent {frame.get(id_field)!r} for {id_field}, "
                f"expected {collective_id!r} — program divergence"
            )
        frames[p] = frame
    return frames


def _local_aggregate_device(x, mesh: Mesh, party_axis: str, rank: int):
    """This party's aggregate as a device-resident sharded ``jax.Array``
    on the party's sub-mesh: re-uses the reduced tiles in place (each
    local device already holds its (1, ...) slab of the global result),
    so no host staging happens between aggregation and the next train
    step."""
    inner_axes = tuple(n for n in mesh.axis_names if n != party_axis)
    local_mesh = Mesh(mesh.devices[rank], inner_axes)
    spec = tuple(x.sharding.spec)
    target = NamedSharding(local_mesh, P(*spec[1:]))
    shape = x.shape[1:]
    tiles = {sh.device: sh.data for sh in x.addressable_shards}
    arrays = [
        tiles[d][0]
        for d in target.addressable_devices_indices_map(shape)
    ]
    return jax.make_array_from_single_device_arrays(shape, target, arrays)


def _local_aggregate(x):
    """This party's aggregate from a reduced global (n_parties, ...) leaf:
    assembled host-side from the local (possibly inner-sharded) tiles."""
    import numpy as np

    shards = list(x.addressable_shards)
    first = np.asarray(shards[0].data)[0]
    if len(shards) == 1:
        return first
    indices = {
        tuple(
            (0 if s.start is None else s.start, s.stop)
            for s in sh.index
        )
        for sh in shards
    }
    if len(indices) == 1:
        return first  # replicated across the party's devices
    out = np.empty(x.shape[1:], x.dtype)
    for sh in shards:
        out[tuple(sh.index[1:])] = np.asarray(sh.data)[0]
    return out


def _push_lane_mean(local_tree):
    """Push-lane fallback: identity task per party -> hierarchical
    fed_aggregate -> broadcast via fed.get."""
    import rayfed_tpu as fed
    from rayfed_tpu._private.global_context import get_global_context
    from rayfed_tpu.api import _get_addresses
    from rayfed_tpu.federated import fed_aggregate

    addresses = _get_addresses(get_global_context().get_job_name())
    parties = sorted(addresses)

    @fed.remote
    def _own_tree(t):
        return t

    objs = {p: _own_tree.party(p).remote(local_tree) for p in parties}
    agg = fed_aggregate(objs, op="mean")
    return fed.get(agg)
