# Copyright 2026 The rayfed-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Configuration system.

Capability parity: reference ``fed/config.py`` — cluster config (addresses /
current party / TLS) and job config stored in the job-scoped KV so that
transport proxies re-read them from the store rather than from driver
globals (ref ``fed/proxy/barriers.py:137-140,209-212``), plus dataclasses
for cross-silo messaging knobs with ``from_dict`` filtering unknown keys
(ref ``fed/config.py:147-161``).

TPU extension: ``ClusterConfig`` additionally carries a per-party device
topology (``party_mesh_config``) — which local devices form this party's
mesh and the logical axis layout (SURVEY.md C8 "adds mesh/slice topology").
"""

from __future__ import annotations

import dataclasses
import pickle
from typing import Any, Dict, List, Optional

import rayfed_tpu._private.constants as constants
from rayfed_tpu._private import kv as internal_kv


class ClusterConfig:
    """Wire-stored cluster-level config (ref ``fed/config.py:15-31``)."""

    def __init__(self, raw_bytes: bytes) -> None:
        self._data = pickle.loads(raw_bytes)

    @property
    def cluster_addresses(self) -> Dict[str, str]:
        return self._data[constants.KEY_OF_CLUSTER_ADDRESSES]

    @property
    def current_party(self) -> str:
        return self._data[constants.KEY_OF_CURRENT_PARTY_NAME]

    @property
    def tls_config(self) -> Dict:
        return self._data[constants.KEY_OF_TLS_CONFIG]


class JobConfig:
    def __init__(self, raw_bytes: Optional[bytes]) -> None:
        self._data = {} if raw_bytes is None else pickle.loads(raw_bytes)

    @property
    def cross_silo_comm_config_dict(self) -> Dict:
        return self._data.get(constants.KEY_OF_CROSS_SILO_COMM_CONFIG_DICT, {})


# Lazy caches keyed per job (ref fed/config.py:46-75 held one slot; two
# concurrent fed.init jobs must each cache their own wire-stored config).
_cluster_configs: Dict[str, ClusterConfig] = {}  # fedlint: disable=global-mutable-singleton (per-job config cache; reset_config_cache() at shutdown)
_job_configs: Dict[str, JobConfig] = {}  # fedlint: disable=global-mutable-singleton (per-job config cache; reset_config_cache() at shutdown)


def get_cluster_config(job_name: str) -> Optional[ClusterConfig]:
    cached = _cluster_configs.get(job_name)
    if cached is None:
        raw = internal_kv.kv_get(job_name, constants.KEY_OF_CLUSTER_CONFIG)
        if raw is None:
            return None
        cached = ClusterConfig(raw)
        _cluster_configs[job_name] = cached
    return cached


def get_job_config(job_name: str) -> JobConfig:
    cached = _job_configs.get(job_name)
    if cached is None:
        cached = JobConfig(
            internal_kv.kv_get(job_name, constants.KEY_OF_JOB_CONFIG)
        )
        _job_configs[job_name] = cached
    return cached


def reset_config_cache(job_name: Optional[str] = None) -> None:
    """Drop cached config — the current job's entries (resolved through
    the tenancy plane) or, with no resolvable job, everything."""
    if job_name is None:
        from rayfed_tpu.tenancy.context import current_job

        job_name = current_job()
    if job_name is None:
        _cluster_configs.clear()
        _job_configs.clear()
    else:
        _cluster_configs.pop(job_name, None)
        _job_configs.pop(job_name, None)


# Receive-path payload cap applied when messages_max_size_in_bytes is
# unset — parity with the reference's gRPC default (grpc_options.py:28-29).
DEFAULT_MAX_MESSAGE_BYTES = 500 * 1024 * 1024

# Canonical transport lane tiers, fastest first. The per-peer tier is
# negotiated at connection setup by ``rayfed_tpu/proxy/lanes.py`` (the
# single transport-selection point); ``cross_silo_comm.lane_tiers``
# restricts/orders the tiers a deployment permits. Kept here (not in
# lanes.py) so config validation needs no proxy import.
LANE_TIERS = ("meshref", "shm", "tcp", "tls", "grpc")


@dataclasses.dataclass
class CrossSiloMessageConfig:
    """Transport-independent cross-party messaging knobs
    (ref ``fed/config.py:78-161``).

    Ray-specific reference knobs that have no meaning for in-process
    thread proxies (``send_resource_label``, ``recv_resource_label`` —
    ref config.py:98-124) are silently dropped by ``from_dict``, so
    reference-written config dicts still load. ``proxy_max_restarts``
    (accept-loop supervision) and ``use_global_proxy`` (per-job proxy
    registry names, consumed by ``fed.init``) ARE honored.

    Attributes:
        timeout_in_ms: per-send timeout (ref default 60000, config.py:126).
        recv_timeout_in_ms: optional deadline for cross-party receives;
            None (default) waits forever like the reference. Set it so a
            pure-receiver party fails fast with TimeoutError when a peer
            vanishes before pushing (no error envelope can cross a dead
            transport — improvement over the reference, which can only
            hang in that case).
        messages_max_size_in_bytes: max payload size. None (default)
            applies the 500MB cap the reference uses for gRPC
            (grpc_options.py:28-29) — on every lane, so an unauthenticated
            peer cannot make the receiver allocate arbitrarily large
            buffers. A non-positive value disables the cap (the 1 TiB
            wire sanity cap still applies).
        serializing_allowed_list: {module: [class, ...]} whitelist for
            unpickling received non-array payloads.
        allow_pickle_payloads: False = strict arrays-only mode — the
            receiver rejects every pickle-kind data frame (error envelopes
            excepted), removing the unpickling attack surface entirely for
            deployments where peers are not fully trusted. Senders fail
            fast on payloads that would need pickling.
        exit_on_sending_failure: SIGINT self when a push ultimately fails.
        expose_error_trace: include the real exception in the
            FedRemoteError envelope sent to peers.
        continue_waiting_for_data_sending_on_error: keep draining queued
            sends during shutdown even after an error was seen.
    """

    timeout_in_ms: int = 60000
    recv_timeout_in_ms: Optional[int] = None
    # Wall-clock budget for one outbound push, shared across ALL of its
    # retry attempts (dial + stream + backoffs). None (default) keeps the
    # legacy shape where only per-attempt timeouts bound a send; set it
    # so a send against a dead peer fails after a predictable total
    # rather than attempts x timeout. Enforced by the unified retry
    # engine (resilience/retry.py) on the native TCP/TPU lanes.
    send_deadline_in_ms: Optional[int] = None
    messages_max_size_in_bytes: Optional[int] = None
    serializing_allowed_list: Optional[Dict[str, List[str]]] = None
    allow_pickle_payloads: bool = True
    # Optional payload compression on the native TCP/TPU lanes ("zstd"
    # or "zlib"; None = off). Worth its CPU on bandwidth-constrained DCN
    # links, not on loopback/ICI; zstd (level 1-3) is several times
    # faster than zlib at similar ratios on gradient data.
    # Incompressible payloads ship raw automatically; the gRPC parity
    # lane ignores it (the reference wire has no such field).
    payload_compression: Optional[str] = None
    compression_level: int = 1
    # LOSSY wire precision on the native TCP/TPU lanes (None = off):
    # "bf16" or "fp16" ships wide-float dense array leaves downcast,
    # halving bytes for fp32 gradient pushes — the standard federated
    # gradient-compression trade (bf16 keeps fp32's exponent range and
    # is the safe choice for gradients; fp16 overflows past 65504).
    # The receiver restores the original dtype, values carry the wire
    # rounding (~2^-8 relative for bf16). Sharded-array leaves, the gRPC
    # parity lane, and the device-DMA lane (device-resident pulls never
    # pass through the host codec) are unaffected — all-jax-Array
    # payloads under ``device_dma: true`` ship native precision.
    # Wire-format note: the downcast rides a tree-meta extension
    # (``odt``); enable only once EVERY party runs a release that
    # understands it — an older receiver would deliver raw bf16/fp16
    # arrays to consumers instead of restored fp32 (deliberately not a
    # WIRE_VERSION bump: that would reject all cross-version traffic,
    # including deployments that never enable this opt-in knob).
    payload_wire_dtype: Optional[str] = None
    # Device-DMA data plane on the TPU transport (opt-in): all-jax-Array
    # payloads are pulled device-to-device through a per-party
    # jax.experimental.transfer server; the ordinary socket frame carries
    # only a descriptor (uuid + server address + avals). Non-array or
    # sharded-leaf payloads, and every frame when the server cannot
    # start, ride the socket lane unchanged. ``dma_listen_addr`` is the
    # bind address ("host:0" picks a free port); the advertised address
    # keeps the bound host, so cross-host deployments must bind a
    # peer-reachable interface, not loopback.
    device_dma: bool = False
    dma_listen_addr: str = "127.0.0.1:0"
    # Same-mesh push fast path (opt-in; colocated deployments only):
    # when sender and receiver parties live in ONE process sharing a
    # composed party mesh (mesh.compose_party_mesh — the CPU simulator,
    # single-host test rigs, in-process benches), an all-array payload is
    # lowered to jax.device_put onto the destination party's sub-mesh and
    # only a tiny reference frame crosses the socket. Never enable it for
    # parties in separate processes: the reference cannot resolve there
    # and the send fails loudly at decode.
    same_mesh_push: bool = False
    # Transport lane-tier policy (docs/architecture.md "Lane tiers").
    # ``lane_tiers`` restricts/orders the tiers this party may pick per
    # peer; None (default) permits every tier in the canonical order
    # ``LANE_TIERS`` = meshref > shm > tcp > tls > grpc. Negotiation
    # (proxy/lanes.py) walks the list and picks the first tier whose
    # predicate holds for the peer; failures demote one tier per push.
    lane_tiers: Optional[List[str]] = None
    # Same-host zero-copy shm lane (opt-in, like device_dma): bulk
    # payloads to a peer on this host are written once into a /dev/shm
    # ring and adopted zero-copy by the receiver; only a tiny descriptor
    # frame (plus the ack) crosses the socket. Requires a plaintext
    # same-host peer; every shm failure falls back to the socket lane
    # per push, so enabling it can never lose a send.
    shm_enabled: bool = False
    # Per-peer ring capacity — the in-flight payload BUDGET, not just a
    # buffer: adoption is zero-copy, so every received value the peer
    # still holds pins its chunk. Size it to the peak pipelined payload
    # volume (e.g. 5 concurrent 100MB pushes need >500MB). Bounds
    # sender-side shm memory at ring_mb x peers; pushes that cannot fit
    # wait up to ``shm_push_timeout_ms`` for the receiver to release
    # space, then ride the socket lane.
    shm_ring_mb: int = 256
    # Payloads below this many bytes skip the shm lane: a descriptor
    # frame + ring round-trip cannot beat the inline small-frame path.
    shm_min_bytes: int = 64 * 1024
    # How long a push may wait for ring space before falling back to
    # the socket lane. Short on purpose: a full ring usually means the
    # receiver is HOLDING earlier values (chunks pinned by live decoded
    # views), and the socket delivers a 100MB payload in well under a
    # second — stalling multiple seconds per push to avoid that is the
    # pathological trade.
    shm_push_timeout_ms: int = 250
    # Small-message fast path: payloads at or below this many bytes skip
    # the per-message fixed costs that dominate latency-bound rounds —
    # they ride the compact msgpack encoding (no tree walk for plain
    # scalars/containers), are never compressed or chunked, are sent
    # inline (and coalesced with other queued small frames into one
    # syscall) instead of hopping through the sender worker queue, and
    # are decoded inline on the receiver instead of on the decode pool.
    # 0 disables the fast path entirely. Large-payload behavior is
    # unchanged at any setting.
    small_message_threshold: int = 64 * 1024
    # Frame integrity (opt-in): checksum every DATA payload (crc32c via
    # the native fastwire fast path, zlib.crc32 otherwise) in the frame
    # header; receivers NACK mismatches with CODE_DATA_CORRUPT and the
    # sender retransmits through the normal resend machinery — an
    # in-flight bit flip becomes a recovered retransmit instead of a
    # poisoned decode. CRC-less peers interoperate (header field, not a
    # wire-version bump).
    frame_crc: bool = False
    # Adaptive deadlines from the per-peer LinkHealth estimator
    # (resilience/linkhealth.py; docs/resilience.md "WAN emulation &
    # link health"). When on: ack timeouts become
    # clamp(rtt_timeout_multiple*srtt + 4*rttvar, min_timeout_in_ms,
    # timeout_in_ms) plus a transfer-time allowance for the in-flight
    # payload; recv deadlines gain RTT-multiple slack (only ever
    # EXTENDED, never shrunk); retry backoff is ceilinged at an
    # RTT-multiple once the link is measured. The configured
    # timeout_in_ms stays the hard ceiling in every formula — adaptive
    # can only tighten within [min_timeout_in_ms, timeout_in_ms].
    adaptive_timeouts: bool = True
    rtt_timeout_multiple: float = 8.0
    min_timeout_in_ms: int = 1000
    # Lane re-promotion (docs/architecture.md lane-tier table): after a
    # shm demotion, probe the shm lane again once this many ms have
    # passed without shm traffic, doubling the hold-off on each re-break
    # (hysteresis, capped at 16x) so a flapping link settles on tcp
    # instead of oscillating. 0 = legacy sticky demotion for the life of
    # the job.
    shm_repromote_after_ms: int = 2000
    exit_on_sending_failure: Optional[bool] = False
    expose_error_trace: Optional[bool] = False
    continue_waiting_for_data_sending_on_error: Optional[bool] = False

    def __post_init__(self):
        if self.lane_tiers is not None:
            tiers = tuple(self.lane_tiers)
            unknown = [t for t in tiers if t not in LANE_TIERS]
            if unknown:
                raise ValueError(
                    f"cross_silo_comm.lane_tiers contains unknown tiers "
                    f"{unknown}; known tiers: {list(LANE_TIERS)}"
                )
            if len(set(tiers)) != len(tiers):
                raise ValueError(
                    f"cross_silo_comm.lane_tiers has duplicates: "
                    f"{list(tiers)}"
                )
            if not tiers:
                raise ValueError(
                    "cross_silo_comm.lane_tiers must not be empty "
                    "(omit it to permit every tier)"
                )
            self.lane_tiers = list(tiers)
        if int(self.shm_ring_mb) < 1:
            raise ValueError(
                f"cross_silo_comm.shm_ring_mb must be >= 1, "
                f"got {self.shm_ring_mb}"
            )
        if int(self.shm_min_bytes) < 0:
            raise ValueError(
                f"cross_silo_comm.shm_min_bytes must be >= 0, "
                f"got {self.shm_min_bytes}"
            )
        if int(self.shm_push_timeout_ms) < 0:
            raise ValueError(
                f"cross_silo_comm.shm_push_timeout_ms must be >= 0, "
                f"got {self.shm_push_timeout_ms}"
            )
        if float(self.rtt_timeout_multiple) <= 0:
            raise ValueError(
                f"cross_silo_comm.rtt_timeout_multiple must be > 0, "
                f"got {self.rtt_timeout_multiple}"
            )
        if int(self.min_timeout_in_ms) < 0:
            raise ValueError(
                f"cross_silo_comm.min_timeout_in_ms must be >= 0, "
                f"got {self.min_timeout_in_ms}"
            )
        if int(self.shm_repromote_after_ms) < 0:
            raise ValueError(
                f"cross_silo_comm.shm_repromote_after_ms must be >= 0, "
                f"got {self.shm_repromote_after_ms}"
            )

    def effective_max_message_bytes(self) -> Optional[int]:
        """The payload cap actually enforced on send and receive paths:
        configured value, or 500MB when unset; None (no cap) only when the
        user explicitly configures a non-positive value."""
        v = self.messages_max_size_in_bytes
        if v is None:
            return DEFAULT_MAX_MESSAGE_BYTES
        return None if v <= 0 else v

    def __json__(self) -> str:
        import json

        return json.dumps(dataclasses.asdict(self))

    @classmethod
    def from_json(cls, json_str: str) -> "CrossSiloMessageConfig":
        import json

        return cls.from_dict(json.loads(json_str))

    @classmethod
    def from_dict(cls, data: Optional[Dict[str, Any]]) -> "CrossSiloMessageConfig":
        """Construct from a dict, silently dropping unknown keys
        (ref ``fed/config.py:147-161``)."""
        data = data or {}
        field_names = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in data.items() if k in field_names})


# RetryPolicy moved to the unified retry engine (resilience/retry.py) so
# every transport shares one backoff implementation; re-exported here
# because config dicts and call sites historically spell it
# ``rayfed_tpu.config.RetryPolicy``.
from rayfed_tpu.resilience.retry import RetryPolicy  # noqa: E402,F401


@dataclasses.dataclass
class TcpCrossSiloMessageConfig(CrossSiloMessageConfig):
    """Knobs specific to the native TCP transport (our default data plane,
    replacing the reference's gRPC channel options,
    ref ``fed/config.py:164-195``).

    Attributes:
        verify_peer_identity: under mutual TLS, require the sender's
            certificate (subject CN or a DNS SAN) to attest the ``src``
            party it claims in each frame; mismatches are rejected with
            code 403. Party certs from ``tools/generate_tls_certs.py``
            carry the party name as CN. Set False for deployments whose
            certs are host-named rather than party-named (those fall back
            to plain shared-CA trust).
        per_party_config: optional {dest_party: {field: value}} overrides
            applied on top of this config for sends to that party (the
            reference's per-destination messages config seam,
            ref ``grpc_proxy.py:156-177``).
        proxy_max_restarts: how many times the receiver's accept loop is
            restarted after an unexpected crash (the reference maps this
            to Ray actor ``max_restarts``, ref ``barriers.py:301-307``).
            0 disables supervision.
        send_window: max unacknowledged frames in flight on the pipelined
            (plaintext) sender lane; bounds resend memory at
            window x payload size. 1 degenerates to half-duplex
            request-response.
        use_reactor: drive plaintext connections from the shared epoll
            reactor loop(s) instead of per-peer reader/writer threads
            (default True where epoll exists). The wire protocol, ack
            semantics, and failure envelope are identical; only the
            threading model changes. TLS connections always use the
            threaded half-duplex path regardless.
        num_reactors: size of the process-wide reactor thread pool that
            connections are distributed over. One loop comfortably
            drives tens of peers; raise it only when a single reactor
            core saturates.
        num_streams: parallel wire lanes per destination for striped
            bulk payloads (reactor mode only). When a ``tree`` payload
            is at least ~1MB and has several buffers (a sharded array's
            per-shard views, a many-leaf gradient pytree), its buffers
            are striped across this many connections concurrently and
            reassembled shard-aligned on the receiver — the sharded
            data plane's host-staging tax killer. 1 (default) keeps the
            single-lane wire byte-for-byte unchanged; K>1 changes only
            framing for payloads that meet the striping gate (both ends
            must run a stripe-aware build). Small frames, compressed
            payloads, error envelopes, and the TLS/device-DMA threaded
            paths never stripe.
    """

    retry_policy: Optional[Dict[str, Any]] = None
    connect_timeout_in_ms: int = 10000
    verify_peer_identity: bool = True
    per_party_config: Optional[Dict[str, Dict[str, Any]]] = None
    proxy_max_restarts: int = 3
    send_window: int = 8
    use_reactor: bool = True
    num_reactors: int = 1
    num_streams: int = 1

    def get_retry_policy(self) -> RetryPolicy:
        return RetryPolicy.from_dict(self.retry_policy)

    def for_dest(self, dest_party: Optional[str]) -> "TcpCrossSiloMessageConfig":
        """The effective config for sends to ``dest_party``: this config
        with any ``per_party_config[dest_party]`` overrides applied."""
        overrides = (self.per_party_config or {}).get(dest_party)
        if not overrides:
            return self
        merged = dataclasses.asdict(self)
        merged.pop("per_party_config", None)
        field_names = {f.name for f in dataclasses.fields(type(self))}
        merged.update(
            {k: v for k, v in overrides.items() if k in field_names}
        )
        return type(self)(**{
            k: v for k, v in merged.items() if k in field_names
        })


# Back-compat alias: the reference spells this GrpcCrossSiloMessageConfig.
GrpcCrossSiloMessageConfig = TcpCrossSiloMessageConfig


@dataclasses.dataclass
class PartyMeshConfig:
    """TPU topology for one party (no reference equivalent — TPU-native).

    Attributes:
        device_ids: indices into ``jax.devices()`` forming this party's mesh
            (None = all local devices).
        mesh_shape: logical mesh shape over those devices.
        axis_names: logical axis names, e.g. ("data", "model").
    """

    device_ids: Optional[List[int]] = None
    mesh_shape: Optional[List[int]] = None
    axis_names: Optional[List[str]] = None

    @classmethod
    def from_dict(cls, data: Optional[Dict[str, Any]]) -> "PartyMeshConfig":
        data = data or {}
        field_names = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in data.items() if k in field_names})


@dataclasses.dataclass
class AsyncAggregationConfig:
    """Buffered-async aggregation knobs (``config['aggregation']`` keys
    prefixed ``async_``, validated at ``fed.init``; docs/async_rounds.md).

    Attributes:
        buffer_k: accepted contributions per K-publish — FedBuff's buffer
            size. 1 degenerates to pure FedAsync (publish every arrival).
        staleness: decay family applied to a contribution ``s`` rounds
            stale: "poly" ``(1+s)**-exp`` (FedBuff's default), "constant"
            (no decay), or "exp" ``exp**s``.
        staleness_exp: the decay family's parameter.
        server_lr: server learning rate mixing each K-publish into the
            running global model, ``new = old + lr * (mean - old)``.
            1.0 (default) replaces the model with the buffered mean
            exactly (bitwise — no mix arithmetic runs).
        suspect_factor: multiplicative down-weight for contributions from
            SUSPECT parties (liveness view); DEAD parties are dropped
            outright regardless.
        max_staleness: drop contributions more than this many rounds
            stale (None = keep all, decay-weighted).
    """

    buffer_k: int = 2
    staleness: str = "poly"
    staleness_exp: float = 0.5
    server_lr: float = 1.0
    suspect_factor: float = 1.0
    max_staleness: Optional[int] = None

    def __post_init__(self):
        if int(self.buffer_k) < 1:
            raise ValueError(
                f"aggregation.async_buffer_k must be >= 1, "
                f"got {self.buffer_k}"
            )
        self.buffer_k = int(self.buffer_k)
        if self.staleness not in ("poly", "constant", "exp"):
            raise ValueError(
                "aggregation.async_staleness must be 'poly', 'constant' "
                f"or 'exp', got {self.staleness!r}"
            )
        if not (0.0 < float(self.server_lr) <= 1.0):
            raise ValueError(
                f"aggregation.async_server_lr must be in (0, 1], "
                f"got {self.server_lr}"
            )
        if not (0.0 <= float(self.suspect_factor) <= 1.0):
            raise ValueError(
                f"aggregation.async_suspect_factor must be in [0, 1], "
                f"got {self.suspect_factor}"
            )
        if self.max_staleness is not None and int(self.max_staleness) < 0:
            raise ValueError(
                f"aggregation.async_max_staleness must be >= 0 or None, "
                f"got {self.max_staleness}"
            )

    _KEY_PREFIX = "async_"

    @classmethod
    def from_aggregation_dict(
        cls, data: Optional[Dict[str, Any]]
    ) -> "AsyncAggregationConfig":
        """Build from the ``aggregation`` config section's ``async_*``
        keys. Unknown ``async_*`` keys raise (the sync keys — topology,
        group_size — are validated by ``topology.set_default``)."""
        data = data or {}
        field_names = {f.name for f in dataclasses.fields(cls)}
        kwargs = {}
        for key, value in data.items():
            if not key.startswith(cls._KEY_PREFIX):
                continue
            name = key[len(cls._KEY_PREFIX):]
            if name not in field_names:
                known = sorted(cls._KEY_PREFIX + f for f in field_names)
                raise ValueError(
                    f"unknown aggregation config key {key!r}; "
                    f"known async keys: {known}"
                )
            kwargs[name] = value
        return cls(**kwargs)

    def as_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class ServingConfig:
    """Inference serving plane knobs (``config['serving']``, docs/serving.md).

    Attributes:
        max_slots: decode rows in the pooled KV cache — the iteration-level
            batch width. Admitted requests beyond this wait in the pending
            queue at token granularity (continuous batching).
        max_len: total positions (prompt + generated) a request may span;
            sizes the pooled cache (one extra sacrificial position is
            allocated internally).
        max_new_tokens: default generation length when a request does not
            specify one.
        max_pending: admission-control bound on the waiting queue; submits
            beyond it fail fast with ``ServerOverloadedError`` instead of
            building unbounded latency.
        temperature: default sampling temperature (0 = greedy).
        eos_id: stop token (None = always decode the full length).
        prefix_reuse: clone a live identical-(version, prompt) donor row
            instead of re-running prefill.
        mode: "continuous" (iteration-level batching, the serving plane) or
            "sequential" (one request at a time — the naive baseline the
            bench compares against).
        prompt_buckets: prefill compiles once per bucket length; prompts
            are right-padded up to the next bucket (padding is causally
            invisible). None = powers of two up to ``max_len``.
        kv_layout: "paged" (block-granular KV pool with on-demand block
            grant — the serving-v2 data path) or "slab" (one contiguous
            ``max_len+1`` row per slot, the PR 8 layout). Bitwise-identical
            outputs on identical traffic; paged admits mixed-length
            traffic without stranding whole rows.
        kv_block_size: positions per KV block (paged layout only).
        kv_blocks: physical blocks in the paged pool (one extra
            sacrificial block is allocated internally). None = slab-
            equivalent capacity: ``max_slots * ceil((max_len+1)/block)``.
        prefill_chunk: prompts longer than this prefill in chunks merged
            into the running decode iteration (chunked prefill) instead of
            one monolithic forward that stalls the live batch.
        prefill_token_budget: max prefill tokens processed per engine
            iteration — the prefill:decode budget that bounds how long a
            long admission can delay the next decode step.
        stream_window: max coalesced token frames in flight per streamed
            request (client streaming backpressure window).
    """

    max_slots: int = 8
    max_len: int = 128
    max_new_tokens: int = 16
    max_pending: int = 64
    temperature: float = 0.0
    eos_id: Optional[int] = None
    prefix_reuse: bool = True
    mode: str = "continuous"
    prompt_buckets: Optional[List[int]] = None
    kv_layout: str = "paged"
    kv_block_size: int = 16
    kv_blocks: Optional[int] = None
    prefill_chunk: int = 32
    prefill_token_budget: int = 64
    stream_window: int = 4

    def __post_init__(self):
        if self.mode not in ("continuous", "sequential"):
            raise ValueError(
                f"serving.mode must be 'continuous' or 'sequential', "
                f"got {self.mode!r}"
            )
        if self.kv_layout not in ("paged", "slab"):
            raise ValueError(
                f"serving.kv_layout must be 'paged' or 'slab', "
                f"got {self.kv_layout!r}"
            )
        if self.max_new_tokens < 1:
            raise ValueError("serving.max_new_tokens must be >= 1")
        if self.max_new_tokens >= self.max_len:
            raise ValueError(
                "serving.max_new_tokens must leave room for a prompt "
                f"(max_new_tokens={self.max_new_tokens} >= "
                f"max_len={self.max_len})"
            )
        if self.kv_block_size < 1:
            raise ValueError("serving.kv_block_size must be >= 1")
        if self.kv_blocks is not None and self.kv_blocks < 1:
            raise ValueError("serving.kv_blocks must be >= 1")
        if self.prefill_chunk < 1:
            raise ValueError("serving.prefill_chunk must be >= 1")
        if self.prefill_token_budget < self.prefill_chunk:
            raise ValueError(
                "serving.prefill_token_budget must be >= prefill_chunk "
                f"({self.prefill_token_budget} < {self.prefill_chunk})"
            )
        if self.stream_window < 1:
            raise ValueError("serving.stream_window must be >= 1")

    @classmethod
    def from_dict(cls, data: Optional[Dict[str, Any]]) -> "ServingConfig":
        """STRICT build from ``config['serving']``: unknown keys raise
        with the known-key list (a typo'd knob rejects ``fed.init``
        instead of silently never taking effect)."""
        data = data or {}
        field_names = {f.name for f in dataclasses.fields(cls)}
        for key in data:
            if key not in field_names:
                raise ValueError(
                    f"unknown serving config key {key!r}; known keys: "
                    f"{sorted(field_names)}"
                )
        return cls(**data)


# MembershipConfig lives with the elastic-membership subsystem
# (membership/config.py); re-exported here because job config classes are
# historically spelled ``rayfed_tpu.config.<Name>`` (same pattern as
# RetryPolicy above).
from rayfed_tpu.membership.config import MembershipConfig  # noqa: E402,F401

# PrivacyConfig lives with the privacy plane (privacy/config.py);
# re-exported for the same reason.
from rayfed_tpu.privacy.config import PrivacyConfig  # noqa: E402,F401
