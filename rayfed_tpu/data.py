# Copyright 2026 The rayfed-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Input pipeline: tokenized LM data -> device-resident sharded batches.

The reference ships no data loading (its engine moves opaque payloads);
training frameworks need one, so this module provides the TPU-shaped
essentials without new dependencies:

 - :class:`TokenDataset`: a flat token array (in-memory or ``np.memmap``)
   cut into (seq+1)-length windows, shuffled deterministically per epoch;
 - :func:`make_batch_iterator`: yields ``(inputs, targets)`` pairs already
   ``device_put`` onto the mesh with the train step's batch sharding, with
   one batch of host->device transfer prefetched ahead of compute (the
   standard TPU double-buffering trick);
 - federated usage: each party constructs its own dataset over its own
   shard of the corpus — data never crosses the perimeter; the engine's
   batch sharding (party x data) then makes XLA's grad all-reduce the
   federated aggregate (see ``parallel/train.py``).
"""

from __future__ import annotations

import queue
import threading
from typing import Iterator, Optional, Tuple

import numpy as np


class TokenDataset:
    """Deterministically-shuffled (seq+1)-token windows over a flat corpus.

    ``tokens`` may be any 1-D integer array-like, including ``np.memmap``
    (corpora larger than RAM stream from disk page-by-page).
    """

    def __init__(self, tokens, seq_len: int, seed: int = 0) -> None:
        self._tokens = np.asarray(tokens)  # no-copy for ndarray/memmap
        assert self._tokens.ndim == 1, "tokens must be a flat 1-D array"
        self._window = seq_len + 1  # inputs + shifted targets
        self._n_windows = len(self._tokens) // self._window
        assert self._n_windows > 0, (
            f"corpus of {len(self._tokens)} tokens is shorter than one "
            f"window ({self._window})"
        )
        self._seed = seed
        self.seq_len = seq_len

    def __len__(self) -> int:
        return self._n_windows

    def epoch(self, epoch: int) -> Iterator[np.ndarray]:
        """Windows of one epoch in a deterministic per-epoch order."""
        order = np.random.RandomState(
            (self._seed * 1_000_003 + epoch) % (2**31)
        ).permutation(self._n_windows)
        for w in order:
            start = int(w) * self._window
            yield np.asarray(self._tokens[start: start + self._window])

    def batches(self, batch: int, epoch: int = 0,
                drop_remainder: bool = True) -> Iterator[np.ndarray]:
        """(batch, seq+1) int32 blocks from one epoch."""
        buf = []
        for window in self.epoch(epoch):
            buf.append(window)
            if len(buf) == batch:
                yield np.stack(buf).astype(np.int32, copy=False)
                buf = []
        if buf and not drop_remainder:
            yield np.stack(buf).astype(np.int32, copy=False)


def make_batch_iterator(
    dataset: TokenDataset,
    batch: int,
    mesh,
    batch_pspec=None,
    *,
    epochs: Optional[int] = None,
    start_epoch: int = 0,
    prefetch: int = 1,
) -> Iterator[Tuple]:
    """Device-resident ``(inputs, targets)`` pairs, transfer-prefetched.

    A loader thread stages the next ``prefetch`` batches host->device
    (``jax.device_put`` with the mesh batch sharding) while the current
    step computes, hiding transfer latency behind the MXU. ``epochs=None``
    iterates forever; the epoch schedule is deterministic, so a restarted
    job can resume at ``start_epoch``.
    """
    import jax
    from jax.sharding import NamedSharding

    if batch_pspec is None:
        from rayfed_tpu.parallel import sharding as shd

        batch_pspec = shd.batch_spec(mesh)
    sharding = NamedSharding(mesh, batch_pspec)

    q: "queue.Queue" = queue.Queue(maxsize=max(1, prefetch))
    stop = threading.Event()
    _END = object()          # clean end-of-stream

    class _LoaderError:
        # Errors cross the thread boundary explicitly: a dead loader must
        # surface its exception at the training loop, not masquerade as a
        # clean end of data.
        def __init__(self, exc: BaseException) -> None:
            self.exc = exc

    def loader() -> None:
        epoch = start_epoch
        try:
            while not stop.is_set() and (
                epochs is None or epoch < start_epoch + epochs
            ):
                for block in dataset.batches(batch, epoch=epoch):
                    if stop.is_set():
                        return
                    pair = (
                        jax.device_put(block[:, :-1], sharding),
                        jax.device_put(block[:, 1:], sharding),
                    )
                    q.put(pair)
                epoch += 1
            q.put(_END)
        except BaseException as e:  # noqa: BLE001 - re-raised in consumer
            if not stop.is_set():
                q.put(_LoaderError(e))

    thread = threading.Thread(
        target=loader, name="fedtpu-data-loader", daemon=True
    )
    thread.start()

    class _Iter:
        def __init__(self):
            self._done = False

        def __iter__(self):
            return self

        def __next__(self):
            # A finished stream stays finished: q.get() with no live
            # producer would block forever, so exhaustion/close/error all
            # latch _done and keep raising StopIteration.
            if self._done:
                raise StopIteration
            while True:
                # Re-check _done between bounded gets: a concurrent
                # close() from another thread drains the queue (possibly
                # eating _END), and a get() with no deadline would then
                # block this consumer forever.
                try:
                    item = q.get(timeout=0.1)
                    break
                except queue.Empty:
                    if self._done:
                        raise StopIteration from None
            if item is _END:
                self._done = True
                raise StopIteration
            if isinstance(item, _LoaderError):
                self._done = True
                raise RuntimeError("data loader failed") from item.exc
            return item

        def close(self) -> None:
            self._done = True
            stop.set()
            # Keep draining until the loader exits: a put-blocked loader
            # needs our get to wake up and observe the stop flag.
            while thread.is_alive():
                try:
                    q.get(timeout=0.05)
                except queue.Empty:
                    pass
            while True:
                try:
                    q.get_nowait()
                except queue.Empty:
                    break

        # Dropping the iterator without close() (break out of a `for`
        # over the infinite epochs=None stream) must not leak the loader
        # thread or the prefetched device batches it holds.
        def __del__(self):
            try:
                self.close()
            except BaseException:  # noqa: BLE001 - interpreter teardown
                pass

        def __enter__(self):
            return self

        def __exit__(self, *exc):
            self.close()
            return False

    return _Iter()


def synthetic_lm_dataset(vocab: int, n_tokens: int, seq_len: int,
                         seed: int = 0) -> TokenDataset:
    """Random-token corpus for benchmarks and tests."""
    rng = np.random.RandomState(seed)
    return TokenDataset(
        rng.randint(0, vocab, size=n_tokens).astype(np.int32),
        seq_len, seed=seed,
    )
