# Copyright 2026 The rayfed-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Cross-party error envelopes.

Capability parity: the reference's ``FedRemoteError`` is the single typed
envelope in which one party's task/actor failure travels to every other party
(reference: ``fed/exceptions.py:16-25``; produced by the cleanup drain at
``fed/cleanup.py:160-172`` and re-raised out of the receiver at
``fed/proxy/barriers.py:227-234``).
"""

from __future__ import annotations


class FedRemoteError(Exception):
    """An error raised in one party, delivered to a peer under the same
    (upstream_seq_id, downstream_seq_id) rendezvous the peer is waiting on.

    ``cause`` is the original exception if the sending party exposed the
    trace (``expose_error_trace=True``), otherwise a string summary — the
    same privacy knob as the reference (``fed/cleanup.py:160-167``).
    """

    def __init__(self, src_party: str, cause):
        self._src_party = src_party
        self._cause = cause

    @property
    def src_party(self) -> str:
        return self._src_party

    @property
    def cause(self):
        return self._cause

    def __str__(self) -> str:
        return (
            f"FedRemoteError occurred at party {self._src_party}."
            f" Caused by {self._cause!r}."
        )

    def __repr__(self) -> str:  # pragma: no cover - cosmetic
        return self.__str__()


class FedLocalError(Exception):
    """Wrapper distinguishing a *local* task failure from a remote envelope
    when both flow through the same send lane (our design; the reference
    conflates these inside the cleanup thread)."""

    def __init__(self, cause: BaseException):
        self._cause = cause

    @property
    def cause(self) -> BaseException:
        return self._cause

    def __str__(self) -> str:
        return f"FedLocalError caused by {self._cause!r}"


class FedActorKilledError(Exception):
    """Raised by method futures of an actor that was ``fed.kill``-ed before
    they could run (the analogue of Ray's RayActorError fail-fast semantics,
    ref ``fed/api.py:611-623``)."""


class StaleCoordinatorError(Exception):
    """A membership sync arrived from a deposed coordinator: its term is
    below the term this party already adopted at a failover. The view it
    carries was folded without the failover's evictions, so applying it
    would fork the roster — every party rejects it instead (docs/ha.md).
    """

    def __init__(self, received_term: int, current_term: int,
                 coordinator=None):
        self.received_term = int(received_term)
        self.current_term = int(current_term)
        self.coordinator = coordinator
        super().__init__(
            f"stale membership sync from deposed coordinator "
            f"{coordinator!r}: term {received_term} < adopted term "
            f"{current_term}"
        )
