# Copyright 2026 The rayfed-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""FedObject: the party-tagged lazy handle for a fed task output.

Capability parity: reference ``fed/fed_object.py:41-81``. A ``FedObject``
produced in *this* party wraps a live value future from the local executor;
one produced in another party is a pure placeholder (``future=None``) until
a ``recv`` future is cached on first resolution (ref
``fed/utils.py:70-76``, ``fed/api.py:580-594``). The sending context
deduplicates pushes per target party (ref ``fed_object.py:18-32``,
exercised by ``fed/tests/test_cache_fed_objects.py``).

TPU note: the resolved value of a FedObject is whatever the task returned —
for the TPU data plane that is typically a (sharded) ``jax.Array`` already
living on the party's mesh; the handle itself stays backend-agnostic.
"""

from __future__ import annotations

from concurrent.futures import Future
from typing import Optional


class FedObjectSendingContext:
    """Tracks which parties this object is being / has been pushed to."""

    def __init__(self) -> None:
        self._is_sending_or_sent = set()

    def mark_is_sending_to_party(self, target_party: str) -> None:
        self._is_sending_or_sent.add(target_party)

    def was_sending_or_sent_to_party(self, target_party: str) -> bool:
        return target_party in self._is_sending_or_sent


class FedObject:
    def __init__(
        self,
        node_party: str,
        fed_task_id: int,
        future: Optional[Future],
        idx_in_task: int = 0,
    ) -> None:
        self._node_party = node_party
        self._future = future
        self._fed_task_id = fed_task_id
        self._idx_in_task = idx_in_task
        self._sending_context = FedObjectSendingContext()

    def get_value_future(self) -> Optional[Future]:
        """The local value future (own party), the cached recv future
        (foreign party, after first resolution), or None."""
        return self._future

    def get_fed_task_id(self) -> str:
        # Wire-visible id: "<seq>#<output index>" (ref fed_object.py:64-65).
        return f"{self._fed_task_id}#{self._idx_in_task}"

    def get_party(self) -> str:
        return self._node_party

    def _mark_is_sending_to_party(self, target_party: str) -> None:
        self._sending_context.mark_is_sending_to_party(target_party)

    def _was_sending_or_sent_to_party(self, target_party: str) -> bool:
        return self._sending_context.was_sending_or_sent_to_party(target_party)

    def _cache_value_future(self, future: Future) -> None:
        self._future = future

    def __repr__(self) -> str:
        state = "bound" if self._future is not None else "placeholder"
        return (
            f"FedObject(party={self._node_party}, "
            f"task_id={self.get_fed_task_id()}, {state})"
        )
