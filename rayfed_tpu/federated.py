# Copyright 2026 The rayfed-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""High-level federated learning API over the execution engine.

The reference leaves FedAvg entirely to user code (``README.md:59-104``);
these helpers package the standard patterns while preserving the engine's
semantics — every helper builds an ordinary fed DAG, so the owner-push
perimeter, seq-id determinism, and error envelopes all apply unchanged.

``fed_aggregate`` reduces per-party FedObjects along a **planned
reduction topology** (``rayfed_tpu/topology.py``): flat star, binary
tree, ring chain, or hierarchical edge-aggregator fan-in, selected per
call (``topology=``) or job-wide (``config['aggregation']['topology']``,
default ``auto``). Each plan step is one k-ary jitted reduce executing at
the step's destination party, so the communication shape — rounds,
per-node fan-in, per-link traffic — is exactly the planner's schedule.
Degraded rounds re-plan over survivors: pass ``liveness=`` (the
``fed.liveness_view()`` dict) and DEAD parties are excluded before the
schedule is laid out.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Sequence

import rayfed_tpu as fed
from rayfed_tpu import topology as topo
from rayfed_tpu.telemetry import metrics as telemetry_metrics

_m_aggregates = telemetry_metrics.get_registry().counter(
    "fed_driver_aggregates_total",
    "fed_aggregate calls laid out by this driver, by mode.",
    labels=("mode",),
)


@fed.remote
def _agg_kary_sum(*trees):
    from rayfed_tpu.ops.aggregate import tree_sum

    return tree_sum(*trees)


@fed.remote
def _agg_kary_weighted(*pairs):
    # pairs: (tree, weight) partials; returns (weighted-sum tree, total).
    from rayfed_tpu.ops.aggregate import tree_sum

    trees = [t for t, _ in pairs]
    total = pairs[0][1]
    for _, w in pairs[1:]:
        total = total + w
    return tree_sum(*trees), total


@fed.remote
def _scale(tree, denom):
    import jax

    return jax.tree_util.tree_map(lambda x: x / denom, tree)


@fed.remote
def _scale_weighted(pair):
    import jax

    tree, total = pair
    return jax.tree_util.tree_map(lambda x: x / total, tree)


@fed.remote
def _premul(tree, w):
    import jax

    return (jax.tree_util.tree_map(lambda x: x * w, tree), w)


@fed.remote
def _agg_psum_flat(parties, weights, *trees):
    # Same-mesh lowering: the whole flat reduction as ONE task at the
    # root — a single shard_map collective across the composed mesh's
    # party axis. Falls back to the identical-bits local fold when the
    # executing process has no composed mesh registered (e.g. a replayed
    # DAG in a plain process), so the result never depends on which path
    # ran.
    from rayfed_tpu import mesh as mesh_mod
    from rayfed_tpu import topology as topo_mod
    from rayfed_tpu.ops.aggregate import psum_by_plan, reduce_by_plan

    plan = topo_mod.plan(list(parties), "flat")
    contributions = dict(zip(parties, trees))
    if mesh_mod.composed_mesh_for(plan.parties) is None:
        return reduce_by_plan(plan, contributions, weights=weights)
    return psum_by_plan(plan, contributions, weights=weights)


@fed.remote
def _secagg_mask(tree, party, parties, domain, round_index, weight):
    # Party-side secure step: clip (DP), premultiply (wmean), encode into
    # the fixed-point ring, mask against every co-contributor. Executes
    # AT the contributing party — only the masked envelope rides the wire.
    from rayfed_tpu.privacy.manager import require_privacy_manager

    mgr = require_privacy_manager("fed_aggregate(secure=True)")
    return mgr.mask_contribution(
        tree, party=party, parties=list(parties), domain=domain,
        round_index=round_index, weight=weight,
    )


@fed.remote
def _secagg_reduce(op, parties, domain, round_index, weights, *envelopes):
    # Root-side secure step: ring-sum the masked envelopes (host fold, or
    # ONE party-axis collective when this process holds a composed mesh
    # for the contributors — bitwise-identical by modular associativity),
    # cancel orphaned masks of dropped parties, decode, scale, add DP
    # noise. The envelopes of parties that died before contributing
    # arrive as None (their FedObject never resolved at the caller).
    from rayfed_tpu.privacy.manager import require_privacy_manager

    mgr = require_privacy_manager("fed_aggregate(secure=True)")
    envs = {
        e["party"]: e
        for e in envelopes
        if isinstance(e, dict) and e.get("__secagg__")
    }
    return mgr.secure_reduce(
        op, list(parties), domain, round_index, weights, envs
    )


# Secure rounds are numbered per aggregation domain by a driver-local
# counter. Every controller calls fed_aggregate in the same order with
# the same arguments (the multi-controller contract), so every driver —
# and therefore every party's masking task — derives the same round
# index without any extra coordination.
from rayfed_tpu.tenancy.context import JobScoped

_secure_round_counters: JobScoped = JobScoped(
    "federated.secure_rounds", default_factory=dict
)

SECURE_SYNC_DOMAIN = "fedagg"


def _next_secure_round(domain: str) -> int:
    counters = _secure_round_counters.get()
    rnd = counters.get(domain, 0)
    counters[domain] = rnd + 1
    return rnd


def _reset_secure_rounds() -> None:
    _secure_round_counters.pop()


def _secure_sync_aggregate(plan, objs, op, weights, publish_to):
    """The secure=True sync lowering: one masking task per party, one
    unmask-by-cancellation reduce at the root. Always single-hop — a
    masked envelope is only unmaskable once ALL contributions meet, so
    intermediate tree/ring hops would see nothing but could compute
    nothing either."""
    rnd = _next_secure_round(SECURE_SYNC_DOMAIN)
    w = None
    if op == "wmean":
        w = {p: float(weights[p]) for p in plan.parties}
    masked = [
        _secagg_mask.party(p).remote(
            objs[p], p, tuple(plan.parties), SECURE_SYNC_DOMAIN, rnd,
            None if w is None else w[p],
        )
        for p in plan.parties
    ]
    root = _secagg_reduce.party(plan.root).remote(
        op, tuple(plan.parties), SECURE_SYNC_DOMAIN, rnd, w, *masked
    )
    if publish_to is not None:
        publish_to.publish(root)
    return root


def _try_same_mesh_aggregate(plan, objs, op, weights):
    """Lower a flat plan to a single-psum task at the root when every
    party resolves onto one registered composed mesh. Returns the result
    FedObject, or None to keep the stepwise DAG lowering."""
    from rayfed_tpu import mesh as mesh_mod

    if op not in ("mean", "wmean"):
        return None  # psum_by_plan computes a weighted mean
    if not topo.plan_is_flat(plan) or len(plan.parties) < 2:
        return None
    if mesh_mod.composed_mesh_for(plan.parties) is None:
        return None
    w = None
    if op == "wmean":
        w = {p: float(weights[p]) for p in plan.parties}
    return _agg_psum_flat.party(plan.root).remote(
        tuple(plan.parties), w, *[objs[p] for p in plan.parties]
    )


def fed_aggregate(
    objs: Dict[str, Any],
    op: str = "mean",
    weights: Optional[Dict[str, float]] = None,
    topology: Optional[str] = None,
    liveness: Optional[Dict[str, str]] = None,
    plan: Optional[topo.TopologyPlan] = None,
    publish_to: Any = None,
    mode: str = "sync",
    buffer_k: Optional[int] = None,
    staleness_fn: Optional[str] = None,
    round_tag: Optional[int] = None,
    secure: bool = False,
) -> Any:
    """Reduce ``{party: FedObject-of-pytree}`` along a planned topology.

    The result lives at the plan's root (the first surviving party);
    pass it to ``fed.get`` to broadcast, or feed it onwards in the DAG.
    All parties must call this with the same arguments
    (multi-controller contract — the plan is a pure function of them, so
    every driver lays out the identical DAG).

    op: "sum", "mean", or "wmean" (sample-count weighting via ``weights``).
    mode: "sync" (default — the lock-step reduction below) or "async"
        (FedBuff-style buffered aggregation, docs/async_rounds.md): each
        contribution is OFFERED to a buffered aggregator at the root and
        the call returns an :class:`~rayfed_tpu.async_rounds.AsyncRoundHandle`
        immediately — ``handle.model`` is a FedObject of the newest
        published ``{"version", "params"}`` at the root, which may not
        yet include this round's contributions. ``buffer_k`` (publish
        every K accepted contributions), ``staleness_fn`` ("poly" |
        "constant" | "exp") and ``round_tag`` (staleness bucket; auto-
        incremented when None) apply only to async mode, which supports
        op "mean"/"wmean"; ``topology``/``plan`` are sync-only (the
        async fold orders itself by arrival).
    topology: "auto" | "flat" | "tree" | "ring" | "hier"; None reads the
        job default set by ``config['aggregation']['topology']``.
    liveness: a ``fed.liveness_view()``-shaped ``{party: state}`` dict;
        DEAD parties are dropped and the schedule re-planned over the
        survivors (their FedObjects are never consumed, "mean" divides by
        the survivor count).
    plan: a pre-computed :class:`~rayfed_tpu.topology.TopologyPlan` —
        overrides ``topology``/``liveness`` (callers that already
        re-planned mid-round pass the new plan directly).
    publish_to: a :class:`~rayfed_tpu.serving.ServeHandle` — the
        continuous train-and-serve hookup (docs/serving.md): the fresh
        aggregate is hot-published to the serving engine as its next
        version (an owner-push over the bulk lane when the plan root is
        not the serving party). In-flight generations finish on the
        version they pinned; the aggregate FedObject is still returned
        for the next round.
    secure: lower the aggregation through the privacy plane
        (docs/privacy.md): each contribution is clipped, fixed-point
        encoded, and pairwise-masked AT its party; only masked envelopes
        ride the wire; the root cancels the masks in the modular ring
        and recovers exactly the aggregate. Requires
        ``config["privacy"]["secure_aggregation"] = True`` at
        ``fed.init``. Supports op sum/mean/wmean; the plan is forced
        flat (an envelope is only unmaskable where ALL contributions
        meet, so intermediate hops cannot partially reduce). Works with
        ``mode="async"`` (masked offers buffer per round at the root).
    """
    assert objs, "need at least one party's object"
    if secure:
        from rayfed_tpu.privacy.manager import require_privacy_manager

        mgr = require_privacy_manager("fed_aggregate(secure=True)")
        if not mgr.config.secure_aggregation:
            raise ValueError(
                "fed_aggregate(secure=True) needs "
                'config["privacy"]["secure_aggregation"] = True at '
                "fed.init (the privacy block is present but secure "
                "aggregation is off)"
            )
        if op not in ("sum", "mean", "wmean"):
            raise ValueError(
                f"secure aggregation supports op sum/mean/wmean, got {op!r}"
            )
        if mode == "sync":
            if topology not in (None, "auto", "flat"):
                raise ValueError(
                    f"secure aggregation is single-hop: a masked envelope "
                    f"is only unmaskable once every contribution meets, so "
                    f"topology={topology!r} cannot partially reduce at "
                    f"intermediate hops — use 'flat' (or drop topology=)"
                )
            topology = "flat"
            if plan is not None and not topo.plan_is_flat(plan):
                raise ValueError(
                    "secure aggregation needs a flat plan (single hop); "
                    "re-plan with topology='flat'"
                )
    if mode in ("sync", "async"):
        _m_aggregates.labels(mode=mode).inc()
    if mode == "async":
        if op not in ("mean", "wmean"):
            raise ValueError(
                f"mode='async' aggregates a staleness-weighted mean; "
                f"op={op!r} is sync-only"
            )
        if plan is not None or topology is not None:
            raise ValueError(
                "mode='async' folds in arrival order — topology=/plan= "
                "are sync-only knobs"
            )
        if op == "wmean" and weights is None:
            raise ValueError("op='wmean' needs weights={party: w}")
        from rayfed_tpu import async_rounds

        return async_rounds.async_round(
            objs,
            round_tag=round_tag,
            weights=weights if op == "wmean" else None,
            buffer_k=buffer_k,
            staleness_fn=staleness_fn,
            publish_to=publish_to,
            secure=secure,
        )
    if mode != "sync":
        raise ValueError(f"mode must be 'sync' or 'async', got {mode!r}")
    if buffer_k is not None or staleness_fn is not None or round_tag is not None:
        raise ValueError(
            "buffer_k/staleness_fn/round_tag are async-only knobs; "
            "pass mode='async'"
        )
    if plan is None:
        default_topo, group_size = topo.get_default()
        dead = set()
        if liveness:
            from rayfed_tpu.resilience.liveness import DEAD

            dead = {p for p, st in liveness.items() if st == DEAD}
        # Elastic membership: parties evicted (or departed) since the
        # caller built ``objs`` are outside the current roster — exclude
        # them like DEAD parties so the schedule re-plans over the
        # members. Every party applied the same epoch bump at the same
        # sync point, so every driver excludes identically.
        from rayfed_tpu.membership.manager import get_membership_manager

        membership = get_membership_manager()
        if membership is not None:
            roster = set(membership.roster())
            dead |= {p for p in objs if p not in roster}
        plan = topo.plan(
            list(objs.keys()),
            topology or default_topo,
            group_size=group_size,
            dead=dead,
        )
    missing = set(plan.parties) - set(objs)
    if missing:
        raise ValueError(
            f"plan references parties with no contribution: {sorted(missing)}"
        )

    if op == "wmean":
        assert weights is not None, "op='wmean' needs weights={party: w}"
        missing_w = set(plan.parties) - set(weights)
        if missing_w:
            raise ValueError(
                f"op='wmean' weights missing entries for parties "
                f"{sorted(missing_w)}"
            )

    if secure:
        # Privacy-plane lowering: masks at the parties, one cancel-and-
        # decode reduce at the root (which itself lowers the ring sum to
        # the composed-mesh collective when one is registered — the
        # secure twin of the fast path below).
        return _secure_sync_aggregate(plan, objs, op, weights, publish_to)

    # Same-mesh fast path: a flat plan over parties that compose into one
    # registered mesh lowers to a single collective task at the root.
    fast = _try_same_mesh_aggregate(plan, objs, op, weights)
    if fast is not None:
        if publish_to is not None:
            publish_to.publish(fast)
        return fast

    if op == "wmean":
        held = {
            p: _premul.party(p).remote(objs[p], float(weights[p]))
            for p in plan.parties
        }
        reducer = _agg_kary_weighted
    else:
        assert op in ("sum", "mean"), op
        held = {p: objs[p] for p in plan.parties}
        reducer = _agg_kary_sum

    # Walk the schedule: each step is one k-ary reduce executing at the
    # step's destination, folding in the plan's explicit src order.
    for level in plan.levels:
        for step in level:
            held[step.dst] = reducer.party(step.dst).remote(
                *[held[s] for s in step.srcs]
            )
            for s in step.srcs[1:]:
                del held[s]

    root, root_owner = held[plan.root], plan.root
    if op == "mean":
        root = _scale.party(root_owner).remote(root, float(len(plan.parties)))
    elif op == "wmean":
        root = _scale_weighted.party(root_owner).remote(root)
    if publish_to is not None:
        publish_to.publish(root)
    return root


class FedAvgTrainer:
    """Multi-round FedAvg orchestration: per-party worker actors train
    locally, aggregates flow through :func:`fed_aggregate`, and the global
    model feeds the next round.

    ``worker_cls`` is a ``@fed.remote`` actor class exposing
    ``train(global_params_or_None) -> params`` (and optionally
    ``num_samples() -> float`` for weighted averaging).
    """

    def __init__(
        self,
        worker_cls,
        parties: Sequence[str],
        worker_args: Optional[Dict[str, tuple]] = None,
        op: str = "mean",
        weights: Optional[Dict[str, float]] = None,
        topology: Optional[str] = None,
    ):
        self._parties = list(parties)
        self._op = op
        self._weights = weights
        self._topology = topology
        worker_args = worker_args or {}
        self._workers = {
            p: worker_cls.party(p).remote(*worker_args.get(p, ()))
            for p in self._parties
        }

    @property
    def workers(self):
        return self._workers

    def run(self, rounds: int, global_params=None):
        """Run ``rounds`` federated rounds; returns the final aggregate as
        a FedObject owned by the first party."""
        for _ in range(rounds):
            locals_ = {
                p: self._workers[p].train.remote(global_params)
                for p in self._parties
            }
            global_params = fed_aggregate(
                locals_, op=self._op, weights=self._weights,
                topology=self._topology,
            )
        return global_params
