# Copyright 2026 The rayfed-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""High-level federated learning API over the execution engine.

The reference leaves FedAvg entirely to user code (``README.md:59-104``);
these helpers package the standard patterns while preserving the engine's
semantics — every helper builds an ordinary fed DAG, so the owner-push
perimeter, seq-id determinism, and error envelopes all apply unchanged.

``fed_aggregate`` reduces per-party FedObjects with a **pairwise
hierarchical tree** (BASELINE.json config #4): with n parties the reduction
runs in ceil(log2 n) rounds of 2-way jitted reduces, halving the
coordinator's fan-in (and its inbound bandwidth) versus the naive
all-to-coordinator star.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Sequence

import rayfed_tpu as fed


@fed.remote
def _agg_pair_sum(a, b):
    from rayfed_tpu.ops.aggregate import tree_sum

    return tree_sum(a, b)


@fed.remote
def _agg_pair_weighted(a, b):
    # a, b: (tree, weight) pairs; returns (weighted-sum tree, total weight).
    from rayfed_tpu.ops.aggregate import tree_sum

    (ta, wa), (tb, wb) = a, b
    return tree_sum(ta, tb), wa + wb


@fed.remote
def _scale(tree, denom):
    import jax

    return jax.tree_util.tree_map(lambda x: x / denom, tree)


@fed.remote
def _scale_weighted(pair):
    import jax

    tree, total = pair
    return jax.tree_util.tree_map(lambda x: x / total, tree)


@fed.remote
def _premul(tree, w):
    import jax

    return (jax.tree_util.tree_map(lambda x: x * w, tree), w)


def fed_aggregate(
    objs: Dict[str, Any],
    op: str = "mean",
    weights: Optional[Dict[str, float]] = None,
) -> Any:
    """Reduce ``{party: FedObject-of-pytree}`` hierarchically.

    The result lives at the first party (tree root); pass it to
    ``fed.get`` to broadcast, or feed it onwards in the DAG. All parties
    must call this with the same arguments (multi-controller contract).

    op: "sum", "mean", or "wmean" (sample-count weighting via ``weights``).
    """
    assert objs, "need at least one party's object"
    parties = list(objs.keys())
    if op == "wmean":
        assert weights is not None, "op='wmean' needs weights={party: w}"
        missing = set(parties) - set(weights)
        if missing:
            raise ValueError(
                f"op='wmean' weights missing entries for parties "
                f"{sorted(missing)}"
            )
        level = [
            _premul.party(p).remote(objs[p], float(weights[p]))
            for p in parties
        ]
        reducer = _agg_pair_weighted
    else:
        assert op in ("sum", "mean"), op
        level = [objs[p] for p in parties]
        reducer = _agg_pair_sum
    owners = list(parties)

    # ceil(log2 n) rounds of pairwise reduces; each reduce executes at the
    # left operand's owner, so traffic per round is one push per pair.
    while len(level) > 1:
        nxt, nxt_owners = [], []
        for i in range(0, len(level) - 1, 2):
            nxt.append(
                reducer.party(owners[i]).remote(level[i], level[i + 1])
            )
            nxt_owners.append(owners[i])
        if len(level) % 2:
            nxt.append(level[-1])
            nxt_owners.append(owners[-1])
        level, owners = nxt, nxt_owners

    root, root_owner = level[0], owners[0]
    if op == "mean":
        return _scale.party(root_owner).remote(root, float(len(parties)))
    if op == "wmean":
        return _scale_weighted.party(root_owner).remote(root)
    return root


class FedAvgTrainer:
    """Multi-round FedAvg orchestration: per-party worker actors train
    locally, aggregates flow through :func:`fed_aggregate`, and the global
    model feeds the next round.

    ``worker_cls`` is a ``@fed.remote`` actor class exposing
    ``train(global_params_or_None) -> params`` (and optionally
    ``num_samples() -> float`` for weighted averaging).
    """

    def __init__(
        self,
        worker_cls,
        parties: Sequence[str],
        worker_args: Optional[Dict[str, tuple]] = None,
        op: str = "mean",
        weights: Optional[Dict[str, float]] = None,
    ):
        self._parties = list(parties)
        self._op = op
        self._weights = weights
        worker_args = worker_args or {}
        self._workers = {
            p: worker_cls.party(p).remote(*worker_args.get(p, ()))
            for p in self._parties
        }

    @property
    def workers(self):
        return self._workers

    def run(self, rounds: int, global_params=None):
        """Run ``rounds`` federated rounds; returns the final aggregate as
        a FedObject owned by the first party."""
        for _ in range(rounds):
            locals_ = {
                p: self._workers[p].train.remote(global_params)
                for p in self._parties
            }
            global_params = fed_aggregate(
                locals_, op=self._op, weights=self._weights
            )
        return global_params
