# Copyright 2026 The rayfed-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""``fedlint``: static analysis for multi-controller federated drivers.

The engine runs ONE copy of the same driver per party (SPMD over
parties), so a whole class of bugs never shows up in single-process
tests: control flow that diverges across parties desynchronizes the
``(upstream_seq_id, downstream_seq_id)`` protocol and deadlocks both
sides; cross-party pulls violate the owner-pushes data perimeter; and
``donate=True`` train steps alias buffers into the async send path.
``fedlint`` checks driver programs for these invariants *before* deploy:

    python -m rayfed_tpu.lint driver.py [more_drivers.py ...]

Rules (see ``docs/fedlint.md`` for the full catalogue):

========  ====================  =============================================
code      name                  contract checked
========  ====================  =============================================
FED001    perimeter             data crosses parties only by owner push
FED002    seq-divergence        every party issues the same fed-call sequence
FED003    donation-aliasing     donate=True step results never consumed
                                locally by reference (train.py contract)
FED004    dangling-fedobject    every produced FedObject has a consumer
FED005    reserved-seq-id       ("ping", "ping") seq pair is the readiness
                                probe, never user data
========  ====================  =============================================

Findings are suppressible per line with ``# fedlint: disable=<rule>``
(rule name or code; bare ``disable`` silences every rule on that line)
and per file with ``# fedlint: disable-file=<rule>``.

This package is dependency-free (stdlib ``ast`` only) so the linter can
run in CI images and pre-commit hooks that carry no jax/numpy.
"""

from rayfed_tpu.lint.core import (  # noqa: F401
    Finding,
    LintError,
    Rule,
    lint_file,
    lint_paths,
    lint_source,
)
from rayfed_tpu.lint.rules import ALL_RULES, rule_by_id  # noqa: F401

__all__ = [
    "Finding",
    "LintError",
    "Rule",
    "ALL_RULES",
    "rule_by_id",
    "lint_file",
    "lint_paths",
    "lint_source",
]
