# Copyright 2026 The rayfed-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""CLI: ``python -m rayfed_tpu.lint <driver.py | dir> ...``

Exit codes: 0 = clean, 1 = findings, 2 = analysis errors (unreadable
file, syntax error) or usage errors. See ``docs/fedlint.md``.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional

from rayfed_tpu.lint.core import lint_paths, parse_units
from rayfed_tpu.lint.project import collect_singletons
from rayfed_tpu.lint.reporters import report_json, report_sarif, report_text
from rayfed_tpu.lint.rules import ALL_RULES


def write_singleton_inventory(paths: List[str], out_path: str) -> int:
    """Emit the FED008 worklist (every module-level mutable singleton,
    suppressed sites included) as machine-readable JSON."""
    _files, units, errors = parse_units(paths)
    entries = [
        s.as_dict() for unit in units for s in collect_singletons(unit)
    ]
    entries.sort(key=lambda e: (e["module"], e["line"]))
    payload = {
        "version": 1,
        "description": (
            "Module-level mutable singletons (fedlint FED008): the "
            "multi-tenant refactor worklist. Suppressed findings still "
            "appear here; regenerate with `python -m rayfed_tpu.lint "
            "rayfed_tpu --singleton-inventory "
            "tools/singleton_inventory.json`."
        ),
        "singletons": entries,
    }
    with open(out_path, "w", encoding="utf-8") as f:
        json.dump(payload, f, indent=2, sort_keys=True)
        f.write("\n")
    print(
        f"fedlint: wrote {len(entries)} singleton(s) to {out_path}",
        file=sys.stderr,
    )
    return 2 if errors else 0


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m rayfed_tpu.lint",
        description=(
            "fedlint: static analysis for multi-controller federated "
            "drivers (data perimeter, seq-id divergence, donation "
            "aliasing, dangling FedObjects, reserved seq ids)."
        ),
    )
    parser.add_argument(
        "paths", nargs="*", metavar="PATH",
        help="driver files or directories (directories are walked for .py)",
    )
    parser.add_argument(
        "-f", "--format", choices=("text", "json", "sarif"), default="text",
        help="output format (default: text)",
    )
    parser.add_argument(
        "--singleton-inventory", metavar="PATH",
        help=(
            "instead of linting, write the FED008 singleton inventory "
            "(the multi-tenant refactor worklist) as JSON to PATH"
        ),
    )
    parser.add_argument(
        "--select", action="append", metavar="RULE",
        help="run only these rules (name or FED code; repeatable)",
    )
    parser.add_argument(
        "--disable", action="append", metavar="RULE",
        help="skip these rules (name or FED code; repeatable)",
    )
    parser.add_argument(
        "--list-rules", action="store_true",
        help="print the rule catalogue and exit",
    )
    args = parser.parse_args(argv)

    if args.list_rules:
        for rule in ALL_RULES:
            print(f"{rule.rule_id}  {rule.name:20s} {rule.summary}")
        return 0
    if not args.paths:
        parser.print_usage(sys.stderr)
        print(
            "python -m rayfed_tpu.lint: error: no paths given "
            "(try --list-rules)", file=sys.stderr,
        )
        return 2
    if args.singleton_inventory:
        return write_singleton_inventory(args.paths, args.singleton_inventory)

    result = lint_paths(args.paths, select=args.select, disable=args.disable)
    if args.format == "json":
        report_json(result, sys.stdout)
    elif args.format == "sarif":
        report_sarif(result, sys.stdout)
    else:
        report_text(result, sys.stdout)
    return result.exit_code


if __name__ == "__main__":
    sys.exit(main())
