# Copyright 2026 The rayfed-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""fedlint rule framework: findings, suppression, and the lint driver.

A rule is a small object with ``rule_id``/``name`` metadata and a
``check(tree, model)`` generator; the framework owns everything around it
(parsing, the shared :class:`~rayfed_tpu.lint.model.DriverModel`,
``# fedlint: disable`` filtering, path walking) so later PRs add a rule
by dropping one module into ``rayfed_tpu/lint/rules/``.
"""

from __future__ import annotations

import ast
import dataclasses
import os
import re
from typing import Dict, Iterable, Iterator, List, Optional, Sequence, Set, Tuple

from rayfed_tpu.lint.model import DriverModel
from rayfed_tpu.lint.project import ParsedModule, ProjectModel

#: Directories never descended into when a directory is linted.
SKIP_DIRS = {
    ".git", "__pycache__", "build", ".jax_cache", ".jax_test_cache",
    ".pytest_cache", ".venv", "venv", "node_modules", ".eggs", "dist",
}

_SUPPRESS_RE = re.compile(
    r"#\s*fedlint:\s*disable(?P<file>-file)?\s*(?:=\s*(?P<rules>[\w\-, ]+))?"
)


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule violation at a source location."""

    path: str
    line: int
    col: int
    rule_id: str
    rule_name: str
    message: str

    def as_dict(self) -> Dict[str, object]:
        return dataclasses.asdict(self)

    def render(self) -> str:
        return (
            f"{self.path}:{self.line}:{self.col}: "
            f"{self.rule_id} [{self.rule_name}] {self.message}"
        )


@dataclasses.dataclass(frozen=True)
class LintError:
    """A file fedlint could not analyze (unreadable / syntax error)."""

    path: str
    line: int
    message: str

    def as_dict(self) -> Dict[str, object]:
        return dataclasses.asdict(self)

    def render(self) -> str:
        return f"{self.path}:{self.line}: error: {self.message}"


class Rule:
    """Base class for fedlint rules.

    Subclasses set ``rule_id`` (stable ``FEDnnn`` code), ``name`` (the
    kebab-case slug accepted by ``# fedlint: disable=``) and ``summary``,
    and implement :meth:`check` yielding ``(node, message)`` pairs; the
    framework turns them into :class:`Finding`\\ s.
    """

    rule_id: str = ""
    name: str = ""
    summary: str = ""

    def check(
        self, tree: ast.Module, model: DriverModel
    ) -> Iterator[Tuple[ast.AST, str]]:
        raise NotImplementedError

    def findings(
        self, path: str, tree: ast.Module, model: DriverModel
    ) -> Iterator[Finding]:
        for node, message in self.check(tree, model):
            yield Finding(
                path=path,
                line=getattr(node, "lineno", 1),
                col=getattr(node, "col_offset", 0) + 1,
                rule_id=self.rule_id,
                rule_name=self.name,
                message=message,
            )


class ProjectRule(Rule):
    """A rule that sees the whole lint target at once.

    Per-file rules get one ``(tree, model)``; project rules get the
    :class:`~rayfed_tpu.lint.project.ProjectModel` built over every file
    in the run, and yield ``(path, node, message)`` so a finding can
    land in any module the analysis crossed. Single-file entry points
    (``lint_source``/``lint_file``) still run project rules — over a
    one-module project — so the fixture corpus exercises them through
    the same API as everything else.
    """

    def check_project(
        self, project: ProjectModel
    ) -> Iterator[Tuple[str, ast.AST, str]]:
        raise NotImplementedError

    def check(
        self, tree: ast.Module, model: DriverModel
    ) -> Iterator[Tuple[ast.AST, str]]:
        return iter(())

    def project_findings(self, project: ProjectModel) -> Iterator[Finding]:
        for path, node, message in self.check_project(project):
            yield Finding(
                path=path,
                line=getattr(node, "lineno", 1),
                col=getattr(node, "col_offset", 0) + 1,
                rule_id=self.rule_id,
                rule_name=self.name,
                message=message,
            )


class _Suppressions:
    """Per-line and per-file ``# fedlint: disable`` directives.

    ``# fedlint: disable=<rule>[,<rule>]`` on a finding's line silences
    those rules there (rule names and FED codes both work; bare
    ``disable`` silences everything on the line). The ``disable-file``
    variant applies to the whole file from any line.
    """

    def __init__(self, source: str):
        self.file_wide: Set[str] = set()
        self.by_line: Dict[int, Set[str]] = {}
        for lineno, text in enumerate(source.splitlines(), start=1):
            if "fedlint" not in text:
                continue
            m = _SUPPRESS_RE.search(text)
            if m is None:
                continue
            rules = m.group("rules")
            tokens = (
                {"*"}
                if rules is None
                else {t.strip().lower() for t in rules.split(",") if t.strip()}
            )
            if m.group("file"):
                self.file_wide |= tokens
            else:
                self.by_line.setdefault(lineno, set()).update(tokens)

    def suppressed(self, finding: Finding) -> bool:
        keys = {"*", finding.rule_id.lower(), finding.rule_name.lower()}
        if keys & self.file_wide:
            return True
        return bool(keys & self.by_line.get(finding.line, set()))


def _resolve_rules(
    select: Optional[Iterable[str]] = None,
    disable: Optional[Iterable[str]] = None,
) -> List[Rule]:
    from rayfed_tpu.lint.rules import ALL_RULES

    def keyset(tokens: Optional[Iterable[str]]) -> Optional[Set[str]]:
        if tokens is None:
            return None
        return {t.strip().lower() for t in tokens if t.strip()}

    selected, disabled = keyset(select), keyset(disable) or set()
    out = []
    for rule in ALL_RULES:
        keys = {rule.rule_id.lower(), rule.name.lower()}
        if selected is not None and not (keys & selected):
            continue
        if keys & disabled:
            continue
        out.append(rule)
    return out


def _parse_unit(source: str, path: str) -> Tuple[Optional[ParsedModule], Optional[LintError]]:
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as e:
        return None, LintError(
            path=path, line=e.lineno or 1, message=f"syntax error: {e.msg}"
        )
    return (
        ParsedModule(
            path=path,
            source=source,
            tree=tree,
            model=DriverModel.build(tree),
            suppressions=_Suppressions(source),
        ),
        None,
    )


def _run_rules(
    units: Sequence[ParsedModule], rules: Sequence[Rule]
) -> List[Finding]:
    """Per-file rules on each unit, project rules on the whole set, with
    every finding filtered through its own file's suppressions."""
    by_path = {unit.path: unit for unit in units}
    file_rules = [r for r in rules if not isinstance(r, ProjectRule)]
    project_rules = [r for r in rules if isinstance(r, ProjectRule)]
    findings: List[Finding] = []
    for unit in units:
        findings.extend(
            f
            for rule in file_rules
            for f in rule.findings(unit.path, unit.tree, unit.model)
            if not unit.suppressions.suppressed(f)
        )
    if project_rules:
        project = ProjectModel.build(list(units))
        for rule in project_rules:
            for f in rule.project_findings(project):
                owner = by_path.get(f.path)
                if owner is None or not owner.suppressions.suppressed(f):
                    findings.append(f)
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule_id))
    return findings


def lint_source(
    source: str,
    path: str = "<string>",
    rules: Optional[Sequence[Rule]] = None,
) -> Tuple[List[Finding], List[LintError]]:
    """Lint one driver program given as source text (project rules run
    over the single-module project)."""
    if rules is None:
        rules = _resolve_rules()
    unit, error = _parse_unit(source, path)
    if unit is None:
        return [], [error]
    return _run_rules([unit], rules), []


def lint_file(
    path: str, rules: Optional[Sequence[Rule]] = None
) -> Tuple[List[Finding], List[LintError]]:
    try:
        with open(path, encoding="utf-8") as f:
            source = f.read()
    except OSError as e:
        return [], [LintError(path=path, line=1, message=str(e))]
    return lint_source(source, path=path, rules=rules)


def iter_python_files(paths: Iterable[str]) -> Iterator[str]:
    """Expand files/directories into the .py files to lint (sorted,
    deduplicated; directories are walked recursively)."""
    seen: Set[str] = set()
    for path in paths:
        if os.path.isdir(path):
            for dirpath, dirnames, filenames in os.walk(path):
                dirnames[:] = sorted(d for d in dirnames if d not in SKIP_DIRS)
                for name in sorted(filenames):
                    if name.endswith(".py"):
                        full = os.path.join(dirpath, name)
                        if full not in seen:
                            seen.add(full)
                            yield full
        else:
            if path not in seen:
                seen.add(path)
                yield path


@dataclasses.dataclass
class LintResult:
    files: List[str]
    findings: List[Finding]
    errors: List[LintError]

    @property
    def exit_code(self) -> int:
        """0 clean, 1 findings, 2 analysis errors (errors dominate)."""
        if self.errors:
            return 2
        return 1 if self.findings else 0


def parse_units(
    paths: Iterable[str],
) -> Tuple[List[str], List[ParsedModule], List[LintError]]:
    """Parse every .py file under ``paths`` into project units (shared by
    ``lint_paths`` and the CLI's singleton-inventory writer)."""
    files: List[str] = []
    units: List[ParsedModule] = []
    errors: List[LintError] = []
    for path in iter_python_files(paths):
        files.append(path)
        try:
            with open(path, encoding="utf-8") as f:
                source = f.read()
        except OSError as e:
            errors.append(LintError(path=path, line=1, message=str(e)))
            continue
        unit, error = _parse_unit(source, path)
        if unit is None:
            errors.append(error)
        else:
            units.append(unit)
    return files, units, errors


def lint_paths(
    paths: Iterable[str],
    select: Optional[Iterable[str]] = None,
    disable: Optional[Iterable[str]] = None,
) -> LintResult:
    """Lint every .py file under ``paths``; the CLI's engine. All files
    are parsed up front so project rules analyze the whole target at
    once instead of one file at a time."""
    rules = _resolve_rules(select=select, disable=disable)
    files, units, errors = parse_units(paths)
    return LintResult(
        files=files, findings=_run_rules(units, rules), errors=errors
    )
