# Copyright 2026 The rayfed-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""The driver-program model shared by every fedlint rule.

One pass over the AST recovers how the source spells the
``fed.init / @fed.remote / .party() / fed.get`` programming model
(``rayfed_tpu/api.py``): which names alias the ``rayfed_tpu`` module,
which locals are ``@fed.remote`` tasks/actors, which party this driver
statically pins itself to (if any), and how to recognize the DAG-building
call shapes — ``f.party("alice").remote(...)``,
``actor.method.remote(...)``, ``fed.get(...)``, ``fed_aggregate(...)``,
``barriers.send/recv(...)``. Rules query the model instead of
re-implementing import resolution.

Everything here is intentionally conservative: when the model cannot
prove a fact statically (the party name comes from ``sys.argv``, an
owner is reassigned in a loop with different parties, ...), it answers
"unknown" and rules stay silent rather than guess.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, Iterator, List, Optional, Set, Tuple

#: Canonical names for the API surface fedlint understands. The resolver
#: maps whatever the driver imported (``import rayfed_tpu as fed``,
#: ``from rayfed_tpu.federated import fed_aggregate as agg``, ...) onto
#: these keys.
FED_GET = "fed.get"
FED_INIT = "fed.init"
FED_REMOTE = "fed.remote"
FED_AGGREGATE = "fed_aggregate"
FED_AVG_TRAINER = "FedAvgTrainer"
MAKE_FED_TRAIN_STEP = "make_fed_train_step"
BARRIERS_SEND = "barriers.send"
BARRIERS_RECV = "barriers.recv"
PING_SEQ_ID = "PING_SEQ_ID"

_TAIL_TO_CANONICAL = {
    ("get",): FED_GET,
    ("api", "get"): FED_GET,
    ("init",): FED_INIT,
    ("api", "init"): FED_INIT,
    ("remote",): FED_REMOTE,
    ("api", "remote"): FED_REMOTE,
    ("send",): BARRIERS_SEND,
    ("recv",): BARRIERS_RECV,
    ("barriers", "send"): BARRIERS_SEND,
    ("barriers", "recv"): BARRIERS_RECV,
    ("proxy", "barriers", "send"): BARRIERS_SEND,
    ("proxy", "barriers", "recv"): BARRIERS_RECV,
    ("fed_aggregate",): FED_AGGREGATE,
    ("federated", "fed_aggregate"): FED_AGGREGATE,
    ("FedAvgTrainer",): FED_AVG_TRAINER,
    ("federated", "FedAvgTrainer"): FED_AVG_TRAINER,
    ("make_fed_train_step",): MAKE_FED_TRAIN_STEP,
    ("train", "make_fed_train_step"): MAKE_FED_TRAIN_STEP,
    ("parallel", "train", "make_fed_train_step"): MAKE_FED_TRAIN_STEP,
    ("PING_SEQ_ID",): PING_SEQ_ID,
    ("constants", "PING_SEQ_ID"): PING_SEQ_ID,
    ("_private", "constants", "PING_SEQ_ID"): PING_SEQ_ID,
}


@dataclasses.dataclass
class RemoteInvocation:
    """A parsed ``....remote(...)`` call shape."""

    node: ast.Call
    #: party name when the chain carries ``.party("<literal>")``.
    pinned_party: Optional[str] = None
    #: True when a ``.party(...)`` pin is present (literal or not).
    has_party_pin: bool = False
    #: the base expression the chain hangs off (task name, actor var, ...).
    base: Optional[ast.expr] = None
    #: ``base``'s identifier when it is a plain name.
    base_name: Optional[str] = None
    #: attribute hop between base and ``.remote`` — an actor method call.
    method: Optional[str] = None


@dataclasses.dataclass
class Scope:
    """A lexical scope (module or one function) with its OWN statements —
    nested function/class bodies belong to their own scopes — plus the
    full subtree for load lookups (closures count as consumption)."""

    node: ast.AST
    qualname: str
    statements: List[ast.stmt]


class DriverModel:
    def __init__(self) -> None:
        #: local names aliasing the ``rayfed_tpu`` package itself.
        self.fed_aliases: Set[str] = set()
        #: local name -> dotted path rooted at ``rayfed_tpu`` for every
        #: ``import``/``from-import`` of engine modules and symbols.
        self.import_paths: Dict[str, Tuple[str, ...]] = {}
        #: names decorated ``@fed.remote`` (plain functions -> tasks).
        self.remote_functions: Set[str] = set()
        #: names decorated ``@fed.remote`` (classes -> actor templates).
        self.remote_classes: Set[str] = set()
        #: this driver's own party when ``fed.init(party="<literal>")``.
        self.current_party: Optional[str] = None
        #: names holding the dynamic party identity (``party=<name>``).
        self.current_party_vars: Set[str] = set()
        #: every fed.init call seen (diagnostics / future rules).
        self.init_calls: List[ast.Call] = []

    # ------------------------------------------------------------------
    # construction
    # ------------------------------------------------------------------

    @classmethod
    def build(cls, tree: ast.Module) -> "DriverModel":
        model = cls()
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                model._take_import(node)
            elif isinstance(node, ast.ImportFrom):
                model._take_import_from(node)
        # Decorators and init calls need import resolution complete first.
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
                model._take_decorated(node)
            elif isinstance(node, ast.Call):
                if model.canonical_call(node) == FED_INIT:
                    model._take_init(node)
        return model

    def _take_import(self, node: ast.Import) -> None:
        for alias in node.names:
            parts = alias.name.split(".")
            if parts[0] != "rayfed_tpu":
                continue
            local = alias.asname or parts[0]
            if alias.asname is None:
                # ``import rayfed_tpu.proxy.barriers`` binds the ROOT name.
                self.fed_aliases.add(parts[0])
            elif len(parts) == 1:
                self.fed_aliases.add(local)
            else:
                self.import_paths[local] = tuple(parts[1:])

    def _take_import_from(self, node: ast.ImportFrom) -> None:
        if node.level or not node.module:
            return
        parts = node.module.split(".")
        if parts[0] != "rayfed_tpu":
            return
        for alias in node.names:
            local = alias.asname or alias.name
            self.import_paths[local] = tuple(parts[1:]) + (alias.name,)

    def _take_decorated(self, node: ast.AST) -> None:
        for deco in getattr(node, "decorator_list", []):
            target = deco.func if isinstance(deco, ast.Call) else deco
            if self.canonical(target) == FED_REMOTE:
                if isinstance(node, ast.ClassDef):
                    self.remote_classes.add(node.name)
                else:
                    self.remote_functions.add(node.name)

    def _take_init(self, node: ast.Call) -> None:
        self.init_calls.append(node)
        for kw in node.keywords:
            if kw.arg != "party":
                continue
            if isinstance(kw.value, ast.Constant) and isinstance(kw.value.value, str):
                self.current_party = kw.value.value
            elif isinstance(kw.value, ast.Name):
                self.current_party_vars.add(kw.value.id)

    # ------------------------------------------------------------------
    # name resolution
    # ------------------------------------------------------------------

    def resolved_path(self, expr: ast.expr) -> Optional[Tuple[str, ...]]:
        """Dotted path (relative to ``rayfed_tpu``) an expression names,
        or None when it does not resolve into the engine's namespace."""
        if isinstance(expr, ast.Name):
            if expr.id in self.fed_aliases:
                return ()
            return self.import_paths.get(expr.id)
        if isinstance(expr, ast.Attribute):
            base = self.resolved_path(expr.value)
            if base is None:
                return None
            return base + (expr.attr,)
        return None

    def canonical(self, expr: ast.expr) -> Optional[str]:
        """Canonical API name (``fed.get``, ``fed_aggregate``, ...) for an
        expression, resolved through whatever import spelling the driver
        used."""
        path = self.resolved_path(expr)
        if path is None:
            return None
        return _TAIL_TO_CANONICAL.get(path)

    def canonical_call(self, call: ast.Call) -> Optional[str]:
        return self.canonical(call.func)

    # ------------------------------------------------------------------
    # call-shape recognizers
    # ------------------------------------------------------------------

    def remote_invocation(self, call: ast.Call) -> Optional[RemoteInvocation]:
        """Parse ``<chain>.remote(...)`` DAG-building calls.

        Recognized chains (``.options(...)`` hops allowed anywhere):
        ``task.party("p").remote(...)``, ``Actor.party("p").remote(...)``,
        ``handle.method.remote(...)``, ``handles[k].method.remote(...)``.
        """
        if not isinstance(call.func, ast.Attribute) or call.func.attr != "remote":
            return None
        inv = RemoteInvocation(node=call)
        cur: ast.expr = call.func.value
        hops = 0
        while hops < 32:
            hops += 1
            if isinstance(cur, ast.Call) and isinstance(cur.func, ast.Attribute):
                if cur.func.attr == "party":
                    inv.has_party_pin = True
                    if (
                        cur.args
                        and isinstance(cur.args[0], ast.Constant)
                        and isinstance(cur.args[0].value, str)
                    ):
                        inv.pinned_party = cur.args[0].value
                    cur = cur.func.value
                elif cur.func.attr == "options":
                    cur = cur.func.value
                else:
                    return None  # some other fluent API's .remote
            elif isinstance(cur, ast.Attribute) and inv.method is None and not (
                inv.has_party_pin
            ):
                inv.method = cur.attr
                cur = cur.value
            else:
                break
        inv.base = cur
        if isinstance(cur, ast.Name):
            inv.base_name = cur.id
        # An accepted invocation needs SOME fed shape: a .party pin, an
        # actor-method hop, or a base that is a known @fed.remote name.
        if (
            inv.has_party_pin
            or inv.method is not None
            or (inv.base_name in self.remote_functions | self.remote_classes)
        ):
            return inv
        return None

    def is_dag_call(self, call: ast.Call) -> bool:
        """True for calls that advance the fed DAG / seq-id counter:
        ``.remote(...)`` invocations, ``fed.get``, ``fed_aggregate``,
        ``FedAvgTrainer(...).run`` and direct barrier sends/recvs."""
        canon = self.canonical_call(call)
        if canon in (FED_GET, FED_AGGREGATE, BARRIERS_SEND, BARRIERS_RECV):
            return True
        if self.remote_invocation(call) is not None:
            return True
        # <trainer>.run(...) — FedAvgTrainer rounds are remote calls.
        return (
            isinstance(call.func, ast.Attribute)
            and call.func.attr == "run"
            and isinstance(call.func.value, ast.Call)
            and self.canonical_call(call.func.value) == FED_AVG_TRAINER
        )

    def contains_dag_call(self, node: ast.AST) -> Optional[ast.Call]:
        """First DAG-advancing call in a subtree, if any."""
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call) and self.is_dag_call(sub):
                return sub
        return None


# ----------------------------------------------------------------------
# scopes
# ----------------------------------------------------------------------

def _own_statements(node: ast.AST) -> List[ast.stmt]:
    """Statements belonging to ``node``'s scope: its body recursively,
    stopping at nested function/class definitions (their bodies are
    separate scopes)."""
    out: List[ast.stmt] = []

    def walk_body(body: List[ast.stmt]) -> None:
        for stmt in body:
            out.append(stmt)
            if isinstance(
                stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
            ):
                continue
            for field in ("body", "orelse", "finalbody"):
                walk_body(getattr(stmt, field, []) or [])
            for handler in getattr(stmt, "handlers", []) or []:
                walk_body(handler.body)

    walk_body(getattr(node, "body", []))
    return out


def iter_scopes(tree: ast.Module) -> Iterator[Scope]:
    """Yield the module scope and every function scope (classes only
    contribute their methods as scopes, matching Python scoping)."""
    yield Scope(node=tree, qualname="<module>", statements=_own_statements(tree))

    def visit(node: ast.AST, prefix: str) -> Iterator[Scope]:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = f"{prefix}{child.name}"
                yield Scope(
                    node=child, qualname=qual, statements=_own_statements(child)
                )
                yield from visit(child, f"{qual}.")
            elif isinstance(child, ast.ClassDef):
                yield from visit(child, f"{prefix}{child.name}.")
            else:
                yield from visit(child, prefix)

    yield from visit(tree, "")


def loads_of(scope_node: ast.AST, name: str) -> List[ast.Name]:
    """Every Load of ``name`` anywhere under the scope (nested scopes
    included — a closure read counts as consumption)."""
    return [
        n
        for n in ast.walk(scope_node)
        if isinstance(n, ast.Name) and n.id == name and isinstance(n.ctx, ast.Load)
    ]


def dotted_name(expr: ast.expr) -> Optional[str]:
    """``self.params`` -> "self.params"; plain names pass through; other
    shapes (subscripts, calls) return None."""
    if isinstance(expr, ast.Name):
        return expr.id
    if isinstance(expr, ast.Attribute):
        base = dotted_name(expr.value)
        return None if base is None else f"{base}.{expr.attr}"
    return None
