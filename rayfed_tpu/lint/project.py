# Copyright 2026 The rayfed-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Whole-program analysis layer: the fedlint v2 project model.

PR 1's rules see one file at a time through a :class:`DriverModel`. The
cross-module rules (FED007 deadlock, FED010 blocking-in-reactor, FED011
lock-order) need to follow calls across files, so this module parses the
whole lint target once into a :class:`ProjectModel`:

* every file becomes a :class:`ParsedModule` carrying its tree, its
  per-file :class:`DriverModel`, a dotted module name recovered from the
  ``__init__.py`` chain on disk, and a *generic* import map (the
  DriverModel only resolves ``rayfed_tpu`` imports; project rules must
  resolve ``from .reactor import _pool`` too);
* :meth:`ProjectModel.resolve_function` answers "which FunctionDef does
  this call target", one static hop at a time, conservatively returning
  ``None`` for anything dynamic.

The singleton inventory consumed by the multi-tenant refactor
(``tools/singleton_inventory.json``) is also computed here —
:func:`collect_singletons` is shared by rule FED008 and the CLI's
``--singleton-inventory`` flag so suppressing a finding never hides the
site from the worklist.
"""

from __future__ import annotations

import ast
import dataclasses
import os
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

from rayfed_tpu.lint.model import DriverModel

#: Constructors whose module-level results are immutable-in-practice and
#: never inventory entries (compiled patterns, loggers, frozen types).
_IMMUTABLE_CTORS = {
    "re.compile", "struct.Struct", "logging.getLogger", "frozenset",
    "tuple", "collections.namedtuple", "namedtuple",
    "types.MappingProxyType", "MappingProxyType", "os.environ.get",
}

#: threading constructors that make a module-level synchronization object.
_LOCK_CTORS = {
    "threading.Lock", "threading.RLock", "threading.Condition",
    "threading.Event", "threading.Semaphore", "threading.BoundedSemaphore",
    "Lock", "RLock", "Condition", "Event", "Semaphore", "BoundedSemaphore",
}

#: Container constructors that make a module-level mutable value.
_CONTAINER_CTORS = {
    "dict", "list", "set", "bytearray",
    "collections.OrderedDict", "OrderedDict",
    "collections.defaultdict", "defaultdict",
    "collections.deque", "deque",
    "collections.Counter", "Counter",
    "weakref.WeakSet", "WeakSet",
    "weakref.WeakValueDictionary", "WeakValueDictionary",
    "weakref.WeakKeyDictionary", "WeakKeyDictionary",
}

#: Method calls that mutate their receiver in place.
_MUTATING_METHODS = {
    "add", "append", "appendleft", "clear", "discard", "extend",
    "extendleft", "insert", "move_to_end", "pop", "popitem", "remove",
    "setdefault", "update",
}


@dataclasses.dataclass
class ParsedModule:
    """One analyzed source file plus everything rules ask about it."""

    path: str
    source: str
    tree: ast.Module
    model: DriverModel
    #: the file's ``# fedlint: disable`` table (core._Suppressions; typed
    #: loosely to keep this module free of a core import cycle).
    suppressions: object
    module_name: str = ""
    #: module-level function defs by name.
    functions: Dict[str, ast.FunctionDef] = dataclasses.field(
        default_factory=dict
    )
    #: module-level class defs by name.
    classes: Dict[str, ast.ClassDef] = dataclasses.field(default_factory=dict)
    #: generic import map: local name -> absolute dotted target. ``import
    #: a.b`` binds ``a -> a``; ``import a.b as c`` binds ``c -> a.b``;
    #: ``from a.b import f as g`` binds ``g -> a.b.f`` (relative imports
    #: resolved against :attr:`module_name`).
    imports: Dict[str, str] = dataclasses.field(default_factory=dict)

    def method(self, cls_name: str, name: str) -> Optional[ast.FunctionDef]:
        cls = self.classes.get(cls_name)
        if cls is None:
            return None
        for stmt in cls.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if stmt.name == name:
                    return stmt
        return None


def module_name_for(path: str) -> str:
    """Dotted module name recovered by walking ``__init__.py`` parents.

    ``.../rayfed_tpu/proxy/barriers.py`` -> ``rayfed_tpu.proxy.barriers``;
    files outside any package resolve to their bare stem.
    """
    path = os.path.abspath(path)
    stem = os.path.splitext(os.path.basename(path))[0]
    parts = [] if stem == "__init__" else [stem]
    parent = os.path.dirname(path)
    while os.path.isfile(os.path.join(parent, "__init__.py")):
        parts.insert(0, os.path.basename(parent))
        nxt = os.path.dirname(parent)
        if nxt == parent:
            break
        parent = nxt
    return ".".join(parts) or stem


def _resolve_relative(module_name: str, level: int, target: Optional[str]) -> str:
    """Absolute dotted base for a ``from ...x import y`` statement found
    inside ``module_name``."""
    parts = module_name.split(".")
    # level 1 = the containing package; the module's own last component
    # is dropped first (for __init__.py modules the name IS the package,
    # but one spurious-level error only widens to "no resolution").
    base = parts[: len(parts) - level] if level <= len(parts) else []
    if target:
        base = base + target.split(".")
    return ".".join(base)


class ProjectModel:
    """Every :class:`ParsedModule` in the lint target, cross-indexed."""

    def __init__(self, modules: Sequence[ParsedModule]):
        self.modules: List[ParsedModule] = list(modules)
        self.by_path: Dict[str, ParsedModule] = {
            m.path: m for m in self.modules
        }
        self.by_name: Dict[str, ParsedModule] = {
            m.module_name: m for m in self.modules if m.module_name
        }

    @classmethod
    def build(cls, modules: Sequence[ParsedModule]) -> "ProjectModel":
        for unit in modules:
            if not unit.module_name:
                unit.module_name = module_name_for(unit.path)
            cls._index(unit)
        return cls(modules)

    @staticmethod
    def _index(unit: ParsedModule) -> None:
        for stmt in unit.tree.body:
            if isinstance(stmt, ast.FunctionDef):
                unit.functions[stmt.name] = stmt
            elif isinstance(stmt, ast.ClassDef):
                unit.classes[stmt.name] = stmt
        for node in ast.walk(unit.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    if alias.asname:
                        unit.imports[alias.asname] = alias.name
                    else:
                        root = alias.name.split(".")[0]
                        unit.imports.setdefault(root, root)
            elif isinstance(node, ast.ImportFrom):
                if node.level:
                    base = _resolve_relative(
                        unit.module_name, node.level, node.module
                    )
                elif node.module:
                    base = node.module
                else:
                    continue
                for alias in node.names:
                    local = alias.asname or alias.name
                    unit.imports[local] = (
                        f"{base}.{alias.name}" if base else alias.name
                    )

    # ------------------------------------------------------------------
    # resolution
    # ------------------------------------------------------------------

    def resolve_module(self, dotted: str) -> Optional[ParsedModule]:
        """A project module by absolute dotted name, accepting the
        package itself for ``pkg/__init__.py``."""
        return self.by_name.get(dotted)

    def resolve_function(
        self, unit: ParsedModule, dotted: str
    ) -> Optional[Tuple[ParsedModule, ast.FunctionDef]]:
        """The FunctionDef a dotted callable name targets, when it stays
        inside the project. ``f`` -> local def or from-import;
        ``mod.f``/``pkg.mod.f`` -> module attribute. ``None`` for
        anything dynamic, builtin, or outside the lint target."""
        head, _, rest = dotted.partition(".")
        if not rest:
            fn = unit.functions.get(head)
            if fn is not None:
                return unit, fn
            target = unit.imports.get(head)
            if target is None:
                return None
            mod_name, _, sym = target.rpartition(".")
            other = self.by_name.get(mod_name)
            if other is not None and sym in other.functions:
                return other, other.functions[sym]
            return None
        # Dotted: the head must name an imported module (possibly itself
        # dotted, e.g. ``proxy.barriers.send`` after ``import
        # rayfed_tpu.proxy``); try longest prefix first.
        parts = dotted.split(".")
        for cut in range(len(parts) - 1, 0, -1):
            prefix = ".".join(parts[:cut])
            target = unit.imports.get(prefix)
            if target is None:
                continue
            mod = self.by_name.get(".".join([target] + parts[cut:-1]))
            if mod is not None and parts[-1] in mod.functions:
                return mod, mod.functions[parts[-1]]
            return None
        return None


# ----------------------------------------------------------------------
# FED008 singleton inventory
# ----------------------------------------------------------------------

@dataclasses.dataclass
class Singleton:
    """One module-level mutable object (a multi-tenant refactor worklist
    entry)."""

    module: str
    path: str
    name: str
    line: int
    #: ``lock`` | ``container`` | ``cache`` (``global``-rebound name).
    kind: str
    value: str
    #: lines of in-module mutation / rebinding sites.
    mutators: List[int]
    node: ast.AST = dataclasses.field(compare=False, repr=False)

    def as_dict(self) -> Dict[str, object]:
        return {
            "module": self.module,
            "path": self.path,
            "name": self.name,
            "line": self.line,
            "kind": self.kind,
            "value": self.value,
            "mutators": self.mutators,
        }


def _ctor_name(call: ast.Call, unit: ParsedModule) -> str:
    from rayfed_tpu.lint.model import dotted_name

    name = dotted_name(call.func) or ""
    head, _, rest = name.partition(".")
    target = unit.imports.get(head)
    if target is not None and target != head:
        name = f"{target}.{rest}" if rest else target
    return name


def _classify_value(value: ast.expr, unit: ParsedModule) -> Optional[str]:
    """``lock``/``container`` for values FED008 cares about, else None."""
    if isinstance(value, (ast.Dict, ast.List, ast.Set, ast.DictComp,
                          ast.ListComp, ast.SetComp)):
        return "container"
    if isinstance(value, ast.Call):
        name = _ctor_name(value, unit)
        if name in _IMMUTABLE_CTORS:
            return None
        if name in _LOCK_CTORS:
            return "lock"
        if name in _CONTAINER_CTORS:
            return "container"
    return None


def _module_assigns(tree: ast.Module) -> Iterator[Tuple[str, ast.stmt, ast.expr]]:
    """(name, stmt, value) for simple module-scope assignments, skipping
    ``if TYPE_CHECKING:`` blocks and dunders."""

    def from_body(body: List[ast.stmt]) -> Iterator[Tuple[str, ast.stmt, ast.expr]]:
        for stmt in body:
            if isinstance(stmt, ast.Assign):
                for target in stmt.targets:
                    if isinstance(target, ast.Name):
                        yield target.id, stmt, stmt.value
            elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
                if isinstance(stmt.target, ast.Name):
                    yield stmt.target.id, stmt, stmt.value
            elif isinstance(stmt, (ast.If, ast.Try)):
                test = getattr(stmt, "test", None)
                label = test and (
                    getattr(test, "id", None) or getattr(test, "attr", None)
                )
                if label == "TYPE_CHECKING":
                    continue
                for field in ("body", "orelse", "finalbody"):
                    yield from from_body(getattr(stmt, field, []) or [])
                for handler in getattr(stmt, "handlers", []) or []:
                    yield from from_body(handler.body)

    for name, stmt, value in from_body(tree.body):
        if not (name.startswith("__") and name.endswith("__")):
            yield name, stmt, value


def _mutation_lines(tree: ast.Module, name: str) -> List[int]:
    """Lines where module code mutates or rebinds the module-level
    ``name`` in place (subscript stores, del, augassign, mutating method
    calls, and assignments inside functions that declare ``global``)."""
    lines: List[int] = []
    global_fns: List[ast.AST] = [
        node
        for node in ast.walk(tree)
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
        and any(
            isinstance(s, ast.Global) and name in s.names
            for s in ast.walk(node)
        )
    ]
    for node in ast.walk(tree):
        if isinstance(node, (ast.Assign, ast.AugAssign)):
            targets = (
                node.targets if isinstance(node, ast.Assign) else [node.target]
            )
            for target in targets:
                if (
                    isinstance(target, ast.Subscript)
                    and isinstance(target.value, ast.Name)
                    and target.value.id == name
                ):
                    lines.append(node.lineno)
        if isinstance(node, ast.Delete):
            for target in node.targets:
                if (
                    isinstance(target, ast.Subscript)
                    and isinstance(target.value, ast.Name)
                    and target.value.id == name
                ):
                    lines.append(node.lineno)
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in _MUTATING_METHODS
            and isinstance(node.func.value, ast.Name)
            and node.func.value.id == name
        ):
            lines.append(node.lineno)
    for fn in global_fns:
        for node in ast.walk(fn):
            if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                targets = (
                    node.targets
                    if isinstance(node, ast.Assign)
                    else [node.target]
                )
                for target in targets:
                    if isinstance(target, ast.Name) and target.id == name:
                        lines.append(node.lineno)
    return sorted(set(lines))


def collect_singletons(unit: ParsedModule) -> List[Singleton]:
    """FED008's detector, shared with the CLI inventory writer.

    A module-level name is a singleton when it is (a) a threading
    synchronization object (always: a lock only exists to serialize
    shared state), (b) a mutable container the module itself mutates, or
    (c) a ``global``-rebound cache. Pure constants and aliased imports
    never match.
    """
    if not unit.module_name:
        unit.module_name = module_name_for(unit.path)
    globally_rebound = {
        n
        for node in ast.walk(unit.tree)
        if isinstance(node, ast.Global)
        for n in node.names
    }
    out: List[Singleton] = []
    seen: set = set()
    for name, stmt, value in _module_assigns(unit.tree):
        if name in seen:
            continue
        kind = _classify_value(value, unit)
        mutators = _mutation_lines(unit.tree, name)
        if kind == "container" and not (mutators or name in globally_rebound):
            continue  # a constant table nobody writes to
        if kind is None:
            if name not in globally_rebound:
                continue
            kind = "cache"
        seen.add(name)
        out.append(
            Singleton(
                module=unit.module_name,
                path=unit.path,
                name=name,
                line=stmt.lineno,
                kind=kind,
                value=ast.unparse(value)[:80],
                mutators=mutators,
                node=stmt,
            )
        )
    out.sort(key=lambda s: s.line)
    return out
