# Copyright 2026 The rayfed-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""fedlint output formats: human text, machine JSON, and SARIF 2.1.0.

The JSON shape is stable (CI consumes it):

    {"version": 1,
     "files": <int>,
     "findings": [{"path", "line", "col", "rule_id", "rule_name",
                   "message"}, ...],
     "errors": [{"path", "line", "message"}, ...]}

SARIF (``--format sarif``) targets the GitHub code-scanning upload
schema so the CI lint job annotates PR diffs in place instead of
dumping text into the job log.
"""

from __future__ import annotations

import json
from typing import IO

from rayfed_tpu.lint.core import LintResult


def report_text(result: LintResult, out: IO[str]) -> None:
    for error in result.errors:
        out.write(error.render() + "\n")
    for finding in result.findings:
        out.write(finding.render() + "\n")
    n_files = len(result.files)
    files = f"{n_files} file{'s' if n_files != 1 else ''}"
    if not result.findings and not result.errors:
        out.write(f"fedlint: {files} checked, no findings\n")
    else:
        parts = []
        if result.findings:
            n = len(result.findings)
            parts.append(f"{n} finding{'s' if n != 1 else ''}")
        if result.errors:
            n = len(result.errors)
            parts.append(f"{n} error{'s' if n != 1 else ''}")
        out.write(f"fedlint: {files} checked, {', '.join(parts)}\n")


def report_json(result: LintResult, out: IO[str]) -> None:
    payload = {
        "version": 1,
        "files": len(result.files),
        "findings": [f.as_dict() for f in result.findings],
        "errors": [e.as_dict() for e in result.errors],
    }
    json.dump(payload, out, indent=2, sort_keys=True)
    out.write("\n")


def report_sarif(result: LintResult, out: IO[str]) -> None:
    """SARIF 2.1.0 for GitHub code scanning (PR-diff annotations)."""
    from rayfed_tpu.lint.rules import ALL_RULES

    results = [
        {
            "ruleId": f.rule_id,
            "level": "warning",
            "message": {"text": f"[{f.rule_name}] {f.message}"},
            "locations": [
                {
                    "physicalLocation": {
                        "artifactLocation": {
                            "uri": f.path.replace("\\", "/"),
                        },
                        "region": {
                            "startLine": f.line,
                            "startColumn": f.col,
                        },
                    }
                }
            ],
        }
        for f in result.findings
    ]
    for e in result.errors:
        results.append(
            {
                "ruleId": "fedlint-error",
                "level": "error",
                "message": {"text": e.message},
                "locations": [
                    {
                        "physicalLocation": {
                            "artifactLocation": {
                                "uri": e.path.replace("\\", "/"),
                            },
                            "region": {"startLine": e.line},
                        }
                    }
                ],
            }
        )
    payload = {
        "$schema": (
            "https://raw.githubusercontent.com/oasis-tcs/sarif-spec/master/"
            "Schemata/sarif-schema-2.1.0.json"
        ),
        "version": "2.1.0",
        "runs": [
            {
                "tool": {
                    "driver": {
                        "name": "fedlint",
                        "informationUri": "docs/fedlint.md",
                        "rules": [
                            {
                                "id": rule.rule_id,
                                "name": rule.name,
                                "shortDescription": {"text": rule.summary},
                            }
                            for rule in ALL_RULES
                        ],
                    }
                },
                "results": results,
            }
        ],
    }
    json.dump(payload, out, indent=2, sort_keys=True)
    out.write("\n")
