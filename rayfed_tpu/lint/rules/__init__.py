# Copyright 2026 The rayfed-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""fedlint rule registry: one module per rule; later PRs extend the
tuple. Rule ids are stable (suppressions and anchors reference them)."""

from typing import Optional, Tuple

from rayfed_tpu.lint.core import Rule
from rayfed_tpu.lint.rules.blocking import BlockingCallInReactorRule
from rayfed_tpu.lint.rules.config_keys import UnvalidatedConfigKeyRule
from rayfed_tpu.lint.rules.dangling import DanglingFedObjectRule
from rayfed_tpu.lint.rules.deadlock import CrossPartyDeadlockRule
from rayfed_tpu.lint.rules.divergence import SeqDivergenceRule
from rayfed_tpu.lint.rules.donation import DonationAliasingRule
from rayfed_tpu.lint.rules.lock_order import LockOrderInconsistencyRule
from rayfed_tpu.lint.rules.perimeter import PerimeterRule
from rayfed_tpu.lint.rules.privacy import InsecureAggregateRule
from rayfed_tpu.lint.rules.reserved_seq import ReservedSeqIdRule
from rayfed_tpu.lint.rules.singleton import GlobalMutableSingletonRule

ALL_RULES: Tuple[Rule, ...] = (
    PerimeterRule(),
    SeqDivergenceRule(),
    DonationAliasingRule(),
    DanglingFedObjectRule(),
    ReservedSeqIdRule(),
    InsecureAggregateRule(),
    CrossPartyDeadlockRule(),
    GlobalMutableSingletonRule(),
    UnvalidatedConfigKeyRule(),
    BlockingCallInReactorRule(),
    LockOrderInconsistencyRule(),
)


def rule_by_id(key: str) -> Optional[Rule]:
    """Look a rule up by code (``FED003``) or name (``donation-aliasing``)."""
    key = key.strip().lower()
    for rule in ALL_RULES:
        if key in (rule.rule_id.lower(), rule.name.lower()):
            return rule
    return None
