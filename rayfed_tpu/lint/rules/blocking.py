# Copyright 2026 The rayfed-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""FED010 ``blocking-call-in-reactor``: no blocking on the loop thread.

One reactor thread services EVERY connection in its pool
(``proxy/tcp/reactor.py``): a ``time.sleep``, an untimed ``.result()``/
``.join()``, a blocking connect, or a ``fed.get`` anywhere on a path the
loop thread executes stalls all lanes at once — the exact pathology the
reactor exists to avoid. Reachability roots are (a) callbacks handed to
``run_soon``/``add_ticker``, (b) the handler-protocol methods
(``on_readable``/``on_flushed``/``on_error``/``on_acceptable``/
``pending_chunks``) of any class that also defines ``fileno``, and (c)
the caller-thread inline-send fast path (``_try_inline_send``), which
holds the submit gate other threads spin on. The walk follows static
calls across project modules (depth-limited); callables merely
*deferred* via a nested ``run_soon`` are not followed — re-deferral is
the correct idiom. Bounded, justified waits suppress per-site with
``# fedlint: disable=blocking-call-in-reactor``.
"""

from __future__ import annotations

import ast
from typing import Iterator, List, Optional, Set, Tuple

from rayfed_tpu.lint.core import ProjectRule
from rayfed_tpu.lint.model import FED_GET, dotted_name
from rayfed_tpu.lint.project import ParsedModule, ProjectModel

_DEFER_METHODS = {"run_soon", "add_ticker"}
_HANDLER_METHODS = {
    "on_readable", "on_flushed", "on_error", "on_acceptable",
    "pending_chunks",
}
_INLINE_SEND = "_try_inline_send"
_MAX_DEPTH = 8


def _resolved_dotted(call: ast.Call, unit: ParsedModule) -> str:
    """Dotted callee name with the leading alias resolved through the
    module's import map (``import time as t; t.sleep`` -> time.sleep)."""
    name = dotted_name(call.func) or ""
    head, _, rest = name.partition(".")
    target = unit.imports.get(head)
    if target is not None and target != head:
        return f"{target}.{rest}" if rest else target
    return name


def _blocking_reason(call: ast.Call, unit: ParsedModule) -> Optional[str]:
    if unit.model.canonical_call(call) == FED_GET:
        return "fed.get (blocks until the peer's bytes arrive)"
    name = _resolved_dotted(call, unit)
    if name == "time.sleep":
        return "time.sleep"
    if name == "socket.create_connection":
        return "socket.create_connection (blocking connect)"
    if (
        isinstance(call.func, ast.Attribute)
        and not call.args
        and not call.keywords
    ):
        if call.func.attr == "result":
            return ".result() with no timeout"
        if call.func.attr == "join":
            return ".join() with no timeout"
    return None


def _is_deferral(call: ast.Call) -> bool:
    return (
        isinstance(call.func, ast.Attribute)
        and call.func.attr in _DEFER_METHODS
    ) or (
        isinstance(call.func, ast.Name) and call.func.id in _DEFER_METHODS
    )


def _live_calls(fn: ast.AST) -> Iterator[ast.Call]:
    """Calls executed when ``fn`` runs: nested def/class/lambda bodies
    are their own call-time, and args of ``run_soon``/``add_ticker`` are
    deferred back onto the queue, so neither is descended into."""

    def visit(node: ast.AST) -> Iterator[ast.Call]:
        if isinstance(
            node,
            (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef, ast.Lambda),
        ):
            return
        if isinstance(node, ast.Call):
            yield node
            if _is_deferral(node):
                # visit the receiver chain, not the deferred callback.
                yield from visit(node.func.value) if isinstance(
                    node.func, ast.Attribute
                ) else iter(())
                return
        for child in ast.iter_child_nodes(node):
            yield from visit(child)

    body = getattr(fn, "body", [])
    for node in body if isinstance(body, list) else [body]:
        yield from visit(node)


class _Root:
    def __init__(self, unit: ParsedModule, fn: ast.AST, cls: Optional[str],
                 label: str):
        self.unit = unit
        self.fn = fn
        self.cls = cls
        self.label = label


class BlockingCallInReactorRule(ProjectRule):
    rule_id = "FED010"
    name = "blocking-call-in-reactor"
    summary = (
        "blocking call reachable from a reactor callback or the lane "
        "inline-send path stalls every connection on the loop thread"
    )

    def check_project(
        self, project: ProjectModel
    ) -> Iterator[Tuple[str, ast.AST, str]]:
        reported: Set[Tuple[str, int, int]] = set()
        for root in self._roots(project):
            yield from self._walk(project, root, reported)

    # ------------------------------------------------------------------
    # roots
    # ------------------------------------------------------------------

    def _roots(self, project: ProjectModel) -> Iterator[_Root]:
        for unit in project.modules:
            # (a) run_soon / add_ticker callback arguments.
            for node in ast.walk(unit.tree):
                if isinstance(node, ast.Call) and _is_deferral(node):
                    attr = (
                        node.func.attr
                        if isinstance(node.func, ast.Attribute)
                        else node.func.id
                    )
                    if node.args:
                        yield from self._callback_root(
                            project, unit, node, node.args[0], attr
                        )
            # (b) handler-protocol methods on fileno-bearing classes, and
            # (c) inline-send fast paths.
            for cls_name, cls in unit.classes.items():
                methods = {
                    s.name: s
                    for s in cls.body
                    if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef))
                }
                is_handler = "fileno" in methods
                for name, fn in methods.items():
                    if is_handler and name in _HANDLER_METHODS:
                        yield _Root(
                            unit, fn, cls_name,
                            f"reactor handler {cls_name}.{name}",
                        )
                    elif name == _INLINE_SEND:
                        yield _Root(
                            unit, fn, cls_name,
                            f"lane inline-send path {cls_name}.{name}",
                        )
            for name, fn in unit.functions.items():
                if name == _INLINE_SEND:
                    yield _Root(unit, fn, None, f"lane inline-send path {name}")

    def _callback_root(
        self,
        project: ProjectModel,
        unit: ParsedModule,
        call: ast.Call,
        arg: ast.expr,
        via: str,
    ) -> Iterator[_Root]:
        label_prefix = f"{via} callback"
        if isinstance(arg, ast.Lambda):
            yield _Root(unit, arg, self._enclosing_class(unit, call),
                        f"{label_prefix} <lambda>")
            return
        if (
            isinstance(arg, ast.Call)
            and _resolved_dotted(arg, unit) in ("functools.partial", "partial")
            and arg.args
        ):
            arg = arg.args[0]
        if isinstance(arg, ast.Name):
            resolved = project.resolve_function(unit, arg.id)
            if resolved is not None:
                yield _Root(resolved[0], resolved[1], None,
                            f"{label_prefix} {arg.id}")
        elif (
            isinstance(arg, ast.Attribute)
            and isinstance(arg.value, ast.Name)
            and arg.value.id == "self"
        ):
            cls = self._enclosing_class(unit, call)
            if cls is not None:
                fn = unit.method(cls, arg.attr)
                if fn is not None:
                    yield _Root(unit, fn, cls,
                                f"{label_prefix} {cls}.{arg.attr}")

    @staticmethod
    def _enclosing_class(unit: ParsedModule, node: ast.AST) -> Optional[str]:
        for cls_name, cls in unit.classes.items():
            for sub in ast.walk(cls):
                if sub is node:
                    return cls_name
        return None

    # ------------------------------------------------------------------
    # reachability
    # ------------------------------------------------------------------

    def _walk(
        self,
        project: ProjectModel,
        root: _Root,
        reported: Set[Tuple[str, int, int]],
    ) -> Iterator[Tuple[str, ast.AST, str]]:
        stack: List[Tuple[ParsedModule, ast.AST, Optional[str], Tuple[str, ...]]] = [
            (root.unit, root.fn, root.cls, ())
        ]
        visited: Set[Tuple[str, int]] = set()
        while stack:
            unit, fn, cls, chain = stack.pop()
            key = (unit.path, id(fn))
            if key in visited or len(chain) > _MAX_DEPTH:
                continue
            visited.add(key)
            for call in _live_calls(fn):
                reason = _blocking_reason(call, unit)
                if reason is not None:
                    site = (unit.path, call.lineno, call.col_offset)
                    if site in reported:
                        continue
                    reported.add(site)
                    via = (
                        f" via {' -> '.join(chain)}" if chain else ""
                    )
                    yield (
                        unit.path,
                        call,
                        f"blocking call ({reason}) reachable from "
                        f"{root.label}{via}: the loop thread services "
                        f"every connection — blocking here stalls all "
                        f"lanes; defer with run_soon or bound the wait",
                    )
                    continue
                if _is_deferral(call):
                    continue
                for nxt in self._call_targets(project, unit, cls, call):
                    nxt_unit, nxt_fn, nxt_cls, name = nxt
                    stack.append((nxt_unit, nxt_fn, nxt_cls, chain + (name,)))

    def _call_targets(
        self,
        project: ProjectModel,
        unit: ParsedModule,
        cls: Optional[str],
        call: ast.Call,
    ) -> Iterator[Tuple[ParsedModule, ast.AST, Optional[str], str]]:
        func = call.func
        if isinstance(func, ast.Name):
            resolved = project.resolve_function(unit, func.id)
            if resolved is not None:
                yield resolved[0], resolved[1], None, func.id
            return
        if not isinstance(func, ast.Attribute):
            return
        if isinstance(func.value, ast.Name) and func.value.id == "self":
            if cls is not None:
                fn = unit.method(cls, func.attr)
                if fn is not None:
                    yield unit, fn, cls, f"self.{func.attr}"
            return
        dotted = dotted_name(func)
        if dotted is not None:
            resolved = project.resolve_function(unit, dotted)
            if resolved is not None:
                yield resolved[0], resolved[1], None, dotted
