# Copyright 2026 The rayfed-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""FED009 ``unvalidated-config-key``: typo'd config keys that runtime
validation would silently drop.

``*Config.from_dict`` keeps reference parity by silently DROPPING
unknown keys (config.py): ``{"timeout_in_msx": 1}`` never errors, the
knob never takes effect, and the job runs with the default. The rule
checks every string key in literal dicts flowing into
``fed.init(config=...)`` (top-level keys, section dicts, and the nested
retry/liveness/failover schemas) and into ``<Class>.from_dict({...})``
against the static schema mirror in ``rayfed_tpu/lint/schema.py``
(pinned against the real dataclasses by a runtime test). String
subscripts on dicts that were passed as a config are checked too.
Messages carry a did-you-mean suggestion.
"""

from __future__ import annotations

import ast
import difflib
from typing import Dict, Iterable, Iterator, Optional, Tuple

from rayfed_tpu.lint import schema
from rayfed_tpu.lint.core import Rule
from rayfed_tpu.lint.model import FED_INIT, DriverModel


def _suggest(key: str, known: Iterable[str]) -> str:
    close = difflib.get_close_matches(key, list(known), n=1, cutoff=0.6)
    return f" (did you mean {close[0]!r}?)" if close else ""


def _literal_str_keys(d: ast.Dict) -> Iterator[Tuple[str, ast.AST, ast.expr]]:
    for key, value in zip(d.keys, d.values):
        if isinstance(key, ast.Constant) and isinstance(key.value, str):
            yield key.value, key, value


class UnvalidatedConfigKeyRule(Rule):
    rule_id = "FED009"
    name = "unvalidated-config-key"
    summary = (
        "config key not in the *Config.from_dict schema: from_dict "
        "silently drops it, so the knob never takes effect"
    )

    def check(
        self, tree: ast.Module, model: DriverModel
    ) -> Iterator[Tuple[ast.AST, str]]:
        dict_bindings = self._dict_bindings(tree)
        config_names = set()
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            if model.canonical_call(node) == FED_INIT:
                for expr in self._config_args(node):
                    if isinstance(expr, ast.Name):
                        config_names.add(expr.id)
                    d = self._as_dict(expr, dict_bindings)
                    if d is not None:
                        yield from self._check_top_level(d, dict_bindings)
            else:
                yield from self._check_from_dict(node, model, dict_bindings)
        yield from self._check_subscripts(tree, config_names, dict_bindings)

    # ------------------------------------------------------------------

    @staticmethod
    def _config_args(call: ast.Call) -> Iterator[ast.expr]:
        for kw in call.keywords:
            if kw.arg == "config":
                yield kw.value
        for arg in call.args:
            if isinstance(arg, ast.Dict):
                yield arg

    @staticmethod
    def _dict_bindings(tree: ast.Module) -> Dict[str, Optional[ast.Dict]]:
        """Name -> literal dict when the name is bound exactly once in
        the file (rebinding makes it ambiguous -> None)."""
        out: Dict[str, Optional[ast.Dict]] = {}
        for node in ast.walk(tree):
            if isinstance(node, ast.Assign):
                targets = [
                    t.id for t in node.targets if isinstance(t, ast.Name)
                ]
                value = node.value
            elif isinstance(node, ast.AnnAssign) and isinstance(
                node.target, ast.Name
            ):
                targets, value = [node.target.id], node.value
            else:
                continue
            for name in targets:
                if name in out:
                    out[name] = None
                elif isinstance(value, ast.Dict):
                    out[name] = value
        return out

    @staticmethod
    def _as_dict(
        expr: Optional[ast.expr], bindings: Dict[str, Optional[ast.Dict]]
    ) -> Optional[ast.Dict]:
        if isinstance(expr, ast.Dict):
            return expr
        if isinstance(expr, ast.Name):
            return bindings.get(expr.id)
        return None

    # ------------------------------------------------------------------

    def _check_top_level(
        self, d: ast.Dict, bindings: Dict[str, Optional[ast.Dict]]
    ) -> Iterator[Tuple[ast.AST, str]]:
        for key, key_node, value in _literal_str_keys(d):
            if key not in schema.TOP_LEVEL_KEYS:
                yield (
                    key_node,
                    f"unknown top-level config key {key!r}"
                    f"{_suggest(key, schema.TOP_LEVEL_KEYS)} — fed.init "
                    f"ignores it silently",
                )
                continue
            section_keys = schema.section_schema(key)
            section_dict = self._as_dict(value, bindings)
            if section_keys is None or section_dict is None:
                continue
            yield from self._check_section(key, section_dict, section_keys, bindings)

    def _check_section(
        self,
        section: str,
        d: ast.Dict,
        allowed,
        bindings: Dict[str, Optional[ast.Dict]],
    ) -> Iterator[Tuple[ast.AST, str]]:
        for key, key_node, value in _literal_str_keys(d):
            if key not in allowed:
                yield (
                    key_node,
                    f"unknown key {key!r} in config section {section!r}"
                    f"{_suggest(key, allowed)} — from_dict drops unknown "
                    f"keys silently, so this knob never takes effect",
                )
                continue
            if (section, key) in schema.OPAQUE_SECTION_VALUES:
                continue
            nested = schema.nested_schema(section, key)
            nested_dict = self._as_dict(value, bindings)
            if nested is not None and nested_dict is not None:
                yield from self._check_section(
                    f"{section}.{key}", nested_dict, nested, bindings
                )

    def _check_from_dict(
        self,
        call: ast.Call,
        model: DriverModel,
        bindings: Dict[str, Optional[ast.Dict]],
    ) -> Iterator[Tuple[ast.AST, str]]:
        func = call.func
        if not (isinstance(func, ast.Attribute) and func.attr == "from_dict"):
            return
        cls_name: Optional[str] = None
        path = model.resolved_path(func.value)
        if path:
            cls_name = path[-1]
        elif isinstance(func.value, ast.Name):
            cls_name = func.value.id
        fields = schema.CONFIG_CLASS_FIELDS.get(cls_name or "")
        if fields is None or not call.args:
            return
        d = self._as_dict(call.args[0], bindings)
        if d is None:
            return
        for key, key_node, _value in _literal_str_keys(d):
            if key not in fields:
                yield (
                    key_node,
                    f"unknown key {key!r} for {cls_name}.from_dict"
                    f"{_suggest(key, fields)} — dropped silently at "
                    f"runtime",
                )

    def _check_subscripts(
        self,
        tree: ast.Module,
        config_names: set,
        bindings: Dict[str, Optional[ast.Dict]],
    ) -> Iterator[Tuple[ast.AST, str]]:
        """String indexing on a dict that was passed as a fed.init
        config must use schema keys."""
        for node in ast.walk(tree):
            key_node: Optional[ast.AST] = None
            name: Optional[str] = None
            if (
                isinstance(node, ast.Subscript)
                and isinstance(node.value, ast.Name)
                and isinstance(node.slice, ast.Constant)
                and isinstance(node.slice.value, str)
            ):
                name, key_node, key = node.value.id, node.slice, node.slice.value
            elif (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "get"
                and isinstance(node.func.value, ast.Name)
                and node.args
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)
            ):
                name, key_node, key = (
                    node.func.value.id, node.args[0], node.args[0].value,
                )
            else:
                continue
            if name not in config_names:
                continue
            if key not in schema.TOP_LEVEL_KEYS:
                yield (
                    key_node,
                    f"config[{key!r}] is not a known top-level config key"
                    f"{_suggest(key, schema.TOP_LEVEL_KEYS)}",
                )
