# Copyright 2026 The rayfed-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""FED004 ``dangling-fedobject``: every produced FedObject needs a
consumer.

A ``.remote(...)`` task invocation (or ``fed_aggregate``) creates a DAG
edge on EVERY party (each burns the same seq ids); the value only ever
leaves the producer when some later call consumes it — ``fed.get``, a
downstream ``.remote`` argument, another aggregate. A FedObject bound to
a name that is never read again is a dead edge: any bytes already pushed
for it sit in the receiving party's rendezvous queue forever, and a
consumer added on one party but not another desynchronizes seq ids
(see FED002). Deliberate fire-and-forget calls (bare expression
statements, e.g. ``actor.update.remote(x)`` with no binding) and names
starting with ``_`` are not flagged.
"""

from __future__ import annotations

import ast
from typing import Iterator, Tuple

from rayfed_tpu.lint.core import Rule
from rayfed_tpu.lint.model import (
    FED_AGGREGATE,
    DriverModel,
    iter_scopes,
    loads_of,
)


class DanglingFedObjectRule(Rule):
    rule_id = "FED004"
    name = "dangling-fedobject"
    summary = "a FedObject bound to a name that is never consumed"

    def check(
        self, tree: ast.Module, model: DriverModel
    ) -> Iterator[Tuple[ast.AST, str]]:
        for scope in iter_scopes(tree):
            for stmt in scope.statements:
                if not isinstance(stmt, ast.Assign):
                    continue
                if not self._produces_fedobject(stmt.value, model):
                    continue
                for target in stmt.targets:
                    if not isinstance(target, ast.Name):
                        continue
                    name = target.id
                    if name.startswith("_"):
                        continue
                    if not loads_of(scope.node, name):
                        yield (
                            stmt,
                            f"FedObject bound to {name!r} is never consumed "
                            f"(no fed.get, no downstream task argument): "
                            f"its DAG edge never resolves, so bytes pushed "
                            f"for it wait in the receiver's queue forever — "
                            f"consume it, or drop the binding to make the "
                            f"fire-and-forget explicit",
                        )

    def _produces_fedobject(self, value: ast.expr, model: DriverModel) -> bool:
        if not isinstance(value, ast.Call):
            return False
        if model.canonical_call(value) == FED_AGGREGATE:
            return True
        inv = model.remote_invocation(value)
        if inv is None:
            return False
        # Actor construction returns a handle, not a FedObject; an unused
        # handle is not a dangling DAG edge.
        is_actor_creation = (
            inv.has_party_pin
            and inv.method is None
            and inv.base_name in model.remote_classes
        )
        return not is_actor_creation
