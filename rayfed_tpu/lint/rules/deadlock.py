# Copyright 2026 The rayfed-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""FED007 ``cross-party-deadlock``: mutual fed.get wait cycles between
``.party()``-pinned tasks.

A remote task whose body calls ``fed.get`` on one of its parameters
BLOCKS its party's worker until the peer's bytes arrive — unlike the
implicit owner-push of a plain FedObject argument, the pull holds the
executing thread. When two such pulling tasks are pinned to different
parties and each one's argument is the other's result variable (the
loop-carried ``a = f.party("alice").remote(b); b =
f.party("bob").remote(a)`` exchange), the parties' blocking pulls form a
wait cycle: each party's round-k pull gates the send the peer's round-k
pull is waiting on, so any divergence — a retry, a dropped connection,
reordered delivery — wedges both parties with no error. The rule walks
the whole project (the task def and its invocations may live in
different modules), builds the variable-level wait graph over
``.party("<literal>")``-pinned invocations of pulling tasks, and flags
every invocation on a cycle. Deliberately pipelined ping-pong exchanges
that tolerate the coupling can suppress with
``# fedlint: disable=cross-party-deadlock`` after review.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, Iterator, List, Optional, Set, Tuple

from rayfed_tpu.lint.core import ProjectRule
from rayfed_tpu.lint.model import FED_GET, iter_scopes
from rayfed_tpu.lint.project import ParsedModule, ProjectModel


def _pulling_params(fn: ast.AST, unit: ParsedModule) -> Set[str]:
    """Parameter names the remote function body ``fed.get``s."""
    params = {a.arg for a in fn.args.args + fn.args.kwonlyargs}
    pulled: Set[str] = set()
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        if unit.model.canonical_call(node) != FED_GET:
            continue
        for arg in node.args:
            elements = (
                list(arg.elts)
                if isinstance(arg, (ast.List, ast.Tuple))
                else [arg]
            )
            for element in elements:
                if isinstance(element, ast.Name) and element.id in params:
                    pulled.add(element.id)
    return pulled


def _pulling_remote_functions(
    project: ProjectModel,
) -> Dict[Tuple[str, str], Set[str]]:
    """(module, task name) -> pulled parameter names, for every
    ``@fed.remote`` function in the project whose body pulls a param."""
    out: Dict[Tuple[str, str], Set[str]] = {}
    for unit in project.modules:
        for name in unit.model.remote_functions:
            fn = unit.functions.get(name)
            if fn is None:
                continue
            pulled = _pulling_params(fn, unit)
            if pulled:
                out[(unit.module_name, name)] = pulled
    return out


@dataclasses.dataclass
class _Binding:
    """Last ``var = task.party("<p>").remote(...)`` seen in a scope."""

    var: str
    party: str
    call: ast.Call
    #: resolved key into the pulling-task table, when the base resolves.
    task: Optional[Tuple[str, str]]
    #: names of the args passed in pulled parameter positions.
    pulled_args: List[str]


class CrossPartyDeadlockRule(ProjectRule):
    rule_id = "FED007"
    name = "cross-party-deadlock"
    summary = (
        "mutual fed.get wait cycle between .party()-pinned tasks whose "
        "bodies pull their arguments"
    )

    def check_project(
        self, project: ProjectModel
    ) -> Iterator[Tuple[str, ast.AST, str]]:
        pulling = _pulling_remote_functions(project)
        if not pulling:
            return
        for unit in project.modules:
            for scope in iter_scopes(unit.tree):
                yield from self._check_scope(
                    unit, scope.statements, pulling, project
                )

    # ------------------------------------------------------------------

    def _resolve_task(
        self, unit: ParsedModule, base_name: str, project: ProjectModel
    ) -> Optional[Tuple[str, str]]:
        """Map an invocation base name onto a (module, task) key."""
        if base_name in unit.model.remote_functions:
            return (unit.module_name, base_name)
        resolved = project.resolve_function(unit, base_name)
        if resolved is not None:
            other, fn = resolved
            if fn.name in other.model.remote_functions:
                return (other.module_name, fn.name)
        return None

    def _check_scope(
        self,
        unit: ParsedModule,
        statements: List[ast.stmt],
        pulling: Dict[Tuple[str, str], Set[str]],
        project: ProjectModel,
    ) -> Iterator[Tuple[str, ast.AST, str]]:
        bindings: Dict[str, _Binding] = {}
        for stmt in statements:
            if not isinstance(stmt, ast.Assign) or not isinstance(
                stmt.value, ast.Call
            ):
                continue
            inv = unit.model.remote_invocation(stmt.value)
            if inv is None or inv.pinned_party is None or inv.base_name is None:
                continue
            task = self._resolve_task(unit, inv.base_name, project)
            if task is None or task not in pulling:
                continue
            fn_unit = project.by_name.get(task[0], unit)
            fn = fn_unit.functions.get(task[1])
            if fn is None:
                continue
            positional = [a.arg for a in fn.args.args]
            pulled_params = pulling[task]
            pulled_args: List[str] = []
            for idx, arg in enumerate(stmt.value.args):
                if not isinstance(arg, ast.Name):
                    continue
                if idx < len(positional) and positional[idx] in pulled_params:
                    pulled_args.append(arg.id)
            for kw in stmt.value.keywords:
                if kw.arg in pulled_params and isinstance(kw.value, ast.Name):
                    pulled_args.append(kw.value.id)
            for target in stmt.targets:
                if isinstance(target, ast.Name):
                    bindings[target.id] = _Binding(
                        var=target.id,
                        party=inv.pinned_party,
                        call=stmt.value,
                        task=task,
                        pulled_args=pulled_args,
                    )
        yield from self._report_cycles(unit, bindings)

    def _report_cycles(
        self, unit: ParsedModule, bindings: Dict[str, _Binding]
    ) -> Iterator[Tuple[str, ast.AST, str]]:
        # Wait edge var -> arg: the task bound to `var` blocks in a
        # fed.get on `arg`'s bytes, and the two run on different parties.
        edges: Dict[str, Set[str]] = {}
        for b in bindings.values():
            for arg in b.pulled_args:
                peer = bindings.get(arg)
                if peer is not None and peer.party != b.party:
                    edges.setdefault(b.var, set()).add(arg)
        on_cycle: Set[str] = set()
        for start in edges:
            if start in on_cycle:
                continue
            # DFS looking for a path back to `start`.
            stack, seen = [(start, iter(edges.get(start, ())))], {start}
            while stack:
                node, it = stack[-1]
                advanced = False
                for nxt in it:
                    if nxt == start:
                        on_cycle.update(n for n, _ in stack)
                        continue
                    if nxt not in seen:
                        seen.add(nxt)
                        stack.append((nxt, iter(edges.get(nxt, ()))))
                        advanced = True
                        break
                if not advanced:
                    stack.pop()
        for var in sorted(on_cycle, key=lambda v: bindings[v].call.lineno):
            b = bindings[var]
            peers = ", ".join(
                f"{a!r} (party {bindings[a].party!r})"
                for a in sorted(b.pulled_args)
                if a in on_cycle
            )
            yield (
                unit.path,
                b.call,
                f"task bound to {var!r} on party {b.party!r} blocks in "
                f"fed.get on {peers}, which in turn waits on {var!r}: a "
                f"cross-party wait cycle — any retry or reordering wedges "
                f"both parties with no error; break the cycle by passing "
                f"FedObjects without an in-task fed.get (owner push) or "
                f"staggering the exchange",
            )
