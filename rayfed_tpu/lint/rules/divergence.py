# Copyright 2026 The rayfed-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""FED002 ``seq-divergence``: every party must issue the same fed calls.

Seq ids are allocated by a deterministic per-process counter
(``get_global_context().next_seq_id()``), so the ``(upstream_seq_id,
downstream_seq_id)`` protocol in ``rayfed_tpu/proxy/barriers.py`` only
rendezvouses when every party executes the SAME sequence of
``fed.remote``/``fed.get`` invocations (multi-controller contract,
docs/migration_from_rayfed.md "Behavioral contract kept"). A branch that
fires on one party but not another shifts the counter on that party only
— after which sender and receiver address different edges and both block
forever: a distributed deadlock with no error.

Flagged: ``if``/``while``/``for`` statements whose condition (or
iterable) depends on party identity, ``fed.get`` results, wall-clock
time, or unseeded randomness, when the branch body issues fed calls (or
escapes via return/break/continue/raise/sys.exit while the surrounding
scope issues fed calls). Values that are broadcast-identical on every
party can make such a branch benign — suppress those sites with
``# fedlint: disable=seq-divergence`` after review.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, Set, Tuple

from rayfed_tpu.lint.core import Rule
from rayfed_tpu.lint.model import (
    FED_GET,
    DriverModel,
    dotted_name,
    iter_scopes,
)

#: dotted call prefixes that read the wall clock.
_CLOCK_CALLS = {
    "time.time", "time.monotonic", "time.perf_counter", "time.time_ns",
    "datetime.datetime.now", "datetime.datetime.utcnow",
    "datetime.date.today",
}

_ESCAPES = (ast.Return, ast.Break, ast.Continue, ast.Raise)

_EXIT_CALLS = {"sys.exit", "os._exit", "exit", "quit"}


class SeqDivergenceRule(Rule):
    rule_id = "FED002"
    name = "seq-divergence"
    summary = (
        "control flow that differs across parties desynchronizes seq ids "
        "and deadlocks the send/recv rendezvous"
    )

    def check(
        self, tree: ast.Module, model: DriverModel
    ) -> Iterator[Tuple[ast.AST, str]]:
        std_aliases = _std_module_aliases(tree)
        tainted = _tainted_names(tree, model, std_aliases)
        for scope in iter_scopes(tree):
            scope_has_fed = model.contains_dag_call(scope.node) is not None
            for stmt in scope.statements:
                yield from self._check_stmt(
                    stmt, model, tainted, std_aliases, scope_has_fed
                )

    def _check_stmt(
        self,
        stmt: ast.stmt,
        model: DriverModel,
        tainted: Set[str],
        std_aliases: Dict[str, str],
        scope_has_fed: bool,
    ) -> Iterator[Tuple[ast.AST, str]]:
        if isinstance(stmt, (ast.If, ast.While)):
            guard = stmt.test
        elif isinstance(stmt, ast.For):
            guard = stmt.iter
        else:
            return
        reason = _taint_reason(guard, model, tainted, std_aliases)
        if reason is None:
            return
        branch_fed_call = None
        branch_escape = None
        for part in list(stmt.body) + list(getattr(stmt, "orelse", [])):
            if branch_fed_call is None:
                branch_fed_call = model.contains_dag_call(part)
            if branch_escape is None:
                branch_escape = _find_escape(part, model, std_aliases)
        if branch_fed_call is not None:
            yield (
                stmt,
                f"branch condition depends on {reason} but its body issues "
                f"fed calls: parties taking different arms issue different "
                f"fed-call sequences, desynchronizing "
                f"(upstream_seq_id, downstream_seq_id) and deadlocking the "
                f"rendezvous — hoist the fed calls out of the branch or "
                f"make the condition party-invariant",
            )
        elif branch_escape is not None and scope_has_fed:
            yield (
                stmt,
                f"branch condition depends on {reason} and can exit the "
                f"control flow ({branch_escape}) past later fed calls: a "
                f"party leaving early stops issuing the shared fed-call "
                f"sequence and strands its peers' rendezvous",
            )


# ----------------------------------------------------------------------
# taint machinery
# ----------------------------------------------------------------------

def _std_module_aliases(tree: ast.Module) -> Dict[str, str]:
    """Aliases for the non-engine modules the rule knows about:
    time/datetime/random/numpy (plus ``from`` imports of their members,
    mapped to dotted form)."""
    interesting = {"time", "datetime", "random", "numpy", "sys", "os"}
    aliases: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                root = alias.name.split(".")[0]
                if root in interesting:
                    aliases[alias.asname or root] = alias.name
        elif isinstance(node, ast.ImportFrom) and node.module:
            root = node.module.split(".")[0]
            if root in interesting and not node.level:
                for alias in node.names:
                    aliases[alias.asname or alias.name] = (
                        f"{node.module}.{alias.name}"
                    )
    return aliases


def _dotted_call_name(call: ast.Call, aliases: Dict[str, str]) -> str:
    name = dotted_name(call.func) or ""
    root, _, rest = name.partition(".")
    resolved = aliases.get(root)
    if resolved is not None:
        return f"{resolved}.{rest}" if rest else resolved
    return name


def _is_divergent_source_call(call: ast.Call, aliases: Dict[str, str]) -> str:
    """Non-empty reason string when the call reads a party-divergent
    source (clock / unseeded randomness)."""
    name = _dotted_call_name(call, aliases)
    if name in _CLOCK_CALLS:
        return f"wall-clock time ({name})"
    if name.startswith("random."):
        return f"process-local randomness ({name})"
    if name.startswith("numpy.random."):
        if name.endswith("default_rng") and call.args:
            return ""  # explicitly seeded generator
        return f"process-local randomness ({name})"
    return ""


def _tainted_names(
    tree: ast.Module, model: DriverModel, aliases: Dict[str, str]
) -> Set[str]:
    """Fixpoint over assignments: names derived from the party identity,
    ``fed.get`` results, clocks, or unseeded randomness. Name-based and
    scope-insensitive — deliberately coarse for a linter."""
    tainted: Set[str] = set(model.current_party_vars)

    def expr_tainted(expr: ast.AST) -> bool:
        for sub in ast.walk(expr):
            if isinstance(sub, ast.Name) and isinstance(sub.ctx, ast.Load):
                if sub.id in tainted:
                    return True
            elif isinstance(sub, ast.Call):
                if model.canonical_call(sub) == FED_GET:
                    return True
                if _is_divergent_source_call(sub, aliases):
                    return True
        return False

    for _ in range(10):
        changed = False
        for node in ast.walk(tree):
            if isinstance(node, ast.Assign):
                targets, value = node.targets, node.value
            elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                targets = [node.target]
                value = node.value
            else:
                continue
            if value is None or not expr_tainted(value):
                continue
            for target in targets:
                for leaf in ast.walk(target):
                    if isinstance(leaf, ast.Name) and leaf.id not in tainted:
                        tainted.add(leaf.id)
                        changed = True
        if not changed:
            break
    return tainted


def _taint_reason(
    expr: ast.expr,
    model: DriverModel,
    tainted: Set[str],
    aliases: Dict[str, str],
):
    """Why this guard expression is party-divergent, or None."""
    for sub in ast.walk(expr):
        if isinstance(sub, ast.Name) and isinstance(sub.ctx, ast.Load):
            if sub.id in model.current_party_vars:
                return f"the party identity ({sub.id!r})"
            if sub.id in tainted:
                return f"a fed.get-derived or party-dependent value ({sub.id!r})"
        elif isinstance(sub, ast.Call):
            if model.canonical_call(sub) == FED_GET:
                return "a fed.get result"
            reason = _is_divergent_source_call(sub, aliases)
            if reason:
                return reason
    return None


def _find_escape(node: ast.AST, model: DriverModel, aliases: Dict[str, str]):
    """Name of the first control-flow escape in a subtree, if any."""
    for sub in ast.walk(node):
        if isinstance(sub, _ESCAPES):
            return type(sub).__name__.lower()
        if isinstance(sub, ast.Call):
            name = _dotted_call_name(sub, aliases)
            if name in _EXIT_CALLS:
                return name
    return None
