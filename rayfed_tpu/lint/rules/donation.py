# Copyright 2026 The rayfed-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""FED003 ``donation-aliasing``: donate=True step state must not be
consumed locally by reference.

``make_fed_train_step(donate=True)`` (the default — see the contract and
``FEDLINT_DONATION_RULE`` anchor in ``rayfed_tpu/parallel/train.py``)
aliases the params/opt_state buffers into each update: the NEXT step
invalidates the previous step's first two outputs. Cross-party pushes
are capture-protected by the engine (values are snapshotted at
resolution, ``rayfed_tpu/proxy/barriers.py``), but a fed task that
RETURNS that state for LOCAL consumption (e.g. an actor whose result
feeds ``fed_aggregate`` in the same party) hands out live device arrays
by reference — the next donating step turns them into "Array has been
deleted" failures that reproduce only under async timing (the race fixed
in ``tests/test_donation_race.py``).

Flagged: a class that builds a step with ``donate`` left True and
returns the step's donated outputs (the first two results of the step
call) from any method. Fix: pass ``donate=False``, or return a copy.
"""

from __future__ import annotations

import ast
from typing import Iterator, List, Optional, Set, Tuple

from rayfed_tpu.lint.core import Rule
from rayfed_tpu.lint.model import (
    MAKE_FED_TRAIN_STEP,
    DriverModel,
    dotted_name,
)

#: ``step_fn(params, opt_state, ...) -> (params, opt_state, loss)`` with
#: ``donate_argnums=(0, 1)``: the first two outputs alias donated inputs.
_DONATED_RESULTS = 2


class DonationAliasingRule(Rule):
    rule_id = "FED003"
    name = "donation-aliasing"
    summary = (
        "donate=True train-step results returned for local consumption "
        "alias buffers the next step invalidates"
    )

    def check(
        self, tree: ast.Module, model: DriverModel
    ) -> Iterator[Tuple[ast.AST, str]]:
        for node in ast.walk(tree):
            if isinstance(node, ast.ClassDef):
                yield from self._check_container(node, model)
        yield from self._check_container(tree, model)

    def _check_container(
        self, container: ast.AST, model: DriverModel
    ) -> Iterator[Tuple[ast.AST, str]]:
        """Analyze one class body (or the module minus its classes)."""

        def nodes() -> Iterator[ast.AST]:
            # Like ast.walk, but nested classes are PRUNED: each class is
            # its own aliasing domain with its own container pass, so its
            # members must not leak into this one.
            stack: List[ast.AST] = [container]
            while stack:
                node = stack.pop()
                yield node
                for child in ast.iter_child_nodes(node):
                    if isinstance(child, ast.ClassDef):
                        continue
                    stack.append(child)

        donating_calls: List[ast.Call] = []
        step_names: Set[str] = set()
        for sub in nodes():
            if not isinstance(sub, ast.Assign):
                continue
            call = sub.value
            if (
                not isinstance(call, ast.Call)
                or model.canonical_call(call) != MAKE_FED_TRAIN_STEP
            ):
                continue
            if not _donates(call):
                continue
            donating_calls.append(call)
            step = _step_target(sub)
            if step is not None:
                step_names.add(step)
        if not donating_calls or not step_names:
            return

        aliased: Set[str] = set()
        for sub in nodes():
            if not isinstance(sub, ast.Assign) or not isinstance(
                sub.value, ast.Call
            ):
                continue
            callee = dotted_name(sub.value.func)
            if callee not in step_names:
                continue
            for target in sub.targets:
                if isinstance(target, ast.Tuple):
                    for element in target.elts[:_DONATED_RESULTS]:
                        name = dotted_name(element)
                        if name is not None:
                            aliased.add(name)
                else:
                    name = dotted_name(target)
                    if name is not None:
                        aliased.add(name)
        if not aliased:
            return

        for sub in nodes():
            if not isinstance(sub, ast.Return) or sub.value is None:
                continue
            leaked = _first_reference(sub.value, aliased)
            if leaked is not None:
                call = donating_calls[0]
                yield (
                    sub,
                    f"returns {leaked!r}, a donated output of the "
                    f"donate=True train step built at line {call.lineno}: "
                    f"a local consumer holds it by reference while the "
                    f"next step donates (and invalidates) its buffers — "
                    f"pass donate=False to make_fed_train_step or return "
                    f"a copy (rayfed_tpu/parallel/train.py aliasing "
                    f"contract)",
                )


def _donates(call: ast.Call) -> bool:
    """donate left at its default (True) or explicitly True."""
    for kw in call.keywords:
        if kw.arg == "donate":
            return not (
                isinstance(kw.value, ast.Constant) and kw.value.value is False
            )
    return True


def _step_target(assign: ast.Assign) -> Optional[str]:
    """The step-fn's bound name in ``init_fn, step_fn = make_fed_train_step(...)``
    (the factory returns the pair; the step is the second element)."""
    for target in assign.targets:
        if isinstance(target, ast.Tuple) and len(target.elts) == 2:
            return dotted_name(target.elts[1])
    return None


def _first_reference(expr: ast.expr, names: Set[str]) -> Optional[str]:
    for sub in ast.walk(expr):
        name = dotted_name(sub) if isinstance(
            sub, (ast.Name, ast.Attribute)
        ) else None
        if name in names:
            return name
    return None
