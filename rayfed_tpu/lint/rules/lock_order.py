# Copyright 2026 The rayfed-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""FED011 ``lock-order-inconsistency``: two locks taken in both orders.

The proxy planes are lock-heavy (submit gates, pool locks, hook locks)
and multi-threaded (caller threads, reactor loops, dial threads): two
locks acquired in opposite orders on two static paths is the classic
ABBA deadlock, needing only unlucky scheduling to fire. The rule
identifies locks structurally — module-level ``threading.Lock/RLock/
Condition`` assignments and ``self.X = threading.Lock()`` instance
attributes (plus any ``with``-acquired name ending in ``lock``/
``mutex``) — keyed as ``module.Class.attr`` so the same attribute on
two *instances* of one class is one lock identity (self-pairs are
skipped: instance-crossing orders need runtime identity the linter
cannot see). Acquisition order is read from ``with`` nesting plus one
static call hop (a function called under lock A that itself takes lock
B contributes the pair A<B). Both orders for a pair => a finding at the
first site of each direction, each naming the other.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set, Tuple

from rayfed_tpu.lint.core import ProjectRule
from rayfed_tpu.lint.model import dotted_name
from rayfed_tpu.lint.project import ParsedModule, ProjectModel

_LOCK_CTORS = {
    "threading.Lock", "threading.RLock", "threading.Condition",
    "Lock", "RLock", "Condition",
}
_LOCK_SUFFIXES = ("lock", "mutex")


def _is_lock_ctor(value: ast.expr, unit: ParsedModule) -> bool:
    if not isinstance(value, ast.Call):
        return False
    name = dotted_name(value.func) or ""
    head, _, rest = name.partition(".")
    target = unit.imports.get(head)
    if target is not None and target != head:
        name = f"{target}.{rest}" if rest else target
    return name in _LOCK_CTORS


class _UnitLocks:
    """Structurally known lock names for one module."""

    def __init__(self, unit: ParsedModule):
        self.unit = unit
        self.module_locks: Set[str] = set()
        self.attr_locks: Dict[str, Set[str]] = {}  # class -> attr names
        for stmt in unit.tree.body:
            if isinstance(stmt, ast.Assign) and _is_lock_ctor(stmt.value, unit):
                for t in stmt.targets:
                    if isinstance(t, ast.Name):
                        self.module_locks.add(t.id)
        for cls_name, cls in unit.classes.items():
            attrs: Set[str] = set()
            for node in ast.walk(cls):
                if isinstance(node, ast.Assign) and _is_lock_ctor(
                    node.value, unit
                ):
                    for t in node.targets:
                        if (
                            isinstance(t, ast.Attribute)
                            and isinstance(t.value, ast.Name)
                            and t.value.id == "self"
                        ):
                            attrs.add(t.attr)
            if attrs:
                self.attr_locks[cls_name] = attrs

    def key(self, expr: ast.expr, cls: Optional[str]) -> Optional[str]:
        """Stable identity for a ``with <expr>:`` acquisition, or None
        when the expression is not recognizably a lock."""
        mod = self.unit.module_name
        if isinstance(expr, ast.Name):
            if expr.id in self.module_locks or expr.id.lower().endswith(
                _LOCK_SUFFIXES
            ):
                return f"{mod}.{expr.id}"
            return None
        if (
            isinstance(expr, ast.Attribute)
            and isinstance(expr.value, ast.Name)
            and expr.value.id == "self"
            and cls is not None
        ):
            known = self.attr_locks.get(cls, set())
            if expr.attr in known or expr.attr.lower().endswith(
                _LOCK_SUFFIXES
            ):
                return f"{mod}.{cls}.{expr.attr}"
        return None


class LockOrderInconsistencyRule(ProjectRule):
    rule_id = "FED011"
    name = "lock-order-inconsistency"
    summary = (
        "two locks acquired in both orders on different static paths "
        "(ABBA deadlock)"
    )

    def check_project(
        self, project: ProjectModel
    ) -> Iterator[Tuple[str, ast.AST, str]]:
        unit_locks = {u.path: _UnitLocks(u) for u in project.modules}
        #: ordered pair (outer, inner) -> first acquisition site.
        pairs: Dict[Tuple[str, str], Tuple[str, ast.AST]] = {}
        for unit in project.modules:
            locks = unit_locks[unit.path]
            for cls, fn in self._functions(unit):
                self._collect(
                    project, unit, locks, unit_locks, cls, fn, pairs
                )
        # Both directions of an inconsistent pair report, one finding at
        # each direction's first site, each naming the other.
        for (a, b), (path, node) in sorted(
            pairs.items(), key=lambda kv: (kv[1][0], kv[1][1].lineno)
        ):
            other = pairs.get((b, a))
            if other is None:
                continue
            yield (
                path,
                node,
                f"lock {b!r} is acquired while holding {a!r} here, but "
                f"the opposite order occurs at {other[0]}:"
                f"{getattr(other[1], 'lineno', 1)} — inconsistent lock "
                f"order deadlocks under concurrent execution; pick one "
                f"global order",
            )

    # ------------------------------------------------------------------

    @staticmethod
    def _functions(
        unit: ParsedModule,
    ) -> Iterator[Tuple[Optional[str], ast.AST]]:
        for fn in unit.functions.values():
            yield None, fn
        for cls_name, cls in unit.classes.items():
            for stmt in cls.body:
                if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    yield cls_name, stmt

    def _collect(
        self,
        project: ProjectModel,
        unit: ParsedModule,
        locks: _UnitLocks,
        unit_locks: Dict[str, _UnitLocks],
        cls: Optional[str],
        fn: ast.AST,
        pairs: Dict[Tuple[str, str], Tuple[str, ast.AST]],
    ) -> None:
        def record(outer: str, inner: str, node: ast.AST) -> None:
            if outer == inner:
                return  # same identity (often two instances); undecidable
            pairs.setdefault((outer, inner), (unit.path, node))

        def callee_locks(
            target_unit: ParsedModule,
            target_cls: Optional[str],
            target_fn: ast.AST,
        ) -> List[str]:
            tlocks = unit_locks[target_unit.path]
            out: List[str] = []
            for node in ast.walk(target_fn):
                if isinstance(node, (ast.With, ast.AsyncWith)):
                    for item in node.items:
                        k = tlocks.key(item.context_expr, target_cls)
                        if k is not None:
                            out.append(k)
            return out

        def visit(node: ast.AST, held: Tuple[str, ...]) -> None:
            if isinstance(node, (ast.With, ast.AsyncWith)):
                new = held
                for item in node.items:
                    k = locks.key(item.context_expr, cls)
                    if k is None:
                        continue
                    for h in new:
                        record(h, k, item.context_expr)
                    new = new + (k,)
                for stmt in node.body:
                    visit(stmt, new)
                return
            if isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
            ):
                return
            if held and isinstance(node, ast.Call):
                for t_unit, t_fn, t_cls in self._call_targets(
                    project, unit, cls, node
                ):
                    for k in callee_locks(t_unit, t_cls, t_fn):
                        for h in held:
                            record(h, k, node)
            for child in ast.iter_child_nodes(node):
                visit(child, held)

        for stmt in getattr(fn, "body", []):
            visit(stmt, ())

    @staticmethod
    def _call_targets(
        project: ProjectModel,
        unit: ParsedModule,
        cls: Optional[str],
        call: ast.Call,
    ) -> Iterator[Tuple[ParsedModule, ast.AST, Optional[str]]]:
        func = call.func
        if isinstance(func, ast.Name):
            resolved = project.resolve_function(unit, func.id)
            if resolved is not None:
                yield resolved[0], resolved[1], None
            return
        if not isinstance(func, ast.Attribute):
            return
        if isinstance(func.value, ast.Name) and func.value.id == "self":
            if cls is not None:
                fn = unit.method(cls, func.attr)
                if fn is not None:
                    yield unit, fn, cls
            return
        dotted = dotted_name(func)
        if dotted is not None:
            resolved = project.resolve_function(unit, dotted)
            if resolved is not None:
                yield resolved[0], resolved[1], None
