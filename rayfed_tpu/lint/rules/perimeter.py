# Copyright 2026 The rayfed-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""FED001 ``perimeter``: data crosses parties only by owner push.

The engine's perimeter contract (docs/index.md highlight #2): a
FedObject produced by a task pinned to party A and consumed by a task
pinned to party B is fine — that is exactly the owner-push lane. What
violates the perimeter:

* ``fed.get`` of an object whose owner is provably a DIFFERENT party
  than the one this driver pins itself to via
  ``fed.init(party="<literal>")`` — a cross-party pull of raw values
  into this process (drivers whose party comes from ``sys.argv`` run
  as every party, so ownership is not locally decidable and the rule
  stays silent);
* a value already materialized by ``fed.get`` passed back into a
  ``.remote(...)`` call as a raw argument — the array re-enters the DAG
  outside the push protocol (every party re-serializes its local copy
  instead of the owner pushing once), so the FedObject itself should be
  passed instead.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, Optional, Tuple

from rayfed_tpu.lint.core import Rule
from rayfed_tpu.lint.model import (
    FED_AGGREGATE,
    FED_GET,
    DriverModel,
    iter_scopes,
)

#: Sentinel owner for names rebound with conflicting owners.
_AMBIGUOUS = object()

#: Statement fields holding nested statements — excluded from per-statement
#: expression walks because the scope's flattened statement list already
#: visits them individually.
_STMT_BODY_FIELDS = ("body", "orelse", "finalbody", "handlers")


def _stmt_calls(stmt: ast.stmt) -> Iterator[ast.Call]:
    """Calls in a statement's OWN expressions (test/iter/value/...), not
    in nested statement bodies."""
    for field, value in ast.iter_fields(stmt):
        if field in _STMT_BODY_FIELDS:
            continue
        nodes = value if isinstance(value, list) else [value]
        for node in nodes:
            if not isinstance(node, ast.AST):
                continue
            for sub in ast.walk(node):
                if isinstance(sub, ast.Call):
                    yield sub


class _Bindings:
    """Source-order owner tracking for one scope: which party owns the
    FedObject/actor a name is bound to, and which names hold values
    already materialized by ``fed.get``."""

    def __init__(self) -> None:
        self.actor_owner: Dict[str, object] = {}
        self.fedobj_owner: Dict[str, object] = {}
        self.materialized: Dict[str, Optional[str]] = {}

    def _bind(self, table: Dict[str, object], name: str, owner: object) -> None:
        if name in table and table[name] != owner:
            table[name] = _AMBIGUOUS
        else:
            table[name] = owner

    def owner_of(self, name: str) -> Optional[str]:
        for table in (self.fedobj_owner, self.actor_owner):
            owner = table.get(name)
            if owner is _AMBIGUOUS:
                return None
            if owner is not None:
                return owner  # type: ignore[return-value]
        return None


class PerimeterRule(Rule):
    rule_id = "FED001"
    name = "perimeter"
    summary = (
        "data must cross parties only by owner push, never by pulling "
        "another party's values or re-injecting materialized arrays"
    )

    def check(
        self, tree: ast.Module, model: DriverModel
    ) -> Iterator[Tuple[ast.AST, str]]:
        for scope in iter_scopes(tree):
            yield from self._check_scope(scope.statements, model)

    # ------------------------------------------------------------------

    def _owner_of_expr(
        self, expr: ast.expr, env: _Bindings, model: DriverModel
    ) -> Optional[str]:
        """Literal owner party of a FedObject-producing expression, when
        statically provable."""
        if isinstance(expr, ast.Name):
            return env.owner_of(expr.id)
        if isinstance(expr, ast.Call):
            inv = model.remote_invocation(expr)
            if inv is not None:
                if inv.pinned_party is not None:
                    return inv.pinned_party
                if inv.method is not None and inv.base_name is not None:
                    owner = env.actor_owner.get(inv.base_name)
                    return None if owner is _AMBIGUOUS else owner  # type: ignore
        return None

    def _record_assign(
        self, stmt: ast.Assign, env: _Bindings, model: DriverModel
    ) -> None:
        value = stmt.value
        targets = []
        for t in stmt.targets:
            if isinstance(t, ast.Name):
                targets.append(t.id)
        if not targets:
            return
        if not isinstance(value, ast.Call):
            # Aliasing propagates materialization/ownership: x = y.
            if isinstance(value, ast.Name):
                for name in targets:
                    if value.id in env.materialized:
                        env.materialized[name] = env.materialized[value.id]
                    owner = env.owner_of(value.id)
                    if owner is not None:
                        env._bind(env.fedobj_owner, name, owner)
            return
        canon = model.canonical_call(value)
        if canon == FED_GET:
            src = value.args[0] if value.args else None
            src_owner = (
                self._owner_of_expr(src, env, model) if src is not None else None
            )
            for name in targets:
                env.materialized[name] = src_owner
            return
        if canon == FED_AGGREGATE:
            for name in targets:
                env._bind(env.fedobj_owner, name, None)
            return
        inv = model.remote_invocation(value)
        if inv is None:
            return
        is_actor_creation = (
            inv.has_party_pin
            and inv.method is None
            and inv.base_name in model.remote_classes
        )
        table = env.actor_owner if is_actor_creation else env.fedobj_owner
        owner: object = inv.pinned_party
        if owner is None and inv.method is not None and inv.base_name:
            owner = env.actor_owner.get(inv.base_name)
            if owner is _AMBIGUOUS:
                owner = None
        for name in targets:
            env._bind(table, name, owner)

    def _check_scope(
        self, statements, model: DriverModel
    ) -> Iterator[Tuple[ast.AST, str]]:
        env = _Bindings()
        for stmt in statements:
            if isinstance(stmt, ast.Assign):
                self._record_assign(stmt, env, model)
            for call in _stmt_calls(stmt):
                yield from self._check_get(call, env, model)
                yield from self._check_raw_arg(call, env, model)

    def _check_get(
        self, call: ast.Call, env: _Bindings, model: DriverModel
    ) -> Iterator[Tuple[ast.AST, str]]:
        if model.canonical_call(call) != FED_GET or model.current_party is None:
            return
        if not call.args:
            return
        arg = call.args[0]
        elements = (
            list(arg.elts) if isinstance(arg, (ast.List, ast.Tuple)) else [arg]
        )
        for element in elements:
            owner = self._owner_of_expr(element, env, model)
            if owner is not None and owner != model.current_party:
                yield (
                    call,
                    f"fed.get pulls a value owned by party {owner!r} into "
                    f"party {model.current_party!r}: data crosses the "
                    f"perimeter only by owner push (pass the FedObject to "
                    f"a task pinned to {owner!r}, or have {owner!r} "
                    f"fed.get its own object to broadcast it)",
                )

    def _check_raw_arg(
        self, call: ast.Call, env: _Bindings, model: DriverModel
    ) -> Iterator[Tuple[ast.AST, str]]:
        inv = model.remote_invocation(call)
        if inv is None:
            return
        arg_exprs = list(call.args) + [kw.value for kw in call.keywords]
        for expr in arg_exprs:
            if isinstance(expr, ast.Name) and expr.id in env.materialized:
                yield (
                    call,
                    f"argument {expr.id!r} was materialized by fed.get and "
                    f"re-enters the DAG as a raw value; pass the FedObject "
                    f"itself so the owner pushes it to the consuming party "
                    f"instead of every party re-serializing its local copy",
                )
