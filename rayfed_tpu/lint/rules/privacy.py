# Copyright 2026 The rayfed-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""FED006 ``insecure-aggregate``: the job enables the privacy plane but
an aggregation bypasses it.

A driver whose ``fed.init`` config literal turns on
``privacy.secure_aggregation`` has declared that per-party updates must
not cross the wire in the clear. Two shapes break that declaration:

1. ``fed_aggregate(...)`` without ``secure=True`` — the reduction runs
   through the plaintext fold, shipping raw updates hop to hop;
2. a raw ``.party(...).remote(...)`` push whose argument is a
   gradient/weight-named tensor — model updates leaving the party
   outside any aggregation, plaintext by construction.

The rule only fires when the privacy block is statically visible as a
dict literal in the same file (conservative: config built elsewhere
stays silent). Intentional plaintext calls — debugging, public metrics —
carry ``# fedlint: disable=insecure-aggregate`` on the line.
"""

from __future__ import annotations

import ast
import re
from typing import Iterator, Optional, Tuple

from rayfed_tpu.lint.core import Rule
from rayfed_tpu.lint.model import FED_AGGREGATE, DriverModel

#: Argument names that look like model updates (the tensors secure
#: aggregation exists to protect).
_UPDATE_NAME_RE = re.compile(
    r"(^|_)(grads?|gradients?|weights?)($|_|\d)", re.IGNORECASE
)


def _privacy_block(init_call: ast.Call) -> Optional[ast.Dict]:
    """The ``privacy`` sub-dict literal of an init call's ``config=``
    dict literal, or None."""
    for kw in init_call.keywords:
        if kw.arg != "config" or not isinstance(kw.value, ast.Dict):
            continue
        for key, value in zip(kw.value.keys, kw.value.values):
            if (
                isinstance(key, ast.Constant)
                and key.value == "privacy"
                and isinstance(value, ast.Dict)
            ):
                return value
    return None


def _dict_truthy(d: ast.Dict, name: str) -> bool:
    for key, value in zip(d.keys, d.values):
        if (
            isinstance(key, ast.Constant)
            and key.value == name
            and isinstance(value, ast.Constant)
        ):
            return bool(value.value)
    return False


class InsecureAggregateRule(Rule):
    rule_id = "FED006"
    name = "insecure-aggregate"
    summary = "privacy plane enabled but an aggregation bypasses it"

    def check(
        self, tree: ast.Module, model: DriverModel
    ) -> Iterator[Tuple[ast.AST, str]]:
        if not self._secure_aggregation_enabled(model):
            return
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            if model.canonical_call(node) == FED_AGGREGATE:
                if not self._passes_secure(node):
                    yield (
                        node,
                        "this job enables privacy.secure_aggregation but "
                        "fed_aggregate runs the PLAINTEXT fold — raw "
                        "per-party updates ride the wire; pass "
                        "secure=True (or suppress an intentional "
                        "plaintext call with "
                        "# fedlint: disable=insecure-aggregate)",
                    )
                continue
            update = self._raw_update_push(node, model)
            if update is not None:
                yield (
                    node,
                    f"this job enables privacy.secure_aggregation but "
                    f"{update!r} is pushed raw via .remote() outside any "
                    f"aggregation — gradient/weight tensors leaving the "
                    f"party in the clear bypass the masks; route them "
                    f"through fed_aggregate(secure=True) (or suppress "
                    f"with # fedlint: disable=insecure-aggregate)",
                )

    def _secure_aggregation_enabled(self, model: DriverModel) -> bool:
        for init_call in model.init_calls:
            block = _privacy_block(init_call)
            if block is not None and _dict_truthy(
                block, "secure_aggregation"
            ):
                return True
        return False

    def _passes_secure(self, call: ast.Call) -> bool:
        for kw in call.keywords:
            if kw.arg == "secure":
                if isinstance(kw.value, ast.Constant):
                    return bool(kw.value.value)
                return True  # computed value: assume the driver decides
            if kw.arg is None:
                return True  # **kwargs: cannot see inside
        return False

    def _raw_update_push(
        self, call: ast.Call, model: DriverModel
    ) -> Optional[str]:
        """The first gradient/weight-named argument of a ``.remote()``
        push, or None when this call is not one."""
        inv = model.remote_invocation(call)
        if inv is None:
            return None
        candidates = list(call.args) + [
            kw.value for kw in call.keywords if kw.arg is not None
        ]
        for arg in candidates:
            if isinstance(arg, ast.Name) and _UPDATE_NAME_RE.search(arg.id):
                return arg.id
        return None
