# Copyright 2026 The rayfed-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""FED005 ``reserved-seq-id``: the ``("ping", "ping")`` pair belongs to
the readiness barrier.

``barriers.send``/``barriers.recv`` address data by
``(upstream_seq_id, downstream_seq_id)``; the pair ``("ping", "ping")``
is reserved for the init readiness probe (``PING_SEQ_ID`` in
``rayfed_tpu/_private/constants.py``) — a frame carrying it is consumed
by the receiver's rendezvous store as a liveness ping and never
delivered to ``recv``. Normal drivers never see this (seq ids are
internal monotonic integers), but code driving the barrier layer
directly with that pair silently corrupts the handshake: the runtime
now raises ``ValueError`` (see ``FEDLINT_RESERVED_SEQ_RULE`` in
``rayfed_tpu/proxy/barriers.py``), and this rule catches it before it
runs.
"""

from __future__ import annotations

import ast
from typing import Iterator, Tuple

from rayfed_tpu.lint.core import Rule
from rayfed_tpu.lint.model import (
    BARRIERS_RECV,
    BARRIERS_SEND,
    PING_SEQ_ID,
    DriverModel,
)

#: positional index of (upstream, downstream/curr) in send(...) and recv(...).
_SEQ_ARG_POSITIONS = (2, 3)
_SEQ_KEYWORDS = {
    BARRIERS_SEND: ("upstream_seq_id", "downstream_seq_id"),
    BARRIERS_RECV: ("upstream_seq_id", "curr_seq_id"),
}


class ReservedSeqIdRule(Rule):
    rule_id = "FED005"
    name = "reserved-seq-id"
    summary = 'the ("ping", "ping") seq-id pair is the readiness probe'

    def check(
        self, tree: ast.Module, model: DriverModel
    ) -> Iterator[Tuple[ast.AST, str]]:
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            canon = model.canonical_call(node)
            if canon not in (BARRIERS_SEND, BARRIERS_RECV):
                continue
            seq_args = self._seq_args(node, canon)
            if len(seq_args) == 2 and all(
                self._is_ping(arg, model) for arg in seq_args
            ):
                fn = "send" if canon == BARRIERS_SEND else "recv"
                yield (
                    node,
                    f'barriers.{fn} called with the reserved '
                    f'("ping", "ping") seq-id pair: that pair is consumed '
                    f"by the receiver's readiness barrier and never "
                    f"delivered as data — use any other ids (the runtime "
                    f"raises ValueError on this collision)",
                )

    def _seq_args(self, call: ast.Call, canon: str):
        out = []
        kw_names = _SEQ_KEYWORDS[canon]
        keywords = {kw.arg: kw.value for kw in call.keywords}
        for position, kw_name in zip(_SEQ_ARG_POSITIONS, kw_names):
            if len(call.args) > position:
                out.append(call.args[position])
            elif kw_name in keywords:
                out.append(keywords[kw_name])
        return out

    def _is_ping(self, expr: ast.expr, model: DriverModel) -> bool:
        if isinstance(expr, ast.Constant) and expr.value == "ping":
            return True
        if model.canonical(expr) == PING_SEQ_ID:
            return True
        return isinstance(expr, ast.Name) and expr.id == "PING_SEQ_ID"
