# Copyright 2026 The rayfed-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""FED008 ``global-mutable-singleton``: module-level mutable state.

Every module-level registry, cache dict, and lock is process-global:
under the planned multi-tenant runtime (ROADMAP, "multi-tenant jobs")
two jobs in one process would share — and corrupt — it. The rule flags
three shapes: threading synchronization objects (a lock only exists to
serialize shared state), mutable containers the module itself writes
to, and ``global``-rebound lazy caches. Constant tables nobody mutates
are not flagged. The detector is shared with the CLI's
``--singleton-inventory`` writer (``tools/singleton_inventory.json``,
the refactor worklist), so a per-site suppression silences the finding
without hiding the site from the inventory. Sites that are deliberate
process-wide state (the proxy registry, the metrics registry) suppress
with a justification comment; the suppression is the refactor's TODO
marker.
"""

from __future__ import annotations

import ast
from typing import Iterator, Tuple

from rayfed_tpu.lint.core import ProjectRule
from rayfed_tpu.lint.project import ProjectModel, collect_singletons

_KIND_BLURB = {
    "lock": "a module-level lock serializes state shared by every job "
            "in the process",
    "container": "a module-level mutable container is shared by every "
                 "job in the process",
    "cache": "a global-rebound cache is shared by every job in the "
             "process",
}


class GlobalMutableSingletonRule(ProjectRule):
    rule_id = "FED008"
    name = "global-mutable-singleton"
    summary = (
        "module-level mutable registries/dicts/locks block the "
        "multi-tenant refactor (inventoried in "
        "tools/singleton_inventory.json)"
    )

    def check_project(
        self, project: ProjectModel
    ) -> Iterator[Tuple[str, ast.AST, str]]:
        for unit in project.modules:
            for s in collect_singletons(unit):
                yield (
                    unit.path,
                    s.node,
                    f"module-level mutable singleton {s.name!r} "
                    f"({s.kind}): {_KIND_BLURB[s.kind]} — scope it "
                    f"per-job for the multi-tenant refactor, or suppress "
                    f"with a justification to keep it on the inventory "
                    f"worklist",
                )
