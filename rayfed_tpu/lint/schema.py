# Copyright 2026 The rayfed-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""The config-key schema FED009 checks literal dicts against.

Most ``*Config.from_dict`` methods silently DROP unknown keys
(``config.py``'s reference-parity contract), so a typo'd knob never
takes effect and never errors — the worst failure mode a linter can
close. (``ServingConfig.from_dict`` is the exception: it raises on
unknown keys at ``fed.init``; FED009 still catches the same typo before
the job ever launches.) The tables
here are a static mirror of the dataclasses in ``rayfed_tpu/config.py``
(+ membership/privacy/serving): fedlint must import nothing heavier than
the stdlib, so the mirror is hand-maintained and pinned by
``tests/test_fedlint.py::test_schema_matches_config_dataclasses``, which
diffs every ``*_FIELDS`` set against ``dataclasses.fields()`` of the
real class. Editing a config dataclass without updating this file is a
test failure, not a silent lint gap.
"""

from __future__ import annotations

from typing import Dict, FrozenSet, Optional, Tuple

#: dataclass-mirrored field sets (pinned by the runtime test).
CROSS_SILO_BASE_FIELDS = frozenset({
    "adaptive_timeouts", "allow_pickle_payloads", "compression_level",
    "continue_waiting_for_data_sending_on_error", "device_dma",
    "dma_listen_addr", "exit_on_sending_failure", "expose_error_trace",
    "frame_crc", "lane_tiers", "messages_max_size_in_bytes",
    "min_timeout_in_ms", "payload_compression", "payload_wire_dtype",
    "recv_timeout_in_ms", "rtt_timeout_multiple", "same_mesh_push",
    "send_deadline_in_ms", "serializing_allowed_list", "shm_enabled",
    "shm_min_bytes", "shm_push_timeout_ms", "shm_repromote_after_ms",
    "shm_ring_mb", "small_message_threshold", "timeout_in_ms",
})

TCP_CROSS_SILO_FIELDS = CROSS_SILO_BASE_FIELDS | frozenset({
    "connect_timeout_in_ms", "num_reactors", "num_streams",
    "per_party_config", "proxy_max_restarts", "retry_policy",
    "send_window", "use_reactor", "verify_peer_identity",
})

RETRY_POLICY_FIELDS = frozenset({
    "backoff_multiplier", "initial_backoff_ms", "jitter", "max_attempts",
    "max_backoff_ms",
})

PARTY_MESH_FIELDS = frozenset({"axis_names", "device_ids", "mesh_shape"})

SERVING_FIELDS = frozenset({
    "eos_id", "kv_block_size", "kv_blocks", "kv_layout", "max_len",
    "max_new_tokens", "max_pending", "max_slots", "mode", "prefill_chunk",
    "prefill_token_budget", "prefix_reuse", "prompt_buckets",
    "stream_window", "temperature",
})

MEMBERSHIP_FIELDS = frozenset({
    "auth_token", "bootstrap_dir", "coordinator", "evict_dead",
    "failover", "join_timeout_s", "sync_timeout_s",
})

PRIVACY_FIELDS = frozenset({
    "clip_norm", "delta", "error_feedback", "fixedpoint_bits",
    "handshake_timeout_s", "mask_seed", "noise_multiplier", "noise_seed",
    "quantize", "secure_aggregation",
})

TELEMETRY_FIELDS = frozenset({
    "collector", "enable_tracing", "http_host", "http_port",
    "push_interval_ms", "span_batch", "stale_after_ms",
})

CHECKPOINT_FIELDS = frozenset({"base_dir", "keep"})

TENANCY_FIELDS = frozenset({
    "executor_quota", "fair_window_mb", "kv_block_quota", "max_wait_ms",
    "shm_ring_quota_mb", "weight",
})

LIVENESS_FIELDS = frozenset({
    "dead_after", "interval_ms", "suspect_after", "timeout_ms",
})

FAILOVER_FIELDS = frozenset({
    "enabled", "resync_window", "takeover_timeout_s",
})

#: AsyncAggregationConfig fields; the ``aggregation`` section spells them
#: with an ``async_`` prefix (``from_aggregation_dict``, config.py).
ASYNC_AGGREGATION_FIELDS = frozenset({
    "buffer_k", "max_staleness", "server_lr", "staleness",
    "staleness_exp", "suspect_factor",
})

AGGREGATION_SECTION_KEYS = frozenset({"topology", "group_size"}) | frozenset(
    f"async_{name}" for name in ASYNC_AGGREGATION_FIELDS
)

#: sections read directly by ``fed.init`` (api.py) rather than a config
#: dataclass — key sets mirror the ``dict.get`` calls there.
COLLECTIVE_SECTION_KEYS = frozenset({
    "coordinator", "inner_axes", "inner_shape", "init_timeout_s",
})
JAX_DISTRIBUTED_SECTION_KEYS = frozenset({
    "coordinator_address", "num_processes", "process_id",
})
KV_STORE_SECTION_KEYS = frozenset({"backend", "path"})
RESILIENCE_SECTION_KEYS = frozenset({"fault_schedule", "liveness"})

#: keys accepted at the top level of ``fed.init(config=...)``.
TOP_LEVEL_KEYS = frozenset({
    "aggregation", "barrier_on_initializing", "checkpoint", "collective",
    "cross_silo_comm", "jax_distributed", "kv_store", "membership",
    "party_mesh", "privacy", "resilience", "serving", "telemetry",
    "tenancy", "transport",
})

#: section name -> allowed keys in a literal dict value.
#: ``use_global_proxy`` is read straight off the cross_silo_comm dict by
#: api.py before from_dict sees it, so it is schema-legal there without
#: being a dataclass field.
SECTION_KEYS: Dict[str, FrozenSet[str]] = {
    "aggregation": AGGREGATION_SECTION_KEYS,
    "checkpoint": CHECKPOINT_FIELDS,
    "collective": COLLECTIVE_SECTION_KEYS,
    "cross_silo_comm": TCP_CROSS_SILO_FIELDS | {"use_global_proxy"},
    "jax_distributed": JAX_DISTRIBUTED_SECTION_KEYS,
    "kv_store": KV_STORE_SECTION_KEYS,
    "membership": MEMBERSHIP_FIELDS,
    "party_mesh": PARTY_MESH_FIELDS,
    "privacy": PRIVACY_FIELDS,
    "resilience": RESILIENCE_SECTION_KEYS,
    "serving": SERVING_FIELDS,
    "telemetry": TELEMETRY_FIELDS,
    "tenancy": TENANCY_FIELDS,
}

#: (section, key) -> schema for a nested literal dict value.
NESTED_SECTION_KEYS: Dict[Tuple[str, str], FrozenSet[str]] = {
    ("cross_silo_comm", "retry_policy"): RETRY_POLICY_FIELDS,
    ("membership", "failover"): FAILOVER_FIELDS,
    ("resilience", "liveness"): LIVENESS_FIELDS,
}

#: (section, key) whose values are free-form (per-party overlays, fault
#: schedules) — never descended into.
OPAQUE_SECTION_VALUES = frozenset({
    ("cross_silo_comm", "per_party_config"),
    ("resilience", "fault_schedule"),
})

#: config class name -> (module tail under rayfed_tpu, from_dict field
#: set). Drives the ``<Class>.from_dict({...})`` check.
CONFIG_CLASS_FIELDS: Dict[str, FrozenSet[str]] = {
    "CrossSiloMessageConfig": CROSS_SILO_BASE_FIELDS,
    "TcpCrossSiloMessageConfig": TCP_CROSS_SILO_FIELDS,
    "RetryPolicy": RETRY_POLICY_FIELDS,
    "PartyMeshConfig": PARTY_MESH_FIELDS,
    "ServingConfig": SERVING_FIELDS,
    "MembershipConfig": MEMBERSHIP_FIELDS,
    "PrivacyConfig": PRIVACY_FIELDS,
    "TelemetryConfig": TELEMETRY_FIELDS,
    "CheckpointConfig": CHECKPOINT_FIELDS,
    "LivenessConfig": LIVENESS_FIELDS,
    "FailoverConfig": FAILOVER_FIELDS,
    "TenancyConfig": TENANCY_FIELDS,
}


def section_schema(section: str) -> Optional[FrozenSet[str]]:
    return SECTION_KEYS.get(section)


def nested_schema(section: str, key: str) -> Optional[FrozenSet[str]]:
    return NESTED_SECTION_KEYS.get((section, key))
