# Copyright 2026 The rayfed-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Elastic membership: parties join, leave, and get replaced mid-training.

No reference equivalent — RayFed fixes the party set at ``fed.init`` for
the life of the job. This subsystem adds an epoch-based membership plane
on top of the existing inline lane (docs/membership.md):

- a membership view ``(epoch, roster, addresses)`` owned by a
  coordinator role at the root party (:mod:`.coordinator`);
- ``fed.join()`` / ``fed.leave()`` handshakes and per-party view state
  (:mod:`.manager`);
- wire frame shapes and the reserved ``mbr:*`` seq-id namespace
  (:mod:`.protocol`).

Every epoch bump re-keys the seq-id space (epoch-prefixed seq ids so a
rejoining party cannot collide with its pre-crash ghosts parked in
``rendezvous.RendezvousStore``), purges rendezvous entries from evicted
sources, updates the liveness monitor's peer set, and flows into the
async plane (``BufferedAggregator`` drops offers stamped with
evicted-epoch ghosts).
"""

from rayfed_tpu.membership.config import FailoverConfig, MembershipConfig
from rayfed_tpu.membership.coordinator import MembershipCoordinator
from rayfed_tpu.membership.manager import (
    MembershipManager,
    clear_membership_manager,
    get_membership_manager,
    set_membership_manager,
)
from rayfed_tpu.membership.view import MembershipView

__all__ = [
    "FailoverConfig",
    "MembershipConfig",
    "MembershipCoordinator",
    "MembershipManager",
    "MembershipView",
    "clear_membership_manager",
    "get_membership_manager",
    "set_membership_manager",
]
