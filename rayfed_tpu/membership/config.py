# Copyright 2026 The rayfed-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Membership-plane configuration (``config['membership']``)."""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional


@dataclasses.dataclass
class MembershipConfig:
    """Elastic-membership knobs (``config['membership']``, validated at
    ``fed.init`` so a typo'd key rejects init, not the first sync;
    docs/membership.md).

    Attributes:
        coordinator: the party owning the membership view (the
            coordinator role). None = the root party by the planner's
            convention: the lexicographically first party of the initial
            roster — identical on every driver, so every party elects
            the same coordinator without a message.
        auth_token: shared join credential. When set, a ``fed.join``
            handshake must present the identical token or the
            coordinator rejects it with code 403 — the same trust bar
            the ``cross_silo_comm`` identity config applies to data
            frames (mutual-TLS deployments get transport-level identity
            on top: a join request rides the data lane, so
            ``verify_peer_identity`` already attests its ``src``).
            None = any party that can reach the lane may join.
        evict_dead: escalate a liveness DEAD verdict at the coordinator
            to eviction at the next sync point (epoch bump, roster
            removal, rendezvous ghost purge).
        join_timeout_s: how long ``fed.join`` waits for the coordinator
            to admit it at a sync point before giving up.
        sync_timeout_s: how long a non-coordinator party waits for the
            coordinator's view broadcast at each ``fed.membership_sync``.
        bootstrap_dir: optional ``checkpoint.py`` base directory the
            coordinator serves join bootstrap state from (the latest
            ``step_<N>`` snapshot) when the driver registered no
            bootstrap provider.
    """

    coordinator: Optional[str] = None
    auth_token: Optional[str] = None
    evict_dead: bool = True
    join_timeout_s: float = 60.0
    sync_timeout_s: float = 60.0
    bootstrap_dir: Optional[str] = None

    def __post_init__(self) -> None:
        if float(self.join_timeout_s) <= 0:
            raise ValueError(
                f"membership.join_timeout_s must be > 0, got "
                f"{self.join_timeout_s}"
            )
        if float(self.sync_timeout_s) <= 0:
            raise ValueError(
                f"membership.sync_timeout_s must be > 0, got "
                f"{self.sync_timeout_s}"
            )

    @classmethod
    def from_dict(cls, data: Optional[Dict[str, Any]]) -> "MembershipConfig":
        """Strict construction: unknown keys raise (this section is new —
        there are no reference-written dicts to stay lenient for, and a
        silently dropped ``auth_token`` typo would be an open door)."""
        data = data or {}
        field_names = {f.name for f in dataclasses.fields(cls)}
        unknown = sorted(set(data) - field_names)
        if unknown:
            raise ValueError(
                f"unknown membership config key(s) {unknown}; known keys: "
                f"{sorted(field_names)}"
            )
        return cls(**data)

    def as_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)
