# Copyright 2026 The rayfed-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Membership-plane configuration (``config['membership']``)."""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional


@dataclasses.dataclass
class FailoverConfig:
    """Coordinator-failover knobs (``config['membership']['failover']``,
    strict like the parent section; docs/ha.md).

    Attributes:
        enabled: let a non-coordinator party take over the coordinator
            role when liveness declares the current coordinator DEAD
            mid-sync. Requires a running liveness monitor — without one
            every party reads ALIVE and failover never fires.
        takeover_timeout_s: how long a member waits on the current
            coordinator's sync broadcast before consulting liveness for
            a DEAD verdict. Lower = faster failover, higher = fewer
            spurious depositions on a slow-but-alive coordinator. The
            overall ``sync_timeout_s`` still bounds the whole wait.
        resync_window: how many recent agreed sync views each party
            retains for takeover re-broadcast. A new coordinator re-sends
            these VERBATIM under its term for members trailing at older
            indices, so every sync index maps to exactly one view on
            every party even across a failover.
    """

    enabled: bool = True
    takeover_timeout_s: float = 5.0
    resync_window: int = 2

    def __post_init__(self) -> None:
        if float(self.takeover_timeout_s) <= 0:
            raise ValueError(
                f"membership.failover.takeover_timeout_s must be > 0, got "
                f"{self.takeover_timeout_s}"
            )
        if int(self.resync_window) < 1:
            raise ValueError(
                f"membership.failover.resync_window must be >= 1, got "
                f"{self.resync_window}"
            )

    @classmethod
    def from_dict(cls, data: Optional[Dict[str, Any]]) -> "FailoverConfig":
        data = data or {}
        field_names = {f.name for f in dataclasses.fields(cls)}
        unknown = sorted(set(data) - field_names)
        if unknown:
            raise ValueError(
                f"unknown membership.failover config key(s) {unknown}; "
                f"known keys: {sorted(field_names)}"
            )
        return cls(**data)


@dataclasses.dataclass
class MembershipConfig:
    """Elastic-membership knobs (``config['membership']``, validated at
    ``fed.init`` so a typo'd key rejects init, not the first sync;
    docs/membership.md).

    Attributes:
        coordinator: the party owning the membership view (the
            coordinator role). None = the root party by the planner's
            convention: the lexicographically first party of the initial
            roster — identical on every driver, so every party elects
            the same coordinator without a message.
        auth_token: shared join credential. When set, a ``fed.join``
            handshake must present the identical token or the
            coordinator rejects it with code 403 — the same trust bar
            the ``cross_silo_comm`` identity config applies to data
            frames (mutual-TLS deployments get transport-level identity
            on top: a join request rides the data lane, so
            ``verify_peer_identity`` already attests its ``src``).
            None = any party that can reach the lane may join.
        evict_dead: escalate a liveness DEAD verdict at the coordinator
            to eviction at the next sync point (epoch bump, roster
            removal, rendezvous ghost purge).
        join_timeout_s: how long ``fed.join`` waits for the coordinator
            to admit it at a sync point before giving up.
        sync_timeout_s: how long a non-coordinator party waits for the
            coordinator's view broadcast at each ``fed.membership_sync``.
        bootstrap_dir: optional ``checkpoint.py`` base directory the
            coordinator serves join bootstrap state from (the latest
            ``step_<N>`` snapshot) when the driver registered no
            bootstrap provider.
        failover: nested :class:`FailoverConfig` (coordinator takeover
            on a liveness DEAD verdict; docs/ha.md).
    """

    coordinator: Optional[str] = None
    auth_token: Optional[str] = None
    evict_dead: bool = True
    join_timeout_s: float = 60.0
    sync_timeout_s: float = 60.0
    bootstrap_dir: Optional[str] = None
    failover: FailoverConfig = dataclasses.field(default_factory=FailoverConfig)

    def __post_init__(self) -> None:
        if float(self.join_timeout_s) <= 0:
            raise ValueError(
                f"membership.join_timeout_s must be > 0, got "
                f"{self.join_timeout_s}"
            )
        if float(self.sync_timeout_s) <= 0:
            raise ValueError(
                f"membership.sync_timeout_s must be > 0, got "
                f"{self.sync_timeout_s}"
            )

    @classmethod
    def from_dict(cls, data: Optional[Dict[str, Any]]) -> "MembershipConfig":
        """Strict construction: unknown keys raise (this section is new —
        there are no reference-written dicts to stay lenient for, and a
        silently dropped ``auth_token`` typo would be an open door)."""
        data = data or {}
        field_names = {f.name for f in dataclasses.fields(cls)}
        unknown = sorted(set(data) - field_names)
        if unknown:
            raise ValueError(
                f"unknown membership config key(s) {unknown}; known keys: "
                f"{sorted(field_names)}"
            )
        kwargs = dict(data)
        failover = kwargs.pop("failover", None)
        if isinstance(failover, FailoverConfig):
            kwargs["failover"] = failover
        elif failover is not None:
            if not isinstance(failover, dict):
                raise ValueError(
                    "membership.failover must be a dict, got "
                    f"{type(failover).__name__}"
                )
            kwargs["failover"] = FailoverConfig.from_dict(failover)
        return cls(**kwargs)

    def as_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)
