# Copyright 2026 The rayfed-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""The coordinator role: pending membership changes and the sync fold.

Lives on exactly one party (``membership.coordinator``, defaulting to
the root party). Requests arrive asynchronously — join/leave control
frames on the transport thread (dispatched by the rendezvous store's
control handler), DEAD verdicts from the liveness monitor's tick thread
— and are only *queued* there; the roster changes exactly at the next
``fed.membership_sync()`` on the driver thread, where the fold computes
one successor view, broadcasts it to the old roster, and sends each
admitted joiner its JoinAccept. Folding at the sync point (not at
arrival) is what keeps the multi-controller contract intact: every
party applies the same bump at the same program point.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Dict, List, Tuple

from rayfed_tpu import tracing
from rayfed_tpu._private.constants import CODE_FORBIDDEN, CODE_OK
from rayfed_tpu.membership import protocol

logger = logging.getLogger(__name__)


class MembershipCoordinator:
    """Pending-change queue + sync-point fold (see module docstring)."""

    def __init__(self, manager) -> None:
        self._manager = manager
        self._lock = threading.Lock()
        # party -> join request dict (party-keyed so BOTH retry shapes
        # collapse to one admission: a retransmit with the same nonce
        # and a fresh-nonce retry after a timed-out handshake. The
        # latest nonce wins — exactly one JoinAccept goes out, addressed
        # to the request the joiner is still parked on).
        self._pending_joins: Dict[str, Dict] = {}
        self._pending_leaves: set = set()
        self._pending_evictions: set = set()
        self.stats: Dict[str, int] = {
            "joins_accepted": 0,
            "joins_rejected": 0,
            "leaves": 0,
            "evictions": 0,
            "epoch_bumps": 0,
        }

    # -- intake (transport / monitor threads) --------------------------

    def handle_control(self, header: Dict, value) -> Tuple[int, str]:
        """Dispatch one ``mbr:req:*`` control frame; the returned code
        rides the frame's ack (403 fails the sender's future). A request
        stamped with a HIGHER term than ours is proof we were deposed
        while partitioned: demote and refuse, naming the successor."""
        if isinstance(value, dict):
            req_term = int(value.get("term") or 0)
            if req_term > self._manager.term():
                self._manager.adopt_term(req_term, None)
                if not self._manager.is_coordinator():
                    return CODE_FORBIDDEN, (
                        f"this party was deposed as coordinator at term "
                        f"{req_term}; re-offer the request to "
                        f"{self._manager.coordinator()!r}"
                    )
        up = header.get("up", "")
        if up == protocol.JOIN_REQ_SEQ:
            return self._handle_join(value)
        if up == protocol.LEAVE_REQ_SEQ:
            return self._handle_leave(value)
        return CODE_FORBIDDEN, f"unknown membership request {up!r}"

    def _handle_join(self, req) -> Tuple[int, str]:
        if not isinstance(req, dict) or req.get("kind") != "join":
            return CODE_FORBIDDEN, "malformed join request"
        party, address, nonce = (
            req.get("party"), req.get("address"), req.get("nonce"),
        )
        if not party or not address or not nonce:
            return CODE_FORBIDDEN, "join request missing party/address/nonce"
        expected = self._manager.config.auth_token
        if expected is not None and req.get("token") != expected:
            with self._lock:
                self.stats["joins_rejected"] += 1
            logger.warning(
                "membership: rejecting join from %r (bad auth token)", party
            )
            return CODE_FORBIDDEN, "membership auth token mismatch"
        with self._lock:
            self._pending_joins[party] = {
                "party": party, "address": address, "nonce": nonce,
            }
        logger.info(
            "membership: queued join of %r (admitted at next sync)", party
        )
        return CODE_OK, "join queued"

    def _handle_leave(self, req) -> Tuple[int, str]:
        if not isinstance(req, dict) or req.get("kind") != "leave":
            return CODE_FORBIDDEN, "malformed leave request"
        party = req.get("party")
        if not party:
            return CODE_FORBIDDEN, "leave request missing party"
        with self._lock:
            # A retransmitted leave (ack lost, sender retried) must not
            # inflate the stat: count only the first insertion.
            if party not in self._pending_leaves:
                self._pending_leaves.add(party)
                self.stats["leaves"] += 1
        logger.info(
            "membership: queued departure of %r (removed at next sync)",
            party,
        )
        return CODE_OK, "leave queued"

    def note_dead(self, party: str) -> None:
        """Liveness DEAD escalation (monitor tick thread): queue the
        eviction; the roster change lands at the next sync."""
        if party not in self._manager.roster():
            return
        with self._lock:
            if party in self._pending_evictions:
                return
            self._pending_evictions.add(party)
        logger.warning(
            "membership: party %r is DEAD — evicting at next sync", party
        )

    def pending(self) -> Dict[str, List[str]]:
        with self._lock:
            return {
                "joins": sorted(
                    j["party"] for j in self._pending_joins.values()
                ),
                "leaves": sorted(self._pending_leaves),
                "evictions": sorted(self._pending_evictions),
            }

    # -- the sync-point fold (driver thread) ---------------------------

    def run_sync(self, sync_index: int):
        """Fold pending changes into a successor view, broadcast it to
        the old roster at ``("mbr:sync", sync_index)``, apply it locally,
        then send each admitted joiner its JoinAccept. Returns the
        (possibly unchanged) applied view."""
        from rayfed_tpu.proxy import barriers

        manager = self._manager
        with self._lock:
            joins = list(self._pending_joins.values())
            self._pending_joins.clear()
            leaves = set(self._pending_leaves)
            self._pending_leaves.clear()
            evictions = set(self._pending_evictions)
            self._pending_evictions.clear()

        old_view = manager.view()
        # A party both joining and leaving/evicted in one window: the
        # explicit removal wins (its new incarnation can re-request); a
        # removal of a non-member is a no-op.
        remove_requested = (leaves | evictions) & set(old_view.roster)
        admitted = {
            j["party"]: j["address"]
            for j in joins
            if j["party"] not in remove_requested
        }
        # A join whose name is ALREADY in the roster is a rejoin: the
        # previous incarnation crashed and restarted before a liveness
        # eviction caught up (impostors are the auth token's problem).
        # Fold it as an implicit evict-then-admit — the epoch MUST bump
        # even when the address is unchanged, so every member purges the
        # pre-crash ghosts, cycles the connection, and the new admission
        # epoch outdates the old incarnation's frames. The joiner itself
        # gets the view from its JoinAccept, never the sync broadcast.
        rejoining = set(admitted) & set(old_view.roster)
        remove = remove_requested | rejoining
        accepted = [j for j in joins if j["party"] in admitted]
        new_view = old_view.with_changes(
            admitted, remove_requested, force_bump=bool(rejoining)
        )
        changed = new_view.epoch != old_view.epoch
        evicted_stamp = (
            {p: new_view.epoch for p in sorted(remove)} if changed else {}
        )
        # Full post-bump ghost tables ride every sync: a member that
        # missed an intermediate bump (recv timeout, lost frame) still
        # reconciles to complete state, not just this bump's delta.
        admissions_tbl, evictions_tbl = manager.ghost_tables()
        if changed:
            for p in remove:
                evictions_tbl[p] = new_view.epoch
                admissions_tbl.pop(p, None)
            for p in admitted:
                admissions_tbl[p] = new_view.epoch
                evictions_tbl.pop(p, None)
        term = manager.term()
        msg = protocol.make_sync(
            new_view.to_wire(), sync_index,
            admitted if changed else {}, evicted_stamp,
            admissions_tbl, evictions_tbl,
            term=term, coordinator=manager.self_party,
        )
        # Broadcast to the OLD roster (minus self, minus the removed):
        # those parties are parked at the same sync point; post-failover
        # terms qualify the key so a deposed predecessor's frame can
        # never have consumed the slot. Joiners learn the view from
        # their JoinAccept instead.
        down_key = protocol.sync_down_key(sync_index, term)
        for p in old_view.roster:
            if p == manager.self_party or p in remove:
                continue
            barriers.send(p, msg, protocol.SYNC_SEQ, down_key)
        with manager._lock:
            manager._record_sync_locked(sync_index, msg)
        if changed:
            applied = manager.apply_sync_msg(msg)
            with self._lock:
                self.stats["epoch_bumps"] += 1
                self.stats["joins_accepted"] += len(accepted)
                self.stats["evictions"] += len(evictions & remove)
        else:
            applied = old_view
        # Accepts AFTER the local apply: the joiner's address is admitted
        # into our sender proxy by the apply, and the ghost tables the
        # accept carries include this very bump.
        if accepted:
            bootstrap = manager.make_bootstrap()
            for j in accepted:
                barriers.send(
                    j["party"],
                    protocol.make_join_accept(
                        applied.to_wire(), sync_index,
                        admissions_tbl, evictions_tbl, bootstrap,
                        term=term,
                    ),
                    protocol.RESPONSE_SEQ,
                    j["nonce"],
                )
                tracing.record(
                    "membership", j["party"],
                    f"epoch:{old_view.epoch}", f"epoch:{applied.epoch}",
                    0, time.perf_counter(), event="admit",
                )
        return applied

    # -- liveness-triggered takeover (HA) ------------------------------

    def run_takeover(self, sync_index: int):
        """First sync after this party won a failover election. Before
        the term-``sync_index`` fold, re-broadcast the retained recent
        sync views VERBATIM (term restamped) at their new-term keys:
        a member whose previous recv failed rolled its index back and is
        re-waiting an OLDER sync point — it must receive the exact view
        the old coordinator agreed there, not our post-takeover fold,
        or rosters diverge per-round across the fleet. Then fold at
        ``sync_index`` as usual, which lands the deposed predecessor's
        eviction and replays every re-offered join/leave."""
        from rayfed_tpu.proxy import barriers

        manager = self._manager
        term = manager.term()
        recent = manager.recent_syncs()
        roster = manager.roster()
        with self._lock:
            pending_remove = set(self._pending_leaves) | set(
                self._pending_evictions
            )
        for idx in sorted(recent):
            if idx >= sync_index:
                continue
            msg = dict(recent[idx])
            msg["term"] = term
            msg["coordinator"] = manager.self_party
            down_key = protocol.sync_down_key(idx, term)
            for p in roster:
                if p == manager.self_party or p in pending_remove:
                    continue
                barriers.send(p, msg, protocol.SYNC_SEQ, down_key)
        tracing.record(
            "failover", manager.self_party, f"sync:{sync_index}",
            f"term:{term}", 0, time.perf_counter(), event="takeover",
            resync=sorted(i for i in recent if i < sync_index),
        )
        logger.warning(
            "membership takeover: %r coordinating from sync %d at term "
            "%d (re-broadcast %s)", manager.self_party, sync_index, term,
            sorted(i for i in recent if i < sync_index),
        )
        return self.run_sync(sync_index)
