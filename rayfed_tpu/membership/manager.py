# Copyright 2026 The rayfed-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Per-party membership state and the join/leave/sync driving logic.

One :class:`MembershipManager` lives on every party of a membership-
enabled job (module singleton, wired by ``fed.init`` /  ``fed.join``).
It owns the party's copy of the agreed view, the ghost tables
(admission/eviction epochs per party), and the side effects an epoch
bump applies to the rest of the engine:

- cluster-config addresses (KV + module cache) — which parties a
  ``fed.get`` owner-push fans out to;
- sender-proxy peer set (``barriers.admit_peer`` / ``forget_peer``) —
  which destinations the reactor pool will dial;
- liveness monitor peer set;
- rendezvous ghost purge (``rendezvous.evict_source_everywhere``);
- the seq-id space: the driver-side counter resets to 0 and the barrier
  layer stamps subsequent integer seq ids with the new epoch, so a
  rejoining party can never collide with its pre-crash ghosts.

The sync protocol (``fed.membership_sync()``, one call per round
boundary on EVERY party — a seq-id-free collective): the coordinator
folds its pending joins/leaves/evictions into a successor view and
broadcasts it at the deterministic key ``("mbr:sync", sync_index)``;
every other party recvs that key. The sync index is a per-driver
monotonic counter advanced identically on all parties (multi-controller
contract), and it is never reset — unlike data seq ids it survives epoch
bumps, so a joiner admitted at sync S knows to recv sync S+1 next.
"""

# fedlint: disable-file=seq-divergence
# Membership is asymmetric by design: the coordinator broadcasts
# epoch bumps and collects acks while followers only respond, so
# sends/gets here are necessarily gated on the local role. Control
# traffic rides reserved ctl: seq ids outside the data DAG;
# FED002's lockstep rule is for drivers, not the control plane.

from __future__ import annotations

import logging
import pickle
import threading
import time
from concurrent.futures import TimeoutError as FuturesTimeout
from typing import Any, Callable, Dict, Optional, Tuple

import rayfed_tpu._private.constants as constants
import rayfed_tpu.config as fed_config
from rayfed_tpu import tracing
from rayfed_tpu._private import kv as internal_kv
from rayfed_tpu._private.global_context import get_global_context
from rayfed_tpu.exceptions import StaleCoordinatorError
from rayfed_tpu.membership import protocol
from rayfed_tpu.membership.config import MembershipConfig
from rayfed_tpu.membership.view import MembershipView
from rayfed_tpu.telemetry import metrics as telemetry_metrics

logger = logging.getLogger(__name__)

_m_epoch = telemetry_metrics.get_registry().gauge(
    "fed_membership_epoch",
    "This party's applied membership epoch.",
)
_m_roster_size = telemetry_metrics.get_registry().gauge(
    "fed_membership_roster_size",
    "Parties in this party's applied roster.",
)
_m_term = telemetry_metrics.get_registry().gauge(
    "fed_membership_coordinator_term",
    "This party's adopted coordinator term (0 = configured coordinator, "
    "bumped once per failover).",
)
_m_failovers = telemetry_metrics.get_registry().counter(
    "fed_membership_failovers_total",
    "Coordinator depositions this party adopted (term bumps).",
)
_m_stale_syncs = telemetry_metrics.get_registry().counter(
    "fed_membership_stale_syncs_rejected_total",
    "Sync broadcasts rejected because their term predates the adopted "
    "term (a deposed coordinator's stale view).",
)


def resolve_coordinator(config: MembershipConfig, roster) -> str:
    """The coordinator party: configured name, else the root party by the
    planner's convention (lexicographically first of the initial roster) —
    identical on every driver, so every party elects the same coordinator
    without a message."""
    if config.coordinator is not None:
        return config.coordinator
    return sorted(roster)[0]


class MembershipManager:
    """This party's membership-plane state (see module docstring)."""

    def __init__(
        self,
        job_name: str,
        self_party: str,
        view: MembershipView,
        config: Optional[MembershipConfig] = None,
        *,
        sync_index: int = 0,
        admissions: Optional[Dict[str, int]] = None,
        evictions: Optional[Dict[str, int]] = None,
        term: int = 0,
    ) -> None:
        self._job_name = job_name
        self._self_party = self_party
        self._config = config or MembershipConfig()
        self._lock = threading.RLock()
        self._view = view
        _m_epoch.set(view.epoch)
        _m_roster_size.set(len(view.roster))
        self._sync_index = int(sync_index)
        # Coordinator term (HA): bumped once per failover, carried in
        # every sync/request frame and in the sync rendezvous key. The
        # deposed chain records every coordinator this party stopped
        # trusting; elections pick sorted(roster - deposed)[0], which is
        # deterministic because liveness never enters the CHOICE — it
        # only decides WHEN a member gives up on the current holder.
        self._term = int(term)
        _m_term.set(self._term)
        self._deposed: set = set()
        # Recent agreed sync broadcasts ({sync_index: msg}, bounded by
        # failover.resync_window): a takeover coordinator re-sends these
        # VERBATIM (term restamped) for members trailing at older
        # indices, so every sync index maps to one view on every party.
        self._recent_syncs: Dict[int, Dict] = {}
        self._ha_stats: Dict[str, int] = {
            "failovers": 0,
            "takeovers": 0,
            "stale_syncs_rejected": 0,
        }
        # In-flight sync/takeover counter: fed.shutdown drains this so a
        # job shutting down during a failover exits cleanly instead of
        # tearing proxies out from under a mid-broadcast takeover.
        self._inflight = 0
        self._drain_cond = threading.Condition(self._lock)
        # Ghost tables. A party's ADMISSION epoch is the epoch of the
        # bump that added it (0 for the initial roster); its EVICTION
        # epoch is the epoch as of which it is out. An offer stamped
        # with epoch e from party p is a ghost iff p is not in the
        # roster, or e predates p's current incarnation (p rejoined
        # after a crash and e belongs to the pre-crash self).
        self._admissions: Dict[str, int] = dict(admissions or {})
        self._evictions: Dict[str, int] = dict(evictions or {})
        self._coordinator_name = resolve_coordinator(self._config, view.roster)
        self._bootstrap_provider: Optional[Callable[[], Any]] = None
        # The coordinator party's pending-change state; None elsewhere.
        self._coordinator = None
        if self._coordinator_name == self_party:
            from rayfed_tpu.membership.coordinator import (
                MembershipCoordinator,
            )

            self._coordinator = MembershipCoordinator(self)

    # -- queries -------------------------------------------------------

    @property
    def job_name(self) -> str:
        return self._job_name

    @property
    def self_party(self) -> str:
        return self._self_party

    @property
    def config(self) -> MembershipConfig:
        return self._config

    def view(self) -> MembershipView:
        with self._lock:
            return self._view

    def current_epoch(self) -> int:
        """Registered as the barrier layer's seq-epoch hook: every
        integer seq id sent or received while this manager is installed
        is stamped ``e<epoch>:<n>``."""
        with self._lock:
            return self._view.epoch

    def roster(self) -> Tuple[str, ...]:
        with self._lock:
            return self._view.roster

    def sync_index(self) -> int:
        with self._lock:
            return self._sync_index

    def coordinator(self) -> str:
        with self._lock:
            return self._coordinator_name

    def is_coordinator(self) -> bool:
        return self._coordinator is not None

    def get_coordinator_state(self):
        return self._coordinator

    def term(self) -> int:
        with self._lock:
            return self._term

    def ha_stats(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._ha_stats)

    def is_ghost(self, party: str, epoch: Optional[int]) -> bool:
        """True when an offer stamped ``epoch`` from ``party`` belongs to
        an evicted incarnation (see the ghost-table comment in
        ``__init__``). ``epoch=None`` (a pre-membership driver) is never
        a ghost unless the party itself is out of the roster."""
        with self._lock:
            if party not in self._view.roster:
                return True
            if epoch is None:
                return False
            return int(epoch) < int(self._admissions.get(party, 0))

    def ghost_tables(self) -> Tuple[Dict[str, int], Dict[str, int]]:
        with self._lock:
            return dict(self._admissions), dict(self._evictions)

    def eviction_table(self) -> Dict[str, int]:
        """Snapshot of the eviction ghost table (party -> epoch as of
        which it is out). The rendezvous expire loop sweeps parked
        frames from exactly these sources — NOT from "anyone outside the
        roster", which would reap a fresh joiner's early frames on a
        member that has not applied the admitting sync yet."""
        with self._lock:
            return dict(self._evictions)

    def plan(self, topology: Optional[str] = None,
             group_size: Optional[int] = None):
        """The aggregation plan over the CURRENT roster — what
        ``fed_aggregate`` lowers to after this epoch's re-plan. Bitwise
        identical to a fresh ``topology.plan`` over the same roster
        (pinned by tests/test_membership.py)."""
        from rayfed_tpu import topology as topo

        with self._lock:
            parties = list(self._view.roster)
        return topo.plan(
            parties,
            topology or topo.get_default()[0],
            group_size=group_size or topo.get_default()[1],
        )

    # -- bootstrap -----------------------------------------------------

    def set_bootstrap_provider(self, fn: Optional[Callable[[], Any]]) -> None:
        """Register the callable whose return value rides each
        JoinAccept as the joiner's bootstrap state. Return BOTH the
        model and the optimizer state (e.g. ``{"model": params,
        "opt_state": opt_state, "round": r}``) — a replacement party
        bootstrapped without optimizer state resumes inference, not
        training. Overrides the ``bootstrap_dir`` checkpoint fallback
        and the live ModelBank fallback."""
        self._bootstrap_provider = fn

    def make_bootstrap(self) -> Any:
        """Bootstrap state for a JoinAccept, by priority: the registered
        provider, else the newest ``checkpoint.py`` snapshot under
        ``membership.bootstrap_dir``, else the newest live ModelBank
        version on this party, else None.

        The checkpoint kind INLINES the snapshot's model and optimizer
        state (plus the pointer for anything else in the cut): a
        replacement joiner must resume training from the same optimizer
        trajectory, not restart momentum from zero against a trained
        model."""
        if self._bootstrap_provider is not None:
            return {"kind": "provider", "state": self._bootstrap_provider()}
        if self._config.bootstrap_dir:
            try:
                from rayfed_tpu import checkpoint

                step = checkpoint.latest_step(self._config.bootstrap_dir)
                if step is not None:
                    path = checkpoint.step_dir(
                        self._config.bootstrap_dir, step
                    )
                    payload = {
                        "kind": "checkpoint",
                        "base_dir": self._config.bootstrap_dir,
                        "step": int(step),
                        "path": path,
                    }
                    try:
                        meta = checkpoint.load_meta(path)
                        if meta.get("kind") == "job":
                            restored = checkpoint.restore_job_state(
                                self._config.bootstrap_dir, step=int(step),
                                install=False,
                            )
                            payload["model"] = restored["model"]
                            payload["opt_state"] = restored["opt_state"]
                        else:
                            state = checkpoint.restore_party_state(path)
                            if isinstance(state, dict):
                                payload["model"] = state.get("model", state)
                                payload["opt_state"] = state.get("opt_state")
                            else:
                                payload["model"] = state
                    except Exception:  # noqa: BLE001 - pointer-only
                        # fallback: the joiner can still read the dir
                        logger.warning(
                            "membership: could not inline checkpoint "
                            "bootstrap state (sending pointer only)",
                            exc_info=True,
                        )
                    return payload
            except Exception:  # noqa: BLE001 - bootstrap is best-effort
                logger.warning(
                    "membership: checkpoint bootstrap lookup failed",
                    exc_info=True,
                )
        import sys as _sys

        server_mod = _sys.modules.get("rayfed_tpu.serving.server")
        if server_mod is not None:
            try:
                for name in sorted(server_mod._servers):
                    bank = server_mod._servers[name].bank
                    if bank.current_version() > 0:
                        version, params = bank.acquire()
                        try:
                            return {
                                "kind": "model_bank",
                                "serve_name": name,
                                "version": int(version),
                                "params": params,
                            }
                        finally:
                            bank.release(version)
            except Exception:  # noqa: BLE001 - bootstrap is best-effort
                logger.warning(
                    "membership: ModelBank bootstrap lookup failed",
                    exc_info=True,
                )
        return None

    # -- engine wiring -------------------------------------------------

    def install(self) -> None:
        """Register this manager's hooks with the rest of the engine:
        the barrier layer's seq-epoch stamp, the rendezvous eviction
        table (for ghost expiry), and — on the coordinator — the
        control-frame handler and the liveness DEAD escalation."""
        from rayfed_tpu.proxy import barriers, rendezvous

        barriers.set_seq_epoch_fn(self.current_epoch)
        rendezvous.set_evicted_fn(self._job_name, self.eviction_table)
        if self._coordinator is not None:
            rendezvous.set_control_handler(
                self._job_name, self._coordinator.handle_control
            )
            from rayfed_tpu.resilience import liveness

            monitor = liveness.get_monitor()
            if monitor is not None and self._config.evict_dead:
                monitor.set_on_dead(self._coordinator.note_dead)

    def uninstall(self) -> None:
        from rayfed_tpu.proxy import barriers, rendezvous

        barriers.clear_seq_epoch_fn()
        rendezvous.clear_evicted_fn(self._job_name)
        rendezvous.clear_control_handler(self._job_name)
        from rayfed_tpu.resilience import liveness

        monitor = liveness.get_monitor()
        if monitor is not None:
            monitor.set_on_dead(None)

    # -- the sync point ------------------------------------------------

    def membership_sync(
        self, timeout: Optional[float] = None
    ) -> MembershipView:
        """One membership sync: every roster party calls this at the
        same program point (a round boundary). Advances the sync index,
        then either folds-and-broadcasts (coordinator) or receives-and-
        applies (member). Consumes NO data seq ids — the sync key is the
        string pair ``("mbr:sync", <sync_index>)``."""
        with self._lock:
            self._sync_index += 1
            idx = self._sync_index
            self._inflight += 1
        try:
            if self._coordinator is not None:
                return self._coordinator.run_sync(idx)
            return self._member_sync(idx, timeout)
        finally:
            with self._lock:
                self._inflight -= 1
                self._drain_cond.notify_all()

    def _member_sync(
        self, idx: int, timeout: Optional[float]
    ) -> MembershipView:
        """The member side of one sync: wait on the coordinator's
        broadcast in ``failover.takeover_timeout_s`` slices; when a slice
        expires AND liveness says the coordinator is DEAD, depose it,
        adopt the next term, and either promote (we are the deterministic
        successor) or re-park at the successor's term-qualified key. The
        overall ``sync_timeout_s`` still bounds the whole wait, and a
        final failure still rolls the sync index back so a retry re-waits
        the SAME sync point."""
        from rayfed_tpu.proxy import barriers
        from rayfed_tpu.resilience import liveness

        fo = self._config.failover
        total = timeout if timeout is not None else self._config.sync_timeout_s
        deadline = time.monotonic() + total
        fut = None
        fut_key = None
        try:
            while True:
                with self._lock:
                    coord = self._coordinator_name
                    term = self._term
                key = protocol.sync_down_key(idx, term)
                if fut_key != (coord, key):
                    # One parked waiter per (coordinator, key): only a
                    # term change re-parks, so waiters never pile up.
                    fut = barriers.recv(
                        self._self_party, coord, protocol.SYNC_SEQ, key
                    )
                    fut_key = (coord, key)
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise FuturesTimeout(
                        f"membership sync {idx} timed out after {total}s "
                        f"(coordinator {coord!r}, term {term})"
                    )
                slice_s = remaining
                if fo.enabled:
                    slice_s = min(remaining, float(fo.takeover_timeout_s))
                try:
                    msg = fut.result(timeout=slice_s)
                except (FuturesTimeout, TimeoutError):
                    # Slice expired — or the rendezvous store expired the
                    # parked waiter at its own recv deadline (the future
                    # itself failed; a fresh recv re-parks it).
                    if fut.done():
                        fut_key = None
                    if (
                        fo.enabled
                        and liveness.party_state(coord) == liveness.DEAD
                    ):
                        self._failover_elect(coord)
                        if self._coordinator is not None:
                            return self._coordinator.run_takeover(idx)
                    continue
                with self._lock:
                    self._record_sync_locked(idx, msg)
                return self.apply_sync_msg(msg)
        except BaseException:
            # The sync did NOT land: roll the index back so a retry
            # re-waits the SAME key (the coordinator's broadcast for it
            # may still be in flight and will park). Without this, the
            # index is consumed and the retry skips straight to the next
            # sync's key, leaving this one permanently unapplied.
            with self._lock:
                if self._sync_index == idx:
                    self._sync_index = idx - 1
            raise

    # -- coordinator failover ------------------------------------------

    def _record_sync_locked(self, idx: int, msg: Dict) -> None:
        self._recent_syncs[int(idx)] = msg
        window = int(self._config.failover.resync_window)
        for old in sorted(self._recent_syncs):
            if len(self._recent_syncs) <= window:
                break
            del self._recent_syncs[old]

    def recent_syncs(self) -> Dict[int, Dict]:
        with self._lock:
            return dict(self._recent_syncs)

    def _failover_elect(self, dead_coord: str) -> str:
        """Depose ``dead_coord``: adopt the next term and elect the
        deterministic successor — sorted(roster − deposed chain)[0].
        Liveness gates WHEN this runs, never WHO wins, so every survivor
        that deposes term T elects the identical term-T+1 coordinator
        without a message. Promotes this party (control handler, DEAD
        escalation, eviction of the deposed holder) when the election
        lands on us. Returns the successor's name."""
        from rayfed_tpu.proxy import rendezvous
        from rayfed_tpu.resilience import liveness

        promote = False
        with self._lock:
            if self._coordinator_name != dead_coord:
                return self._coordinator_name
            self._deposed.add(dead_coord)
            candidates = sorted(set(self._view.roster) - self._deposed)
            if not candidates:
                raise RuntimeError(
                    "membership failover: no candidate left for the "
                    "coordinator role (every roster party is deposed)"
                )
            old_term = self._term
            self._term += 1
            self._coordinator_name = candidates[0]
            successor = self._coordinator_name
            self._ha_stats["failovers"] += 1
            _m_term.set(self._term)
            _m_failovers.inc()
            if successor == self._self_party and self._coordinator is None:
                from rayfed_tpu.membership.coordinator import (
                    MembershipCoordinator,
                )

                self._coordinator = MembershipCoordinator(self)
                self._ha_stats["takeovers"] += 1
                promote = True
            new_term = self._term
        tracing.record(
            "failover", dead_coord, f"term:{old_term}", f"term:{new_term}",
            0, time.perf_counter(), event="depose", successor=successor,
        )
        logger.warning(
            "membership failover: coordinator %r is DEAD — term %d -> %d, "
            "successor %r%s", dead_coord, old_term, new_term, successor,
            " (this party takes over)" if promote else "",
        )
        if promote:
            coordinator = self._coordinator
            rendezvous.set_control_handler(
                self._job_name, coordinator.handle_control
            )
            monitor = liveness.get_monitor()
            if monitor is not None and self._config.evict_dead:
                monitor.set_on_dead(coordinator.note_dead)
            if self._config.evict_dead:
                # The deposed holder leaves the roster at our first sync
                # as coordinator — the takeover bump.
                coordinator.note_dead(dead_coord)
        return successor

    def adopt_term(self, term: int, coordinator: Optional[str]) -> None:
        """Adopt a HIGHER term learned from a frame (a sync or request
        stamped ahead of us): record the deposition we missed and track
        the sender's coordinator. A coordinator that learns of its own
        deposition this way demotes — it stops folding; its own stale
        broadcasts are rejected by every member's term check anyway."""
        with self._lock:
            if int(term) <= self._term:
                return
            old_term = self._term
            self._term = int(term)
            _m_term.set(self._term)
            _m_failovers.inc()
            self._ha_stats["failovers"] += 1
            if coordinator is None:
                # The frame proves a deposition happened but not who
                # won: depose the current holder and elect from the
                # chain — the same deterministic choice the deposers
                # made, so it names the same winner.
                self._deposed.add(self._coordinator_name)
                candidates = sorted(set(self._view.roster) - self._deposed)
                coordinator = (
                    candidates[0] if candidates else self._self_party
                )
            demoted = False
            if coordinator != self._self_party:
                if self._coordinator_name != coordinator:
                    self._deposed.add(self._coordinator_name)
                    self._coordinator_name = coordinator
                demoted = self._coordinator is not None
                if demoted:
                    self._coordinator = None
        if demoted:
            from rayfed_tpu.proxy import rendezvous

            rendezvous.clear_control_handler(self._job_name)
            logger.warning(
                "membership failover: this party was deposed as "
                "coordinator (term %d -> %d, successor %r)",
                old_term, term, coordinator,
            )
        else:
            logger.info(
                "membership failover: adopted term %d (coordinator %r)",
                term, coordinator,
            )

    # -- checkpoint cut (docs/ha.md) -----------------------------------

    def export_snapshot(self) -> Dict[str, Any]:
        """This party's membership state for a job checkpoint cut: the
        agreed view, the never-reset sync index, the adopted term and
        deposed chain, and the full ghost tables. Wire/JSON-clean."""
        with self._lock:
            return {
                "view": self._view.to_wire(),
                "sync_index": self._sync_index,
                "term": self._term,
                "deposed": sorted(self._deposed),
                "admissions": dict(self._admissions),
                "evictions": dict(self._evictions),
            }

    def restore_snapshot(self, snap: Dict[str, Any]) -> None:
        """Fast-forward this manager to a checkpointed cut. Only state
        AT or AHEAD of ours applies (sync index, term, epoch) — a
        restart re-inits at epoch 0/term 0 and then replays the cut, so
        every restored party resumes with the identical epoch stamp,
        sync key, and ghost tables it checkpointed with."""
        view = MembershipView.from_wire(snap["view"])
        promote = False
        with self._lock:
            if int(snap.get("sync_index", 0)) > self._sync_index:
                self._sync_index = int(snap["sync_index"])
            if int(snap.get("term", 0)) > self._term:
                self._term = int(snap["term"])
                _m_term.set(self._term)
            self._deposed |= set(snap.get("deposed") or ())
            if self._term > 0:
                # Post-failover cut: the election result, not the
                # configured name, is the coordinator going forward.
                candidates = sorted(set(view.roster) - self._deposed)
                if candidates:
                    self._coordinator_name = candidates[0]
            if view.epoch > self._view.epoch:
                self._apply_bump_locked(
                    view, {}, {},
                    snap.get("admissions"), snap.get("evictions"),
                )
            else:
                self._admissions.update(
                    {p: int(e) for p, e in
                     (snap.get("admissions") or {}).items()}
                )
                self._evictions.update(
                    {p: int(e) for p, e in
                     (snap.get("evictions") or {}).items()}
                )
            if (
                self._coordinator_name == self._self_party
                and self._coordinator is None
            ):
                from rayfed_tpu.membership.coordinator import (
                    MembershipCoordinator,
                )

                self._coordinator = MembershipCoordinator(self)
                promote = True
        if promote:
            # Re-run the coordinator half of install(): the cut says the
            # role migrated to this party before the checkpoint.
            self.install()
        logger.info(
            "membership: restored checkpoint cut (epoch %d, sync %d, "
            "term %d)", self.current_epoch(), self.sync_index(),
            self.term(),
        )

    def drain_takeover(self, timeout: float = 2.0) -> bool:
        """Block until no membership sync / takeover is in flight (or
        the timeout lapses). ``fed.shutdown`` calls this before tearing
        the membership plane down so a mid-takeover broadcast finishes
        against live proxies. Returns True when quiescent."""
        deadline = time.monotonic() + max(0.0, timeout)
        with self._lock:
            while self._inflight > 0:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._drain_cond.wait(remaining)
            return True

    def apply_sync_msg(self, msg: Dict) -> MembershipView:
        # Term fencing FIRST: a deposed coordinator's sync was folded
        # without the failover's evictions — applying it would fork the
        # roster. (The rendezvous key already keeps it from consuming
        # the live broadcast's slot; this rejects one handed to us
        # directly.) A HIGHER term is a failover we missed: adopt it.
        msg_term = int(msg.get("term") or 0)
        with self._lock:
            if msg_term < self._term:
                self._ha_stats["stale_syncs_rejected"] += 1
                _m_stale_syncs.inc()
                raise StaleCoordinatorError(
                    msg_term, self._term, msg.get("coordinator")
                )
        if msg_term > self.term():
            self.adopt_term(msg_term, msg.get("coordinator"))
        new_view = MembershipView.from_wire(msg["view"])
        admitted = dict(msg.get("admitted") or {})
        evicted = {
            p: int(e) for p, e in (msg.get("evicted") or {}).items()
        }
        with self._lock:
            if new_view.epoch == self._view.epoch:
                return self._view
            if new_view.epoch < self._view.epoch:
                raise RuntimeError(
                    f"membership sync went backwards: applied epoch "
                    f"{self._view.epoch}, received {new_view.epoch}"
                )
            return self._apply_bump_locked(
                new_view, admitted, evicted,
                msg.get("admissions"), msg.get("evictions"),
            )

    def _apply_bump_locked(
        self,
        new_view: MembershipView,
        admitted: Dict[str, str],
        evicted: Dict[str, int],
        admissions: Optional[Dict[str, int]] = None,
        evictions: Optional[Dict[str, int]] = None,
    ) -> MembershipView:
        """Install a successor view and apply its side effects. Caller
        holds the lock; the side effects below touch only module-level
        seams (KV, proxies, monitor) that take their own locks.

        ``admitted``/``evicted`` are THIS bump's delta (tracing, eager
        ghost purge); ``admissions``/``evictions`` are the coordinator's
        full post-bump ghost tables. The side effects reconcile the FULL
        view, not the delta — the received epoch may be several bumps
        ahead of ours (a sync recv timed out and a later one applied),
        and a delta-only apply would leave intermediate joiners unknown
        to the sender proxy and intermediate leavers undropped."""
        old_view = self._view
        old_epoch = old_view.epoch
        if admissions is not None and evictions is not None:
            # Self-contained sync: the tables replace ours wholesale.
            self._admissions = {p: int(e) for p, e in admissions.items()}
            self._evictions = {p: int(e) for p, e in evictions.items()}
        else:
            for p, e in evicted.items():
                self._evictions[p] = int(e)
                self._admissions.pop(p, None)
            for p in admitted:
                self._admissions[p] = new_view.epoch
                self._evictions.pop(p, None)
        self._view = new_view
        _m_epoch.set(new_view.epoch)
        _m_roster_size.set(len(new_view.roster))
        # A re-admitted party is a fresh incarnation: make it electable
        # again (the deposed chain fences the DEAD incarnation, not the
        # name forever).
        self._deposed -= set(admitted)

        from rayfed_tpu.proxy import barriers, rendezvous

        # Addresses first: the cluster config is what fed.get broadcasts
        # and new sender workers dial from.
        self._store_addresses_locked(new_view.addresses)
        from rayfed_tpu.resilience import liveness

        monitor = liveness.get_monitor()
        # Removal side effects FIRST, admissions second: a rejoining
        # party appears in BOTH sets (implicit evict-then-admit) and has
        # to come out the other side admitted — connection cycled, pre-
        # crash parked frames purged. Beyond the delta, drop any peer
        # that silently fell out of the roster across a missed bump.
        stale = set(old_view.roster) - set(new_view.roster)
        for p in sorted(set(evicted) | stale):
            if p == self._self_party:
                continue
            barriers.forget_peer(p)
            if monitor is not None:
                monitor.remove_peer(p)
            # Purge the party's parked frames NOW. For a rejoiner this
            # eager purge is the ONLY purge: once re-admitted it leaves
            # the eviction table, so the expire-loop sweep no longer
            # matches its old frames.
            rendezvous.evict_source_everywhere(self._job_name, p)
        # Admissions reconcile the full roster: every roster address is
        # (re-)taught to the sender proxy and the liveness monitor, both
        # idempotent — so joiners admitted at a bump we never saw still
        # get dialed.
        for p, addr in new_view.addresses.items():
            if p == self._self_party:
                continue
            barriers.admit_peer(p, addr)
            if monitor is not None:
                monitor.add_peer(p)

        # Re-key the seq-id space: the driver-side counter restarts at 0
        # and the barrier layer stamps the new epoch onto every integer
        # seq id from here on. Every party performs this at its own sync
        # call — the same program point — so the DAG numbering stays
        # aligned across the bump.
        ctx = get_global_context()
        if ctx is not None:
            ctx.reset_seq_id()

        now = time.perf_counter()
        for p in admitted:
            tracing.record(
                "membership", p, f"epoch:{old_epoch}",
                f"epoch:{new_view.epoch}", 0, now, event="join",
            )
        for p in evicted:
            tracing.record(
                "membership", p, f"epoch:{old_epoch}",
                f"epoch:{new_view.epoch}", 0, now, event="evict",
            )
        tracing.record(
            "membership", self._self_party, f"epoch:{old_epoch}",
            f"epoch:{new_view.epoch}", 0, now, event="epoch-bump",
            roster=list(new_view.roster),
        )
        logger.info(
            "membership epoch %d -> %d: roster=%s admitted=%s evicted=%s",
            old_epoch, new_view.epoch, list(new_view.roster),
            sorted(admitted), sorted(evicted),
        )
        return new_view

    def _store_addresses_locked(self, addresses: Dict[str, str]) -> None:
        """Rewrite the KV cluster config with the new roster addresses
        (preserving party identity and TLS) and drop the module cache so
        the next ``get_cluster_config`` re-reads it."""
        cfg = fed_config.get_cluster_config(self._job_name)
        tls = cfg.tls_config if cfg is not None else {}
        cluster_config = {
            constants.KEY_OF_CLUSTER_ADDRESSES: dict(addresses),
            constants.KEY_OF_CURRENT_PARTY_NAME: self._self_party,
            constants.KEY_OF_TLS_CONFIG: tls,
        }
        internal_kv.kv_put(
            self._job_name,
            constants.KEY_OF_CLUSTER_CONFIG,
            pickle.dumps(cluster_config),
        )
        fed_config.reset_config_cache()

    # -- graceful departure -------------------------------------------

    def leave(self, timeout: Optional[float] = None) -> None:
        """Graceful departure: tell the coordinator (it removes us at
        its next sync), then stop participating. The caller (fed.leave)
        tears the runtime down afterwards — the cleanup manager drains
        in-flight sends there, and shutdown releases our rendezvous
        entries with the proxies."""
        if self._coordinator is not None:
            raise RuntimeError(
                "the coordinator party cannot leave the job it "
                "coordinates (hand the role off by restarting the job "
                "with a different membership.coordinator)"
            )
        from rayfed_tpu.proxy import barriers
        from rayfed_tpu.resilience import liveness

        timeout = (
            timeout if timeout is not None else self._config.sync_timeout_s
        )
        nonce = protocol.new_nonce()
        coord = self.coordinator()
        try:
            barriers.send(
                coord,
                protocol.make_leave_request(
                    self._self_party, nonce, term=self.term()
                ),
                protocol.LEAVE_REQ_SEQ,
                nonce,
            ).result(timeout=timeout)
        except Exception:  # noqa: BLE001 - departure is best-effort: an
            # unreachable coordinator will evict us via liveness anyway.
            # Re-offer once against the failover successor first — the
            # takeover replays membership intent from exactly these
            # re-offered requests (docs/ha.md).
            reoffered = False
            if (
                self._config.failover.enabled
                and liveness.party_state(coord) == liveness.DEAD
            ):
                successor = self._failover_elect(coord)
                if successor not in (coord, self._self_party):
                    try:
                        barriers.send(
                            successor,
                            protocol.make_leave_request(
                                self._self_party, nonce, term=self.term()
                            ),
                            protocol.LEAVE_REQ_SEQ,
                            nonce,
                        ).result(timeout=timeout)
                        reoffered = True
                    except Exception:  # noqa: BLE001 - same best-effort
                        pass
            if not reoffered:
                logger.warning(
                    "membership: leave notification to coordinator %s "
                    "failed (liveness eviction will reap this party "
                    "instead)", coord, exc_info=True,
                )
        tracing.record(
            "membership", self._self_party,
            f"epoch:{self.current_epoch()}", "left", 0,
            time.perf_counter(), event="leave",
        )


# -- joiner handshake --------------------------------------------------


def join_handshake(
    job_name: str,
    self_party: str,
    self_address: str,
    coordinator_party: str,
    config: MembershipConfig,
    timeout: Optional[float] = None,
) -> Tuple[MembershipManager, Any]:
    """Run the join handshake against an already-initialized two-party
    runtime ({self, coordinator}): send a JoinRequest, park on the
    JoinAccept, then build + install the manager and admit the full
    roster. Returns ``(manager, bootstrap)``.

    The accept arrives at the coordinator's NEXT sync point, where the
    whole roster's epoch bump admits us — so by the time this returns,
    every member party has (or is applying) a view containing us, our
    seq counter is 0, and our epoch stamp matches theirs.
    """
    from rayfed_tpu.proxy import barriers

    timeout = timeout if timeout is not None else config.join_timeout_s
    deadline = time.monotonic() + timeout
    nonce = protocol.new_nonce()
    # Park on the accept BEFORE the request is acked: the coordinator's
    # sync may fire between ack and a later recv registration, and the
    # accept must find a waiter (or park as arrived) either way.
    accept_fut = barriers.recv(
        self_party, coordinator_party, protocol.RESPONSE_SEQ, nonce
    )
    req_fut = barriers.send(
        coordinator_party,
        protocol.make_join_request(
            self_party, self_address, nonce, config.auth_token
        ),
        protocol.JOIN_REQ_SEQ,
        nonce,
    )
    # The request's ack carries the control handler's verdict: a 403
    # (bad token) fails this future immediately, long before the accept
    # timeout would expire.
    req_fut.result(timeout=max(0.1, deadline - time.monotonic()))
    accept = accept_fut.result(
        timeout=max(0.1, deadline - time.monotonic())
    )
    if not isinstance(accept, dict) or accept.get("kind") != "join-accept":
        raise RuntimeError(
            f"malformed join accept from coordinator: {type(accept)}"
        )

    view = MembershipView.from_wire(accept["view"])
    manager = MembershipManager(
        job_name,
        self_party,
        view,
        config,
        sync_index=int(accept["sync_index"]),
        admissions=accept.get("admissions") or {},
        evictions=accept.get("evictions") or {},
        term=int(accept.get("term") or 0),
    )
    # Admit the full roster locally: addresses into the KV config and
    # the sender proxy, peers into the liveness monitor.
    manager._store_addresses_locked(view.addresses)
    from rayfed_tpu.resilience import liveness

    monitor = liveness.get_monitor()
    for p, addr in view.addresses.items():
        if p == self_party:
            continue
        barriers.admit_peer(p, addr)
        if monitor is not None:
            monitor.add_peer(p)
    # Align the seq-id space with the epoch bump that admitted us: every
    # member reset to 0 at that bump; we start there too.
    ctx = get_global_context()
    if ctx is not None:
        ctx.reset_seq_id()
    manager.install()
    set_membership_manager(manager)
    # Warm the reactor dial to every peer (best-effort — the data lane
    # dials lazily on first send regardless).
    for p in view.roster:
        if p != self_party:
            try:
                barriers.send_ping(p)
            except Exception:  # noqa: BLE001 - lazy dial covers it
                pass
    tracing.record(
        "membership", self_party, "join",
        f"epoch:{view.epoch}", 0, time.perf_counter(), event="joined",
        sync_index=manager.sync_index(),
    )
    logger.info(
        "membership: joined job %r as %r at epoch %d (roster=%s)",
        job_name, self_party, view.epoch, list(view.roster),
    )
    return manager, accept.get("bootstrap")


# -- per-job manager slot wired by fed.init / fed.join -----------------

from rayfed_tpu.tenancy.context import JobScoped

_managers: "JobScoped[MembershipManager]" = JobScoped("membership.manager")


def set_membership_manager(manager: Optional[MembershipManager]) -> None:
    if manager is None:
        _managers.pop()
    else:
        _managers.set(manager)


def get_membership_manager() -> Optional[MembershipManager]:
    return _managers.peek()


def clear_membership_manager() -> None:
    manager = _managers.pop()
    if manager is not None:
        try:
            manager.uninstall()
        except Exception:  # noqa: BLE001 - teardown best-effort
            logger.warning("membership uninstall failed", exc_info=True)


def current_epoch_or_none() -> Optional[int]:
    """The installed manager's epoch, or None on membership-free jobs —
    the stamp the async plane attaches to offers."""
    manager = _managers.peek()
    return None if manager is None else manager.current_epoch()
