# Copyright 2026 The rayfed-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Membership wire protocol: reserved seq-id namespace and frame shapes.

Membership messages ride the ordinary data lane — the same send/recv
path, retry engine, TLS identity and job isolation as every data frame —
addressed by STRING seq ids in the reserved ``mbr:`` namespace (internal
data seq ids are monotonic integers, optionally epoch-prefixed
``e<epoch>:<n>``, so the namespaces can never collide):

- ``("mbr:req:join", <nonce>)`` / ``("mbr:req:leave", <nonce>)``:
  requests TO the coordinator. The receiver's rendezvous store does not
  park these — it dispatches them to the registered control handler and
  the handler's verdict rides back in the frame's ack (a 403 ack fails
  the sender's future, which is how a rejected join surfaces).
- ``("mbr:rsp", <nonce>)``: the coordinator's JoinAccept, a normal
  stored frame the joiner is parked on.
- ``("mbr:sync", <sync_index>)``: the coordinator's view broadcast at
  sync point ``sync_index``, a normal stored frame each member party
  recvs at its own ``fed.membership_sync()`` call.

Epoch-prefixed seq ids: while a membership manager is installed, every
INTEGER seq id is stamped ``e<epoch>:<n>`` at the barrier layer on both
send and recv. Send and its matching recv sit at the same program point
of the same driver program, so both sides stamp the same epoch; a frame
from a pre-bump incarnation parks under its old-epoch key and can never
be taken by post-bump code — the re-key that makes rejoin safe.
"""

from __future__ import annotations

import uuid
from typing import Any, Dict, Optional

#: Prefix of request seq ids dispatched to the coordinator's control
#: handler instead of being parked in the rendezvous store.
CONTROL_PREFIX = "mbr:req:"

JOIN_REQ_SEQ = "mbr:req:join"
LEAVE_REQ_SEQ = "mbr:req:leave"
RESPONSE_SEQ = "mbr:rsp"
SYNC_SEQ = "mbr:sync"


def is_control_seq_id(seq_id: Any) -> bool:
    return isinstance(seq_id, str) and seq_id.startswith(CONTROL_PREFIX)


def new_nonce() -> str:
    return uuid.uuid4().hex


def sync_down_key(sync_index: int, term: int) -> str:
    """Rendezvous down-key for the sync broadcast at ``sync_index`` under
    coordinator ``term``. Term 0 (the configured coordinator, no failover
    yet) keeps the bare index so pre-HA peers interoperate; any later
    term qualifies the key. The qualification is what fences a deposed
    coordinator at the STORE: rendezvous keys are ``(up, down)`` only and
    delivered keys are tombstoned against duplicates, so a stale term-T
    sync parked (or expired) at its own key can never consume the slot
    the term-T+1 broadcast must land in."""
    return str(int(sync_index)) if int(term) <= 0 else (
        f"{int(sync_index)}t{int(term)}"
    )


def make_join_request(
    party: str, address: str, nonce: str, token: Optional[str],
    term: int = 0,
) -> Dict:
    return {
        "kind": "join",
        "party": party,
        "address": address,
        "nonce": nonce,
        "token": token,
        "term": int(term),
    }


def make_leave_request(party: str, nonce: str, term: int = 0) -> Dict:
    return {"kind": "leave", "party": party, "nonce": nonce, "term": int(term)}


def make_join_accept(
    view_wire: Dict,
    sync_index: int,
    admissions: Dict[str, int],
    evictions: Dict[str, int],
    bootstrap: Any,
    term: int = 0,
) -> Dict:
    return {
        "kind": "join-accept",
        "view": view_wire,
        "sync_index": int(sync_index),
        "admissions": dict(admissions),
        "evictions": dict(evictions),
        "bootstrap": bootstrap,
        "term": int(term),
    }


def make_sync(
    view_wire: Dict,
    sync_index: int,
    admitted: Dict[str, str],
    evicted: Dict[str, int],
    admissions: Optional[Dict[str, int]] = None,
    evictions: Optional[Dict[str, int]] = None,
    term: int = 0,
    coordinator: Optional[str] = None,
) -> Dict:
    """The per-sync view broadcast. ``admitted`` maps parties admitted at
    THIS bump to their addresses; ``evicted`` maps parties removed at
    this bump to the epoch as of which they are out (ghost stamp).
    ``admissions``/``evictions`` are the coordinator's FULL ghost tables
    after the bump — they make every sync self-contained, so a member
    that missed an intermediate sync (recv timed out, frame lost) still
    reconciles to the complete state instead of just this bump's delta.
    ``term`` is the sender's coordinator term; receivers reject any sync
    whose term is below their own (a deposed coordinator's stale view)."""
    return {
        "kind": "sync",
        "view": view_wire,
        "sync_index": int(sync_index),
        "admitted": dict(admitted),
        "evicted": dict(evicted),
        "admissions": dict(admissions) if admissions is not None else None,
        "evictions": dict(evictions) if evictions is not None else None,
        "term": int(term),
        "coordinator": coordinator,
    }
