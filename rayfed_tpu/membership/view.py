# Copyright 2026 The rayfed-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""The membership view: ``(epoch, roster, addresses)``.

The view is the one piece of state every party must agree on for the
multi-controller contract to survive churn: the roster decides which
parties a ``fed.get`` broadcast fans out to and which contributions an
aggregation plan folds, and the epoch namespaces the seq-id space so
traffic from a pre-bump incarnation of a party can never collide with
its post-rejoin self. Views are immutable; an epoch bump produces a new
one (``with_changes``).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Iterable, Mapping, Tuple


@dataclasses.dataclass(frozen=True)
class MembershipView:
    """One agreed membership state.

    Attributes:
        epoch: monotonically increasing; bumped exactly when the roster
            changes (join, leave, eviction — possibly several folded
            into one bump at a sync point).
        roster: sorted party names currently in the job.
        addresses: ``{party: "host:port"}`` for every roster party.
    """

    epoch: int
    roster: Tuple[str, ...]
    addresses: Dict[str, str]

    def __post_init__(self) -> None:
        object.__setattr__(self, "roster", tuple(sorted(self.roster)))
        missing = [p for p in self.roster if p not in self.addresses]
        if missing:
            raise ValueError(
                f"membership view has roster parties without addresses: "
                f"{missing}"
            )

    def with_changes(
        self,
        add: Mapping[str, str] = (),
        remove: Iterable[str] = (),
        *,
        force_bump: bool = False,
    ) -> "MembershipView":
        """The successor view: ``add`` maps joining parties to their
        addresses, ``remove`` names leaving/evicted parties. Returns
        ``self`` unchanged (same epoch) when nothing actually changes —
        unless ``force_bump``, which bumps the epoch even for an
        identical roster (a crashed party rejoining under its own name
        at its old address must still re-key the seq-id space and purge
        its pre-crash ghosts)."""
        add = dict(add)
        remove = set(remove)
        roster = (set(self.roster) - remove) | set(add)
        addresses = {
            p: a for p, a in self.addresses.items() if p not in remove
        }
        addresses.update(add)
        if (
            not force_bump
            and tuple(sorted(roster)) == self.roster
            and addresses == dict(self.addresses)
        ):
            return self
        return MembershipView(
            epoch=self.epoch + 1,
            roster=tuple(sorted(roster)),
            addresses=addresses,
        )

    # -- wire form (msgpack-clean plain dict) --------------------------
    def to_wire(self) -> Dict:
        return {
            "epoch": int(self.epoch),
            "roster": list(self.roster),
            "addresses": dict(self.addresses),
        }

    @classmethod
    def from_wire(cls, data: Mapping) -> "MembershipView":
        return cls(
            epoch=int(data["epoch"]),
            roster=tuple(data["roster"]),
            addresses=dict(data["addresses"]),
        )
