# Copyright 2026 The rayfed-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Party device-mesh management (TPU-native; no reference equivalent).

``fed.init`` binds each party to a sub-mesh of the local devices (SURVEY.md
§3.1: "In a TPU build `init` additionally establishes the party-slice
mesh"). Party-local tasks jit onto this mesh; the TPU transport places
received arrays onto it; federated aggregation uses the joint mesh helpers
in :mod:`rayfed_tpu.collective`.

JAX is imported lazily: control-plane-only processes never pay for it.
"""

from __future__ import annotations

import logging
import math
from typing import List, Optional

from rayfed_tpu.config import PartyMeshConfig

logger = logging.getLogger(__name__)

_party_mesh = None  # fedlint: disable=global-mutable-singleton (mesh cache over the per-process jax runtime; one device set per process)
_party_mesh_config: Optional[PartyMeshConfig] = None  # fedlint: disable=global-mutable-singleton (mesh cache over the per-process jax runtime; one device set per process)


def init_distributed(
    coordinator_address: str,
    num_processes: int,
    process_id: int,
    local_device_ids=None,
) -> None:
    """Join a multi-host JAX process group (real multi-host TPU slices).

    A *party* spanning several hosts calls this on each host before
    ``fed.init`` (or passes ``config['jax_distributed']``), after which
    ``jax.devices()`` spans the party's whole slice and the party mesh /
    collectives ride ICI+DCN. Cross-party traffic still flows through the
    fed transport — the process group is per-party, preserving the data
    perimeter.
    """
    import jax

    if jax.distributed.is_initialized():
        # Repeat fed.init in the same process (shutdown()+init() restart
        # pattern): the process group outlives the fed runtime.
        logger.info("jax.distributed already initialized; reusing group.")
        return
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
        local_device_ids=local_device_ids,
    )
    logger.info(
        "Joined jax.distributed group %s as process %d/%d",
        coordinator_address, process_id, num_processes,
    )


def build_mesh(
    device_ids: Optional[List[int]] = None,
    mesh_shape: Optional[List[int]] = None,
    axis_names: Optional[List[str]] = None,
):
    """Create a ``jax.sharding.Mesh`` over the selected local devices.

    Defaults: all local devices, 1-D mesh on axis ``("data",)``.
    """
    import jax
    import numpy as np

    devices = jax.devices()
    if device_ids is not None:
        devices = [devices[i] for i in device_ids]
    n = len(devices)
    if mesh_shape is None:
        mesh_shape = [n]
    if math.prod(mesh_shape) != n:
        raise ValueError(
            f"mesh_shape {mesh_shape} does not cover {n} devices"
        )
    if axis_names is None:
        default_names = ["data", "model", "seq", "expert"]
        axis_names = default_names[: len(mesh_shape)]
        if len(axis_names) < len(mesh_shape):
            axis_names += [f"ax{i}" for i in range(len(axis_names), len(mesh_shape))]
    from jax.sharding import Mesh

    dev_array = np.array(devices).reshape(mesh_shape)
    return Mesh(dev_array, tuple(axis_names))


def init_party_mesh(cfg: Optional[PartyMeshConfig] = None):
    """Establish this party's mesh once, at ``fed.init`` time."""
    global _party_mesh, _party_mesh_config
    cfg = cfg or PartyMeshConfig()
    _party_mesh = build_mesh(cfg.device_ids, cfg.mesh_shape, cfg.axis_names)
    _party_mesh_config = cfg
    logger.info(
        "Party mesh established: shape=%s axes=%s",
        dict(zip(_party_mesh.axis_names, _party_mesh.devices.shape)),
        _party_mesh.axis_names,
    )
    return _party_mesh


def get_party_mesh():
    return _party_mesh


def get_party_mesh_config() -> Optional[PartyMeshConfig]:
    return _party_mesh_config


def clear_party_mesh() -> None:
    global _party_mesh, _party_mesh_config
    _party_mesh = None
    _party_mesh_config = None
    clear_composed_mesh()


# ---------------------------------------------------------------------------
# Composed party mesh (same-mesh fast path)
# ---------------------------------------------------------------------------
#
# When the parties of a job are colocated on one device pool — the CPU
# simulator, a single-host multi-party test rig, or a pod slice shared via
# jax.distributed — their sub-meshes compose into ONE mesh with a leading
# "party" axis (party x data x model ...). Registering that composition
# unlocks the same-mesh fast paths: pushes lower to jax.device_put onto
# the destination party's sub-mesh (no wire, no host staging) and flat
# aggregation plans lower to a single collective across the party axis
# (ops.aggregate.psum_by_plan). The registry is process-local and
# strictly opt-in; nothing engages unless it is populated.

_composed_mesh = None  # fedlint: disable=global-mutable-singleton (mesh cache over the per-process jax runtime; one device set per process)
_composed_parties: Optional[tuple] = None  # fedlint: disable=global-mutable-singleton (mesh cache over the per-process jax runtime; one device set per process)


def compose_party_mesh(parties, devices=None, inner_axes=None,
                       inner_shape=None):
    """Compose and register the job's party x data x model mesh.

    ``parties`` fixes the party-axis order (coordinate p on the "party"
    axis IS ``parties[p]``), so every process must pass the same order —
    sorted names or config order, the multi-controller contract. Inner
    axes default to this party's established mesh shape (so the composed
    mesh is party x <party mesh>), else a 1-D ``data`` axis.
    """
    global _composed_mesh, _composed_parties
    from rayfed_tpu.collective import party_axis_mesh

    parties = tuple(dict.fromkeys(parties))
    if len(parties) < 2:
        raise ValueError("composing a party mesh needs at least 2 parties")
    if inner_axes is None:
        if _party_mesh is not None:
            inner_axes = tuple(str(a) for a in _party_mesh.axis_names)
            if inner_shape is None:
                inner_shape = tuple(int(d) for d in _party_mesh.devices.shape)
        else:
            inner_axes = ("data",)
    composed = party_axis_mesh(
        len(parties), devices=devices,
        inner_axes=tuple(inner_axes), inner_shape=inner_shape,
    )
    _composed_mesh = composed
    _composed_parties = parties
    logger.info(
        "Composed party mesh registered: parties=%s shape=%s",
        parties, dict(zip(composed.axis_names, composed.devices.shape)),
    )
    return composed


def composed_mesh_for(parties):
    """The registered composed mesh iff it covers exactly ``parties`` in
    the registered order (plans index the party axis by position), else
    None."""
    if _composed_mesh is None or tuple(parties) != _composed_parties:
        return None
    return _composed_mesh


def get_composed_parties() -> Optional[tuple]:
    return _composed_parties


def party_submesh(party: str):
    """One party's inner sub-mesh of the composed mesh (its slice along
    the party axis, with the inner axes only), or None when no composed
    mesh covers it."""
    if _composed_mesh is None or party not in (_composed_parties or ()):
        return None
    from jax.sharding import Mesh

    i = _composed_parties.index(party)
    return Mesh(
        _composed_mesh.devices[i], tuple(_composed_mesh.axis_names[1:])
    )


def clear_composed_mesh() -> None:
    global _composed_mesh, _composed_parties
    _composed_mesh = None
    _composed_parties = None
