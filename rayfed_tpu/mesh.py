# Copyright 2026 The rayfed-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Party device-mesh management (TPU-native; no reference equivalent).

``fed.init`` binds each party to a sub-mesh of the local devices (SURVEY.md
§3.1: "In a TPU build `init` additionally establishes the party-slice
mesh"). Party-local tasks jit onto this mesh; the TPU transport places
received arrays onto it; federated aggregation uses the joint mesh helpers
in :mod:`rayfed_tpu.collective`.

JAX is imported lazily: control-plane-only processes never pay for it.
"""

from __future__ import annotations

import logging
import math
from typing import List, Optional

from rayfed_tpu.config import PartyMeshConfig

logger = logging.getLogger(__name__)

_party_mesh = None
_party_mesh_config: Optional[PartyMeshConfig] = None


def init_distributed(
    coordinator_address: str,
    num_processes: int,
    process_id: int,
    local_device_ids=None,
) -> None:
    """Join a multi-host JAX process group (real multi-host TPU slices).

    A *party* spanning several hosts calls this on each host before
    ``fed.init`` (or passes ``config['jax_distributed']``), after which
    ``jax.devices()`` spans the party's whole slice and the party mesh /
    collectives ride ICI+DCN. Cross-party traffic still flows through the
    fed transport — the process group is per-party, preserving the data
    perimeter.
    """
    import jax

    if jax.distributed.is_initialized():
        # Repeat fed.init in the same process (shutdown()+init() restart
        # pattern): the process group outlives the fed runtime.
        logger.info("jax.distributed already initialized; reusing group.")
        return
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
        local_device_ids=local_device_ids,
    )
    logger.info(
        "Joined jax.distributed group %s as process %d/%d",
        coordinator_address, process_id, num_processes,
    )


def build_mesh(
    device_ids: Optional[List[int]] = None,
    mesh_shape: Optional[List[int]] = None,
    axis_names: Optional[List[str]] = None,
):
    """Create a ``jax.sharding.Mesh`` over the selected local devices.

    Defaults: all local devices, 1-D mesh on axis ``("data",)``.
    """
    import jax
    import numpy as np

    devices = jax.devices()
    if device_ids is not None:
        devices = [devices[i] for i in device_ids]
    n = len(devices)
    if mesh_shape is None:
        mesh_shape = [n]
    if math.prod(mesh_shape) != n:
        raise ValueError(
            f"mesh_shape {mesh_shape} does not cover {n} devices"
        )
    if axis_names is None:
        default_names = ["data", "model", "seq", "expert"]
        axis_names = default_names[: len(mesh_shape)]
        if len(axis_names) < len(mesh_shape):
            axis_names += [f"ax{i}" for i in range(len(axis_names), len(mesh_shape))]
    from jax.sharding import Mesh

    dev_array = np.array(devices).reshape(mesh_shape)
    return Mesh(dev_array, tuple(axis_names))


def init_party_mesh(cfg: Optional[PartyMeshConfig] = None):
    """Establish this party's mesh once, at ``fed.init`` time."""
    global _party_mesh, _party_mesh_config
    cfg = cfg or PartyMeshConfig()
    _party_mesh = build_mesh(cfg.device_ids, cfg.mesh_shape, cfg.axis_names)
    _party_mesh_config = cfg
    logger.info(
        "Party mesh established: shape=%s axes=%s",
        dict(zip(_party_mesh.axis_names, _party_mesh.devices.shape)),
        _party_mesh.axis_names,
    )
    return _party_mesh


def get_party_mesh():
    return _party_mesh


def get_party_mesh_config() -> Optional[PartyMeshConfig]:
    return _party_mesh_config


def clear_party_mesh() -> None:
    global _party_mesh, _party_mesh_config
    _party_mesh = None
    _party_mesh_config = None
