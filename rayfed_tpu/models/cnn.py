# Copyright 2026 The rayfed-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Small convnet for federated image classification (CIFAR-10 shapes —
BASELINE.json config #5). Functional JAX; NHWC layout with channel counts
sized so XLA tiles the convs onto the MXU."""

from __future__ import annotations

from typing import Any, Dict, Sequence

import jax
import jax.numpy as jnp

Params = Dict[str, Any]


def init_cnn(
    rng,
    num_classes: int = 10,
    channels: Sequence[int] = (32, 64),
    input_hw: int = 32,
    in_channels: int = 3,
    dtype=jnp.float32,
) -> Params:
    keys = jax.random.split(rng, len(channels) + 2)
    convs = []
    c_in = in_channels
    for k, c_out in zip(keys, channels):
        scale = (2.0 / (9 * c_in)) ** 0.5
        convs.append(
            {
                "w": (jax.random.normal(k, (3, 3, c_in, c_out)) * scale).astype(dtype),
                "b": jnp.zeros((c_out,), dtype),
            }
        )
        c_in = c_out
    # Two 2x2 pools per conv stage halve H/W each time.
    hw = input_hw // (2 ** len(channels))
    flat = hw * hw * c_in
    dense_scale = (2.0 / flat) ** 0.5
    return {
        "convs": convs,
        "dense": {
            "w": (jax.random.normal(keys[-2], (flat, 128)) * dense_scale).astype(dtype),
            "b": jnp.zeros((128,), dtype),
        },
        "head": {
            "w": (jax.random.normal(keys[-1], (128, num_classes)) * 0.1).astype(dtype),
            "b": jnp.zeros((num_classes,), dtype),
        },
    }


def cnn_apply(params: Params, x) -> jax.Array:
    """x: (B, H, W, C) -> logits (B, num_classes)."""
    for conv in params["convs"]:
        x = jax.lax.conv_general_dilated(
            x, conv["w"], window_strides=(1, 1), padding="SAME",
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        ) + conv["b"]
        x = jax.nn.relu(x)
        x = jax.lax.reduce_window(
            x, -jnp.inf, jax.lax.max, (1, 2, 2, 1), (1, 2, 2, 1), "VALID"
        )
    x = x.reshape(x.shape[0], -1)
    x = jax.nn.relu(x @ params["dense"]["w"] + params["dense"]["b"])
    return x @ params["head"]["w"] + params["head"]["b"]


def cnn_loss(params: Params, x, y) -> jax.Array:
    logits = cnn_apply(params, x)
    logz = jax.nn.logsumexp(logits, axis=-1)
    gold = jnp.take_along_axis(logits, y[:, None], axis=-1)[:, 0]
    return (logz - gold).mean()
