# Copyright 2026 The rayfed-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""KV-cache autoregressive decoding for the flagship transformer.

The reference engine never runs models, so inference is pure new surface
for this framework: party-local generation on whatever checkpoint a
federated job just trained (e.g. sample from the aggregated model after a
FedAvg round, or serve the label party's head in split learning).

TPU-first design:
 - the K/V cache is **stacked over layers** — (n_layers, B, T, H, Dh) —
   mirroring the stacked layer parameters, so one ``lax.scan`` over layers
   threads (x, cache) through a single compiled block body;
 - the decode loop is a ``lax.scan`` over steps with static lengths: one
   compile for the whole generation, no per-token retrace, cache updates
   via ``lax.dynamic_update_slice_in_dim`` (in-place on TPU thanks to
   donation inside the scan carry);
 - prefill and decode share one cached-block implementation (prefill is
   just the S>1 case at offset 0), and the projections/FFN come from
   :mod:`rayfed_tpu.models.transformer` so the numerics match training
   bit-for-bit at equal dtypes.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from rayfed_tpu.models import transformer as tfm

Cache = dict


def cache_spec(
    mesh: Mesh,
    party_axis: Optional[str] = "party",
    data_axis: Optional[str] = "data",
    model_axis: Optional[str] = "model",
    n_heads: Optional[int] = None,
) -> P:
    """PartitionSpec for the stacked (L, B, T, H, Dh) K/V cache: batch over
    party x data, heads over the tensor-parallel axis — the same layout the
    Megatron rules give the attention activations, so cached decode runs
    with zero resharding against tp-sharded parameters. Pass ``n_heads``
    to replicate the head dim when it does not divide the model axis
    (e.g. a tiny draft model on a wide tp mesh)."""
    from rayfed_tpu.parallel import sharding as shd

    batch = shd.batch_spec(mesh, party_axis, data_axis)[0]
    heads = model_axis if model_axis in mesh.axis_names else None
    if (
        heads is not None
        and n_heads is not None
        and n_heads % mesh.shape[model_axis] != 0
    ):
        heads = None
    return P(None, batch, None, heads, None)


def init_cache(
    cfg: tfm.TransformerConfig, batch: int, max_len: int, dtype=None
) -> Cache:
    """Zero-filled K/V cache covering ``max_len`` total positions."""
    dtype = dtype or cfg.compute_dtype
    shape = (cfg.n_layers, batch, max_len, cfg.n_heads, cfg.head_dim)
    return {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}


def forward_with_cache(
    params, tokens, cache: Cache, offset, cfg: tfm.TransformerConfig
):
    """Run ``tokens`` (B, S) int32 starting at global position ``offset``
    (S=1 while decoding, S=prompt length during prefill), reading and
    updating ``cache``. Returns (logits (B, S, vocab) f32, new_cache).

    The stacked (L, B, T, H, Dh) cache rides the **carry** of the layer
    scan: each layer writes only its (B, S, H, Dh) slice via
    ``dynamic_update_slice``, so XLA updates the donated carry buffer in
    place — per-step cache traffic is one slab read (the attention) plus
    one slice write, not a rewrite of the whole stack. Cache slots past
    ``offset + S`` hold zeros; the causal mask in
    :func:`transformer.causal_attention` (q_pos >= k_pos) never attends
    to them.
    """
    b, s = tokens.shape
    max_len = cache["k"].shape[2]
    # dynamic_update_slice would silently CLAMP an out-of-range start index
    # (misplacing K/V and corrupting logits); fail loudly where the bound
    # is checkable — s is always static, offset whenever passed concrete.
    if s > max_len:
        raise ValueError(f"token block ({s}) longer than cache ({max_len})")
    if not isinstance(offset, jax.core.Tracer) and int(offset) + s > max_len:
        raise ValueError(
            f"cache overflow: offset {int(offset)} + block {s} > {max_len}"
        )
    x = params["embed"][tokens].astype(cfg.compute_dtype)
    positions = jnp.broadcast_to(offset + jnp.arange(s)[None, :], (b, s))
    cdt = cfg.compute_dtype

    def body(carry, layer):
        x, ck, cv, i = carry
        q, k, v = tfm.qkv_proj(x, layer, positions, cfg)
        at = (i, 0, offset, 0, 0)
        ck = jax.lax.dynamic_update_slice(ck, k[None].astype(ck.dtype), at)
        cv = jax.lax.dynamic_update_slice(cv, v[None].astype(cv.dtype), at)
        o = tfm.causal_attention(
            q,
            jax.lax.dynamic_index_in_dim(ck, i, axis=0, keepdims=False),
            jax.lax.dynamic_index_in_dim(cv, i, axis=0, keepdims=False),
            q_offset=offset,
        )
        x = x + jnp.einsum("bshk,hkd->bsd", o, layer["wo"].astype(cdt))
        hmlp = tfm.rms_norm(x, layer["ln2"])
        x = x + tfm.ffn_apply(hmlp, layer, cfg)
        return (x, ck, cv, i + 1), None

    init = (x, cache["k"], cache["v"], jnp.asarray(0, jnp.int32))
    (x, ck, cv, _), _ = jax.lax.scan(body, init, params["layers"])
    x = tfm.rms_norm(x, params["ln_f"])
    logits = (x @ params["lm_head"].astype(cfg.compute_dtype)).astype(
        jnp.float32
    )
    return logits, {"k": ck, "v": cv}


def prefill(params, prompt, cache: Cache, cfg: tfm.TransformerConfig):
    """Fill the cache from a (B, S) prompt; returns (last-position logits
    (B, vocab), cache)."""
    logits, cache = forward_with_cache(params, prompt, cache, 0, cfg)
    return logits[:, -1], cache


def _sharded_jit(fn, mesh: Mesh, party_axis, data_axis, n_extra_args: int,
                 n_param_trees: int = 1):
    """jit ``fn(*param_trees, prompt, *extras)`` with Megatron param
    shardings for each leading param tree and a party x data prompt
    sharding, keyed per tree structure/shapes/dtypes — a later call with
    a different tree (e.g. LoRA-merged vs base) gets its own
    in_shardings instead of reusing stale ones. Shared by the sharded
    generate / beam-search / speculative dispatchers so the keying
    scheme cannot drift between them."""
    from rayfed_tpu.parallel import sharding as shd

    prompt_sharding = NamedSharding(
        mesh, shd.batch_spec(mesh, party_axis, data_axis)
    )
    jitted_by_tree = {}

    def tree_key(tree):
        leaves, treedef = jax.tree_util.tree_flatten(tree)
        return (treedef, tuple((x.shape, x.dtype) for x in leaves))

    def dispatch(*args):
        trees, rest = args[:n_param_trees], args[n_param_trees:]
        key = tuple(tree_key(t) for t in trees)
        jitted = jitted_by_tree.get(key)
        if jitted is None:
            shardings = tuple(
                shd.make_param_shardings(mesh, t) for t in trees
            )
            jitted = jitted_by_tree[key] = jax.jit(
                fn,
                in_shardings=shardings + (prompt_sharding,)
                + (None,) * n_extra_args,
            )
        return jitted(*args)

    return dispatch


def make_generate_fn(
    cfg: tfm.TransformerConfig,
    *,
    max_new_tokens: int,
    temperature: float = 0.0,
    top_k: Optional[int] = None,
    top_p: Optional[float] = None,
    eos_id: Optional[int] = None,
    jit: bool = True,
    mesh: Optional[Mesh] = None,
    party_axis: Optional[str] = "party",
    data_axis: Optional[str] = "data",
):
    """Build ``generate(params, prompt, rng=None) -> (B, S+max_new)``.

    Greedy when ``temperature == 0`` (rng unused), otherwise softmax
    sampling at the given temperature, optionally truncated to the
    ``top_k`` highest-probability tokens and/or the ``top_p`` nucleus
    (smallest set of tokens whose probability mass reaches ``top_p``).
    Lengths are static: the returned function compiles once per prompt
    shape. With ``eos_id``, a row that emits it keeps emitting EOS for
    the rest of the (static-length) generation — the output still has
    shape (B, S+max_new), terminated rows are EOS-padded.

    With ``mesh``, decoding runs sharded: params follow the Megatron tp
    rules (:mod:`rayfed_tpu.parallel.sharding`), the prompt/batch shards
    over party x data, and the K/V cache pins heads to the ``model`` axis
    via :func:`cache_spec` — per-step collectives are the same one
    all-reduce per block as the training forward.
    """
    if max_new_tokens < 1:
        raise ValueError("max_new_tokens must be >= 1")
    if top_k is not None and not 1 <= top_k <= cfg.vocab:
        raise ValueError(f"top_k must be in [1, {cfg.vocab}], got {top_k}")
    if top_p is not None and not 0.0 < top_p <= 1.0:
        raise ValueError(f"top_p must be in (0, 1], got {top_p}")
    if eos_id is not None and not 0 <= eos_id < cfg.vocab:
        raise ValueError(f"eos_id must be in [0, {cfg.vocab}), got {eos_id}")
    if temperature <= 0.0 and (top_k is not None or top_p is not None):
        raise ValueError(
            "top_k/top_p truncate the sampling distribution; with "
            "temperature<=0 decoding is greedy and they would be silently "
            "ignored — set temperature > 0"
        )

    cache_sharding = None
    if mesh is not None:
        cache_sharding = NamedSharding(
            mesh, cache_spec(mesh, party_axis, data_axis, n_heads=cfg.n_heads)
        )

    def sample(logits, key):
        if temperature <= 0.0:
            return jnp.argmax(logits, axis=-1)
        logits = logits / temperature
        if top_k is not None and top_k < cfg.vocab:
            kth = jnp.sort(logits, axis=-1)[..., -top_k, None]
            logits = jnp.where(logits < kth, -jnp.inf, logits)
        if top_p is not None and top_p < 1.0:
            desc = jnp.sort(logits, axis=-1)[..., ::-1]
            cum_excl = jnp.cumsum(
                jax.nn.softmax(desc, axis=-1), axis=-1
            ) - jax.nn.softmax(desc, axis=-1)
            # Nucleus = tokens whose exclusive cumulative mass is still
            # under top_p (always contains the argmax); mask the rest.
            thresh = jnp.min(
                jnp.where(cum_excl < top_p, desc, jnp.inf),
                axis=-1, keepdims=True,
            )
            logits = jnp.where(logits < thresh, -jnp.inf, logits)
        return jax.random.categorical(key, logits, axis=-1)

    def generate(params, prompt, rng: Optional[jax.Array] = None):
        b, s = prompt.shape
        if rng is None:
            rng = jax.random.PRNGKey(0)
        # The cache only ever holds tokens that later tokens attend to, so
        # the final sampled token needs no slot (and no forward pass).
        cache = init_cache(cfg, b, s + max_new_tokens - 1)
        if cache_sharding is not None:
            cache = jax.tree_util.tree_map(
                lambda c: jax.lax.with_sharding_constraint(c, cache_sharding),
                cache,
            )
        last_logits, cache = prefill(params, prompt, cache, cfg)
        rng, sub = jax.random.split(rng)
        first = sample(last_logits, sub).astype(prompt.dtype)
        done0 = (
            first == eos_id if eos_id is not None
            else jnp.zeros(first.shape, bool)
        )

        def step(carry, _):
            tok, cache, pos, key, done = carry
            logits, cache = forward_with_cache(
                params, tok[:, None], cache, pos, cfg
            )
            key, sub = jax.random.split(key)
            nxt = sample(logits[:, -1], sub).astype(prompt.dtype)
            if eos_id is not None:
                nxt = jnp.where(done, jnp.asarray(eos_id, nxt.dtype), nxt)
                done = done | (nxt == eos_id)
            return (nxt, cache, pos + 1, key, done), nxt

        _, toks = jax.lax.scan(
            step,
            (first, cache, jnp.asarray(s, jnp.int32), rng, done0),
            None,
            length=max_new_tokens - 1,
        )
        new = jnp.concatenate([first[:, None], jnp.moveaxis(toks, 0, 1)], axis=1)
        return jnp.concatenate([prompt, new], axis=1)

    if not jit:
        return generate
    if mesh is None:
        return jax.jit(generate)

    dispatch = _sharded_jit(generate, mesh, party_axis, data_axis, 1)

    def sharded_generate(params, prompt, rng: Optional[jax.Array] = None):
        return dispatch(
            params, prompt, rng if rng is not None else jax.random.PRNGKey(0)
        )

    return sharded_generate


def make_beam_search_fn(
    cfg: tfm.TransformerConfig,
    *,
    max_new_tokens: int,
    n_beams: int,
    eos_id: Optional[int] = None,
    jit: bool = True,
    mesh: Optional[Mesh] = None,
    party_axis: Optional[str] = "party",
    data_axis: Optional[str] = "data",
):
    """Build ``beam_search(params, prompt) -> (seqs, scores)``.

    Beam search over ``max_new_tokens`` steps, returning ``seqs``
    (B, n_beams, S+max_new) and their total log-probabilities ``scores``
    (B, n_beams), best first. With ``eos_id`` set, a beam that emits it
    is FINISHED: its score freezes and its remaining slots pad with
    ``eos_id`` (the scored sequence is everything up to and including
    the first EOS) — the result is the exact top-K over the space of
    EOS-terminated-or-length-capped continuations when the beam is wide
    enough (pinned against enumeration in tests). Without ``eos_id``
    every beam decodes the full length.

    With ``mesh``, the search runs sharded exactly like
    :func:`make_generate_fn`: Megatron-tp params, the prompt batch over
    party x data, and the K/V cache pinned by :func:`cache_spec` (its
    batch dim is B*n_beams rows; beam reordering is a batched gather
    XLA turns into on-device collectives where rows cross shards).

    TPU-first shape: ONE compile for the whole search — the step body is
    a ``lax.scan`` whose carry holds the flattened (B*n_beams) decode
    rows; beam reordering is a batched gather over the K/V cache's batch
    dim (``jnp.take``), which XLA lowers to an on-device dynamic-gather
    with no host trips; all candidate expansion is a single
    (B, n_beams*vocab) ``top_k``. Prefill runs once at batch B and the
    cache is tiled to B*n_beams afterwards, so prompt compute is not
    duplicated per beam.
    """
    if max_new_tokens < 1:
        raise ValueError("max_new_tokens must be >= 1")
    if n_beams < 1:
        raise ValueError("n_beams must be >= 1")
    if eos_id is not None and not 0 <= eos_id < cfg.vocab:
        raise ValueError(f"eos_id must be in [0, {cfg.vocab}), got {eos_id}")
    k_beams = n_beams
    vocab = cfg.vocab
    cache_sharding = None
    if mesh is not None:
        cache_sharding = NamedSharding(
            mesh, cache_spec(mesh, party_axis, data_axis, n_heads=cfg.n_heads)
        )

    def beam_search(params, prompt):
        b, s = prompt.shape
        cache = init_cache(cfg, b, s + max_new_tokens - 1)
        if cache_sharding is not None:
            # Pin the layout BEFORE prefill (like make_generate_fn) so
            # GSPMD cannot pick a different prefill-time layout and
            # reshard the whole stack at the tile below.
            cache = jax.tree_util.tree_map(
                lambda c: jax.lax.with_sharding_constraint(
                    c, cache_sharding
                ),
                cache,
            )
        last_logits, cache = prefill(params, prompt, cache, cfg)
        logp0 = jax.nn.log_softmax(last_logits.astype(jnp.float32), axis=-1)

        # First expansion: top-K tokens of the prompt's next-token
        # distribution seed the K beams (B, K). With K > vocab only
        # vocab distinct depth-1 prefixes exist — the surplus beams are
        # seeded dead (-inf) and repopulated by later expansions.
        k0 = min(k_beams, vocab)
        scores0, first0 = jax.lax.top_k(logp0, k0)
        pad = k_beams - k0
        scores = jnp.pad(scores0, ((0, 0), (0, pad)),
                         constant_values=-jnp.inf)
        first = jnp.pad(first0, ((0, 0), (0, pad))).astype(prompt.dtype)
        finished = (
            first == eos_id if eos_id is not None
            else jnp.zeros(first.shape, bool)
        )

        # Tile the cache to B*K rows: row b*K + j = beam j of batch b.
        cache = jax.tree_util.tree_map(
            lambda c: jnp.repeat(c, k_beams, axis=1), cache
        )
        if cache_sharding is not None:
            cache = jax.tree_util.tree_map(
                lambda c: jax.lax.with_sharding_constraint(
                    c, cache_sharding
                ),
                cache,
            )
        seqs = jnp.zeros((b, k_beams, max_new_tokens), prompt.dtype)
        seqs = seqs.at[:, :, 0].set(first)

        def step(carry, t):
            tok, cache, seqs, scores, finished = carry
            logits, cache = forward_with_cache(
                params, tok.reshape(b * k_beams, 1), cache, s + t, cfg
            )
            logp = jax.nn.log_softmax(
                logits[:, -1].astype(jnp.float32), axis=-1
            ).reshape(b, k_beams, vocab)
            if eos_id is not None:
                # A finished beam survives UNCHANGED: its only candidate
                # is "emit EOS again at zero cost", so its frozen score
                # competes in the top-K and its trailing slots pad with
                # EOS.
                freeze = jnp.full((vocab,), -jnp.inf).at[eos_id].set(0.0)
                logp = jnp.where(finished[:, :, None], freeze, logp)
            cand = scores[:, :, None] + logp           # (B, K, V)
            scores, flat = jax.lax.top_k(
                cand.reshape(b, k_beams * vocab), k_beams
            )
            parent = flat // vocab                     # (B, K) beam index
            nxt = (flat % vocab).astype(tok.dtype)     # (B, K) token
            # Reorder histories and cache rows under the surviving beams.
            seqs = jnp.take_along_axis(seqs, parent[:, :, None], axis=1)
            seqs = seqs.at[:, :, t + 1].set(nxt)
            if eos_id is not None:
                finished = jnp.take_along_axis(finished, parent, axis=1)
                finished = finished | (nxt == eos_id)
            rows = (
                jnp.arange(b)[:, None] * k_beams + parent
            ).reshape(b * k_beams)
            cache = jax.tree_util.tree_map(
                lambda c: jnp.take(c, rows, axis=1), cache
            )
            return (nxt, cache, seqs, scores, finished), None

        if max_new_tokens > 1:
            (_, _, seqs, scores, _), _ = jax.lax.scan(
                step,
                (first, cache, seqs, scores, finished),
                jnp.arange(max_new_tokens - 1),
            )
        prompts = jnp.broadcast_to(
            prompt[:, None, :], (b, k_beams, s)
        ).astype(prompt.dtype)
        return jnp.concatenate([prompts, seqs], axis=2), scores

    if not jit:
        return beam_search
    if mesh is None:
        return jax.jit(beam_search)

    return _sharded_jit(beam_search, mesh, party_axis, data_axis, 0)
