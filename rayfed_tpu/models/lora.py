# Copyright 2026 The rayfed-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""LoRA adapters for the flagship transformer (parameter-efficient
federated finetuning).

No reference equivalent (the reference ships no models, SURVEY.md §0) —
but the pattern is a natural fit for a federated engine: parties train
low-rank deltas locally and push/aggregate ONLY the adapter tree, which
is orders of magnitude smaller than the base weights, so every FedAvg
round's wire cost drops accordingly (examples/test: ~1-2%% of the full
push).

TPU-first shape choices: adapters are stacked over layers like the base
parameters (one (L, ..., r) leaf per target), so the merged forward is
still a single ``lax.scan`` over layers, and merging is one einsum per
target that XLA fuses into the surrounding step. ``b`` starts at zero —
step 0 reproduces the base model exactly.
"""

from __future__ import annotations

from typing import Any, Dict, Sequence

import jax
import jax.numpy as jnp

from rayfed_tpu.models import transformer as tfm

Params = Dict[str, Any]

# target -> (einsum for delta, a-shape builder, b-shape builder); shapes
# carry the stacked leading n_layers dim.
_TARGETS = {
    "wq": ("ldr,lrhk->ldhk", lambda d, h, dh, f, r: ((d, r), (r, h, dh))),
    "wk": ("ldr,lrhk->ldhk", lambda d, h, dh, f, r: ((d, r), (r, h, dh))),
    "wv": ("ldr,lrhk->ldhk", lambda d, h, dh, f, r: ((d, r), (r, h, dh))),
    "wo": ("lhkr,lrd->lhkd", lambda d, h, dh, f, r: ((h, dh, r), (r, d))),
    "w_gate": ("ldr,lrf->ldf", lambda d, h, dh, f, r: ((d, r), (r, f))),
    "w_up": ("ldr,lrf->ldf", lambda d, h, dh, f, r: ((d, r), (r, f))),
    "w_down": ("lfr,lrd->lfd", lambda d, h, dh, f, r: ((f, r), (r, d))),
}

ATTN_TARGETS = ("wq", "wk", "wv", "wo")


def init_lora(
    rng,
    cfg: tfm.TransformerConfig,
    rank: int = 8,
    targets: Sequence[str] = ATTN_TARGETS,
    alpha: float | None = None,
    dtype=None,
) -> Params:
    """A LoRA tree {"layers": {target: {"a": ..., "b": ...}}, "scale"-free}:
    ``a`` is N(0, 1/rank)-initialized, ``b`` zero, so the initial delta is
    exactly zero. ``alpha`` defaults to ``rank`` (scale 1.0)."""
    if rank < 1:
        raise ValueError(f"rank must be >= 1, got {rank}")
    unknown = set(targets) - set(_TARGETS)
    if unknown:
        raise ValueError(f"unknown LoRA targets: {sorted(unknown)}")
    if cfg.n_experts > 0 and set(targets) & {"w_gate", "w_up", "w_down"}:
        raise ValueError(
            "MoE configs have no dense FFN weights; LoRA targets must be "
            f"attention-only ({ATTN_TARGETS})"
        )
    dtype = dtype or cfg.param_dtype
    d, h, dh, f = cfg.d_model, cfg.n_heads, cfg.head_dim, cfg.d_ff
    keys = jax.random.split(rng, len(targets))
    layers = {}
    for key, t in zip(keys, targets):
        a_shape, b_shape = _TARGETS[t][1](d, h, dh, f, rank)
        layers[t] = {
            "a": (
                jax.random.normal(key, (cfg.n_layers,) + a_shape)
                * (rank**-0.5)
            ).astype(dtype),
            "b": jnp.zeros((cfg.n_layers,) + b_shape, dtype),
        }
    if alpha is not None and alpha <= 0:
        raise ValueError(f"alpha must be > 0, got {alpha}")
    return {"layers": layers,
            "alpha": float(alpha if alpha is not None else rank),
            "rank": rank}


def merge_lora(params: Params, lora: Params) -> Params:
    """Base params with every adapter folded in:
    ``W' = W + (alpha / rank) * a @ b``. Gradients through the merge flow
    only into the adapter leaves when the caller differentiates w.r.t.
    ``lora``; the base tree is shared, untouched, and never copied except
    for the targeted leaves."""
    scale = lora["alpha"] / lora["rank"]
    new_layers = dict(params["layers"])
    for t, ab in lora["layers"].items():
        eq = _TARGETS[t][0]
        w = params["layers"][t]
        delta = jnp.einsum(eq, ab["a"], ab["b"]) * scale
        new_layers[t] = w + delta.astype(w.dtype)
    out = dict(params)
    out["layers"] = new_layers
    return out


def lora_loss(params: Params, lora: Params, inputs, targets,
              cfg: tfm.TransformerConfig, **kw) -> jax.Array:
    """LM loss of the merged model; differentiate w.r.t. ``lora`` only
    for parameter-efficient training."""
    return tfm.lm_loss_pair(merge_lora(params, lora), inputs, targets,
                            cfg, **kw)


def make_lora_train_step(cfg: tfm.TransformerConfig, lr: float = 1e-3):
    """(step_fn) jitted: ``step(params, lora, opt_state, inputs, targets)
    -> (lora, opt_state, loss)``. The base params are frozen (no
    gradient, no optimizer state); only the adapter tree updates. Use
    ``optax.adam(lr).init(lora["layers"])`` for the initial state."""
    import optax

    optimizer = optax.adam(lr)

    def step(params, lora, opt_state, inputs, targets):
        def loss_fn(ab_tree):
            live = dict(lora)
            live["layers"] = ab_tree
            return lora_loss(params, live, inputs, targets, cfg)

        loss, grads = jax.value_and_grad(loss_fn)(lora["layers"])
        updates, opt_state = optimizer.update(grads, opt_state)
        new = dict(lora)
        new["layers"] = optax.apply_updates(lora["layers"], updates)
        return new, opt_state, loss

    return jax.jit(step, donate_argnums=(2,)), optimizer


def lora_nbytes(lora: Params) -> int:
    return sum(
        leaf.size * leaf.dtype.itemsize
        for leaf in jax.tree_util.tree_leaves(lora["layers"])
    )
