# Copyright 2026 The rayfed-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Logistic regression / MLP models (functional JAX).

The reference ships no models — federated learning is user code
(``README.md:59-104``). These are the model families the BASELINE.json
bench configs name (2-party FedAvg logistic regression at MNIST shapes) and
the building blocks for federated examples/tests.

TPU-first notes: pure functional params-pytree style (no framework
classes), bf16-friendly matmuls sized for the MXU, batch dimension laid out
for ``data``-axis sharding on the party mesh.
"""

from __future__ import annotations

from typing import Any, Dict, Sequence

import jax
import jax.numpy as jnp

Params = Dict[str, Any]


def init_logreg(rng, dim: int, classes: int, dtype=jnp.float32) -> Params:
    wkey, _ = jax.random.split(rng)
    return {
        "w": (jax.random.normal(wkey, (dim, classes)) * 0.01).astype(dtype),
        "b": jnp.zeros((classes,), dtype),
    }


def logreg_logits(params: Params, x) -> jax.Array:
    return x @ params["w"] + params["b"]


def _softmax_xent(logits, labels) -> jax.Array:
    logz = jax.nn.logsumexp(logits, axis=-1)
    gold = jnp.take_along_axis(logits, labels[:, None], axis=-1)[:, 0]
    return (logz - gold).mean()


def logreg_loss(params: Params, x, y) -> jax.Array:
    return _softmax_xent(logreg_logits(params, x), y)


def init_mlp(rng, sizes: Sequence[int], dtype=jnp.float32) -> Params:
    """MLP with ``len(sizes)-1`` dense layers, GELU between."""
    layers = []
    keys = jax.random.split(rng, len(sizes) - 1)
    for k, (d_in, d_out) in zip(keys, zip(sizes[:-1], sizes[1:])):
        scale = (2.0 / d_in) ** 0.5
        layers.append(
            {
                "w": (jax.random.normal(k, (d_in, d_out)) * scale).astype(dtype),
                "b": jnp.zeros((d_out,), dtype),
            }
        )
    return {"layers": layers}


def mlp_apply(params: Params, x) -> jax.Array:
    *hidden, last = params["layers"]
    for layer in hidden:
        x = jax.nn.gelu(x @ layer["w"] + layer["b"])
    return x @ last["w"] + last["b"]


def mlp_loss(params: Params, x, y) -> jax.Array:
    return _softmax_xent(mlp_apply(params, x), y)
