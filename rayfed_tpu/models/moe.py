# Copyright 2026 The rayfed-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Mixture-of-experts FFN with expert parallelism.

Experts shard over an ``expert`` mesh axis: under ``shard_map`` each device
computes its local experts' FFN for all tokens scaled by the router's
(top-1 masked) gate, and one ``psum`` over the expert axis combines —
expert weights and FLOPs scale out with the axis. Dense-gating math keeps
the computation static-shaped (no data-dependent dispatch), which is the
XLA-friendly formulation; the top-1 mask reproduces switch-style routing
numerics exactly.
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp
from jax import lax, shard_map
from jax.sharding import Mesh, PartitionSpec as P

Params = Dict[str, Any]


def init_moe_ffn(rng, d_model: int, d_ff: int, n_experts: int,
                 dtype=jnp.float32) -> Params:
    kr, ku, kd = jax.random.split(rng, 3)
    scale = (2.0 / d_model) ** 0.5
    return {
        "router": (jax.random.normal(kr, (d_model, n_experts)) * 0.02).astype(dtype),
        "w_up": (jax.random.normal(ku, (n_experts, d_model, d_ff)) * scale).astype(dtype),
        "w_down": (
            jax.random.normal(kd, (n_experts, d_ff, d_model)) * (2.0 / d_ff) ** 0.5
        ).astype(dtype),
    }


def _router_probs(params: Params, x):
    """Router probabilities in float32 — THE routing numerics, shared by
    every gating variant (top-1, top-k, aux loss): changes to temperature,
    z-loss scaling etc. belong here and nowhere else."""
    logits = x @ params["router"]  # (..., E)
    return jax.nn.softmax(logits.astype(jnp.float32), axis=-1)


def _gates(params: Params, x, top1: bool):
    probs = _router_probs(params, x)
    if top1:
        # argmax, not probs==max: a max-comparison can select TWO experts
        # on low-precision ties, which desyncs the dense and a2a lanes.
        mask = jax.nn.one_hot(
            jnp.argmax(probs, axis=-1), probs.shape[-1], dtype=probs.dtype
        )
        probs = probs * mask
    return probs.astype(x.dtype)


def _expert_ffn(w_up, w_down, toks):
    """THE per-expert FFN core: toks (E, T, d) -> (E, T, d). Every lane
    (dense, expert-parallel, all-to-all) routes through this one function —
    they must never diverge (the *_matches_dense tests pin equivalence)."""
    up = jnp.einsum("etd,edf->etf", toks, w_up)
    return jnp.einsum("etf,efd->etd", jax.nn.gelu(up), w_down)


def _expert_ffn_combine(w_up, w_down, x, gates):
    """Run all experts on all tokens and gate-combine (dense/EP lanes)."""
    e = w_up.shape[0]
    flat = x.reshape(-1, x.shape[-1])
    toks = jnp.broadcast_to(flat, (e,) + flat.shape)
    out = _expert_ffn(w_up, w_down, toks)          # (E, N, d)
    flat_gates = gates.reshape(-1, gates.shape[-1])
    combined = jnp.einsum("end,ne->nd", out, flat_gates)
    return combined.reshape(x.shape)


def moe_ffn_apply(params: Params, x, top1: bool = True):
    """Reference (single-device) forward: x (..., d) -> (..., d)."""
    gates = _gates(params, x, top1)  # (..., E)
    return _expert_ffn_combine(params["w_up"], params["w_down"], x, gates)


def make_ep_moe_apply(mesh: Mesh, expert_axis: str = "expert"):
    """Expert-parallel forward: expert-sharded params, replicated tokens,
    one psum to combine. Call with params whose expert-leading leaves are
    (global) full-size; shard_map slices them per device."""
    e_spec = {"router": P(), "w_up": P(expert_axis), "w_down": P(expert_axis)}

    def body(params, x):
        n_exp_local = params["w_up"].shape[0]
        idx = lax.axis_index(expert_axis)
        # Global gates, locally sliced to this device's experts.
        gates = _gates(params, x, top1=True)  # router replicated -> (.., E)
        lo = idx * n_exp_local
        local_gates = lax.dynamic_slice_in_dim(
            gates, lo, n_exp_local, axis=-1
        )
        local = _expert_ffn_combine(
            params["w_up"], params["w_down"], x, local_gates
        )
        return lax.psum(local, expert_axis)

    return shard_map(
        body,
        mesh=mesh,
        in_specs=(e_spec, P()),
        out_specs=P(),
        check_vma=False,
    )


def make_a2a_moe_apply(mesh: Mesh, expert_axis: str = "expert",
                       capacity_factor: float = 1.25, k: int = 1):
    """Capacity-based all-to-all expert dispatch (switch-style) — the
    scalable EP form: tokens are sharded over the expert axis, each device
    selects up to C (token, choice) assignments per expert, one
    ``all_to_all`` routes them to their expert's device, the FFN runs on
    E_local experts, and a second ``all_to_all`` routes results home.
    Compute per device is O(E_local * C) instead of the dense path's
    O(E * N); assignments over an expert's capacity are dropped (that
    choice contributes zero), the standard trade.

    ``k`` = experts per token: 1 reproduces switch-style top-1 routing
    (``_gates``), k>1 routes each token to its top-k experts with
    renormalized gates (``topk_gates``) — capacity scales with k so the
    expected slot load is unchanged.

    Call with token-sharded x of shape (N, d) — N divisible by the axis
    size — and full-size expert params; returns (N, d).
    """
    n_dev = mesh.shape[expert_axis]

    def body(params, x):
        n_local, d = x.shape
        e_local = params["w_up"].shape[0]
        n_experts = e_local * n_dev
        capacity = max(1, int(k * n_local * capacity_factor / n_experts))

        if k == 1:
            gates = _gates(params, x, top1=True)      # (N_local, E)
        else:
            gates = topk_gates(params, x, k)          # (N_local, E), k>0/row
        # Ranks MUST accumulate in int32: a low-precision cumsum (bf16 has
        # an 8-bit mantissa) silently collides tokens onto the same slot
        # once ranks exceed the dtype's exact-integer range.
        onehot_i = (gates > 0).astype(jnp.int32)       # (N_local, E)

        # Rank of each (token, choice) within its expert's queue; drop
        # overflow.
        pos = jnp.cumsum(onehot_i, axis=0) * onehot_i  # 1-based ranks
        keep = (pos > 0) & (pos <= capacity)
        loc = jnp.clip(pos - 1, 0, capacity - 1)

        # (N_local, E, C) dispatch tensor.
        loc_onehot = jax.nn.one_hot(loc, capacity, dtype=x.dtype)
        dispatch = (
            keep.astype(x.dtype)[..., None] * loc_onehot
        )                                              # (N, E, C)

        # Scatter tokens into per-expert slots, then route slots to the
        # expert's device: (E, C, d) -> (n_dev, e_local, C, d) a2a.
        slots = jnp.einsum("nec,nd->ecd", dispatch, x)
        slots = slots.reshape(n_dev, e_local, capacity, d)
        recv = lax.all_to_all(
            slots, expert_axis, split_axis=0, concat_axis=0, tiled=False
        )                                              # (n_dev, e_local, C, d)

        # Local experts run on tokens gathered from every device.
        toks = jnp.moveaxis(recv, 1, 0).reshape(
            e_local, n_dev * capacity, d
        )
        out = _expert_ffn(params["w_up"], params["w_down"], toks)

        # Route results back to the tokens' home devices.
        back = jnp.moveaxis(
            out.reshape(e_local, n_dev, capacity, d), 1, 0
        )                                              # (n_dev, e_local, C, d)
        home = lax.all_to_all(
            back, expert_axis, split_axis=0, concat_axis=0, tiled=False
        ).reshape(n_experts, capacity, d)

        # Combine weights = gate * dispatch: each surviving (token, choice)
        # contributes its expert's output scaled by its gate.
        combine = dispatch * gates[..., None]
        return jnp.einsum("nec,ecd->nd", combine, home)

    e_spec = {"router": P(), "w_up": P(expert_axis), "w_down": P(expert_axis)}
    return shard_map(
        body,
        mesh=mesh,
        in_specs=(e_spec, P(expert_axis)),
        out_specs=P(expert_axis),
        check_vma=False,
    )


def topk_gates(params: Params, x, k: int = 2):
    """Top-k routing: per token, the k best experts with their softmax
    probabilities renormalized to sum to 1. Returns (..., E) gates."""
    probs = _router_probs(params, x)
    _, idx = lax.top_k(probs, k)
    mask = jax.nn.one_hot(idx, probs.shape[-1], dtype=probs.dtype).sum(axis=-2)
    kept = probs * mask
    kept = kept / jnp.maximum(kept.sum(axis=-1, keepdims=True), 1e-9)
    return kept.astype(x.dtype)


def load_balance_loss(params: Params, x, k: int = 1):
    """Switch-transformer auxiliary load-balancing loss:
    E * sum_e f_e * P_e, where f_e is the fraction of routed assignments
    landing on expert e (over the same top-k choices the gating uses — an
    aux loss that only watches top-1 would let every second choice collapse
    onto one expert unpenalized) and P_e the mean router probability.
    Minimized (-> 1.0) by a uniform distribution; add a small multiple to
    the task loss when training MoE models so experts stay utilized. Pass
    the same ``k`` as the gating in use."""
    probs = _router_probs(params, x.reshape(-1, x.shape[-1]))
    n_experts = probs.shape[-1]
    _, idx = lax.top_k(probs, k)
    chosen = jax.nn.one_hot(idx, n_experts, dtype=jnp.float32).sum(axis=-2)
    f = chosen.mean(axis=0) / k   # fraction of assignments per expert
    p = probs.mean(axis=0)        # mean router probability per expert
    return n_experts * jnp.sum(f * p)


def moe_ffn_apply_topk(params: Params, x, k: int = 2):
    """Dense-compute forward with top-k routing (k experts per token)."""
    gates = topk_gates(params, x, k)
    return _expert_ffn_combine(params["w_up"], params["w_down"], x, gates)
