"""Mixture-of-experts FFN with expert parallelism.

Experts shard over an ``expert`` mesh axis: under ``shard_map`` each device
computes its local experts' FFN for all tokens scaled by the router's
(top-1 masked) gate, and one ``psum`` over the expert axis combines —
expert weights and FLOPs scale out with the axis. Dense-gating math keeps
the computation static-shaped (no data-dependent dispatch), which is the
XLA-friendly formulation; the top-1 mask reproduces switch-style routing
numerics exactly.
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp
from jax import lax, shard_map
from jax.sharding import Mesh, PartitionSpec as P

Params = Dict[str, Any]


def init_moe_ffn(rng, d_model: int, d_ff: int, n_experts: int,
                 dtype=jnp.float32) -> Params:
    kr, ku, kd = jax.random.split(rng, 3)
    scale = (2.0 / d_model) ** 0.5
    return {
        "router": (jax.random.normal(kr, (d_model, n_experts)) * 0.02).astype(dtype),
        "w_up": (jax.random.normal(ku, (n_experts, d_model, d_ff)) * scale).astype(dtype),
        "w_down": (
            jax.random.normal(kd, (n_experts, d_ff, d_model)) * (2.0 / d_ff) ** 0.5
        ).astype(dtype),
    }


def _gates(params: Params, x, top1: bool):
    logits = x @ params["router"]  # (..., E)
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    if top1:
        best = probs.max(axis=-1, keepdims=True)
        probs = jnp.where(probs == best, probs, 0.0)
    return probs.astype(x.dtype)


def _expert_ffn_combine(w_up, w_down, x, gates):
    """Shared FFN math: run `E_local` experts on all tokens, gate-combine.
    Both the dense and the expert-parallel paths call this — they must
    never diverge (test_ep_moe_matches_dense pins the equivalence)."""
    up = jnp.einsum("...d,edf->...ef", x, w_up)
    act = jax.nn.gelu(up)
    out = jnp.einsum("...ef,efd->...ed", act, w_down)
    return jnp.einsum("...ed,...e->...d", out, gates)


def moe_ffn_apply(params: Params, x, top1: bool = True):
    """Reference (single-device) forward: x (..., d) -> (..., d)."""
    gates = _gates(params, x, top1)  # (..., E)
    return _expert_ffn_combine(params["w_up"], params["w_down"], x, gates)


def make_ep_moe_apply(mesh: Mesh, expert_axis: str = "expert"):
    """Expert-parallel forward: expert-sharded params, replicated tokens,
    one psum to combine. Call with params whose expert-leading leaves are
    (global) full-size; shard_map slices them per device."""
    e_spec = {"router": P(), "w_up": P(expert_axis), "w_down": P(expert_axis)}

    def body(params, x):
        n_exp_local = params["w_up"].shape[0]
        idx = lax.axis_index(expert_axis)
        # Global gates, locally sliced to this device's experts.
        gates = _gates(params, x, top1=True)  # router replicated -> (.., E)
        lo = idx * n_exp_local
        local_gates = lax.dynamic_slice_in_dim(
            gates, lo, n_exp_local, axis=-1
        )
        local = _expert_ffn_combine(
            params["w_up"], params["w_down"], x, local_gates
        )
        return lax.psum(local, expert_axis)

    return shard_map(
        body,
        mesh=mesh,
        in_specs=(e_spec, P()),
        out_specs=P(),
        check_vma=False,
    )
