# Copyright 2026 The rayfed-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Speculative decoding: a small draft model proposes, the target verifies.

Draft-and-verify: per round the draft proposes ``k_draft`` tokens
autoregressively, the target scores ALL of them in one batched forward,
and 1 to ``k_draft``+1 tokens are emitted per target pass. At
``temperature=0`` (greedy) verification is prefix matching and the
output is **exactly** the target's greedy decoding; at ``temperature >
0`` the full Leviathan et al. rejection-sampling scheme runs (accept
with min(1, p_target/p_draft), resample the first rejection from the
normalized residual) and the output DISTRIBUTION is exactly ancestral
sampling from the target — both pinned in tests. On TPU this converts the memory-bound one-token-at-
a-time decode into k+1-token target forwards that amortize the HBM
weight streaming the same way a larger batch would.

TPU-first mechanics (everything static-shaped inside one jit):

 - The loop is a ``lax.while_loop`` whose carry holds the token buffer,
   both K/V caches, and the scalar write position; each round's variable
   acceptance count only moves the position scalar.
 - Cache validity bookkeeping is COLLAPSED by recomputation: each round
   re-runs the trailing ``k_draft+1``-token window through both models
   at its true offset before extending. Re-processing tokens whose
   cache entries were already correct rewrites identical values, and the
   window always covers the one position a rejection can have staled
   (the correction slot), so no validity state needs tracking — the
   cost is one extra window's worth of compute per round.
 - Batched prompts accept ``min`` over rows per round (rows with longer
   matches simply waste some speculation) so the position stays scalar;
   the emitted correction token is still per-row correct because it
   conditions only on accepted tokens.

The reference engine has no inference at all; within this framework the
draft model is the natural thing to train with federated distillation
and serve next to the aggregated target.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from rayfed_tpu.models import transformer as tfm
from rayfed_tpu.models.decode import forward_with_cache, init_cache, prefill


def make_speculative_generate_fn(
    cfg: tfm.TransformerConfig,
    draft_cfg: tfm.TransformerConfig,
    *,
    max_new_tokens: int,
    k_draft: int = 4,
    temperature: float = 0.0,
    eos_id: Optional[int] = None,
    jit: bool = True,
    return_stats: bool = False,
    mesh=None,
    party_axis: Optional[str] = "party",
    data_axis: Optional[str] = "data",
    model_axis: Optional[str] = "model",
):
    """Build ``generate(params, draft_params, prompt) -> (B, S+max_new)``.

    ``params``/``cfg`` are the target model, ``draft_params``/
    ``draft_cfg`` the proposal model (same vocab required). At the
    default ``temperature=0`` decoding is greedy and the result is
    bit-for-bit the target's own greedy decode. With ``eos_id``, a row
    that emits EOS pads the rest of its (static-length) output with EOS
    — exactly the semantics of
    :func:`rayfed_tpu.models.decode.make_generate_fn`; EOS tokens
    already inside the prompt are ignored. Prompt length must be
    at least ``k_draft + 1`` (the verification window).

    With ``temperature > 0`` the full rejection-sampling scheme runs
    (Leviathan et al.): the draft SAMPLES its proposals, each is
    accepted with probability ``min(1, p_target/p_draft)``, and the
    first rejection resamples from the normalized residual
    ``max(p_target - p_draft, 0)`` — the output distribution is exactly
    ancestral sampling from the target at that temperature (pinned
    against the enumerated exact distribution in tests). ``generate``
    then takes an ``rng`` argument. Batched rows stop at the min
    acceptance across the batch; truncating speculation early is
    distribution-preserving (rows that accepted at the cutoff emit
    their accepted proposal, not the residual).

    With ``mesh``, both models run sharded like
    :func:`rayfed_tpu.models.decode.make_generate_fn`: Megatron tp
    params for target AND draft (both trees must satisfy the rules'
    divisibility on the mesh), prompt batch over party x data, each
    model's K/V cache head-sharded where its head count divides the
    ``model`` axis (cache heads replicate otherwise).

    With ``return_stats=True`` the function returns ``(tokens,
    n_rounds)`` — the number of verify rounds (= target forwards) the
    generation took: ``max_new_tokens / n_rounds`` is the realized
    tokens-per-target-pass, the speedup knob speculation exists for
    (ceil(max_new / (k_draft+1)) when the draft always agrees,
    max_new when it never does).
    """
    if max_new_tokens < 1:
        raise ValueError("max_new_tokens must be >= 1")
    if k_draft < 1:
        raise ValueError("k_draft must be >= 1")
    if cfg.vocab != draft_cfg.vocab:
        raise ValueError(
            f"target and draft must share a vocab; got {cfg.vocab} vs "
            f"{draft_cfg.vocab}"
        )
    if temperature < 0.0:
        raise ValueError(f"temperature must be >= 0, got {temperature}")
    if eos_id is not None and not 0 <= eos_id < cfg.vocab:
        raise ValueError(f"eos_id must be in [0, {cfg.vocab}), got {eos_id}")
    w = k_draft + 1  # verification window
    sampled = temperature > 0.0

    def _cache_sharding(model_cfg):
        if mesh is None:
            return None
        from jax.sharding import NamedSharding

        from rayfed_tpu.models.decode import cache_spec

        return NamedSharding(mesh, cache_spec(
            mesh, party_axis, data_axis, model_axis,
            n_heads=model_cfg.n_heads,
        ))

    t_cache_sh = _cache_sharding(cfg)
    d_cache_sh = _cache_sharding(draft_cfg)

    def generate(params, draft_params, prompt, rng=None):
        if sampled and rng is None:
            raise ValueError(
                "temperature > 0 samples: pass rng=jax.random.PRNGKey(...) "
                "(a silent fixed key would make every call identical)"
            )
        b, s = prompt.shape
        if s < w:
            raise ValueError(
                f"prompt length {s} shorter than the verification window "
                f"{w} (= k_draft + 1)"
            )
        total = s + max_new_tokens
        # Slack absorbs the last round's overshoot (writes past `total`
        # are never returned; cache slots past it are never attended).
        cap = total + k_draft + 1
        buf = jnp.zeros((b, cap), prompt.dtype)
        buf = jax.lax.dynamic_update_slice(buf, prompt, (0, 0))
        t_cache = init_cache(cfg, b, cap)
        d_cache = init_cache(draft_cfg, b, cap)
        if mesh is not None:
            constrain = jax.lax.with_sharding_constraint
            t_cache = jax.tree_util.tree_map(
                lambda c: constrain(c, t_cache_sh), t_cache
            )
            d_cache = jax.tree_util.tree_map(
                lambda c: constrain(c, d_cache_sh), d_cache
            )
        _, t_cache = prefill(params, prompt, t_cache, cfg)
        _, d_cache = prefill(draft_params, prompt, d_cache, draft_cfg)

        def round_(carry):
            buf, t_cache, d_cache, pos, rounds, done = carry
            win = jax.lax.dynamic_slice(buf, (0, pos - w), (b, w))
            # Fresh randomness per (round start, position): pos strictly
            # advances each round, so folded keys never repeat even when
            # a rejected position is re-proposed next round.
            kr = jax.random.fold_in(rng, pos) if sampled else None

            def pick(logits, key):
                if not sampled:
                    return jnp.argmax(logits, axis=-1).astype(buf.dtype)
                return jax.random.categorical(
                    key, logits / temperature, axis=-1
                ).astype(buf.dtype)

            # Draft: window pass re-validates its cache and yields q_1;
            # k_draft-1 single-token steps yield q_2..q_k (keeping the
            # draft's log-probs for the acceptance ratios when sampling).
            d_logits, d_cache = forward_with_cache(
                draft_params, win, d_cache, pos - w, draft_cfg
            )
            q1 = pick(d_logits[:, -1],
                      jax.random.fold_in(kr, 1000) if sampled else None)

            def d_step(c, i):
                tok, cache, p = c
                lg, cache = forward_with_cache(
                    draft_params, tok[:, None], cache, p, draft_cfg
                )
                nxt = pick(lg[:, -1],
                           jax.random.fold_in(kr, 1001 + i)
                           if sampled else None)
                return (nxt, cache, p + 1), (nxt, lg[:, -1])

            (_, d_cache, _), (q_rest, d_lgs) = jax.lax.scan(
                d_step, (q1, d_cache, pos), jnp.arange(k_draft - 1)
            )
            q = (jnp.concatenate(
                [q1[:, None], jnp.moveaxis(q_rest, 0, 1)], axis=1
            ) if k_draft > 1 else q1[:, None])                    # (B, k)
            # Draft logits at the k proposal positions (B, k, V) — only
            # the sampled path pays for materializing the stack.
            d_stack = (jnp.concatenate(
                [d_logits[:, -1:], jnp.moveaxis(d_lgs, 0, 1)], axis=1
            ) if k_draft > 1 else d_logits[:, -1:]) if sampled else None

            # Target: one forward over [window, q_1..q_k] — its logits
            # at indices w-1..w+k-1 cover positions pos..pos+k given the
            # proposals.
            t_in = jnp.concatenate([win, q], axis=1)
            t_logits, t_cache = forward_with_cache(
                params, t_in, t_cache, pos - w, cfg
            )
            t_stack = t_logits[:, w - 1:]                      # (B, k+1, V)

            if not sampled:
                t_pred = jnp.argmax(t_stack, axis=-1).astype(buf.dtype)
                # Longest prefix of proposals the target agrees with,
                # min over batch rows (keeps `pos` scalar).
                eq = (q == t_pred[:, :k_draft]).astype(jnp.int32)
                n = jnp.min(jnp.cumprod(eq, axis=1).sum(axis=1))
                correction = jnp.take_along_axis(
                    t_pred, jnp.full((b, 1), n), axis=1
                )[:, 0]
            else:
                t_lp = jax.nn.log_softmax(
                    t_stack.astype(jnp.float32) / temperature, axis=-1
                )
                d_lp = jax.nn.log_softmax(
                    d_stack.astype(jnp.float32) / temperature, axis=-1
                )
                qi = q[..., None].astype(jnp.int32)
                lt_q = jnp.take_along_axis(t_lp[:, :k_draft], qi, -1)[..., 0]
                ld_q = jnp.take_along_axis(d_lp, qi, -1)[..., 0]
                u = jax.random.uniform(
                    jax.random.fold_in(kr, 2), (b, k_draft)
                )
                accept = (
                    jnp.log(jnp.maximum(u, 1e-38)) < (lt_q - ld_q)
                ).astype(jnp.int32)                              # (B, k)
                n_row = jnp.cumprod(accept, axis=1).sum(axis=1)
                n = jnp.min(n_row)
                # Correction at position pos+n: rows that rejected there
                # resample from the residual max(p_t - p_d, 0); rows the
                # batch-min merely cut short emit their accepted
                # proposal; n == k means everyone accepted everything
                # and the extra token samples straight from the target.
                t_ln = jnp.take_along_axis(
                    t_lp, jnp.full((b, 1, 1), n), axis=1
                )[:, 0]                                           # (B, V)
                d_ln = jnp.take_along_axis(
                    d_lp, jnp.full((b, 1, 1), jnp.minimum(n, k_draft - 1)),
                    axis=1,
                )[:, 0]
                pt, pd_ = jnp.exp(t_ln), jnp.exp(d_ln)
                res = jnp.maximum(pt - pd_, 0.0)
                z = res.sum(axis=-1, keepdims=True)
                res_probs = jnp.where(z > 1e-30, res / jnp.maximum(z, 1e-30),
                                      pt)
                final_probs = jnp.where(n < k_draft, res_probs, pt)
                sampled_corr = jax.random.categorical(
                    jax.random.fold_in(kr, 3),
                    jnp.log(jnp.maximum(final_probs, 1e-38)), axis=-1
                ).astype(buf.dtype)
                accepted_at_n = jnp.where(
                    n < k_draft,
                    jnp.take_along_axis(
                        accept, jnp.full((b, 1), jnp.minimum(n, k_draft - 1)),
                        axis=1,
                    )[:, 0],
                    jnp.zeros((b,), jnp.int32),
                )
                next_q = jnp.take_along_axis(
                    q, jnp.full((b, 1), jnp.minimum(n, k_draft - 1)), axis=1
                )[:, 0]
                correction = jnp.where(
                    accepted_at_n == 1, next_q, sampled_corr
                )

            # Emit q_1..q_n then the correction. Slots past n hold
            # proposals; a later round overwrites them before they can
            # ever be part of the consumed prefix.
            idx = jnp.arange(k_draft + 1)[None, :]
            padded_q = jnp.concatenate([q, q[:, -1:]], axis=1)
            emit = jnp.where(idx == n, correction[:, None], padded_q)
            if eos_id is not None:
                # EOS-pad within the emitted block (everything after a
                # row's first EOS — matching make_generate_fn's padding)
                # and across rounds (rows already done stay EOS).
                past_eos = jnp.cumsum(
                    (emit == eos_id).astype(jnp.int32), axis=1
                ) - (emit == eos_id).astype(jnp.int32)
                emit = jnp.where(
                    (past_eos > 0) | done[:, None],
                    jnp.asarray(eos_id, emit.dtype), emit,
                )
                # Only the consumed prefix of the block (n+1 tokens) can
                # finish a row; speculative slots past it are junk.
                consumed_eos = ((emit == eos_id) & (idx <= n)).any(axis=1)
                done = done | consumed_eos
            buf = jax.lax.dynamic_update_slice(buf, emit, (0, pos))
            return buf, t_cache, d_cache, pos + n + 1, rounds + 1, done

        def cond(carry):
            return carry[3] < total

        buf, _, _, _, rounds, _ = jax.lax.while_loop(
            cond, round_,
            (buf, t_cache, d_cache, jnp.asarray(s, jnp.int32),
             jnp.asarray(0, jnp.int32), jnp.zeros((b,), bool)),
        )
        out = jax.lax.dynamic_slice(buf, (0, 0), (b, total))
        return (out, rounds) if return_stats else out

    if not jit:
        return generate
    if mesh is None:
        return jax.jit(generate)

    from rayfed_tpu.models.decode import _sharded_jit

    dispatch = _sharded_jit(
        generate, mesh, party_axis, data_axis,
        n_extra_args=1, n_param_trees=2,
    )

    def sharded_generate(params, draft_params, prompt, rng=None):
        if sampled and rng is None:
            # generate() raises the same error at trace time; surface it
            # before jit dispatch for a cleaner traceback.
            raise ValueError(
                "temperature > 0 samples: pass rng=jax.random.PRNGKey(...) "
                "(a silent fixed key would make every call identical)"
            )
        return dispatch(
            params, draft_params, prompt,
            rng if rng is not None else jax.random.PRNGKey(0),
        )

    return sharded_generate
