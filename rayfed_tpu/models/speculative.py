# Copyright 2026 The rayfed-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Speculative decoding: a small draft model proposes, the target verifies.

Greedy draft-and-verify (Leviathan et al.'s rejection scheme reduces to
prefix matching when both models decode greedily): per round the draft
proposes ``k_draft`` tokens autoregressively, the target scores ALL of
them in one batched forward, the longest matching prefix is accepted and
the target's own next token is appended as the correction — so every
round emits between 1 and ``k_draft``+1 tokens for ONE target forward,
and the output is **exactly** the target model's greedy decoding
(pinned in tests). On TPU this converts the memory-bound one-token-at-
a-time decode into k+1-token target forwards that amortize the HBM
weight streaming the same way a larger batch would.

TPU-first mechanics (everything static-shaped inside one jit):

 - The loop is a ``lax.while_loop`` whose carry holds the token buffer,
   both K/V caches, and the scalar write position; each round's variable
   acceptance count only moves the position scalar.
 - Cache validity bookkeeping is COLLAPSED by recomputation: each round
   re-runs the trailing ``k_draft+1``-token window through both models
   at its true offset before extending. Re-processing tokens whose
   cache entries were already correct rewrites identical values, and the
   window always covers the one position a rejection can have staled
   (the correction slot), so no validity state needs tracking — the
   cost is one extra window's worth of compute per round.
 - Batched prompts accept ``min`` over rows per round (rows with longer
   matches simply waste some speculation) so the position stays scalar;
   the emitted correction token is still per-row correct because it
   conditions only on accepted tokens.

The reference engine has no inference at all; within this framework the
draft model is the natural thing to train with federated distillation
and serve next to the aggregated target.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from rayfed_tpu.models import transformer as tfm
from rayfed_tpu.models.decode import forward_with_cache, init_cache, prefill


def make_speculative_generate_fn(
    cfg: tfm.TransformerConfig,
    draft_cfg: tfm.TransformerConfig,
    *,
    max_new_tokens: int,
    k_draft: int = 4,
    jit: bool = True,
    return_stats: bool = False,
):
    """Build ``generate(params, draft_params, prompt) -> (B, S+max_new)``.

    ``params``/``cfg`` are the target model, ``draft_params``/
    ``draft_cfg`` the proposal model (same vocab required). Greedy only;
    the result is bit-for-bit the target's own greedy decode. Prompt
    length must be at least ``k_draft + 1`` (the verification window).

    With ``return_stats=True`` the function returns ``(tokens,
    n_rounds)`` — the number of verify rounds (= target forwards) the
    generation took: ``max_new_tokens / n_rounds`` is the realized
    tokens-per-target-pass, the speedup knob speculation exists for
    (ceil(max_new / (k_draft+1)) when the draft always agrees,
    max_new when it never does).
    """
    if max_new_tokens < 1:
        raise ValueError("max_new_tokens must be >= 1")
    if k_draft < 1:
        raise ValueError("k_draft must be >= 1")
    if cfg.vocab != draft_cfg.vocab:
        raise ValueError(
            f"target and draft must share a vocab; got {cfg.vocab} vs "
            f"{draft_cfg.vocab}"
        )
    w = k_draft + 1  # verification window

    def generate(params, draft_params, prompt):
        b, s = prompt.shape
        if s < w:
            raise ValueError(
                f"prompt length {s} shorter than the verification window "
                f"{w} (= k_draft + 1)"
            )
        total = s + max_new_tokens
        # Slack absorbs the last round's overshoot (writes past `total`
        # are never returned; cache slots past it are never attended).
        cap = total + k_draft + 1
        buf = jnp.zeros((b, cap), prompt.dtype)
        buf = jax.lax.dynamic_update_slice(buf, prompt, (0, 0))
        t_cache = init_cache(cfg, b, cap)
        d_cache = init_cache(draft_cfg, b, cap)
        _, t_cache = prefill(params, prompt, t_cache, cfg)
        _, d_cache = prefill(draft_params, prompt, d_cache, draft_cfg)

        def round_(carry):
            buf, t_cache, d_cache, pos, rounds = carry
            win = jax.lax.dynamic_slice(buf, (0, pos - w), (b, w))

            # Draft: window pass re-validates its cache and yields q_1;
            # k_draft-1 single-token steps yield q_2..q_k.
            d_logits, d_cache = forward_with_cache(
                draft_params, win, d_cache, pos - w, draft_cfg
            )
            q1 = jnp.argmax(d_logits[:, -1], axis=-1).astype(buf.dtype)

            def d_step(c, _):
                tok, cache, p = c
                lg, cache = forward_with_cache(
                    draft_params, tok[:, None], cache, p, draft_cfg
                )
                nxt = jnp.argmax(lg[:, -1], axis=-1).astype(buf.dtype)
                return (nxt, cache, p + 1), nxt

            (_, d_cache, _), qs = jax.lax.scan(
                d_step, (q1, d_cache, pos), None, length=k_draft - 1
            )
            q = jnp.concatenate(
                [q1[:, None], jnp.moveaxis(qs, 0, 1)], axis=1
            ) if k_draft > 1 else q1[:, None]                     # (B, k)

            # Target: one forward over [window, q_1..q_k] — its logits at
            # indices w-1..w+k-1 are the argmax choices for positions
            # pos..pos+k given the proposals.
            t_in = jnp.concatenate([win, q], axis=1)
            t_logits, t_cache = forward_with_cache(
                params, t_in, t_cache, pos - w, cfg
            )
            t_pred = jnp.argmax(t_logits[:, w - 1:], axis=-1).astype(
                buf.dtype
            )                                                    # (B, k+1)

            # Longest prefix of proposals the target agrees with, min
            # over batch rows (keeps `pos` scalar; see module docstring).
            eq = (q == t_pred[:, :k_draft]).astype(jnp.int32)
            n = jnp.min(jnp.cumprod(eq, axis=1).sum(axis=1))

            # Emit q_1..q_n then the target's correction t_{n+1}. Slots
            # past n are filled with proposals; a later round overwrites
            # them before they can ever be part of the consumed prefix.
            idx = jnp.arange(k_draft + 1)[None, :]
            padded_q = jnp.concatenate([q, q[:, -1:]], axis=1)
            correction = jnp.take_along_axis(
                t_pred, jnp.full((b, 1), n), axis=1
            )
            emit = jnp.where(idx == n, correction, padded_q)
            buf = jax.lax.dynamic_update_slice(buf, emit, (0, pos))
            return buf, t_cache, d_cache, pos + n + 1, rounds + 1

        def cond(carry):
            return carry[3] < total

        buf, _, _, _, rounds = jax.lax.while_loop(
            cond, round_,
            (buf, t_cache, d_cache, jnp.asarray(s, jnp.int32),
             jnp.asarray(0, jnp.int32)),
        )
        out = jax.lax.dynamic_slice(buf, (0, 0), (b, total))
        return (out, rounds) if return_stats else out

    return jax.jit(generate) if jit else generate
