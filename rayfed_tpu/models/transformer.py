# Copyright 2026 The rayfed-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Decoder-only transformer LM (functional JAX, TPU-first).

The reference ships no models (its engine moves opaque payloads); this is
the flagship model family for federated LM training on party meshes — the
driver's graft entry jits its forward, and ``parallel/`` shards its train
step over party/data/model/seq mesh axes.

TPU-first design choices:
 - layer parameters are **stacked** along a leading (n_layers, ...) axis and
   the forward is a single ``lax.scan`` over layers: one compiled layer body
   regardless of depth, XLA-friendly, and the stacked leaves shard cleanly;
 - matmul-heavy blocks (QKV/O projections, SwiGLU) are einsums that tile
   onto the MXU; compute dtype is configurable (bf16 by default) with
   params and softmax/logsumexp accumulation kept in f32;
 - RoPE + causal attention with an optional ring-attention path
   (:mod:`rayfed_tpu.parallel.ring`) for sequence-parallel long context.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp
from jax.ad_checkpoint import checkpoint_name

Params = Dict[str, Any]


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab: int = 32000
    d_model: int = 512
    n_heads: int = 8
    n_layers: int = 4
    d_ff: int = 1408  # SwiGLU (or per-expert MoE) hidden width
    rope_theta: float = 10000.0
    compute_dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    # 0 = dense SwiGLU FFN; >0 = top-1 MoE FFN with this many experts
    # (expert-parallel over an "expert" mesh axis; see models/moe.py).
    n_experts: int = 0

    @property
    def head_dim(self) -> int:
        assert self.d_model % self.n_heads == 0
        return self.d_model // self.n_heads


def tiny_config(**overrides) -> TransformerConfig:
    """A config small enough to compile in seconds on one chip / CPU sim."""
    base = dict(vocab=256, d_model=64, n_heads=4, n_layers=2, d_ff=176)
    base.update(overrides)
    return TransformerConfig(**base)


# ---------------------------------------------------------------------------
# Init
# ---------------------------------------------------------------------------


def init_params(rng, cfg: TransformerConfig) -> Params:
    d, h, dh, f = cfg.d_model, cfg.n_heads, cfg.head_dim, cfg.d_ff
    k_embed, k_layers, k_out = jax.random.split(rng, 3)

    def dense(key, shape, fan_in):
        return (jax.random.normal(key, shape) * (fan_in**-0.5)).astype(
            cfg.param_dtype
        )

    def layer(key):
        ks = jax.random.split(key, 7)
        out = {
            "ln1": jnp.ones((d,), cfg.param_dtype),
            "wq": dense(ks[0], (d, h, dh), d),
            "wk": dense(ks[1], (d, h, dh), d),
            "wv": dense(ks[2], (d, h, dh), d),
            "wo": dense(ks[3], (h, dh, d), h * dh),
            "ln2": jnp.ones((d,), cfg.param_dtype),
        }
        if cfg.n_experts > 0:
            from rayfed_tpu.models.moe import init_moe_ffn

            out["moe"] = init_moe_ffn(
                ks[4], d, f, cfg.n_experts, dtype=cfg.param_dtype
            )
        else:
            out.update(
                w_gate=dense(ks[4], (d, f), d),
                w_up=dense(ks[5], (d, f), d),
                w_down=dense(ks[6], (f, d), f),
            )
        return out

    layer_keys = jax.random.split(k_layers, cfg.n_layers)
    stacked = jax.tree_util.tree_map(
        lambda *xs: jnp.stack(xs), *[layer(k) for k in layer_keys]
    )
    return {
        "embed": (jax.random.normal(k_embed, (cfg.vocab, d)) * 0.02).astype(
            cfg.param_dtype
        ),
        "layers": stacked,
        "ln_f": jnp.ones((d,), cfg.param_dtype),
        # Untied output head: keeps vocab-dim sharding independent.
        "lm_head": dense(k_out, (d, cfg.vocab), d),
    }


# ---------------------------------------------------------------------------
# Blocks
# ---------------------------------------------------------------------------


def rms_norm(x, scale, eps: float = 1e-6):
    dtype = x.dtype
    x32 = x.astype(jnp.float32)
    inv = jax.lax.rsqrt(jnp.mean(x32 * x32, axis=-1, keepdims=True) + eps)
    return (x32 * inv).astype(dtype) * scale.astype(dtype)


def rope(q, k, positions, theta: float):
    """Rotary position embedding on (B, S, H, Dh) q/k."""
    dh = q.shape[-1]
    half = dh // 2
    freqs = theta ** (-jnp.arange(0, half, dtype=jnp.float32) / half)
    angles = positions[..., None].astype(jnp.float32) * freqs  # (B, S, half)
    cos = jnp.cos(angles)[:, :, None, :]
    sin = jnp.sin(angles)[:, :, None, :]

    def rot(x):
        x1, x2 = x[..., :half], x[..., half:]
        out = jnp.concatenate(
            [x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1
        )
        return out.astype(x.dtype)

    return rot(q), rot(k)


def causal_attention(q, k, v, q_offset=None):
    """Standard causal attention on (B, S, H, Dh); softmax in f32.

    ``q_offset`` shifts query positions (used by sequence-parallel callers
    where this shard's queries start at a global offset).
    """
    b, sq, h, dh = q.shape
    sk = k.shape[1]
    scale = dh**-0.5
    scores = jnp.einsum(
        "bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32
    ) * scale
    q_pos = jnp.arange(sq)[:, None] + (0 if q_offset is None else q_offset)
    mask = q_pos >= jnp.arange(sk)[None, :]
    scores = jnp.where(mask[None, None], scores, -jnp.inf)
    probs = jax.nn.softmax(scores, axis=-1).astype(v.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


AttnFn = Callable[..., jax.Array]


def qkv_proj(x, layer: Params, positions, cfg: TransformerConfig):
    """Pre-norm + Q/K/V projections + RoPE for one block; shared by the
    training forward and the KV-cache decode path (models/decode.py)."""
    cdt = cfg.compute_dtype
    h = rms_norm(x, layer["ln1"])
    q = jnp.einsum("bsd,dhk->bshk", h, layer["wq"].astype(cdt))
    k = jnp.einsum("bsd,dhk->bshk", h, layer["wk"].astype(cdt))
    v = jnp.einsum("bsd,dhk->bshk", h, layer["wv"].astype(cdt))
    q, k = rope(q, k, positions, cfg.rope_theta)
    return q, k, v


def ffn_apply(hmlp, layer: Params, cfg: TransformerConfig):
    """The block's FFN half on a pre-normed input: SwiGLU, or the MoE FFN
    when ``cfg.n_experts > 0``."""
    cdt = cfg.compute_dtype
    if cfg.n_experts > 0:
        from rayfed_tpu.models.moe import moe_ffn_apply

        moe = jax.tree_util.tree_map(
            lambda p: p.astype(cdt), layer["moe"]
        )
        return moe_ffn_apply(moe, hmlp)
    gate = jax.nn.silu(hmlp @ layer["w_gate"].astype(cdt))
    up = hmlp @ layer["w_up"].astype(cdt)
    return (gate * up) @ layer["w_down"].astype(cdt)


def layer_fn(x, layer: Params, positions, cfg: TransformerConfig,
             attn_fn: Optional[AttnFn] = None):
    """One pre-norm decoder block; ``attn_fn(q, k, v)`` is pluggable so
    sequence-parallel callers can swap in ring attention."""
    attn_fn = attn_fn or causal_attention
    cdt = cfg.compute_dtype
    q, k, v = qkv_proj(x, layer, positions, cfg)
    # Named for selective rematerialization: saving each layer's attention
    # output (B*S*D, the cheapest-to-keep/most-expensive-to-recompute
    # tensor) lets the remat backward skip re-running the attention kernel.
    o = checkpoint_name(attn_fn(q, k, v), "attn_out")
    x = x + jnp.einsum("bshk,hkd->bsd", o, layer["wo"].astype(cdt))
    hmlp = rms_norm(x, layer["ln2"])
    return x + ffn_apply(hmlp, layer, cfg)


def hidden_states(params: Params, tokens, cfg: TransformerConfig,
                  attn_fn: Optional[AttnFn] = None,
                  positions=None, remat: bool = False) -> jax.Array:
    """tokens (B, S) int32 -> final hidden states (B, S, d_model), post
    final-norm, in compute dtype.

    Layers run under one ``lax.scan`` over the stacked parameters.
    ``remat=True`` checkpoints each layer (recompute activations in the
    backward pass — HBM for FLOPs, the standard trade for deep/long
    configs).
    """
    b, s = tokens.shape
    if positions is None:
        positions = jnp.broadcast_to(jnp.arange(s), (b, s))
    x = params["embed"][tokens].astype(cfg.compute_dtype)

    def body(x, layer):
        return layer_fn(x, layer, positions, cfg, attn_fn), None

    if remat:
        # prevent_cse=False: scan's loop semantics already block the CSE
        # that checkpoint's default barriers guard against; leaving them on
        # just costs XLA fusion opportunities. remat="attn" additionally
        # saves each layer's attention output (B*S*d_model bf16) so the
        # backward skips re-running the attention kernel — opt-in: the
        # named-save policy costs dramatically longer XLA compiles around
        # the Pallas custom_vjp under scan.
        policy = (
            jax.checkpoint_policies.save_only_these_names("attn_out")
            if remat == "attn" else None
        )
        body = jax.checkpoint(body, prevent_cse=False, policy=policy)
    x, _ = jax.lax.scan(body, x, params["layers"])
    return rms_norm(x, params["ln_f"])


def forward(params: Params, tokens, cfg: TransformerConfig,
            attn_fn: Optional[AttnFn] = None,
            positions=None, remat: bool = False) -> jax.Array:
    """tokens (B, S) int32 -> logits (B, S, vocab) float32."""
    x = hidden_states(params, tokens, cfg, attn_fn, positions, remat)
    return (x @ params["lm_head"].astype(cfg.compute_dtype)).astype(jnp.float32)


def lm_loss_pair(params: Params, inputs, targets, cfg: TransformerConfig,
                 attn_fn: Optional[AttnFn] = None,
                 remat: bool = False,
                 loss_chunk: Optional[int] = None) -> jax.Array:
    """Next-token cross entropy over pre-shifted (inputs, targets) pairs,
    both (B, S) — the sharding-friendly form (S stays divisible by the seq
    axis; no in-jit slicing of sharded dims). f32 accumulation.

    ``loss_chunk`` evaluates the vocab head + CE in checkpointed chunks of
    that many sequence positions, so the full (B, S, vocab) f32 logits
    never materialize — at 32k vocab they dominate step memory. Leave None
    when the sequence dim is sharded (chunking reshapes S).
    """
    x = hidden_states(params, inputs, cfg, attn_fn, remat=remat)
    w = params["lm_head"].astype(cfg.compute_dtype)
    if not loss_chunk or x.shape[1] % loss_chunk:
        logits = (x @ w).astype(jnp.float32)
        logz = jax.nn.logsumexp(logits, axis=-1)
        gold = jnp.take_along_axis(logits, targets[..., None], axis=-1)[..., 0]
        return (logz - gold).mean()

    b, s, d = x.shape
    n = s // loss_chunk

    def chunk_ce(carry, xt):
        xc, tc = xt  # (B, chunk, D), (B, chunk)
        logits = (xc @ w).astype(jnp.float32)
        logz = jax.nn.logsumexp(logits, axis=-1)
        gold = jnp.take_along_axis(logits, tc[..., None], axis=-1)[..., 0]
        return carry + (logz - gold).sum(), None

    xs = jnp.moveaxis(x.reshape(b, n, loss_chunk, d), 1, 0)
    ts = jnp.moveaxis(targets.reshape(b, n, loss_chunk), 1, 0)
    total, _ = jax.lax.scan(
        jax.checkpoint(chunk_ce, prevent_cse=False), jnp.zeros((), jnp.float32),
        (xs, ts),
    )
    return total / (b * s)


def lm_loss(params: Params, tokens, cfg: TransformerConfig,
            attn_fn: Optional[AttnFn] = None) -> jax.Array:
    """Next-token cross entropy over a (B, S+1) token block."""
    return lm_loss_pair(params, tokens[:, :-1], tokens[:, 1:], cfg, attn_fn)
