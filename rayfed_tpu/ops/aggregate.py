# Copyright 2026 The rayfed-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Jitted federated aggregation ops.

The reference expresses aggregation as plain user Python (the README
``aggregate`` at ``README.md:83-86``, weight averaging in
``fed/tests/test_fed_get.py:66-83``). Here aggregation is a first-class,
jit-compiled tree op so FedAvg-style reductions fuse into single XLA
programs on the party mesh (MXU-friendly: one fused elementwise pass over
each leaf, no Python loop per tensor).

Determinism note (SURVEY.md §7 "bitwise-identical aggregates"): summation
order over parties is fixed by argument order — a left-to-right fold — and
accumulation happens in ``acc_dtype`` (default float32), so the same inputs
produce bitwise-identical outputs on every party and transport.
"""

from __future__ import annotations

import functools
from typing import Any, Optional, Sequence

import jax
import jax.numpy as jnp


def _fold_sum(leaves: Sequence[Any], acc_dtype):
    acc = leaves[0].astype(acc_dtype) if acc_dtype else leaves[0]
    for x in leaves[1:]:
        acc = acc + (x.astype(acc_dtype) if acc_dtype else x)
    return acc


@functools.partial(jax.jit, static_argnames=("acc_dtype",))
def _tree_sum(trees, acc_dtype: Optional[str] = "float32"):
    dtype = jnp.dtype(acc_dtype) if acc_dtype else None
    return jax.tree_util.tree_map(
        lambda *xs: _fold_sum(xs, dtype).astype(xs[0].dtype), *trees
    )


@functools.partial(jax.jit, static_argnames=("acc_dtype",))
def _tree_mean(trees, acc_dtype: Optional[str] = "float32"):
    n = len(trees)
    dtype = jnp.dtype(acc_dtype) if acc_dtype else None
    return jax.tree_util.tree_map(
        lambda *xs: (_fold_sum(xs, dtype) / n).astype(xs[0].dtype), *trees
    )


@functools.partial(jax.jit, static_argnames=("acc_dtype",))
def _tree_weighted_mean(trees, weights, acc_dtype: Optional[str] = "float32"):
    dtype = jnp.dtype(acc_dtype) if acc_dtype else None
    total = _fold_sum([jnp.asarray(w) for w in weights], dtype)

    def leaf(*xs):
        acc = xs[0] * weights[0] if dtype is None else xs[0].astype(dtype) * weights[0]
        for x, w in zip(xs[1:], weights[1:]):
            acc = acc + (x.astype(dtype) if dtype else x) * w
        return (acc / total).astype(xs[0].dtype)

    return jax.tree_util.tree_map(leaf, *trees)


def tree_sum(*trees, acc_dtype: Optional[str] = "float32"):
    """Elementwise sum of N identically-shaped pytrees (FedSum)."""
    if len(trees) == 1:
        return trees[0]
    return _tree_sum(tuple(trees), acc_dtype=acc_dtype)


def tree_mean(*trees, acc_dtype: Optional[str] = "float32"):
    """Elementwise mean of N identically-shaped pytrees (FedAvg)."""
    if len(trees) == 1:
        return trees[0]
    return _tree_mean(tuple(trees), acc_dtype=acc_dtype)


def tree_weighted_mean(trees, weights, acc_dtype: Optional[str] = "float32"):
    """Sample-count-weighted FedAvg: sum_i w_i * tree_i / sum_i w_i."""
    assert len(trees) == len(weights) and trees
    if len(trees) == 1:
        return trees[0]
    return _tree_weighted_mean(tuple(trees), tuple(weights), acc_dtype=acc_dtype)


@functools.partial(jax.jit, static_argnames=("acc_dtype",))
def _tree_mix(old, new, lr, acc_dtype: Optional[str] = "float32"):
    dtype = jnp.dtype(acc_dtype) if acc_dtype else None

    def leaf(o, n):
        oa = o.astype(dtype) if dtype is not None else o
        na = n.astype(dtype) if dtype is not None else n
        return (oa + lr * (na - oa)).astype(n.dtype)

    return jax.tree_util.tree_map(leaf, old, new)


def tree_mix(old, new, lr: float, acc_dtype: Optional[str] = "float32"):
    """Server-learning-rate mix for buffered-async rounds (FedBuff's
    server step): ``old + lr * (new - old)`` per leaf, accumulated in
    ``acc_dtype`` and cast back to the leaf dtype.

    ``lr == 1.0`` or ``old is None`` returns ``new`` UNTOUCHED — the
    async determinism contract requires the default configuration's
    published model to be bitwise the buffered mean, with no mix
    arithmetic perturbing it."""
    if old is None or lr == 1.0:
        return new
    return _tree_mix(old, new, float(lr), acc_dtype=acc_dtype)


def reduce_by_plan(
    plan,
    contributions,
    weights=None,
    acc_dtype: Optional[str] = "float32",
):
    """Fold ``{party: tree}`` following a
    :class:`~rayfed_tpu.topology.TopologyPlan`'s exact association order.

    This is the local-execution twin of ``fed_aggregate``'s distributed
    lowering: each plan step k-ary-folds its ``srcs`` partials (weighted:
    premultiplied trees + running weight totals), so the arithmetic — and
    therefore the bits — matches what the wire topology produces. Used by
    the scale bench and the bitwise-identity tests to compare topologies
    without N processes, and by :func:`elastic_weighted_mean` when a
    topology is requested.

    Returns the weighted mean over ``plan.parties``.
    """
    missing = set(plan.parties) - set(contributions)
    if missing:
        raise ValueError(
            f"plan references parties with no contribution: {sorted(missing)}"
        )
    held = {}
    totals = {}
    for p in plan.parties:
        w = 1.0 if weights is None else weights[p]
        held[p] = jax.tree_util.tree_map(
            lambda x, w=w: x * w, contributions[p]
        )
        totals[p] = w
    for level in plan.levels:
        for step in level:
            held[step.dst] = tree_sum(
                *[held[s] for s in step.srcs], acc_dtype=acc_dtype
            )
            totals[step.dst] = sum(totals[s] for s in step.srcs)
            for s in step.srcs[1:]:
                del held[s], totals[s]
    total = totals[plan.root]
    return jax.tree_util.tree_map(
        lambda x: x / total, held[plan.root]
    )


def psum_by_plan(
    plan,
    contributions,
    weights=None,
    acc_dtype: Optional[str] = "float32",
    mesh=None,
    deterministic: bool = True,
):
    """Lower a FLAT plan to one collective across the composed party
    mesh's ``party`` axis — the same weighted mean :func:`reduce_by_plan`
    computes, BITWISE-equal, in a single shard_map program instead of a
    premultiply/fold/scale chain.

    Eligibility: ``topology.plan_is_flat(plan)`` and a composed mesh
    registered for exactly ``plan.parties``
    (``mesh.compose_party_mesh``), or passed via ``mesh=``. Each party's
    contribution is premultiplied by its weight in its own dtype, stacked
    along the party axis, and reduced on device.

    ``deterministic=True`` (default) all_gathers the party slots and
    folds them in plan order in ``acc_dtype`` — the exact association
    :func:`reduce_by_plan` uses, so bit-equality holds on every backend.
    ``deterministic=False`` lowers to a raw ``jax.lax.psum``, whose
    association order is backend-defined: bitwise-equal on backends whose
    all-reduce folds linearly (the CPU simulator does), cheaper on TPU
    rings, but not a portable bit-contract.
    """
    from rayfed_tpu import mesh as mesh_mod
    from rayfed_tpu import topology as topo

    if not topo.plan_is_flat(plan):
        raise ValueError(
            f"psum_by_plan needs a flat plan; got topology="
            f"{plan.topology!r} with {plan.num_rounds} rounds"
        )
    missing = set(plan.parties) - set(contributions)
    if missing:
        raise ValueError(
            f"plan references parties with no contribution: {sorted(missing)}"
        )
    parties = plan.parties
    ws = [1.0 if weights is None else weights[p] for p in parties]
    # Premultiply in the leaf's own dtype, then total the weights the way
    # reduce_by_plan's ``sum()`` does (0 + w0 + w1 + ...): both choices
    # are part of the bit contract.
    pre = [
        jax.tree_util.tree_map(lambda x, w=w: x * w, contributions[p])
        for p, w in zip(parties, ws)
    ]
    total = sum(ws)
    if len(parties) == 1:
        return jax.tree_util.tree_map(lambda x: x / total, pre[0])
    if mesh is None:
        mesh = mesh_mod.composed_mesh_for(parties)
    if mesh is None:
        raise ValueError(
            f"no composed party mesh registered for parties {parties} "
            "(call mesh.compose_party_mesh first)"
        )
    from jax.sharding import NamedSharding, PartitionSpec as P

    n = len(parties)
    stacked = jax.tree_util.tree_map(
        lambda *xs: jax.device_put(
            jnp.stack([jnp.asarray(x) for x in xs]),
            NamedSharding(mesh, P("party")),
        ),
        *pre,
    )
    reduced = _psum_flat_fn(mesh, n, acc_dtype or "", deterministic)(stacked)
    # Every party slot holds the identical sum; slot 0 stands in. The
    # division happens HERE, outside the cached program, so changing
    # weights between rounds never recompiles — same op on the same
    # values as reduce_by_plan's final scale, so the bits still match.
    return jax.tree_util.tree_map(lambda x: x[0] / total, reduced)


@functools.lru_cache(maxsize=32)
def _psum_flat_fn(mesh, n: int, acc_dtype: str, deterministic: bool):
    """The compiled party-axis reduction for :func:`psum_by_plan`. Cached
    on (mesh, n, acc_dtype, deterministic) — repeat aggregation rounds on
    the same composed mesh reuse one XLA program instead of re-tracing
    the shard_map every call (jit's own cache handles leaf shapes)."""
    from jax.sharding import PartitionSpec as P

    try:
        from jax import shard_map
    except ImportError:  # jax 0.4.x
        from jax.experimental.shard_map import shard_map

    dtype = jnp.dtype(acc_dtype) if acc_dtype else None

    def body(local_tree):
        def leaf(x):  # x: this party's slot, shape (1, ...)
            orig = x.dtype
            if deterministic:
                g = jax.lax.all_gather(x[0], "party", axis=0)
                acc = g[0].astype(dtype) if dtype is not None else g[0]
                for i in range(1, n):
                    nxt = g[i].astype(dtype) if dtype is not None else g[i]
                    acc = acc + nxt
            else:
                acc = jax.lax.psum(
                    x[0].astype(dtype) if dtype is not None else x[0],
                    "party",
                )
            return acc.astype(orig)[None]

        return jax.tree_util.tree_map(leaf, local_tree)

    return jax.jit(
        shard_map(body, mesh=mesh, in_specs=P("party"), out_specs=P("party"))
    )


def elastic_weighted_mean(
    contributions,
    weights=None,
    liveness=None,
    acc_dtype: Optional[str] = "float32",
    topology: Optional[str] = None,
    group_size: Optional[int] = None,
):
    """Degraded-mode FedAvg: the weighted mean over SURVIVING
    contributors, re-normalized so the aggregate stays an average of what
    actually arrived (docs/resilience.md).

    ``contributions`` is ``{party: tree_or_missing}``. A contributor is
    dropped when its value is absent — None or the ``fed.MISSING``
    sentinel, i.e. what ``fed.get(..., on_missing="default")`` yields for
    a lost push — or when ``liveness`` (a ``{party: state}`` view from
    ``fed.liveness_view()``) marks it DEAD. The DEAD check matters even
    when the value DID arrive: a partitioned peer's stale round-k update
    averaged into round k+n is worse than no update (the classic
    straggler-poisoning failure), so the liveness verdict wins.

    ``weights`` maps party -> sample count (uniform when None). Raises
    ``ValueError`` when no contributor survives — an empty average has no
    meaningful value, and silently returning zeros would train on them.

    Survivor fold order is party-name order, independent of which subset
    survived, so the same surviving set produces bitwise-identical
    aggregates on every party (the determinism contract above).

    ``topology`` (None = the flat left-to-right fold above) folds along a
    planned reduction shape instead — the plan is laid out over the
    surviving set (a DEAD party re-plans the topology rather than
    leaving a hole in it), and the association order matches what
    ``fed_aggregate`` produces on the wire for the same survivors.
    """
    from rayfed_tpu.resilience.degraded import MISSING
    from rayfed_tpu.resilience.liveness import DEAD

    liveness = liveness or {}
    survivors = [
        p for p in sorted(contributions)
        if contributions[p] is not None
        and contributions[p] is not MISSING
        and liveness.get(p) != DEAD
    ]
    if not survivors:
        raise ValueError(
            "no surviving contributors to aggregate: all values missing "
            "or their parties marked DEAD"
        )
    if topology is not None:
        from rayfed_tpu import topology as topo

        surv_plan = topo.plan(survivors, topology, group_size=group_size)
        return reduce_by_plan(
            surv_plan,
            {p: contributions[p] for p in survivors},
            weights=None if weights is None
            else {p: weights[p] for p in survivors},
            acc_dtype=acc_dtype,
        )
    trees = [contributions[p] for p in survivors]
    w = [1.0 if weights is None else weights[p] for p in survivors]
    return tree_weighted_mean(trees, w, acc_dtype=acc_dtype)
