"""Pallas TPU flash attention (causal, forward).

Blockwise attention with an online softmax: each q-block streams through
the k/v blocks at or below its diagonal, keeping the running max and
normalizer in registers, so the S x S score matrix never materializes in
HBM — O(S) memory instead of O(S^2), with the block matmuls sized for the
MXU (128-lane tiles, f32 accumulation via ``preferred_element_type``).

On non-TPU backends the same kernel runs in interpret mode (tests), and
:func:`make_flash_attn_fn` plugs it into the transformer's ``attn_fn`` seam
(``models/transformer.layer_fn``), composing with the ring-attention lane:
ring handles the cross-device sequence axis, this kernel the on-device
blocks.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

_NEG_BIG = -1e30


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, *, block_q: int, block_k: int,
                  scale: float, seq_len: int, q_offset_base: int):
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32) * scale  # (block_q, D)
    d = q.shape[-1]

    q_pos = (
        q_offset_base + qi * block_q
        + jax.lax.broadcasted_iota(jnp.int32, (block_q, 1), 0)
    )

    # Causal: only k/v blocks at or below this q block's last row.
    last_q_pos = q_offset_base + qi * block_q + block_q - 1
    n_kb = jax.lax.min(
        (last_q_pos // block_k) + 1,
        jnp.int32(seq_len // block_k),
    )

    def body(kb, carry):
        m, l, acc = carry
        kblk = k_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        vblk = v_ref[0, pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, kblk,
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # (block_q, block_k)
        k_pos = (
            kb * block_k
            + jax.lax.broadcasted_iota(jnp.int32, (1, block_k), 1)
        )
        s = jnp.where(q_pos >= k_pos, s, _NEG_BIG)
        m_new = jnp.maximum(m, s.max(axis=-1))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new[:, None])
        l_new = l * alpha + p.sum(axis=-1)
        acc_new = acc * alpha[:, None] + jax.lax.dot_general(
            p, vblk,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return m_new, l_new, acc_new

    m0 = jnp.full((block_q,), _NEG_BIG, jnp.float32)
    l0 = jnp.zeros((block_q,), jnp.float32)
    acc0 = jnp.zeros((block_q, d), jnp.float32)
    m, l, acc = jax.lax.fori_loop(0, n_kb, body, (m0, l0, acc0))
    o_ref[0] = (acc / jnp.maximum(l, 1e-30)[:, None]).astype(o_ref.dtype)


@functools.partial(
    jax.jit,
    static_argnames=("block_q", "block_k", "interpret", "q_offset"),
)
def flash_attention(
    q, k, v,
    block_q: int = 128,
    block_k: int = 128,
    interpret: Optional[bool] = None,
    q_offset: int = 0,
):
    """Causal flash attention on (B, S, H, D) tensors.

    ``q_offset`` shifts query positions (sequence-parallel callers pass the
    shard's global offset). Sequence length must be divisible by the block
    sizes (pad upstream); block sizes auto-shrink for short sequences.
    """
    b, sq, h, d = q.shape
    sk = k.shape[1]
    block_q = min(block_q, sq)
    block_k = min(block_k, sk)
    assert sq % block_q == 0 and sk % block_k == 0, (sq, sk, block_q, block_k)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    scale = d**-0.5

    # Fold batch and heads into one leading grid axis: (B*H, S, D).
    def fold(x):
        return jnp.transpose(x, (0, 2, 1, 3)).reshape(b * h, x.shape[1], d)

    qf, kf, vf = fold(q), fold(k), fold(v)

    kernel = functools.partial(
        _flash_kernel,
        block_q=block_q,
        block_k=block_k,
        scale=scale,
        seq_len=sk,
        q_offset_base=q_offset,
    )
    out = pl.pallas_call(
        kernel,
        grid=(b * h, sq // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, qi: (bh, qi, 0)),
            pl.BlockSpec((1, sk, d), lambda bh, qi: (bh, 0, 0)),
            pl.BlockSpec((1, sk, d), lambda bh, qi: (bh, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda bh, qi: (bh, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, sq, d), q.dtype),
        interpret=interpret,
    )(qf, kf, vf)
    return jnp.transpose(out.reshape(b, h, sq, d), (0, 2, 1, 3))


def make_flash_attn_fn(block_q: int = 128, block_k: int = 128):
    """An ``attn_fn`` for ``models.transformer.forward``: (B, S, H, D)
    q/k/v -> (B, S, H, D), causal."""

    def attn(q, k, v):
        return flash_attention(q, k, v, block_q=block_q, block_k=block_k)

    return attn
