# Copyright 2026 The rayfed-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Pallas TPU flash attention (causal) with a blockwise backward pass.

Forward: blockwise attention with an online softmax — each q-block streams
through the k/v blocks at or below its diagonal, keeping the running max
and normalizer in registers, so the S x S score matrix never materializes
in HBM: O(S) memory instead of O(S^2), with the block matmuls sized for
the MXU (128-lane tiles, f32 accumulation via ``preferred_element_type``).
The kernel also emits the per-row logsumexp, which makes the attention
differentiable without rerunning the online softmax.

Backward: the standard FlashAttention recurrences (dP = dO V^T,
dS = P (dP - D), dQ = dS K, dK = dS^T Q, dV = P^T dO) as two Pallas
kernels — a dq pass (one q-block per program streaming its causal k/v
blocks) and a dk/dv pass (one k-block per program streaming its q blocks).
The p/dS tiles live only in VMEM, so the backward, like the forward, never
puts S^2 score traffic through HBM.

On non-TPU backends the kernel runs in interpret mode (tests), and
:func:`make_flash_attn_fn` plugs it into the transformer's ``attn_fn`` seam
(``models/transformer.layer_fn``), composing with the ring-attention lane:
ring handles the cross-device sequence axis, this kernel the on-device
blocks.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

_NEG_BIG = -1e30


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, l_ref, *, block_q: int,
                  block_k: int, scale: float, seq_len: int,
                  q_offset_base: int):
    qi = pl.program_id(1)
    # Keep q/k/v in their storage dtype (bf16) for the MXU — f32 matmul
    # inputs run at a fraction of the bf16 rate; accumulation is f32 via
    # preferred_element_type. Scaling happens on the f32 scores.
    q = q_ref[0]  # (block_q, D)
    d = q.shape[-1]

    q_pos = (
        q_offset_base + qi * block_q
        + jax.lax.broadcasted_iota(jnp.int32, (block_q, 1), 0)
    )

    # Causal: only k/v blocks at or below this q block's last row.
    last_q_pos = q_offset_base + qi * block_q + block_q - 1
    n_kb = jax.lax.min(
        (last_q_pos // block_k) + 1,
        jnp.int32(seq_len // block_k),
    )

    def body(kb, carry):
        m, l, acc = carry
        kblk = k_ref[0, pl.ds(kb * block_k, block_k), :]
        vblk = v_ref[0, pl.ds(kb * block_k, block_k), :]
        s = jax.lax.dot_general(
            q, kblk,
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale  # (block_q, block_k) f32
        k_pos = (
            kb * block_k
            + jax.lax.broadcasted_iota(jnp.int32, (1, block_k), 1)
        )
        s = jnp.where(q_pos >= k_pos, s, _NEG_BIG)
        m_new = jnp.maximum(m, s.max(axis=-1))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new[:, None])
        l_new = l * alpha + p.sum(axis=-1)
        acc_new = acc * alpha[:, None] + jax.lax.dot_general(
            p.astype(vblk.dtype), vblk,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return m_new, l_new, acc_new

    m0 = jnp.full((block_q,), _NEG_BIG, jnp.float32)
    l0 = jnp.zeros((block_q,), jnp.float32)
    acc0 = jnp.zeros((block_q, d), jnp.float32)
    m, l, acc = jax.lax.fori_loop(0, n_kb, body, (m0, l0, acc0))
    l_safe = jnp.maximum(l, 1e-30)
    o_ref[0] = (acc / l_safe[:, None]).astype(o_ref.dtype)
    # Per-row logsumexp of the (scaled, masked) scores — the backward's
    # softmax replay key. Trailing singleton keeps the block TPU-tileable.
    l_ref[0] = (m + jnp.log(l_safe))[:, None]


def _flash_fwd_raw(q, k, v, block_q, block_k, interpret, q_offset):
    b, sq, h, d = q.shape
    sk = k.shape[1]
    scale = d**-0.5

    # Fold batch and heads into one leading grid axis: (B*H, S, D).
    def fold(x):
        return jnp.transpose(x, (0, 2, 1, 3)).reshape(b * h, x.shape[1], d)

    qf, kf, vf = fold(q), fold(k), fold(v)

    kernel = functools.partial(
        _flash_kernel,
        block_q=block_q,
        block_k=block_k,
        scale=scale,
        seq_len=sk,
        q_offset_base=q_offset,
    )
    out, lse = pl.pallas_call(
        kernel,
        grid=(b * h, sq // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, qi: (bh, qi, 0)),
            pl.BlockSpec((1, sk, d), lambda bh, qi: (bh, 0, 0)),
            pl.BlockSpec((1, sk, d), lambda bh, qi: (bh, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, qi: (bh, qi, 0)),
            pl.BlockSpec((1, block_q, 1), lambda bh, qi: (bh, qi, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, sq, d), q.dtype),
            jax.ShapeDtypeStruct((b * h, sq, 1), jnp.float32),
        ],
        interpret=interpret,
    )(qf, kf, vf)
    out = jnp.transpose(out.reshape(b, h, sq, d), (0, 2, 1, 3))
    lse = jnp.transpose(lse.reshape(b, h, sq), (0, 2, 1))  # (B, S, H)
    return out, lse


def _flash_bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                         dq_ref, *, block_q: int, block_k: int, scale: float,
                         seq_len: int, q_offset_base: int):
    """dQ pass: one q-block per program, streaming its causal k/v blocks.
    The p/dS tiles live only in VMEM — no S^2 HBM traffic."""
    qi = pl.program_id(1)
    q = q_ref[0]              # (block_q, D) storage dtype
    do = do_ref[0]            # (block_q, D)
    lse = lse_ref[0]          # (block_q, 1) f32
    delta = delta_ref[0]      # (block_q, 1) f32
    d = q.shape[-1]

    q_pos = (
        q_offset_base + qi * block_q
        + jax.lax.broadcasted_iota(jnp.int32, (block_q, 1), 0)
    )
    last_q_pos = q_offset_base + qi * block_q + block_q - 1
    n_kb = jax.lax.min(
        (last_q_pos // block_k) + 1, jnp.int32(seq_len // block_k)
    )

    def body(kb, dq):
        kblk = k_ref[0, pl.ds(kb * block_k, block_k), :]
        vblk = v_ref[0, pl.ds(kb * block_k, block_k), :]
        s = jax.lax.dot_general(
            q, kblk, dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale
        k_pos = (
            kb * block_k
            + jax.lax.broadcasted_iota(jnp.int32, (1, block_k), 1)
        )
        p = jnp.where(q_pos >= k_pos, jnp.exp(s - lse), 0.0)
        dp = jax.lax.dot_general(
            do, vblk, dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = (p * (dp - delta) * scale).astype(q.dtype)
        return dq + jax.lax.dot_general(
            ds, kblk, dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    dq = jax.lax.fori_loop(
        0, n_kb, body, jnp.zeros((block_q, d), jnp.float32)
    )
    dq_ref[0] = dq.astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                          dk_ref, dv_ref, *, block_q: int, block_k: int,
                          scale: float, seq_len_q: int, q_offset_base: int):
    """dK/dV pass: one k-block per program, streaming the q blocks at or
    above its diagonal."""
    ki = pl.program_id(1)
    kblk = k_ref[0]           # (block_k, D)
    vblk = v_ref[0]           # (block_k, D)
    d = kblk.shape[-1]

    k_pos = (
        ki * block_k
        + jax.lax.broadcasted_iota(jnp.int32, (1, block_k), 1)
    )
    # First q block whose last global position reaches this k block.
    first_q_pos = ki * block_k - q_offset_base
    qb_start = jax.lax.max(
        jnp.int32(0), (first_q_pos - (block_q - 1)) // block_q
    )
    n_qb = jnp.int32(seq_len_q // block_q)

    def body(qb, carry):
        dk, dv = carry
        qblk = q_ref[0, pl.ds(qb * block_q, block_q), :]
        doblk = do_ref[0, pl.ds(qb * block_q, block_q), :]
        lse = lse_ref[0, pl.ds(qb * block_q, block_q), :]
        delta = delta_ref[0, pl.ds(qb * block_q, block_q), :]
        s = jax.lax.dot_general(
            qblk, kblk, dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale
        q_pos = (
            q_offset_base + qb * block_q
            + jax.lax.broadcasted_iota(jnp.int32, (block_q, 1), 0)
        )
        p = jnp.where(q_pos >= k_pos, jnp.exp(s - lse), 0.0)
        p_lo = p.astype(qblk.dtype)
        dv = dv + jax.lax.dot_general(
            p_lo, doblk, dimension_numbers=(((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dp = jax.lax.dot_general(
            doblk, vblk, dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = (p * (dp - delta) * scale).astype(qblk.dtype)
        dk = dk + jax.lax.dot_general(
            ds, qblk, dimension_numbers=(((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return dk, dv

    dk0 = jnp.zeros((block_k, d), jnp.float32)
    dv0 = jnp.zeros((block_k, d), jnp.float32)
    dk, dv = jax.lax.fori_loop(qb_start, n_qb, body, (dk0, dv0))
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _flash_bwd_pallas(q, k, v, o, lse, do, block_q, block_k, interpret,
                      q_offset):
    """Backward via the two Pallas passes; inputs (B, S, H, D)."""
    b, sq, h, d = q.shape
    sk = k.shape[1]
    scale = d**-0.5

    def fold(x):
        return jnp.transpose(x, (0, 2, 1, 3)).reshape(b * h, x.shape[1], d)

    qf, kf, vf, dof = fold(q), fold(k), fold(v), fold(do)
    # delta_i = rowsum(dO * O) — cheap elementwise, stays in XLA.
    delta = jnp.einsum(
        "bqhd,bqhd->bqh", do.astype(jnp.float32), o.astype(jnp.float32)
    )
    deltaf = jnp.transpose(delta, (0, 2, 1)).reshape(b * h, sq, 1)
    lsef = jnp.transpose(lse, (0, 2, 1)).reshape(b * h, sq, 1)

    row_spec = pl.BlockSpec((1, block_q, d), lambda bh, i: (bh, i, 0))
    stat_spec = pl.BlockSpec((1, block_q, 1), lambda bh, i: (bh, i, 0))
    full_q = pl.BlockSpec((1, sq, d), lambda bh, i: (bh, 0, 0))
    full_k = pl.BlockSpec((1, sk, d), lambda bh, i: (bh, 0, 0))
    full_stat = pl.BlockSpec((1, sq, 1), lambda bh, i: (bh, 0, 0))

    dq = pl.pallas_call(
        functools.partial(
            _flash_bwd_dq_kernel, block_q=block_q, block_k=block_k,
            scale=scale, seq_len=sk, q_offset_base=q_offset,
        ),
        grid=(b * h, sq // block_q),
        in_specs=[row_spec, full_k, full_k, row_spec, stat_spec, stat_spec],
        out_specs=row_spec,
        out_shape=jax.ShapeDtypeStruct((b * h, sq, d), q.dtype),
        interpret=interpret,
    )(qf, kf, vf, dof, lsef, deltaf)

    kcol_spec = pl.BlockSpec((1, block_k, d), lambda bh, i: (bh, i, 0))
    dk, dv = pl.pallas_call(
        functools.partial(
            _flash_bwd_dkv_kernel, block_q=block_q, block_k=block_k,
            scale=scale, seq_len_q=sq, q_offset_base=q_offset,
        ),
        grid=(b * h, sk // block_k),
        in_specs=[full_q, kcol_spec, kcol_spec, full_q, full_stat, full_stat],
        out_specs=[kcol_spec, kcol_spec],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, sk, d), k.dtype),
            jax.ShapeDtypeStruct((b * h, sk, d), v.dtype),
        ],
        interpret=interpret,
    )(qf, kf, vf, dof, lsef, deltaf)

    def unfold(x, s):
        return jnp.transpose(x.reshape(b, h, s, d), (0, 2, 1, 3))

    return unfold(dq, sq), unfold(dk, sk), unfold(dv, sk)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash_attention_diff(q, k, v, block_q, block_k, interpret, q_offset):
    out, _ = _flash_fwd_raw(q, k, v, block_q, block_k, interpret, q_offset)
    return out


def _flash_diff_fwd(q, k, v, block_q, block_k, interpret, q_offset):
    out, lse = _flash_fwd_raw(q, k, v, block_q, block_k, interpret, q_offset)
    return out, (q, k, v, out, lse)


def _flash_diff_bwd(block_q, block_k, interpret, q_offset, res, do):
    q, k, v, out, lse = res
    return _flash_bwd_pallas(
        q, k, v, out, lse, do, block_q, block_k, interpret, q_offset
    )


_flash_attention_diff.defvjp(_flash_diff_fwd, _flash_diff_bwd)


@functools.partial(
    jax.jit,
    static_argnames=("block_q", "block_k", "interpret", "q_offset"),
)
def flash_attention(
    q, k, v,
    block_q: int = 512,
    block_k: int = 512,
    interpret: Optional[bool] = None,
    q_offset: int = 0,
):
    """Causal flash attention on (B, S, H, D) tensors; differentiable.

    ``q_offset`` shifts query positions (sequence-parallel callers pass the
    shard's global offset). Sequence length must be divisible by the block
    sizes (pad upstream); block sizes auto-shrink for short sequences.
    """
    b, sq, h, d = q.shape
    sk = k.shape[1]
    # Shrink to the largest power-of-two divisor so any 8-divisible S works
    # with the default block sizes.
    block_q = min(block_q, _pow2_block(sq, cap=block_q))
    block_k = min(block_k, _pow2_block(sk, cap=block_k))
    assert sq % block_q == 0 and sk % block_k == 0, (sq, sk, block_q, block_k)
    if interpret is None:
        from rayfed_tpu.utils import is_tpu_backend

        interpret = not is_tpu_backend()
    return _flash_attention_diff(q, k, v, block_q, block_k, interpret, q_offset)


def _pow2_block(s: int, cap: int = 128) -> int:
    """Largest power-of-two divisor of ``s``, capped."""
    blk = 1
    while blk < cap and s % (blk * 2) == 0:
        blk *= 2
    return blk


def make_flash_attn_fn(block_q: int = 512, block_k: int = 512,
                       min_block: int = 16):
    """An ``attn_fn`` for ``models.transformer.forward``: (B, S, H, D)
    q/k/v -> (B, S, H, D), causal. Falls back to the XLA attention at
    trace time when the sequence doesn't tile into at least ``min_block``
    blocks (flash pays off only at block scale)."""
    from rayfed_tpu.models.transformer import causal_attention

    def attn(q, k, v):
        bq = min(block_q, _pow2_block(q.shape[1], cap=block_q))
        bk = min(block_k, _pow2_block(k.shape[1], cap=block_k))
        if bq < min_block or bk < min_block:
            return causal_attention(q, k, v)
        return flash_attention(q, k, v, block_q=bq, block_k=bk)

    return attn
