# Copyright 2026 The rayfed-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Pipeline parallelism: GPipe-style microbatch schedule over a ``stage``
mesh axis.

The transformer's stacked layer parameters reshape to
``(n_stages, layers_per_stage, ...)`` with the leading dim sharded over
``stage``; inside ``shard_map`` each device applies only its own stage's
layers, activations hop stage→stage via ``lax.ppermute`` (one ICI neighbor
hop per pipeline tick), and the whole schedule is a ``lax.scan`` of
``n_microbatches + n_stages - 1`` ticks — so XLA compiles ONE tick body and
autodiff derives the reverse schedule through the scan + ppermute
transpose. The last stage accumulates the LM loss; a final ``psum`` over
the stage axis publishes it everywhere.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax, shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from rayfed_tpu.models import transformer as tfm


def stack_to_stages(params, n_stages: int):
    """Reshape stacked layer leaves (L, ...) -> (S, L/S, ...)."""
    def reshape(x):
        l = x.shape[0]
        assert l % n_stages == 0, (l, n_stages)
        return x.reshape((n_stages, l // n_stages) + x.shape[1:])

    return jax.tree_util.tree_map(reshape, params["layers"])


def make_pp_loss_fn(
    cfg: tfm.TransformerConfig,
    mesh: Mesh,
    stage_axis: str = "stage",
    n_microbatches: int = 4,
):
    """Build ``loss(params, inputs, targets)`` running the pipeline over
    ``mesh``'s ``stage_axis``. ``params`` is a standard transformer param
    tree; batch must be divisible by ``n_microbatches``; ``cfg.n_layers``
    by the stage count."""
    n_stages = mesh.shape[stage_axis]
    assert cfg.n_layers % n_stages == 0, (cfg.n_layers, n_stages)
    m_micro = n_microbatches

    def body(stages_local, embed, ln_f, lm_head, inputs, targets):
        # stages_local leaves: (1, L/S, ...) — this device's stage slice.
        layers_local = jax.tree_util.tree_map(lambda x: x[0], stages_local)
        s = lax.axis_index(stage_axis)
        batch, seq = inputs.shape
        assert batch % m_micro == 0, (batch, m_micro)
        mb = batch // m_micro
        micro_in = inputs.reshape(m_micro, mb, seq)
        micro_tgt = targets.reshape(m_micro, mb, seq)
        positions = jnp.broadcast_to(jnp.arange(seq), (mb, seq))
        fwd_perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

        def apply_stage(h):
            def one_layer(h, layer):
                return tfm.layer_fn(h, layer, positions, cfg), None

            h, _ = lax.scan(one_layer, h, layers_local)
            return h

        def micro_loss(h, tgt):
            x = tfm.rms_norm(h, ln_f)
            logits = (x @ lm_head.astype(cfg.compute_dtype)).astype(
                jnp.float32
            )
            logz = jax.nn.logsumexp(logits, axis=-1)
            gold = jnp.take_along_axis(logits, tgt[..., None], axis=-1)[..., 0]
            return (logz - gold).mean()

        def tick(carry, t):
            h_prev, acc = carry
            m = t - s
            valid = jnp.logical_and(m >= 0, m < m_micro)
            m_c = jnp.clip(m, 0, m_micro - 1)
            # Stage 0 ingests a fresh (embedded) microbatch; later stages
            # consume the activation ppermuted in on the previous tick.
            # cond (not where) so non-first stages skip the gather and
            # non-last stages skip the full-vocab projection entirely.
            h_in = lax.cond(
                s == 0,
                lambda: embed[micro_in[m_c]].astype(cfg.compute_dtype),
                lambda: h_prev,
            )
            h_out = apply_stage(h_in)
            is_last = s == n_stages - 1
            acc = acc + lax.cond(
                jnp.logical_and(valid, is_last),
                lambda: micro_loss(h_out, micro_tgt[m_c]),
                lambda: jnp.float32(0.0),
            )
            h_next = lax.ppermute(h_out, stage_axis, fwd_perm)
            return (h_next, acc), None

        h0 = jnp.zeros((mb, seq, cfg.d_model), cfg.compute_dtype)
        (_, acc), _ = lax.scan(
            tick, (h0, jnp.float32(0.0)), jnp.arange(m_micro + n_stages - 1)
        )
        # Only the last stage accumulated loss; publish it to all stages.
        return lax.psum(acc, stage_axis) / m_micro

    stage_spec_leaves = P(stage_axis)
    rep = P()

    smapped = shard_map(
        body,
        mesh=mesh,
        in_specs=(stage_spec_leaves, rep, rep, rep, rep, rep),
        out_specs=rep,
        check_vma=False,
        # Manual over the stage axis only: any other mesh axes (model/data)
        # stay GSPMD-automatic, so TP-sharded params and DP-sharded batches
        # keep their shardings inside the pipeline body.
        axis_names={stage_axis},
    )

    def loss_fn(params, inputs, targets):
        stages = stack_to_stages(params, n_stages)
        return smapped(
            stages, params["embed"], params["ln_f"], params["lm_head"],
            inputs, targets,
        )

    return loss_fn


def schedule_1f1b(n_stages: int, n_micro: int):
    """Tick tables for the synchronous 1F1B schedule.

    Each tick has one forward slot and one backward slot per stage.
    ``F[t, s]``/``B[t, s]`` hold the microbatch index stage ``s`` processes
    in that slot at tick ``t`` (-1 = idle). Derivation: forwards fill
    GPipe-style, then interleave 1:1 with backwards
    (``t_F = max(m+s, 2m+2s-(S-1))``); the last stage backs a microbatch
    up the same tick it forwards it, and its gradient travels one
    stage-hop per tick (``t_B = 2m + 2(S-1) - s``). In-flight activations
    per stage stay bounded by stage depth (~1.5·(S-1-s)+1), independent
    of the microbatch count — the 1F1B memory property.

    Returns (F, B, R, ring): ``R[t, s]`` is the microbatch whose activation
    arrives at stage ``s`` on tick ``t`` (the previous stage forwarded it
    on tick ``t-1``; a warmup-stage producer can run several ticks ahead
    of its consumer, so arrivals are stashed in the ring buffer rather
    than consumed from the wire on the consuming tick). ``ring`` is the
    buffer depth covering each microbatch's stash-to-backward lifetime.
    """
    import numpy as np

    S, M = n_stages, n_micro
    T = 2 * (M - 1) + 2 * (S - 1) + 1
    F = np.full((T, S), -1, np.int32)
    B = np.full((T, S), -1, np.int32)
    for s in range(S):
        for m in range(M):
            tf = max(m + s, 2 * m + 2 * s - (S - 1))
            tb = 2 * m + 2 * (S - 1) - s
            assert F[tf, s] == -1 and B[tb, s] == -1, "slot double-booked"
            assert tb >= tf, (tb, tf)
            F[tf, s] = m
            B[tb, s] = m
    # A stage's input must have left the previous stage on an earlier tick.
    for s in range(1, S):
        for m in range(M):
            tf_here = int(np.where(F[:, s] == m)[0][0])
            tf_prev = int(np.where(F[:, s - 1] == m)[0][0])
            assert tf_here > tf_prev, (s, m)
    R = np.full((T, S), -1, np.int32)
    R[1:, 1:] = F[:-1, :-1]
    ring = 0
    for s in range(S):
        live = 0
        for t in range(T):
            # A slot is occupied from activation arrival (stage 0: its own
            # forward) through the backward that consumes it.
            if (R if s else F)[t, s] >= 0:
                live += 1
            ring = max(ring, live)
            if B[t, s] >= 0:
                live -= 1
    return F, B, R, ring


def make_1f1b_loss_and_grad(
    cfg: tfm.TransformerConfig,
    mesh: Mesh,
    stage_axis: str = "stage",
    n_microbatches: int = 4,
    batch_axes: tuple = (),
):
    """Build ``fn(params, inputs, targets) -> (loss, grads)`` running the
    1F1B pipeline schedule over ``mesh``'s ``stage_axis``.

    Unlike the GPipe lane (one scan, autodiff derives the reverse
    schedule — all forward activations in flight), the backward here is
    hand-scheduled: each backward slot re-runs its stage from the stashed
    stage *input* under ``jax.vjp`` (recompute-in-backward, exactly what
    GPipe-with-remat pays) and the activation ring buffer holds only
    O(n_stages) microbatches instead of all of them. Gradients therefore
    come from this function directly — do not wrap it in ``jax.grad``.

    ``batch_axes`` names mesh axes (party/data) the batch is sharded
    over. They are handled *manually*: each device runs the schedule on
    its local batch and loss/grads are psum-averaged at the end — the
    psum-average over the party axis IS the federated aggregate. Any
    remaining mesh axes (e.g. ``model``) stay GSPMD-automatic, so
    Megatron-sharded stage params compose with this schedule the same
    way they do with the GPipe lane.
    """
    n_stages = mesh.shape[stage_axis]
    assert cfg.n_layers % n_stages == 0, (cfg.n_layers, n_stages)
    batch_axes = tuple(a for a in batch_axes if a and mesh.shape.get(a, 1) > 1)
    n_replicas = 1
    for a in batch_axes:
        n_replicas *= mesh.shape[a]
    M = n_microbatches
    F_np, B_np, R_np, ring = schedule_1f1b(n_stages, M)
    T = F_np.shape[0]

    def body(stages_local, embed, ln_f, lm_head, inputs, targets):
        layers_local = jax.tree_util.tree_map(lambda x: x[0], stages_local)
        s = lax.axis_index(stage_axis)
        F_tab = jnp.asarray(F_np)
        B_tab = jnp.asarray(B_np)
        R_tab = jnp.asarray(R_np)
        batch, seq = inputs.shape
        assert batch % M == 0, (batch, M)
        mb = batch // M
        micro_in = inputs.reshape(M, mb, seq)
        micro_tgt = targets.reshape(M, mb, seq)
        positions = jnp.broadcast_to(jnp.arange(seq), (mb, seq))
        cdt = cfg.compute_dtype
        fwd_perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]
        bwd_perm = [((i + 1) % n_stages, i) for i in range(n_stages)]
        is_first = s == 0
        is_last = s == n_stages - 1

        def apply_stage(layers, h):
            def one_layer(h, layer):
                return tfm.layer_fn(h, layer, positions, cfg), None

            h, _ = lax.scan(one_layer, h, layers)
            return h

        def last_stage_loss(layers, lnf, head, h, tgt):
            x = tfm.rms_norm(apply_stage(layers, h), lnf)
            logits = (x @ head.astype(cdt)).astype(jnp.float32)
            logz = jax.nn.logsumexp(logits, axis=-1)
            gold = jnp.take_along_axis(
                logits, tgt[..., None], axis=-1
            )[..., 0]
            return (logz - gold).mean()

        zeros_like_f32 = lambda t: jax.tree_util.tree_map(  # noqa: E731
            lambda x: jnp.zeros(x.shape, jnp.float32), t
        )

        def tick(carry, t):
            buf, h_msg, dh_msg, dlayers, dembed, dlnf, dhead, loss_acc = carry
            fm = F_tab[t, s]
            bm = B_tab[t, s]
            rm = R_tab[t, s]
            fm_c = jnp.clip(fm, 0, M - 1)
            bm_c = jnp.clip(bm, 0, M - 1)
            rm_c = jnp.clip(rm, 0, M - 1)

            # ---- arrival: stash the activation the previous stage sent
            # last tick (a warmup producer runs ahead of this consumer, so
            # consumption happens from the ring, not straight off the wire).
            buf = lax.cond(
                rm >= 0,
                lambda b: b.at[rm_c % ring].set(h_msg),
                lambda b: b,
                buf,
            )

            # ---- forward slot -------------------------------------------
            def do_f(buf):
                h_in = lax.cond(
                    is_first,
                    lambda: embed[micro_in[fm_c]].astype(cdt),
                    lambda: buf[fm_c % ring],
                )
                h_out = apply_stage(layers_local, h_in)
                # Stage 0 stashes its own input for the backward; others
                # already hold it from the arrival stash.
                buf = lax.cond(
                    is_first,
                    lambda b: b.at[fm_c % ring].set(h_in),
                    lambda b: b,
                    buf,
                )
                return buf, h_out

            buf, h_out = lax.cond(
                fm >= 0,
                do_f,
                lambda buf: (buf, jnp.zeros_like(h_msg)),
                buf,
            )

            # ---- backward slot ------------------------------------------
            h_saved = buf[bm_c % ring]

            def do_b(args):
                dlayers, dembed, dlnf, dhead, loss_acc = args

                def b_last():
                    loss_m, vjp = jax.vjp(
                        last_stage_loss, layers_local, ln_f, lm_head,
                        h_saved, micro_tgt[bm_c],
                    )
                    dl, dlnf_m, dhead_m, dh_in, _ = vjp(jnp.float32(1.0))
                    return loss_m, dl, dlnf_m, dhead_m, dh_in

                def b_mid():
                    _, vjp = jax.vjp(apply_stage, layers_local, h_saved)
                    dl, dh_in = vjp(dh_msg)
                    return (
                        jnp.float32(0.0), dl,
                        jnp.zeros_like(ln_f), jnp.zeros_like(lm_head),
                        dh_in,
                    )

                loss_m, dl, dlnf_m, dhead_m, dh_in = lax.cond(
                    is_last, b_last, b_mid
                )
                dlayers = jax.tree_util.tree_map(
                    lambda a, g: a + g.astype(jnp.float32), dlayers, dl
                )
                # Stage 0's input grad lands in the embedding table.
                dembed = lax.cond(
                    is_first,
                    lambda: dembed.at[micro_in[bm_c]].add(
                        dh_in.astype(jnp.float32)
                    ),
                    lambda: dembed,
                )
                return (
                    (
                        dlayers, dembed,
                        dlnf + dlnf_m.astype(jnp.float32),
                        dhead + dhead_m.astype(jnp.float32),
                        loss_acc + loss_m,
                    ),
                    dh_in,
                )

            (dlayers, dembed, dlnf, dhead, loss_acc), dh_in = lax.cond(
                bm >= 0,
                do_b,
                lambda args: (args, jnp.zeros_like(h_msg)),
                (dlayers, dembed, dlnf, dhead, loss_acc),
            )

            # Hops ride every tick (collectives stay outside the conds);
            # receivers gate on their own schedule slots.
            h_next = lax.ppermute(h_out, stage_axis, fwd_perm)
            dh_next = lax.ppermute(dh_in, stage_axis, bwd_perm)
            return (
                buf, h_next, dh_next, dlayers, dembed, dlnf, dhead, loss_acc
            ), None

        h0 = jnp.zeros((mb, seq, cfg.d_model), cdt)
        carry0 = (
            jnp.zeros((ring, mb, seq, cfg.d_model), cdt),
            h0,
            h0,
            zeros_like_f32(layers_local),
            jnp.zeros(embed.shape, jnp.float32),
            jnp.zeros(ln_f.shape, jnp.float32),
            jnp.zeros(lm_head.shape, jnp.float32),
            jnp.float32(0.0),
        )
        carry, _ = lax.scan(tick, carry0, jnp.arange(T))
        _, _, _, dlayers, dembed, dlnf, dhead, loss_acc = carry
        # Mean over microbatches, then over batch-axis replicas (party x
        # data): the psum-average over party IS the federated aggregate.
        inv = 1.0 / (M * n_replicas)
        # Each stage owns its layer-grad slice; the replicated leaves were
        # computed by one stage only (zeros elsewhere) -> psum publishes.
        dlayers = jax.tree_util.tree_map(
            lambda g: lax.psum(g, batch_axes)[None] * inv
            if batch_axes else g[None] * inv,
            dlayers,
        )
        all_axes = (stage_axis,) + batch_axes
        psum = lambda x: lax.psum(x, all_axes)  # noqa: E731
        return (
            psum(loss_acc) * inv,
            dlayers,
            psum(dembed) * inv,
            psum(dlnf) * inv,
            psum(dhead) * inv,
        )

    stage_spec = P(stage_axis)
    rep = P()
    batch_spec = P(batch_axes if batch_axes else None)
    smapped = shard_map(
        body,
        mesh=mesh,
        in_specs=(stage_spec, rep, rep, rep, batch_spec, batch_spec),
        out_specs=(rep, stage_spec, rep, rep, rep),
        check_vma=False,
        axis_names={stage_axis, *batch_axes},
    )

    def loss_and_grad(params, inputs, targets):
        stages = stack_to_stages(params, n_stages)
        loss, dstages, dembed, dlnf, dhead = smapped(
            stages, params["embed"], params["ln_f"], params["lm_head"],
            inputs, targets,
        )
        dlayers = jax.tree_util.tree_map(
            lambda g: g.reshape((-1,) + g.shape[2:]), dstages
        )
        grads = {
            "embed": dembed.astype(params["embed"].dtype),
            "layers": jax.tree_util.tree_map(
                lambda g, p: g.astype(p.dtype), dlayers, params["layers"]
            ),
            "ln_f": dlnf.astype(params["ln_f"].dtype),
            "lm_head": dhead.astype(params["lm_head"].dtype),
        }
        return loss, grads

    return loss_and_grad


def make_pp_train_step(
    cfg: tfm.TransformerConfig,
    mesh: Mesh,
    *,
    stage_axis: str = "stage",
    party_axis=None,
    data_axis=None,
    n_microbatches: int = 4,
    microbatch_group: int = 0,
    schedule: str = "gpipe",
    lr: float = 3e-4,
):
    """Full pp(x tp)(x dp)(x party) training step in ONE jit over ``mesh``.

    The pipeline schedule is manual over ``stage_axis`` only; every other
    mesh axis stays GSPMD-automatic, so Megatron-sharded params (``model``
    axis, via ``parallel.sharding``) and batch sharding over
    ``party``/``data`` compose with the stage loop in the same program —
    the party/data grad all-reduce doubles as the federated aggregate
    exactly as in :func:`rayfed_tpu.parallel.train.make_fed_train_step`.

    ``schedule`` picks the pipeline schedule:

    - ``"gpipe"`` (default): one scan over ticks, autodiff derives the
      reverse schedule. ``microbatch_group`` > 0 bounds in-flight
      activations to the group size via a rematerialized
      gradient-accumulation scan, paying one fill/drain per group.
    - ``"1f1b"``: the hand-scheduled one-forward-one-backward interleave
      (:func:`make_1f1b_loss_and_grad`) — in-flight activations bounded
      by stage depth with a single fill/drain, no grouping needed.
    """
    import optax

    from rayfed_tpu.parallel import sharding as shd
    from rayfed_tpu.parallel.train import make_optimizer

    optimizer = make_optimizer(lr)
    if schedule not in ("gpipe", "1f1b"):
        raise ValueError(f"schedule must be 'gpipe' or '1f1b'; got {schedule!r}")
    if schedule == "1f1b" and microbatch_group:
        raise ValueError(
            "microbatch_group is a gpipe-schedule knob; 1f1b already bounds "
            "in-flight activations by stage depth"
        )

    batch_axes = tuple(
        a for a in (party_axis, data_axis) if a and mesh.shape.get(a, 1) > 1
    )
    batch_pspec = P(batch_axes if batch_axes else None)
    batch_sharding = NamedSharding(mesh, batch_pspec)

    if schedule == "1f1b":
        loss_grad_fn = make_1f1b_loss_and_grad(
            cfg, mesh, stage_axis=stage_axis, n_microbatches=n_microbatches,
            batch_axes=(party_axis, data_axis),
        )
        loss_fn = None
    else:
        loss_grad_fn = None
        groups = 1
        per_group = n_microbatches
        if microbatch_group:
            assert n_microbatches % microbatch_group == 0, (
                n_microbatches, microbatch_group,
            )
            groups = n_microbatches // microbatch_group
            per_group = microbatch_group
        group_loss = make_pp_loss_fn(
            cfg, mesh, stage_axis=stage_axis, n_microbatches=per_group
        )

        def loss_fn(params, inputs, targets):
            if groups == 1:
                return group_loss(params, inputs, targets)
            b = inputs.shape[0]
            assert b % groups == 0, (b, groups)
            gi = inputs.reshape(groups, b // groups, -1)
            gt = targets.reshape(groups, b // groups, -1)

            def acc(total, xs):
                i, t = xs
                return total + group_loss(params, i, t), None

            body = jax.checkpoint(acc, prevent_cse=False)
            total, _ = lax.scan(body, jnp.float32(0.0), (gi, gt))
            return total / groups

    def step(params, opt_state, inputs, targets):
        if loss_grad_fn is not None:
            loss, grads = loss_grad_fn(params, inputs, targets)
        else:
            loss, grads = jax.value_and_grad(loss_fn)(
                params, inputs, targets
            )
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    def init_fn(rng, sample_tokens):
        params = tfm.init_params(rng, cfg)
        params = shd.shard_params(mesh, params)
        opt_state = jax.jit(optimizer.init)(params)
        return params, opt_state

    step_fn = jax.jit(
        step,
        in_shardings=(None, None, batch_sharding, batch_sharding),
        donate_argnums=(0, 1),
    )
    return init_fn, step_fn
