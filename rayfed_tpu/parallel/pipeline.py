"""Pipeline parallelism: GPipe-style microbatch schedule over a ``stage``
mesh axis.

The transformer's stacked layer parameters reshape to
``(n_stages, layers_per_stage, ...)`` with the leading dim sharded over
``stage``; inside ``shard_map`` each device applies only its own stage's
layers, activations hop stage→stage via ``lax.ppermute`` (one ICI neighbor
hop per pipeline tick), and the whole schedule is a ``lax.scan`` of
``n_microbatches + n_stages - 1`` ticks — so XLA compiles ONE tick body and
autodiff derives the reverse schedule through the scan + ppermute
transpose. The last stage accumulates the LM loss; a final ``psum`` over
the stage axis publishes it everywhere.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax, shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from rayfed_tpu.models import transformer as tfm


def stack_to_stages(params, n_stages: int):
    """Reshape stacked layer leaves (L, ...) -> (S, L/S, ...)."""
    def reshape(x):
        l = x.shape[0]
        assert l % n_stages == 0, (l, n_stages)
        return x.reshape((n_stages, l // n_stages) + x.shape[1:])

    return jax.tree_util.tree_map(reshape, params["layers"])


def make_pp_loss_fn(
    cfg: tfm.TransformerConfig,
    mesh: Mesh,
    stage_axis: str = "stage",
    n_microbatches: int = 4,
):
    """Build ``loss(params, inputs, targets)`` running the pipeline over
    ``mesh``'s ``stage_axis``. ``params`` is a standard transformer param
    tree; batch must be divisible by ``n_microbatches``; ``cfg.n_layers``
    by the stage count."""
    n_stages = mesh.shape[stage_axis]
    assert cfg.n_layers % n_stages == 0, (cfg.n_layers, n_stages)
    m_micro = n_microbatches

    def body(stages_local, embed, ln_f, lm_head, inputs, targets):
        # stages_local leaves: (1, L/S, ...) — this device's stage slice.
        layers_local = jax.tree_util.tree_map(lambda x: x[0], stages_local)
        s = lax.axis_index(stage_axis)
        batch, seq = inputs.shape
        assert batch % m_micro == 0, (batch, m_micro)
        mb = batch // m_micro
        micro_in = inputs.reshape(m_micro, mb, seq)
        micro_tgt = targets.reshape(m_micro, mb, seq)
        positions = jnp.broadcast_to(jnp.arange(seq), (mb, seq))
        fwd_perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

        def apply_stage(h):
            def one_layer(h, layer):
                return tfm.layer_fn(h, layer, positions, cfg), None

            h, _ = lax.scan(one_layer, h, layers_local)
            return h

        def micro_loss(h, tgt):
            x = tfm.rms_norm(h, ln_f)
            logits = (x @ lm_head.astype(cfg.compute_dtype)).astype(
                jnp.float32
            )
            logz = jax.nn.logsumexp(logits, axis=-1)
            gold = jnp.take_along_axis(logits, tgt[..., None], axis=-1)[..., 0]
            return (logz - gold).mean()

        def tick(carry, t):
            h_prev, acc = carry
            m = t - s
            valid = jnp.logical_and(m >= 0, m < m_micro)
            m_c = jnp.clip(m, 0, m_micro - 1)
            # Stage 0 ingests a fresh (embedded) microbatch; later stages
            # consume the activation ppermuted in on the previous tick.
            # cond (not where) so non-first stages skip the gather and
            # non-last stages skip the full-vocab projection entirely.
            h_in = lax.cond(
                s == 0,
                lambda: embed[micro_in[m_c]].astype(cfg.compute_dtype),
                lambda: h_prev,
            )
            h_out = apply_stage(h_in)
            is_last = s == n_stages - 1
            acc = acc + lax.cond(
                jnp.logical_and(valid, is_last),
                lambda: micro_loss(h_out, micro_tgt[m_c]),
                lambda: jnp.float32(0.0),
            )
            h_next = lax.ppermute(h_out, stage_axis, fwd_perm)
            return (h_next, acc), None

        h0 = jnp.zeros((mb, seq, cfg.d_model), cfg.compute_dtype)
        (_, acc), _ = lax.scan(
            tick, (h0, jnp.float32(0.0)), jnp.arange(m_micro + n_stages - 1)
        )
        # Only the last stage accumulated loss; publish it to all stages.
        return lax.psum(acc, stage_axis) / m_micro

    stage_spec_leaves = P(stage_axis)
    rep = P()

    smapped = shard_map(
        body,
        mesh=mesh,
        in_specs=(stage_spec_leaves, rep, rep, rep, rep, rep),
        out_specs=rep,
        check_vma=False,
        # Manual over the stage axis only: any other mesh axes (model/data)
        # stay GSPMD-automatic, so TP-sharded params and DP-sharded batches
        # keep their shardings inside the pipeline body.
        axis_names={stage_axis},
    )

    def loss_fn(params, inputs, targets):
        stages = stack_to_stages(params, n_stages)
        return smapped(
            stages, params["embed"], params["ln_f"], params["lm_head"],
            inputs, targets,
        )

    return loss_fn


def make_pp_train_step(
    cfg: tfm.TransformerConfig,
    mesh: Mesh,
    *,
    stage_axis: str = "stage",
    party_axis=None,
    data_axis=None,
    n_microbatches: int = 4,
    microbatch_group: int = 0,
    lr: float = 3e-4,
):
    """Full pp(x tp)(x dp)(x party) training step in ONE jit over ``mesh``.

    The pipeline schedule is manual over ``stage_axis`` only; every other
    mesh axis stays GSPMD-automatic, so Megatron-sharded params (``model``
    axis, via ``parallel.sharding``) and batch sharding over
    ``party``/``data`` compose with the stage loop in the same program —
    the party/data grad all-reduce doubles as the federated aggregate
    exactly as in :func:`rayfed_tpu.parallel.train.make_fed_train_step`.

    ``microbatch_group`` > 0 runs the schedule in groups of that many
    microbatches under a gradient-accumulation scan with the group body
    rematerialized: in-flight activations are bounded by the group size
    instead of the full microbatch count — the memory bound 1F1B provides
    — at the cost of one pipeline fill/drain per group (the classic
    schedule trade; a fused fwd/bwd interleave would cut the extra
    bubbles too).
    """
    import optax

    from rayfed_tpu.parallel import sharding as shd
    from rayfed_tpu.parallel.train import make_optimizer

    optimizer = make_optimizer(lr)
    groups = 1
    per_group = n_microbatches
    if microbatch_group:
        assert n_microbatches % microbatch_group == 0, (
            n_microbatches, microbatch_group,
        )
        groups = n_microbatches // microbatch_group
        per_group = microbatch_group
    group_loss = make_pp_loss_fn(
        cfg, mesh, stage_axis=stage_axis, n_microbatches=per_group
    )

    batch_axes = tuple(
        a for a in (party_axis, data_axis) if a and mesh.shape.get(a, 1) > 1
    )
    batch_pspec = P(batch_axes if batch_axes else None)
    batch_sharding = NamedSharding(mesh, batch_pspec)

    def loss_fn(params, inputs, targets):
        if groups == 1:
            return group_loss(params, inputs, targets)
        b = inputs.shape[0]
        assert b % groups == 0, (b, groups)
        gi = inputs.reshape(groups, b // groups, -1)
        gt = targets.reshape(groups, b // groups, -1)

        def acc(total, xs):
            i, t = xs
            return total + group_loss(params, i, t), None

        body = jax.checkpoint(acc, prevent_cse=False)
        total, _ = lax.scan(body, jnp.float32(0.0), (gi, gt))
        return total / groups

    def step(params, opt_state, inputs, targets):
        loss, grads = jax.value_and_grad(loss_fn)(params, inputs, targets)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    def init_fn(rng, sample_tokens):
        params = tfm.init_params(rng, cfg)
        params = shd.shard_params(mesh, params)
        opt_state = jax.jit(optimizer.init)(params)
        return params, opt_state

    step_fn = jax.jit(
        step,
        in_shardings=(None, None, batch_sharding, batch_sharding),
        donate_argnums=(0, 1),
    )
    return init_fn, step_fn
