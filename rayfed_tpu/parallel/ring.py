"""Ring attention: sequence-parallel causal attention over a named mesh axis.

Long-context support is first-class in this framework: K/V shards rotate
around the ``seq`` axis ring via ``lax.ppermute`` (one hop per step —
traffic rides ICI neighbor links, never a global all-gather), while each
device's queries stream blockwise through a numerically-stable online
softmax (running max + normalizer, f32 accumulation). Peak memory per
device is O(S_local^2) scores instead of O(S^2).

Used inside ``shard_map`` where q/k/v are the local sequence shards; the
global causal mask is reconstructed from each block's ring-source index.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

_NEG_BIG = -1e30  # mask value; avoids -inf NaNs in the online softmax


def ring_attention(q, k, v, axis_name: str):
    """Causal attention where (q, k, v) are (B, S_local, H, Dh) shards of
    the sequence dimension over ``axis_name``. Returns the local output
    shard (B, S_local, H, Dh).

    Must be called inside shard_map/manual-SPMD context over ``axis_name``.
    """
    n = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    b, s_local, h, dh = q.shape
    scale = dh**-0.5
    q_offset = idx * s_local
    q32 = q.astype(jnp.float32)

    fwd_perm = [(j, (j + 1) % n) for j in range(n)]

    def step(carry, i):
        o, m, l, k_blk, v_blk = carry
        # This k/v block originated at ring position (idx - i) mod n.
        src = (idx - i) % n
        k_offset = src * s_local
        scores = (
            jnp.einsum(
                "bqhd,bkhd->bhqk", q32, k_blk.astype(jnp.float32)
            )
            * scale
        )
        q_pos = q_offset + jnp.arange(s_local)
        k_pos = k_offset + jnp.arange(s_local)
        mask = q_pos[:, None] >= k_pos[None, :]
        scores = jnp.where(mask[None, None], scores, _NEG_BIG)

        m_new = jnp.maximum(m, scores.max(axis=-1))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(scores - m_new[..., None])
        # Fully-masked rows contribute p=exp(_NEG_BIG - m_new) == 0 as long
        # as m_new is finite — guaranteed because step 0 processes the
        # device's own block, whose diagonal is always unmasked.
        l_new = l * alpha + p.sum(axis=-1)
        o_new = o * alpha[..., None] + jnp.einsum(
            "bhqk,bkhd->bhqd", p, v_blk.astype(jnp.float32)
        )
        k_next = lax.ppermute(k_blk, axis_name, fwd_perm)
        v_next = lax.ppermute(v_blk, axis_name, fwd_perm)
        return (o_new, m_new, l_new, k_next, v_next), None

    o0 = jnp.zeros((b, h, s_local, dh), jnp.float32)
    m0 = jnp.full((b, h, s_local), _NEG_BIG, jnp.float32)
    l0 = jnp.zeros((b, h, s_local), jnp.float32)
    (o, m, l, _, _), _ = lax.scan(
        step, (o0, m0, l0, k, v), jnp.arange(n)
    )
    out = o / jnp.maximum(l[..., None], 1e-30)
    return jnp.transpose(out, (0, 2, 1, 3)).astype(q.dtype)


def reference_attention_for_tests(q, k, v):
    """Single-device causal attention with the same f32 accumulation —
    ground truth for ring_attention equivalence tests."""
    from rayfed_tpu.models.transformer import causal_attention

    return causal_attention(q, k, v)
