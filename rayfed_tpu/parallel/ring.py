# Copyright 2026 The rayfed-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Ring attention: sequence-parallel causal attention over a named mesh axis.

Long-context support is first-class in this framework: K/V shards rotate
around the ``seq`` axis ring via ``lax.ppermute`` (one hop per step —
traffic rides ICI neighbor links, never a global all-gather), while each
device's queries stream blockwise through a numerically-stable online
softmax (running max + normalizer, f32 accumulation). Peak memory per
device is O(S_local^2) scores instead of O(S^2).

Used inside ``shard_map`` where q/k/v are the local sequence shards; the
global causal mask is reconstructed from each block's ring-source index.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

_NEG_BIG = -1e30  # mask value; avoids -inf NaNs in the online softmax


def ring_attention(q, k, v, axis_name: str):
    """Causal attention where (q, k, v) are (B, S_local, H, Dh) shards of
    the sequence dimension over ``axis_name``. Returns the local output
    shard (B, S_local, H, Dh).

    Must be called inside shard_map/manual-SPMD context over ``axis_name``.
    """
    n = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    b, s_local, h, dh = q.shape
    scale = dh**-0.5
    q_offset = idx * s_local

    fwd_perm = [(j, (j + 1) % n) for j in range(n)]

    def step(carry, i):
        o, m, l, k_blk, v_blk = carry
        # This k/v block originated at ring position (idx - i) mod n.
        src = (idx - i) % n
        k_offset = src * s_local
        # Operands stay in storage dtype (bf16 runs the MXU at full rate);
        # accumulation is f32 via preferred_element_type.
        scores = (
            jnp.einsum(
                "bqhd,bkhd->bhqk", q, k_blk,
                preferred_element_type=jnp.float32,
            )
            * scale
        )
        q_pos = q_offset + jnp.arange(s_local)
        k_pos = k_offset + jnp.arange(s_local)
        mask = q_pos[:, None] >= k_pos[None, :]
        scores = jnp.where(mask[None, None], scores, _NEG_BIG)

        m_new = jnp.maximum(m, scores.max(axis=-1))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(scores - m_new[..., None])
        # Fully-masked rows contribute p=exp(_NEG_BIG - m_new) == 0 as long
        # as m_new is finite — guaranteed because step 0 processes the
        # device's own block, whose diagonal is always unmasked.
        l_new = l * alpha + p.sum(axis=-1)
        o_new = o * alpha[..., None] + jnp.einsum(
            "bhqk,bkhd->bhqd", p.astype(v_blk.dtype), v_blk,
            preferred_element_type=jnp.float32,
        )
        k_next = lax.ppermute(k_blk, axis_name, fwd_perm)
        v_next = lax.ppermute(v_blk, axis_name, fwd_perm)
        return (o_new, m_new, l_new, k_next, v_next), None

    o0 = jnp.zeros((b, h, s_local, dh), jnp.float32)
    m0 = jnp.full((b, h, s_local), _NEG_BIG, jnp.float32)
    l0 = jnp.zeros((b, h, s_local), jnp.float32)
    (o, m, l, _, _), _ = lax.scan(
        step, (o0, m0, l0, k, v), jnp.arange(n)
    )
    out = o / jnp.maximum(l[..., None], 1e-30)
    return jnp.transpose(out, (0, 2, 1, 3)).astype(q.dtype)


def reference_attention_for_tests(q, k, v):
    """Single-device causal attention with the same f32 accumulation —
    ground truth for ring_attention equivalence tests."""
    from rayfed_tpu.models.transformer import causal_attention

    return causal_attention(q, k, v)


# ---------------------------------------------------------------------------
# Ring + flash: the Pallas kernels inside the ring
# ---------------------------------------------------------------------------
#
# The dense ring above materializes (S_local x S_local) f32 scores per
# step. This lane runs each ring step through the flash kernels instead —
# O(S_local) memory on-device — and merges the per-block partials by
# logsumexp. The ring loop is a Python unroll (the axis size is static
# under shard_map), so each step's relative query offset is a static
# kernel parameter; steps whose k/v block lies entirely in this device's
# future are masked out of the merge (their true offset would be
# negative, i.e. fully non-causal).


def _merge_partials(o_acc, lse_acc, o_i, lse_i):
    lse_new = jnp.logaddexp(lse_acc, lse_i)
    w_acc = jnp.exp(lse_acc - lse_new)[..., None]
    w_i = jnp.exp(lse_i - lse_new)[..., None]
    return o_acc * w_acc + o_i.astype(jnp.float32) * w_i, lse_new


def ring_flash_attention(q, k, v, axis_name: str,
                         block_q: int = 512, block_k: int = 512,
                         interpret=None):
    """Causal ring attention with Pallas flash blocks; differentiable.

    Same contract as :func:`ring_attention` (call inside shard_map over
    ``axis_name`` with (B, S_local, H, Dh) shards); backward rotates
    dk/dv accumulators around the ring with the blocks, so gradients
    arrive home after the full circle.
    """
    from rayfed_tpu.ops.flash_attention import _pow2_block

    if interpret is None:
        from rayfed_tpu.utils import is_tpu_backend

        interpret = not is_tpu_backend()
    s_local = q.shape[1]
    block_q = _pow2_block(s_local, cap=block_q)
    block_k = _pow2_block(s_local, cap=block_k)
    return _ring_flash(q, k, v, axis_name, block_q, block_k, bool(interpret))


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _ring_flash(q, k, v, axis_name, block_q, block_k, interpret):
    out, _ = _ring_flash_fwd_impl(
        q, k, v, axis_name, block_q, block_k, interpret
    )
    return out


def _ring_flash_fwd_impl(q, k, v, axis_name, block_q, block_k, interpret):
    from rayfed_tpu.ops.flash_attention import _flash_fwd_raw

    n = lax.psum(1, axis_name)  # static under shard_map
    idx = lax.axis_index(axis_name)
    b, s_local, h, dh = q.shape
    fwd_perm = [(j, (j + 1) % n) for j in range(n)]

    o_acc = jnp.zeros((b, s_local, h, dh), jnp.float32)
    lse_acc = jnp.full((b, s_local, h), _NEG_BIG, jnp.float32)
    k_blk, v_blk = k, v
    for i in range(n):
        # Block held this step originated i hops back: contributions are
        # causal only on devices with idx >= i (else the block is from
        # this device's future and fully masked).
        o_i, lse_i = _flash_fwd_raw(
            q, k_blk, v_blk, block_q, block_k, interpret,
            q_offset=i * s_local,
        )
        valid = idx >= i
        lse_i = jnp.where(valid, lse_i, _NEG_BIG)
        o_i = jnp.where(valid, o_i, 0)
        o_acc, lse_acc = _merge_partials(o_acc, lse_acc, o_i, lse_i)
        if i + 1 < n:
            k_blk = lax.ppermute(k_blk, axis_name, fwd_perm)
            v_blk = lax.ppermute(v_blk, axis_name, fwd_perm)
    return o_acc.astype(q.dtype), lse_acc


def _ring_flash_vjp_fwd(q, k, v, axis_name, block_q, block_k, interpret):
    out, lse = _ring_flash_fwd_impl(
        q, k, v, axis_name, block_q, block_k, interpret
    )
    return out, (q, k, v, out, lse)


def _ring_flash_vjp_bwd(axis_name, block_q, block_k, interpret, res, do):
    from rayfed_tpu.ops.flash_attention import _flash_bwd_pallas

    q, k, v, out, lse = res
    n = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    s_local = q.shape[1]
    fwd_perm = [(j, (j + 1) % n) for j in range(n)]

    dq_acc = jnp.zeros(q.shape, jnp.float32)
    dk_acc = jnp.zeros(k.shape, jnp.float32)
    dv_acc = jnp.zeros(v.shape, jnp.float32)
    k_blk, v_blk = k, v
    for i in range(n):
        dq_i, dk_i, dv_i = _flash_bwd_pallas(
            q, k_blk, v_blk, out, lse, do, block_q, block_k, interpret,
            q_offset=i * s_local,
        )
        valid = idx >= i
        dq_acc = dq_acc + jnp.where(valid, dq_i, 0).astype(jnp.float32)
        dk_acc = dk_acc + jnp.where(valid, dk_i, 0).astype(jnp.float32)
        dv_acc = dv_acc + jnp.where(valid, dv_i, 0).astype(jnp.float32)
        # dk/dv accumulators travel WITH the blocks: after the remaining
        # rotations each tile's gradients arrive back at its owner. The
        # k/v shards themselves are never read again on the last step.
        if i + 1 < n:
            k_blk = lax.ppermute(k_blk, axis_name, fwd_perm)
            v_blk = lax.ppermute(v_blk, axis_name, fwd_perm)
        dk_acc = lax.ppermute(dk_acc, axis_name, fwd_perm)
        dv_acc = lax.ppermute(dv_acc, axis_name, fwd_perm)
    return (
        dq_acc.astype(q.dtype),
        dk_acc.astype(k.dtype),
        dv_acc.astype(v.dtype),
    )


_ring_flash.defvjp(_ring_flash_vjp_fwd, _ring_flash_vjp_bwd)
