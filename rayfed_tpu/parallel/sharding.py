# Copyright 2026 The rayfed-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Parameter partition rules and mesh helpers for the model families.

Megatron-style tensor parallelism expressed as GSPMD sharding rules: QKV
projections and MLP up/gate shard their output features over the ``model``
axis, output/down projections shard their input features, so each block is
one all-reduce per residual add (inserted automatically by XLA). Batch dims
shard over ``party`` x ``data`` (federated data parallelism: the gradient
all-reduce over ``party`` IS the FedAvg aggregate), and activations can
additionally shard the sequence dim over ``seq``.
"""

from __future__ import annotations

import re
from typing import List, Optional, Tuple

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# (regex over param path, spec) — first match wins. Paths look like
# "layers/wq", "embed", "layers/w_down". Stacked layer leaves carry a
# leading n_layers dim, handled by _prepend_none below.
TRANSFORMER_RULES: List[Tuple[str, P]] = [
    (r"layers/w[qkv]$", P(None, "model", None)),
    (r"layers/wo$", P("model", None, None)),
    (r"layers/w_(gate|up)$", P(None, "model")),
    (r"layers/w_down$", P("model", None)),
    (r"layers/ln[12]$", P()),
    # MoE FFN: experts shard over the "expert" axis; router replicated.
    (r"layers/moe/router$", P()),
    (r"layers/moe/w_(up|down)$", P("expert", None, None)),
    (r"embed$", P(None, None)),
    (r"lm_head$", P(None, "model")),
    (r"ln_f$", P()),
]

MLP_RULES: List[Tuple[str, P]] = [
    (r"layers/\d+/w$", P(None, "model")),
    (r"layers/\d+/b$", P("model")),
    (r".*", P()),
]


def _path_str(path) -> str:
    parts = []
    for p in path:
        if hasattr(p, "key"):
            parts.append(str(p.key))
        elif hasattr(p, "idx"):
            parts.append(str(p.idx))
        else:
            parts.append(str(p))
    return "/".join(parts)


def spec_for(path_str: str, leaf, rules, stacked_prefix: str = "layers") -> P:
    for pattern, spec in rules:
        if re.search(pattern, path_str):
            if (
                stacked_prefix
                and path_str.startswith(stacked_prefix)
                and len(spec) == leaf.ndim - 1
            ):
                # Stacked layer leaf: leading n_layers dim is unsharded.
                return P(*((None,) + tuple(spec)))
            return spec
    return P()


def make_param_specs(params, rules=TRANSFORMER_RULES):
    """Pytree of PartitionSpec matching ``params`` by path regex."""
    return jax.tree_util.tree_map_with_path(
        lambda path, leaf: spec_for(_path_str(path), leaf, rules), params
    )


def prune_spec_to_mesh(spec: P, mesh: Mesh) -> P:
    """Drop axis names the mesh does not have (e.g. the 'model' rules on a
    party x expert mesh): absent axes mean 'replicated here'.

    One deliberate fallback: on a mesh with no ``expert`` axis but a
    ``model`` axis, the expert dimension shards over ``model`` instead of
    replicating — MoE composes into the flagship party x data x model
    (x seq) mesh without a fifth axis, Megatron-style (experts ride the
    tp group; XLA inserts the cross-expert collectives). Configs should
    keep ``n_experts`` divisible by the model-axis size."""
    def one(name):
        if name in mesh.axis_names:
            return name
        if name == "expert" and "model" in mesh.axis_names:
            return "model"
        return None

    def keep(entry):
        if entry is None:
            return None
        if isinstance(entry, (tuple, list)):
            kept = []
            for a in entry:
                m = one(a)
                if m is not None and m not in kept:
                    kept.append(m)
            return tuple(kept) if kept else None
        return one(entry)

    return P(*(keep(e) for e in spec))


def make_param_shardings(mesh: Mesh, params, rules=TRANSFORMER_RULES):
    specs = make_param_specs(params, rules)
    return jax.tree_util.tree_map(
        lambda spec: NamedSharding(mesh, prune_spec_to_mesh(spec, mesh)),
        specs,
        is_leaf=lambda x: isinstance(x, P),
    )


def shard_params(mesh: Mesh, params, rules=TRANSFORMER_RULES):
    """Place a (host or single-device) param tree onto the mesh per rules."""
    shardings = make_param_shardings(mesh, params, rules)
    return jax.tree_util.tree_map(jax.device_put, params, shardings)


def batch_spec(mesh: Mesh, party_axis: Optional[str] = "party",
               data_axis: Optional[str] = "data",
               seq_axis: Optional[str] = None) -> P:
    """PartitionSpec for a (B, S) token batch: batch over party x data,
    optionally sequence over seq."""
    batch_axes = tuple(
        a for a in (party_axis, data_axis) if a and a in mesh.axis_names
    )
    first = batch_axes if batch_axes else None
    if seq_axis and seq_axis in mesh.axis_names:
        return P(first, seq_axis)
    return P(first)
