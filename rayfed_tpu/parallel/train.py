# Copyright 2026 The rayfed-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""Sharded training steps: federated data/tensor/sequence parallel in one jit.

The train step compiles once over the whole mesh:

 - ``party`` x ``data`` shard the batch — because the loss is a mean over
   the global batch, XLA's gradient all-reduce over these axes IS the
   federated aggregate (synchronized FedSGD). Multi-local-step FedAvg runs
   over the engine's push/psum lanes instead (``rayfed_tpu.collective``).
 - ``model`` shards attention heads + MLP hidden via the GSPMD rules in
   :mod:`rayfed_tpu.parallel.sharding` (tensor parallelism).
 - ``seq`` (optional) shards the sequence dim of activations; attention
   runs as ring attention over the seq axis inside ``shard_map``
   (:mod:`rayfed_tpu.parallel.ring`).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from jax import shard_map  # requires jax >= 0.7 (axis_names/check_vma API)

from rayfed_tpu.models import transformer as tfm
from rayfed_tpu.parallel import sharding as shd
from rayfed_tpu.parallel.ring import ring_attention


#: Machine-readable anchor for the static analyzer (``rayfed_tpu.lint``):
#: the fedlint rule that enforces this module's donation-aliasing
#: contract (``make_fed_train_step(donate=True)`` outputs must not be
#: returned for local by-reference consumption — see the contract
#: comment inside ``make_fed_train_step`` and docs/fedlint.md). Pinned
#: against the rule registry by ``tests/test_fedlint.py``.
FEDLINT_DONATION_RULE = "FED003"


def make_optimizer(lr: float = 3e-4, weight_decay: float = 0.01):
    return optax.adamw(lr, b1=0.9, b2=0.95, weight_decay=weight_decay)


def make_fed_train_step(
    cfg: tfm.TransformerConfig,
    mesh: Mesh,
    *,
    party_axis: Optional[str] = "party",
    data_axis: Optional[str] = "data",
    seq_axis: Optional[str] = None,
    lr: float = 3e-4,
    remat: bool = False,
    attn: str = "auto",
    seq_parallel: str = "ring",
    accum_steps: int = 1,
    shard_opt_state: bool = False,
    donate: bool = True,
):
    """Build (init_fn, step_fn) jitted over ``mesh``.

    ``init_fn(rng, sample_tokens) -> (params, opt_state)`` places state
    according to the partition rules; ``step_fn(params, opt_state, inputs,
    targets) -> (params, opt_state, loss)`` is one synchronized federated
    step over pre-shifted (B, S) input/target blocks.

    ``attn`` selects the on-device attention: ``"flash"`` = the Pallas
    flash kernel (O(S) memory, differentiable), ``"xla"`` = the dense
    reference attention, ``"auto"`` (default) = flash on TPU backends,
    dense elsewhere (the kernel's interpret mode is test-speed only).
    When the ``seq`` axis is sharded, attention runs sequence-parallel
    over that axis; ``seq_parallel`` picks the strategy:
    ``"ring"`` (default) rotates K/V blocks via ``ppermute`` (no cap on
    the axis size, every hop overlapped with compute; with flash each
    step runs the Pallas kernels so per-device memory stays O(S_local));
    ``"a2a"`` is Ulysses-style — one all_to_all to head-sharded layout,
    the unmodified local kernel over the full sequence, one all_to_all
    back (fewer collectives at long S; needs n_heads divisible by the
    axis size).

    ``accum_steps > 1`` splits the global batch into that many
    microbatches and accumulates gradients under one ``lax.scan`` —
    activation memory scales with the microbatch while the update sees
    the full-batch gradient (mean of equal-sized microbatch means, f32
    accumulation; matches the single-pass gradient up to float
    reduction-order rounding, ~1e-5 relative).

    ``shard_opt_state=True`` additionally shards optimizer moments
    ZeRO-1 style: any moment dim the parameter rules leave unsharded is
    sharded over party x data when divisible, cutting optimizer memory by
    the dp world size; XLA inserts the per-step all-gather on the
    update path automatically.
    """
    optimizer = make_optimizer(lr)
    use_sp = seq_axis is not None and mesh.shape.get(seq_axis, 1) > 1
    if attn not in ("auto", "flash", "xla"):
        raise ValueError(f"attn must be 'auto', 'flash', or 'xla'; got {attn!r}")
    if seq_parallel not in ("ring", "a2a"):
        raise ValueError(
            f"seq_parallel must be 'ring' or 'a2a'; got {seq_parallel!r}"
        )
    if attn == "auto":
        from rayfed_tpu.utils import is_tpu_backend

        attn = "flash" if is_tpu_backend() else "xla"

    if use_sp:
        # Sequence-parallel attention: shard_map over the seq axis;
        # every other axis stays GSPMD-automatic.
        if seq_parallel == "a2a":
            from rayfed_tpu.parallel.ulysses import (
                make_ulysses_flash,
                ulysses_attention,
            )

            if cfg.n_heads % mesh.shape[seq_axis] != 0:
                raise ValueError(
                    f"seq_parallel='a2a' needs n_heads ({cfg.n_heads}) "
                    f"divisible by the '{seq_axis}' axis size "
                    f"({mesh.shape[seq_axis]}); use seq_parallel='ring'"
                )
            block_attn = (
                make_ulysses_flash(seq_axis)
                if attn == "flash"
                else functools.partial(ulysses_attention, axis_name=seq_axis)
            )
        else:
            from rayfed_tpu.parallel.ring import ring_flash_attention

            block_attn = (
                functools.partial(ring_flash_attention, axis_name=seq_axis)
                if attn == "flash"
                else functools.partial(ring_attention, axis_name=seq_axis)
            )

        def sp_attn(q, k, v):
            pspec = P(None, seq_axis, None, None)
            return shard_map(
                block_attn,
                mesh=mesh,
                in_specs=(pspec, pspec, pspec),
                out_specs=pspec,
                check_vma=False,
                axis_names={seq_axis},
            )(q, k, v)

        attn_fn = sp_attn
    elif attn == "flash":
        from rayfed_tpu.ops.flash_attention import make_flash_attn_fn

        attn_fn = make_flash_attn_fn()
    else:
        attn_fn = None

    batch_pspec = shd.batch_spec(mesh, party_axis, data_axis, seq_axis)
    batch_sharding = NamedSharding(mesh, batch_pspec)
    # Chunked head+CE keeps (B, S, vocab) f32 logits out of HBM; disabled
    # when S is sharded (chunking reshapes the sequence dim).
    loss_chunk = None if use_sp else 512

    def loss_fn(params, inputs, targets):
        return tfm.lm_loss_pair(
            params, inputs, targets, cfg, attn_fn, remat=remat,
            loss_chunk=loss_chunk,
        )

    if accum_steps < 1:
        raise ValueError(f"accum_steps must be >= 1, got {accum_steps}")

    def grad_step(params, inputs, targets):
        if accum_steps == 1:
            return jax.value_and_grad(loss_fn)(params, inputs, targets)
        b, s = inputs.shape
        if b % accum_steps:
            raise ValueError(
                f"batch {b} not divisible by accum_steps={accum_steps}"
            )
        mb = b // accum_steps
        # Strided split (microbatch i = rows i::accum_steps), NOT
        # contiguous chunks: the batch dim is sharded over party x data,
        # and a contiguous microbatch would hold only some shards' rows —
        # XLA would then reshard raw token data across parties every
        # step. Strided microbatches take an equal slice of every dp
        # shard (zero-communication when mb divides by the dp extent);
        # the constraint pins that layout for GSPMD.
        mb_sharding = NamedSharding(mesh, P(None, *batch_pspec))

        def split(t):
            t = jnp.moveaxis(t.reshape(mb, accum_steps, s), 1, 0)
            return jax.lax.with_sharding_constraint(t, mb_sharding)

        xs, ts = split(inputs), split(targets)

        def body(carry, xt):
            acc_loss, acc_grads = carry
            x, t = xt
            loss, grads = jax.value_and_grad(loss_fn)(params, x, t)
            acc_grads = jax.tree_util.tree_map(
                lambda a, g: a + g.astype(a.dtype), acc_grads, grads
            )
            return (acc_loss + loss, acc_grads), None

        init = (
            jnp.zeros((), jnp.float32),
            jax.tree_util.tree_map(
                lambda p: jnp.zeros(p.shape, jnp.float32), params
            ),
        )
        (tot_loss, tot_grads), _ = jax.lax.scan(body, init, (xs, ts))
        inv = 1.0 / accum_steps
        grads = jax.tree_util.tree_map(
            lambda p, g: (g * inv).astype(p.dtype), params, tot_grads
        )
        return tot_loss * inv, grads

    def step(params, opt_state, inputs, targets):
        loss, grads = grad_step(params, inputs, targets)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    if shard_opt_state:
        dp_axes = tuple(
            a for a in (party_axis, data_axis)
            if a and a in mesh.axis_names and mesh.shape[a] > 1
        )
        dp_size = 1
        for a in dp_axes:
            dp_size *= mesh.shape[a]

        def _zero1(param_spec: P, leaf) -> NamedSharding:
            # Extend the parameter's own spec (moments keep the tp layout)
            # by sharding the first unsharded, divisible dim over the dp
            # axes — ZeRO-1: each dp rank keeps 1/dp of the moments.
            spec = list(param_spec) + [None] * (leaf.ndim - len(param_spec))
            if dp_size > 1:
                for i, entry in enumerate(spec):
                    if entry is None and leaf.shape[i] and \
                            leaf.shape[i] % dp_size == 0:
                        spec[i] = dp_axes if len(dp_axes) > 1 else dp_axes[0]
                        break
            return NamedSharding(mesh, P(*spec))

        def _dict_path(path):
            return tuple(
                p.key for p in path
                if isinstance(p, jax.tree_util.DictKey)
            )

        def _opt_shardings(params):
            is_spec = lambda x: isinstance(x, P)  # noqa: E731
            param_specs = jax.tree_util.tree_map(
                lambda s: shd.prune_spec_to_mesh(s, mesh),
                shd.make_param_specs(params), is_leaf=is_spec,
            )
            # optax states embed param-shaped dict trees (mu/nu); an opt
            # leaf's dict-key path equals its parameter's, while non-param
            # leaves (count scalars) match nothing and replicate.
            flat_specs = {
                _dict_path(path): spec
                for path, spec in jax.tree_util.tree_flatten_with_path(
                    param_specs, is_leaf=is_spec
                )[0]
            }
            opt_shapes = jax.eval_shape(optimizer.init, params)

            def for_leaf(path, leaf):
                spec = flat_specs.get(_dict_path(path))
                if spec is None or leaf.ndim < len(spec):
                    spec = P()
                return _zero1(spec, leaf)

            return jax.tree_util.tree_map_with_path(for_leaf, opt_shapes)

    def init_fn(rng, sample_tokens):
        params = tfm.init_params(rng, cfg)
        params = shd.shard_params(mesh, params)
        if shard_opt_state:
            shardings = _opt_shardings(params)
            opt_state = jax.jit(
                optimizer.init, out_shardings=shardings
            )(params)
        else:
            # Moment tensors inherit each parameter's sharding via XLA's
            # sharding propagation — no explicit out_shardings needed.
            opt_state = jax.jit(optimizer.init)(params)
        return params, opt_state

    # ``donate=True`` (default) aliases params/opt_state buffers into the
    # update — the right memory trade on TPU. Contract (jax's own rule
    # for aliased values): buffers handed to OTHER consumers must not be
    # donated afterwards. Cross-party pushes on the socket lanes are
    # capture-protected (the engine snapshots pushed values at
    # resolution, barriers.py); under ``device_dma`` donate only after
    # the send resolves. A fed task that RETURNS its params for LOCAL
    # consumption (e.g. an actor whose result feeds fed_aggregate in the
    # same party) must pass donate=False or return a copy — zero-copy
    # local chaining hands device arrays by reference. This contract is
    # machine-checked: fedlint rule FEDLINT_DONATION_RULE (module-level
    # anchor above) flags
    # drivers that return donated step outputs (docs/fedlint.md).
    step_fn = jax.jit(
        step,
        in_shardings=(None, None, batch_sharding, batch_sharding),
        donate_argnums=(0, 1) if donate else (),
    )
    return init_fn, step_fn
