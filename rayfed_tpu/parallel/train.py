"""Sharded training steps: federated data/tensor/sequence parallel in one jit.

The train step compiles once over the whole mesh:

 - ``party`` x ``data`` shard the batch — because the loss is a mean over
   the global batch, XLA's gradient all-reduce over these axes IS the
   federated aggregate (synchronized FedSGD). Multi-local-step FedAvg runs
   over the engine's push/psum lanes instead (``rayfed_tpu.collective``).
 - ``model`` shards attention heads + MLP hidden via the GSPMD rules in
   :mod:`rayfed_tpu.parallel.sharding` (tensor parallelism).
 - ``seq`` (optional) shards the sequence dim of activations; attention
   runs as ring attention over the seq axis inside ``shard_map``
   (:mod:`rayfed_tpu.parallel.ring`).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from jax import shard_map  # requires jax >= 0.7 (axis_names/check_vma API)

from rayfed_tpu.models import transformer as tfm
from rayfed_tpu.parallel import sharding as shd
from rayfed_tpu.parallel.ring import ring_attention


def make_optimizer(lr: float = 3e-4, weight_decay: float = 0.01):
    return optax.adamw(lr, b1=0.9, b2=0.95, weight_decay=weight_decay)


def make_fed_train_step(
    cfg: tfm.TransformerConfig,
    mesh: Mesh,
    *,
    party_axis: Optional[str] = "party",
    data_axis: Optional[str] = "data",
    seq_axis: Optional[str] = None,
    lr: float = 3e-4,
    remat: bool = False,
    attn: str = "auto",
):
    """Build (init_fn, step_fn) jitted over ``mesh``.

    ``init_fn(rng, sample_tokens) -> (params, opt_state)`` places state
    according to the partition rules; ``step_fn(params, opt_state, inputs,
    targets) -> (params, opt_state, loss)`` is one synchronized federated
    step over pre-shifted (B, S) input/target blocks.

    ``attn`` selects the on-device attention: ``"flash"`` = the Pallas
    flash kernel (O(S) memory, differentiable), ``"xla"`` = the dense
    reference attention, ``"auto"`` (default) = flash on TPU backends,
    dense elsewhere (the kernel's interpret mode is test-speed only).
    When the ``seq`` axis is sharded, attention runs as ring attention
    over that axis; with flash selected, each ring step runs through the
    Pallas kernels (``ring_flash_attention``) so per-device memory stays
    O(S_local) even at very long context.
    """
    optimizer = make_optimizer(lr)
    use_ring = seq_axis is not None and mesh.shape.get(seq_axis, 1) > 1
    if attn not in ("auto", "flash", "xla"):
        raise ValueError(f"attn must be 'auto', 'flash', or 'xla'; got {attn!r}")
    if attn == "auto":
        attn = "flash" if jax.default_backend() == "tpu" else "xla"

    if use_ring:
        # Sequence-parallel attention: shard_map over the seq axis with K/V
        # ring rotation; every other axis stays GSPMD-automatic.
        from rayfed_tpu.parallel.ring import ring_flash_attention

        block_attn = (
            functools.partial(ring_flash_attention, axis_name=seq_axis)
            if attn == "flash"
            else functools.partial(ring_attention, axis_name=seq_axis)
        )

        def ring_attn(q, k, v):
            pspec = P(None, seq_axis, None, None)
            return shard_map(
                block_attn,
                mesh=mesh,
                in_specs=(pspec, pspec, pspec),
                out_specs=pspec,
                check_vma=False,
                axis_names={seq_axis},
            )(q, k, v)

        attn_fn = ring_attn
    elif attn == "flash":
        from rayfed_tpu.ops.flash_attention import make_flash_attn_fn

        attn_fn = make_flash_attn_fn()
    else:
        attn_fn = None

    batch_pspec = shd.batch_spec(mesh, party_axis, data_axis, seq_axis)
    batch_sharding = NamedSharding(mesh, batch_pspec)
    # Chunked head+CE keeps (B, S, vocab) f32 logits out of HBM; disabled
    # when S is sharded (chunking reshapes the sequence dim).
    loss_chunk = None if use_ring else 512

    def loss_fn(params, inputs, targets):
        return tfm.lm_loss_pair(
            params, inputs, targets, cfg, attn_fn, remat=remat,
            loss_chunk=loss_chunk,
        )

    def step(params, opt_state, inputs, targets):
        loss, grads = jax.value_and_grad(loss_fn)(params, inputs, targets)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    def init_fn(rng, sample_tokens):
        params = tfm.init_params(rng, cfg)
        params = shd.shard_params(mesh, params)
        # Moment tensors inherit each parameter's sharding via XLA's
        # sharding propagation — no explicit out_shardings needed.
        opt_state = jax.jit(optimizer.init)(params)
        return params, opt_state

    step_fn = jax.jit(
        step,
        in_shardings=(None, None, batch_sharding, batch_sharding),
        donate_argnums=(0, 1),
    )
    return init_fn, step_fn
