# Copyright 2026 The rayfed-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""All-to-all (Ulysses-style) sequence parallelism.

The second of the framework's two long-context strategies (the first is
K/V rotation, :mod:`rayfed_tpu.parallel.ring`): instead of streaming K/V
blocks around a ring, one ``all_to_all`` reshards the activations from
sequence-sharded (B, S/n, H, Dh) to head-sharded (B, S, H/n, Dh), each
device runs ORDINARY causal attention over the full sequence for its
head subset, and a second ``all_to_all`` reshards back.

Trade-off vs ring (why both exist):
 - Ulysses moves each of q/k/v/o exactly once (2 collectives of
   3x + 1x activation bytes) regardless of sequence length; ring moves
   K/V n-1 times but overlaps every hop with a block of compute.
 - Ulysses runs the UNMODIFIED local attention kernel (any Pallas/XLA
   kernel works as-is; no online-softmax merging across steps), so it
   composes with kernels that cannot be ring-stepped.
 - Ulysses caps the sequence axis at n <= n_heads (heads must divide);
   ring has no such cap. Head-dim tensor parallelism also competes with
   Ulysses for the head axis, while ring composes freely with tp.

On TPU both collectives lower to XLA ``all-to-all`` riding ICI. Used
inside ``shard_map`` over the sequence axis, like ``ring_attention``.
"""

from __future__ import annotations

from typing import Callable, Optional

from jax import lax


def ulysses_attention(q, k, v, axis_name: str,
                      inner_attn: Optional[Callable] = None):
    """Causal attention where (q, k, v) are (B, S_local, H, Dh) shards of
    the sequence dimension over ``axis_name``; returns the local output
    shard (B, S_local, H, Dh).

    Must be called inside shard_map/manual-SPMD context over
    ``axis_name``. ``inner_attn(q, k, v)`` is the full-sequence causal
    attention run on each device's head subset (default: the model's XLA
    attention); H must be divisible by the axis size.
    """
    if inner_attn is None:
        from rayfed_tpu.models.transformer import causal_attention

        inner_attn = causal_attention
    n = lax.psum(1, axis_name)
    h = q.shape[2]
    if h % n != 0:
        raise ValueError(
            f"ulysses sequence parallelism needs n_heads ({h}) divisible "
            f"by the '{axis_name}' axis size ({n}); use ring attention "
            f"for meshes wider than the head count"
        )

    def seq_to_heads(x):
        # (B, S/n, H, Dh) -> (B, S, H/n, Dh); chunk j of the concat comes
        # from ring position j, which holds global positions
        # [j*S_local, (j+1)*S_local) — device order IS sequence order, so
        # the gathered sequence is globally ordered and the standard
        # causal mask applies unchanged.
        return lax.all_to_all(
            x, axis_name, split_axis=2, concat_axis=1, tiled=True
        )

    def heads_to_seq(x):
        return lax.all_to_all(
            x, axis_name, split_axis=1, concat_axis=2, tiled=True
        )

    o = inner_attn(seq_to_heads(q), seq_to_heads(k), seq_to_heads(v))
    return heads_to_seq(o)


def make_ulysses_flash(axis_name: str, block_q: int = 512,
                       block_k: int = 512):
    """Ulysses with the Pallas flash kernel as the local attention — the
    kernel runs UNMODIFIED on the full-sequence/head-subset layout (the
    composability ulysses buys over ring stepping)."""
    import functools

    from rayfed_tpu.ops.flash_attention import make_flash_attn_fn

    return functools.partial(
        ulysses_attention, axis_name=axis_name,
        inner_attn=make_flash_attn_fn(block_q=block_q, block_k=block_k),
    )


def reference_full_attention(q, k, v):
    """Unsharded causal attention for tests (mirrors ring's helper)."""
    from rayfed_tpu.models.transformer import causal_attention

    return causal_attention(q, k, v)


__all__ = [
    "ulysses_attention",
    "make_ulysses_flash",
    "reference_full_attention",
]
