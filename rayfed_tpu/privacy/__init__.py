# Copyright 2026 The rayfed-tpu Authors.
#
# Licensed under the Apache License, Version 2.0 (the "License");
# you may not use this file except in compliance with the License.
# You may obtain a copy of the License at
#
#     http://www.apache.org/licenses/LICENSE-2.0
#
# Unless required by applicable law or agreed to in writing, software
# distributed under the License is distributed on an "AS IS" BASIS,
# WITHOUT WARRANTIES OR CONDITIONS OF ANY KIND, either express or implied.
# See the License for the specific language governing permissions and
# limitations under the License.

"""The privacy plane: secure aggregation, the DP ledger, and quantized
pushes (docs/privacy.md).

Three layers, all off by default and enabled through a validated
``config["privacy"]`` block at ``fed.init``:

- **Secure aggregation** (``secagg.py`` + ``manager.py``): pairwise
  additive masks over the ``Z_{2^32}`` fixed-point ring, seeds exchanged
  over authenticated ``prv:`` control frames, dropout recovery driven by
  the liveness view and membership eviction. ``fed_aggregate(...,
  secure=True)`` lowers through it on the stepwise, same-mesh psum, and
  async buffered paths.
- **Differential privacy** (``dp.py``): per-party clipping before a
  contribution leaves the party, aggregator-side Gaussian noise, and the
  per-party epsilon ledger ``fed.privacy_ledger()`` exposes.
- **Quantized pushes** (``quantize.py``): the int8 wire tier
  (``payload_wire_dtype="int8"``) and the driver-tier error-feedback
  quantizer.
"""

from rayfed_tpu.privacy.config import (
    PrivacyConfig,
    QUANTIZE_TIERS,
    validate_wire_dtype_gate,
)
from rayfed_tpu.privacy.dp import (
    PrivacyLedger,
    clip_tree,
    gaussian_epsilon,
    gaussian_noise_tree,
    tree_l2_norm,
)
from rayfed_tpu.privacy.manager import (
    PrivacyManager,
    get_privacy_manager,
    install_privacy,
    record_quantized_bytes_saved,
    require_privacy_manager,
    uninstall_privacy,
)
from rayfed_tpu.privacy.protocol import (
    PRIVACY_SEQ_PREFIX,
    RECOVER_SEQ,
    SEED_SEQ,
    is_privacy_seq_id,
)
from rayfed_tpu.privacy.quantize import (
    ErrorFeedbackQuantizer,
    dequantize_leaf,
    dequantize_tree,
    quantize_leaf,
    quantize_tree,
)
from rayfed_tpu.privacy.secagg import SecAggError

__all__ = [
    "PrivacyConfig",
    "QUANTIZE_TIERS",
    "validate_wire_dtype_gate",
    "PrivacyLedger",
    "clip_tree",
    "gaussian_epsilon",
    "gaussian_noise_tree",
    "tree_l2_norm",
    "PrivacyManager",
    "get_privacy_manager",
    "install_privacy",
    "record_quantized_bytes_saved",
    "require_privacy_manager",
    "uninstall_privacy",
    "PRIVACY_SEQ_PREFIX",
    "RECOVER_SEQ",
    "SEED_SEQ",
    "is_privacy_seq_id",
    "SecAggError",
    "ErrorFeedbackQuantizer",
    "dequantize_leaf",
    "dequantize_tree",
    "quantize_leaf",
    "quantize_tree",
]
